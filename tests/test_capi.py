"""C inference API e2e (reference inference/capi_exp/): save an
inference model, compile a real C program against pt_capi.h /
libpaddle_tpu_capi.so, run it as a separate process, and check its
output against the Python predictor.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.static as static

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

C_DRIVER = r"""
#include <stdio.h>
#include <stdlib.h>
#include "pt_capi.h"

int main(int argc, char** argv) {
  void* p = pt_predictor_create(argv[1]);
  if (!p) return 2;
  if (pt_predictor_num_inputs(p) != 1) return 3;
  float in[8];
  for (int i = 0; i < 8; ++i) in[i] = (float)i;
  int64_t shape[2] = {2, 4};
  pt_tensor_copy_from_cpu_float(p, pt_predictor_input_name(p, 0), in,
                                shape, 2);
  if (pt_predictor_run(p) != 0) return 4;
  const char* out_name = pt_predictor_output_name(p, 0);
  int nd = pt_tensor_ndim(p, out_name);
  int64_t oshape[8];
  pt_tensor_shape(p, out_name, oshape);
  long total = 1;
  for (int i = 0; i < nd; ++i) total *= oshape[i];
  float* out = (float*)malloc(total * sizeof(float));
  pt_tensor_copy_to_cpu_float(p, out_name, out);
  for (long i = 0; i < total; ++i) printf("%.6f\n", out[i]);
  free(out);
  pt_predictor_destroy(p);
  return 0;
}
"""


@pytest.mark.skipif(not os.path.exists(
    os.path.join(REPO, "paddle_tpu", "lib", "libpaddle_tpu_capi.so")),
    reason="capi lib not built")
class TestCAPI:
    def test_c_program_matches_python_predictor(self, tmp_path):
        # 1) save a tiny inference model
        paddle.seed(0)
        static.enable_static()
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [2, 4], "float32")
            lin = nn.Linear(4, 3)
            y = lin(x).tanh()
        exe = static.Executor()
        exe.run(startup)
        prefix = str(tmp_path / "m")
        static.save_inference_model(prefix, [x], [y], exe, program=main)
        static.disable_static()

        # python-side expected output
        import paddle_tpu.inference as inf

        pred = inf.create_predictor(inf.Config(prefix))
        xin = np.arange(8, dtype=np.float32).reshape(2, 4)
        (want,) = pred.run([xin])

        # 2) compile the C driver
        cdir = tmp_path
        csrc = cdir / "driver.c"
        csrc.write_text(C_DRIVER)
        exe_path = str(cdir / "driver")
        libdir = os.path.join(REPO, "paddle_tpu", "lib")
        r = subprocess.run(
            ["gcc", "-o", exe_path, str(csrc),
             "-I", os.path.join(REPO, "csrc"),
             "-L", libdir, "-lpaddle_tpu_capi",
             "-Wl,-rpath," + libdir],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr

        # 3) run it in a clean process (the embedded interpreter must
        #    find paddle_tpu and use the CPU backend)
        env = dict(os.environ)
        env.update({"PYTHONPATH": REPO + os.pathsep
                    + env.get("PYTHONPATH", ""),
                    "JAX_PLATFORMS": "cpu"})
        env.pop("PALLAS_AXON_POOL_IPS", None)
        out = subprocess.run([exe_path, prefix], env=env,
                             capture_output=True, text=True, timeout=240)
        assert out.returncode == 0, (out.stdout[-800:], out.stderr[-1500:])
        got = np.array([float(l) for l in out.stdout.split()],
                       np.float32).reshape(want.shape)
        np.testing.assert_allclose(got, np.asarray(want), rtol=1e-5,
                                   atol=1e-6)


@pytest.mark.skipif(not os.path.exists(
    os.path.join(REPO, "paddle_tpu", "lib", "libpaddle_tpu_capi.so")),
    reason="capi lib not built")
class TestCppJitLayer:
    CPP = r"""
#include <cstdio>
#include "pt_jit.h"
int main(int argc, char** argv) {
  auto layer = paddle_tpu::jit::Load(argv[1]);
  paddle_tpu::jit::Tensor in;
  in.shape = {2, 4};
  for (int i = 0; i < 8; ++i) in.data.push_back((float)i);
  auto outs = layer.Forward({in});
  for (float v : outs[0].data) printf("%.6f\n", v);
  return 0;
}
"""

    def test_cpp_layer_matches_python(self, tmp_path):
        import paddle_tpu.inference as inf

        paddle.seed(0)
        static.enable_static()
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [2, 4], "float32")
            y = nn.Linear(4, 3)(x).tanh()
        exe = static.Executor()
        exe.run(startup)
        prefix = str(tmp_path / "m")
        static.save_inference_model(prefix, [x], [y], exe, program=main)
        static.disable_static()
        pred = inf.create_predictor(inf.Config(prefix))
        xin = np.arange(8, dtype=np.float32).reshape(2, 4)
        (want,) = pred.run([xin])

        src = tmp_path / "drv.cc"
        src.write_text(self.CPP)
        exe_path = str(tmp_path / "drv")
        libdir = os.path.join(REPO, "paddle_tpu", "lib")
        r = subprocess.run(
            ["g++", "-std=c++17", "-o", exe_path, str(src),
             "-I", os.path.join(REPO, "csrc"),
             "-L", libdir, "-lpaddle_tpu_capi",
             "-Wl,-rpath," + libdir],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        env = dict(os.environ)
        env.update({"PYTHONPATH": REPO + os.pathsep
                    + env.get("PYTHONPATH", ""),
                    "JAX_PLATFORMS": "cpu"})
        env.pop("PALLAS_AXON_POOL_IPS", None)
        out = subprocess.run([exe_path, prefix], env=env,
                             capture_output=True, text=True, timeout=240)
        assert out.returncode == 0, (out.stdout[-500:], out.stderr[-1500:])
        got = np.array([float(l) for l in out.stdout.split()],
                       np.float32).reshape(np.asarray(want).shape)
        np.testing.assert_allclose(got, np.asarray(want), rtol=1e-5,
                                   atol=1e-6)
