"""ZeRO stage semantics (VERDICT #3).

Parity: reference fleet/meta_parallel/sharding/group_sharded_stage2.py,
group_sharded_stage3.py, dygraph_sharding_optimizer.py:29.

  stage 1: optimizer state sharded over 'sharding'; params replicated
  stage 2: + gradients reduce-scattered (assert on compiled HLO)
  stage 3: + parameters sharded (assert per-device bytes shrink ~N x)

All stages must produce the same loss (sharding is layout, not math).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import mesh as pmesh
from paddle_tpu.parallel.engine import CompiledTrainStep

N_SHARD = 8
DIM = 64


def _shard_bytes(arr):
    """Bytes held by one device for this jax.Array."""
    shape = arr.sharding.shard_shape(arr.shape)
    return int(np.prod(shape)) * arr.dtype.itemsize


def _total_bytes(arr):
    return int(np.prod(arr.shape)) * arr.dtype.itemsize


def _build(stage):
    pmesh.build_hybrid_mesh(dp=1, mp=1, sharding=N_SHARD)
    paddle.seed(0)
    model = nn.Sequential(
        nn.Linear(DIM, 4 * DIM), nn.ReLU(), nn.Linear(4 * DIM, DIM))
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    step = CompiledTrainStep(
        model, lambda out, y: F.mse_loss(out, y), opt, zero_stage=stage)
    return model, opt, step


def _batch():
    rng = np.random.RandomState(0)
    x = rng.randn(16, DIM).astype(np.float32)
    y = rng.randn(16, DIM).astype(np.float32)
    return paddle.to_tensor(x), paddle.to_tensor(y)


class TestZeroStages:
    def test_stage0_everything_replicated(self):
        model, _, step = _build(0)
        for p in model.parameters():
            assert _shard_bytes(p._value) == _total_bytes(p._value)
        for slots in step._opt_state.values():
            for s in slots:
                assert _shard_bytes(s) == _total_bytes(s)

    def test_stage1_opt_state_sharded_params_replicated(self):
        model, _, step = _build(1)
        for p in model.parameters():
            assert _shard_bytes(p._value) == _total_bytes(p._value)
        saved = 0
        for n, slots in step._opt_state.items():
            for s in slots:
                if s.shape and s.ndim >= 1 and any(
                        d % N_SHARD == 0 and d >= N_SHARD for d in s.shape):
                    assert _shard_bytes(s) * N_SHARD == _total_bytes(s), (
                        n, s.shape, s.sharding)
                    saved += 1
        assert saved >= 4  # Adam m+v for both Linear weights at least

    def test_stage2_grads_reduce_scattered(self):
        """The grad -> sharded-update -> all-gather(params) ZeRO-2 pattern.

        On TPU XLA emits reduce-scatter for the partial->sharded grad hop;
        the CPU backend lowers the same semantics as all-reduce + slice
        (no reduce-scatter-creator pass), so assert the portable signature:
        the update runs sharded and new params are all-gathered back.
        """
        _, _, step = _build(2)
        x, y = _batch()
        hlo = step.lowered_hlo(x, y)
        assert "reduce-scatter" in hlo or "all-gather" in hlo, hlo[-2000:]

    def test_stage0_no_param_allgather(self):
        """Replicated baseline: grads all-reduced, nothing gathered."""
        _, _, step = _build(0)
        x, y = _batch()
        hlo = step.lowered_hlo(x, y)
        assert "all-gather" not in hlo
        assert "reduce-scatter" not in hlo

    def test_stage3_params_sharded_nx_memory(self):
        model, _, step = _build(3)
        shard_total = sum(_shard_bytes(p._value) for p in model.parameters())
        full_total = sum(_total_bytes(p._value) for p in model.parameters())
        # weights shard N x; small biases may stay replicated
        assert shard_total * 2 <= full_total, (shard_total, full_total)
        weights = [p for p in model.parameters() if len(p.shape) == 2]
        for p in weights:
            assert _shard_bytes(p._value) * N_SHARD == _total_bytes(p._value)
        for slots in step._opt_state.values():
            for s in slots:
                if s.ndim == 2:
                    assert _shard_bytes(s) * N_SHARD == _total_bytes(s)

    def test_all_stages_same_loss(self):
        losses = {}
        for stage in (0, 1, 2, 3):
            _, _, step = _build(stage)
            x, y = _batch()
            losses[stage] = float(step(x, y))
        base = losses[0]
        for stage, v in losses.items():
            assert np.isfinite(v)
            np.testing.assert_allclose(v, base, rtol=2e-5, err_msg=str(stage))

    def test_loss_decreases_stage3(self):
        _, _, step = _build(3)
        x, y = _batch()
        first = float(step(x, y))
        for _ in range(10):
            last = float(step(x, y))
        assert last < first

    def test_zero_composes_with_mp(self):
        """Explicit mp annotation wins on its dim; ZeRO shards another."""
        pmesh.build_hybrid_mesh(dp=1, mp=2, sharding=4)
        paddle.seed(0)
        from jax.sharding import PartitionSpec as P

        model = nn.Linear(DIM, DIM)
        model.weight._sharding_spec = P(None, "mp")
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        step = CompiledTrainStep(
            model, lambda out, y: F.mse_loss(out, y), opt, zero_stage=2)
        x, y = _batch()
        loss = float(step(x, y))
        assert np.isfinite(loss)
        spec = model.weight._value.sharding.spec
        assert tuple(spec) == (None, "mp"), spec
        # opt-state moments should carry BOTH mp and sharding axes
        m = step._opt_state["weight"][0]
        mspec = tuple(m.sharding.spec)
        assert "sharding" in mspec and "mp" in mspec, mspec


class TestEmbeddingGradPartitioning:
    def test_no_scatter_on_sharded_embedding_grad(self):
        """Regression for the GSPMD full-remat warning (VERDICT r2 #3):
        with a vocab-sharded (mp) embedding under ZeRO, the weight grad
        must come from the one-hot contraction (dot), never a
        scatter-add from the batch-sharded cotangent — the scatter is
        what forced replicate-then-slice resharding."""
        import numpy as np

        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F
        from paddle_tpu.distributed import mesh as pmesh
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.parallel.engine import CompiledTrainStep

        pmesh.build_hybrid_mesh(dp=2, mp=2, sharding=2)
        paddle.seed(0)
        cfg = LlamaConfig.tiny(hidden_size=64, num_attention_heads=4,
                               intermediate_size=128, num_hidden_layers=1,
                               vocab_size=256)
        model = LlamaForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())

        def loss_fn(logits, labels):
            return F.cross_entropy(logits.reshape([-1, cfg.vocab_size]),
                                   labels.reshape([-1]))

        step = CompiledTrainStep(model, loss_fn, opt, zero_stage=2)
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(
            rng.randint(0, 256, (8, 16)).astype(np.int32))
        labels = paddle.to_tensor(
            rng.randint(0, 256, (8, 16)).astype(np.int32))
        hlo = step.lowered_hlo(ids, labels)
        # the embedding weight grad is [vocab, hidden]-shaped (possibly
        # mp/sharding-partitioned): NO scatter may produce any shard of
        # it. (Other small scatters — e.g. index updates — are fine.)
        vocab, hidden = cfg.vocab_size, cfg.hidden_size
        # vocab dim shards over mp (2), hidden over sharding (2): the
        # possible embed-grad shard shapes keep vocab//dv >= 128 so none
        # collide with the [64, 64] attention weights
        embed_shard_shapes = {
            "f32[%d,%d]" % (vocab // dv, hidden // dh)
            for dv in (1, 2) for dh in (1, 2)}
        offending = [
            ln.strip()[:160] for ln in hlo.splitlines()
            if "scatter(" in ln and "reduce-scatter" not in ln
            and any(s + "{" in ln or s + " " in ln
                    for s in embed_shard_shapes)]
        assert not offending, (
            "embedding grad fell back to scatter-add under a sharded "
            "mesh — the GSPMD full-remat regression:\n%s"
            % "\n".join(offending))
        # and the one-hot contraction path IS present: a dot (or its
        # fusion) PRODUCING an embed-grad-shaped value
        producing = [
            ln for ln in hlo.splitlines()
            if ("dot(" in ln or "fusion(" in ln)
            and any("= " + s in ln for s in embed_shard_shapes)]
        assert producing, "no dot/fusion produces the embed-grad shape"
