"""Inference deployment API: Config/Predictor + convert_to_mixed_precision.

Parity model: reference inference/api/analysis_predictor.cc tests and
fluid/tests/unittests/ir/test_convert_to_mixed_precision.py.
"""
import os
import pickle

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.inference import (
    Config,
    PrecisionType,
    convert_to_mixed_precision,
    create_predictor,
)


def _save_model(tmp_path, with_ln=True):
    paddle.seed(0)
    layers = [nn.Linear(8, 16), nn.ReLU()]
    if with_ln:
        layers.append(nn.LayerNorm(16))
    layers.append(nn.Linear(16, 4))
    m = nn.Sequential(*layers)
    prefix = str(tmp_path / "fp32" / "m")
    paddle.jit.save(
        m, prefix,
        input_spec=[paddle.static.InputSpec([None, 8], "float32")])
    return m, prefix


class TestConvertToMixedPrecision:
    def test_bf16_roundtrip_with_blacklist(self, tmp_path):
        import ml_dtypes

        m, prefix = _save_model(tmp_path)
        mixed = str(tmp_path / "mixed" / "m")
        convert_to_mixed_precision(
            prefix + ".pdmodel", prefix + ".pdiparams",
            mixed + ".pdmodel", mixed + ".pdiparams",
            PrecisionType.Bfloat16,
            black_list={"2.weight", "2.bias"})

        st = pickle.load(open(mixed + ".pdiparams", "rb"))
        assert st["0.weight"].dtype == ml_dtypes.bfloat16
        assert st["2.weight"].dtype == np.float32  # black_list kept fp32

        x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        ref = np.asarray(m(paddle.to_tensor(x))._value)
        out = paddle.jit.load(mixed)(paddle.to_tensor(x))
        assert str(out.dtype).endswith("float32")  # keep_io_types default
        np.testing.assert_allclose(np.asarray(out._value), ref,
                                   rtol=2e-2, atol=2e-2)

    def test_params_file_path_honored(self, tmp_path):
        """params_file may live at a different path than the model file."""
        import shutil

        m, prefix = _save_model(tmp_path, with_ln=False)
        alt = str(tmp_path / "elsewhere" / "weights")
        os.makedirs(os.path.dirname(alt))
        shutil.move(prefix + ".pdiparams", alt + ".pdiparams")
        mixed = str(tmp_path / "mixedalt" / "m")
        convert_to_mixed_precision(
            prefix + ".pdmodel", alt + ".pdiparams",
            mixed + ".pdmodel", mixed + ".pdiparams",
            PrecisionType.Bfloat16)
        assert os.path.exists(mixed + ".pdiparams")

    def test_fp16_and_io_types(self, tmp_path):
        m, prefix = _save_model(tmp_path, with_ln=False)
        mixed = str(tmp_path / "mixed16" / "m")
        convert_to_mixed_precision(
            prefix + ".pdmodel", prefix + ".pdiparams",
            mixed + ".pdmodel", mixed + ".pdiparams",
            PrecisionType.Half, keep_io_types=False)
        x = np.random.RandomState(1).randn(2, 8).astype(np.float32)
        out = paddle.jit.load(mixed)(paddle.to_tensor(x))
        assert str(out.dtype).endswith("float16")  # io converted too

    def test_int8_rejected(self, tmp_path):
        _, prefix = _save_model(tmp_path, with_ln=False)
        with pytest.raises(ValueError, match="quantization"):
            convert_to_mixed_precision(
                prefix + ".pdmodel", prefix + ".pdiparams",
                prefix + "q.pdmodel", prefix + "q.pdiparams",
                PrecisionType.Int8)


class TestPredictor:
    def test_config_predictor_roundtrip(self, tmp_path):
        from paddle_tpu import static

        paddle.seed(1)
        static.enable_static()
        try:
            prefix = str(tmp_path / "pred" / "m")
            main, startup = static.Program(), static.Program()
            with static.program_guard(main, startup):
                inp = static.data("x", [-1, 4], "float32")
                out = static.nn.fc(inp, 3)
            exe = static.Executor()
            exe.run(startup)
            ref = exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                          fetch_list=[out])[0]
            static.save_inference_model(prefix, [inp], [out], exe,
                                        program=main)
        finally:
            static.disable_static()
        cfg = Config(prefix + ".pdmodel")
        cfg.enable_tpu()
        pred = create_predictor(cfg)
        assert pred.get_input_names()
        outs = pred.run([np.ones((2, 4), np.float32)])
        np.testing.assert_allclose(outs[0], ref, rtol=1e-5, atol=1e-6)


class TestPredictorPool:
    def test_pool_members_have_isolated_handles(self, tmp_path):
        from paddle_tpu import static
        from paddle_tpu.inference import PredictorPool

        paddle.seed(2)
        static.enable_static()
        try:
            prefix = str(tmp_path / "pool" / "m")
            main, startup = static.Program(), static.Program()
            with static.program_guard(main, startup):
                inp = static.data("x", [-1, 4], "float32")
                out = static.nn.fc(inp, 2)
            exe = static.Executor()
            exe.run(startup)
            static.save_inference_model(prefix, [inp], [out], exe,
                                        program=main)
        finally:
            static.disable_static()
        pool = PredictorPool(Config(prefix + ".pdmodel"), size=3)
        assert len(pool) == 3
        a, b = pool.retrieve(0), pool.retrieve(1)
        assert a is not b
        xa = np.ones((2, 4), np.float32)
        xb = np.full((2, 4), 2.0, np.float32)
        oa = a.run([xa])[0]
        ob = b.run([xb])[0]
        assert not np.allclose(oa, ob)  # different inputs, different outs
        # a's bound handles were not disturbed by b's run
        name = a.get_input_names()[0]
        np.testing.assert_allclose(a.get_input_handle(name).copy_to_cpu(),
                                   xa)
        # a third member computes the same function
        np.testing.assert_allclose(pool.retrieve(2).run([xa])[0], oa,
                                   rtol=1e-6)
        with pytest.raises(IndexError):
            pool.retrieve(3)
        with pytest.raises(IndexError):
            pool.retrieve(-1)  # no silent wrap-around
        # members share one loaded program (reference Clone())
        assert pool.retrieve(1)._prog is pool.retrieve(0)._prog


class TestDistModelShardedServing:
    def _save_static(self, tmp_path):
        from paddle_tpu import static

        paddle.seed(1)
        static.enable_static()
        try:
            prefix = str(tmp_path / "dm" / "m")
            main, startup = static.Program(), static.Program()
            with static.program_guard(main, startup):
                inp = static.data("x", [-1, 8], "float32")
                out = static.nn.fc(inp, 4)
            exe = static.Executor()
            exe.run(startup)
            static.save_inference_model(prefix, [inp], [out], exe,
                                        program=main)
        finally:
            static.disable_static()
        return prefix

    def test_dp_sharded_run_matches_single_device(self, tmp_path):
        """reference fleet_executor/dist_model.cc role: the same saved
        model serves a batch SHARDED over the dp mesh axis, numerically
        identical to the unsharded predictor."""
        from paddle_tpu.distributed import mesh as pmesh
        from paddle_tpu.distributed.fleet_executor import DistModel

        prefix = self._save_static(tmp_path)
        cfg = Config(prefix + ".pdmodel")
        x = np.random.RandomState(0).randn(8, 8).astype(np.float32)

        single = create_predictor(cfg).run([x])

        pmesh.build_hybrid_mesh(dp=8)
        dm = DistModel(cfg)  # picks up the active dp mesh
        assert dm._dp_degree() == 8
        sharded = dm.run([x])
        np.testing.assert_allclose(np.asarray(sharded[0]),
                                   np.asarray(single[0]), rtol=1e-5,
                                   atol=1e-6)

    def test_indivisible_batch_falls_back_replicated(self, tmp_path):
        from paddle_tpu.distributed import mesh as pmesh
        from paddle_tpu.distributed.fleet_executor import DistModel

        prefix = self._save_static(tmp_path)
        pmesh.build_hybrid_mesh(dp=8)
        dm = DistModel(Config(prefix + ".pdmodel"))
        x = np.random.RandomState(1).randn(3, 8).astype(np.float32)
        out = dm.run([x])  # 3 % 8 != 0: replicated, still correct
        assert np.asarray(out[0]).shape == (3, 4)
