"""Multi-host worker: one OS process hosting 4 virtual CPU devices,
joining a 2-process global mesh of 8 devices via init_parallel_env
(reference pattern: unittests/test_dist_base.py worker model files with
runtime_main — the same file is spawnable worker and library).

Env contract (set by the parent test):
  PADDLE_NNODES=2  PADDLE_NODE_RANK=<0|1>  PADDLE_MASTER=host:port
  PADDLE_TRAINERS_NUM=2  PADDLE_TRAINER_ID=<0|1>
  JAX_PLATFORMS=cpu  XLA_FLAGS=--xla_force_host_platform_device_count=4

Runs N dp train steps of the tiny Llama on the GLOBAL 8-device mesh and
prints one line per step: LOSS <step> <value>. The parent compares the
sequence against a single-process 8-device golden run.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def runtime_main(steps=3):
    import numpy as np
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import mesh as pmesh
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.parallel.engine import CompiledTrainStep

    dist.init_parallel_env()
    assert jax.device_count() == 8, jax.device_count()
    if int(os.environ.get("PADDLE_NNODES", "1")) > 1:
        assert jax.process_count() == 2, jax.process_count()
        assert jax.local_device_count() == 4

    pmesh.build_hybrid_mesh(dp=8)
    paddle.seed(0)
    cfg = LlamaConfig.tiny(use_parallel=False)
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())

    def loss_fn(logits, labels):
        return F.cross_entropy(
            logits.reshape([-1, cfg.vocab_size]), labels.reshape([-1]))

    step = CompiledTrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(7)  # identical data in every process
    for i in range(steps):
        ids = rng.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32)
        # LEARNABLE target (labels == inputs, the copy task): with
        # random labels the loss just random-walks around ln(vocab) and
        # the parent's "training progresses" assertion was a coin flip
        # (the PR-7-noted flake); on the copy task the tiny model's
        # loss drops monotonically within a handful of steps on every
        # jax build, so progress is a deterministic signal again
        loss = step(paddle.to_tensor(ids), paddle.to_tensor(ids))
        print("LOSS %d %.6f" % (i, float(loss)), flush=True)


if __name__ == "__main__":
    runtime_main(int(sys.argv[1]) if len(sys.argv) > 1 else 3)
