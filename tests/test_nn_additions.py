"""nn additions: unfold/fold layers, Unflatten, sequence_mask/zeropad2d,
soft-margin family losses, BeamSearchDecoder + dynamic_decode
(reference nn/layer/common.py, nn/functional/{common,extension,loss}.py,
nn/decode.py and their unittests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

RNG = np.random.RandomState(3)


class TestUnfoldFold:
    def test_unfold_matches_manual_patches(self):
        x = paddle.to_tensor(RNG.randn(1, 2, 4, 4).astype(np.float32))
        u = nn.Unfold(kernel_sizes=2, strides=2)(x)
        assert u.shape == [1, 2 * 2 * 2, 4]
        xv = np.asarray(x._value)
        # first block = x[:, :, 0:2, 0:2] flattened channel-major
        first = xv[0, :, 0:2, 0:2].reshape(-1)
        np.testing.assert_allclose(np.asarray(u._value)[0, :, 0], first,
                                   rtol=1e-6)

    def test_fold_inverts_unfold_on_disjoint_blocks(self):
        x = paddle.to_tensor(RNG.randn(1, 3, 4, 4).astype(np.float32))
        u = F.unfold(x, 2, strides=2)
        back = nn.Fold(output_sizes=[4, 4], kernel_sizes=2, strides=2)(u)
        np.testing.assert_allclose(np.asarray(back._value),
                                   np.asarray(x._value), rtol=1e-6)

    def test_unflatten(self):
        x = paddle.to_tensor(np.zeros((2, 12), np.float32))
        out = nn.Unflatten(1, [3, 4])(x)
        assert out.shape == [2, 3, 4]
        out = nn.Unflatten(-1, [2, 6])(x)
        assert out.shape == [2, 2, 6]


class TestNewLosses:
    def test_soft_margin_scalar_oracle(self):
        x = np.asarray([0.5, -2.0], np.float32)
        y = np.asarray([1.0, -1.0], np.float32)
        got = float(F.soft_margin_loss(paddle.to_tensor(x),
                                       paddle.to_tensor(y)))
        np.testing.assert_allclose(got, np.log1p(np.exp(-y * x)).mean(),
                                   rtol=1e-6)

    def test_multi_label_soft_margin_oracle(self):
        x = RNG.randn(4, 3).astype(np.float32)
        y = (RNG.rand(4, 3) > 0.5).astype(np.float32)
        got = float(F.multi_label_soft_margin_loss(
            paddle.to_tensor(x), paddle.to_tensor(y)))

        def sig(v):
            return 1.0 / (1.0 + np.exp(-v))

        ref = -(y * np.log(sig(x)) + (1 - y) * np.log(sig(-x)))
        np.testing.assert_allclose(got, ref.mean(axis=-1).mean(),
                                   rtol=1e-5)

    def test_npair_loss_grads(self):
        a = paddle.to_tensor(RNG.randn(4, 8).astype(np.float32))
        a.stop_gradient = False
        p = paddle.to_tensor(RNG.randn(4, 8).astype(np.float32))
        lab = paddle.to_tensor(np.asarray([0, 1, 0, 2], np.int64))
        loss = F.npair_loss(a, p, lab)
        loss.backward()
        assert np.isfinite(float(loss))
        assert a.grad is not None


class TestBeamSearchDecoder:
    def test_decodes_and_scores_order(self):
        paddle.seed(10)
        vocab, hidden = 12, 16
        emb = nn.Embedding(vocab, hidden)
        cell = nn.GRUCell(hidden, hidden)
        out_fc = nn.Linear(hidden, vocab)
        dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=1,
                                   beam_size=3, embedding_fn=emb,
                                   output_fn=out_fc)
        h0 = paddle.to_tensor(RNG.randn(2, hidden).astype(np.float32))
        ids, final = nn.dynamic_decode(dec, inits=[h0], max_step_num=6)
        got = np.asarray(ids._value)
        assert got.shape[0] == 2 and got.shape[2] == 3
        assert got.shape[1] <= 6
        assert (got >= 0).all() and (got < vocab).all()

    def test_greedy_equivalence_beam1(self):
        """beam_size=1 must follow the argmax chain of the cell."""
        paddle.seed(11)
        vocab, hidden = 8, 8
        emb = nn.Embedding(vocab, hidden)
        cell = nn.GRUCell(hidden, hidden)
        fc = nn.Linear(hidden, vocab)
        dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=7,
                                   beam_size=1, embedding_fn=emb,
                                   output_fn=fc)
        h0 = paddle.to_tensor(RNG.randn(1, hidden).astype(np.float32))
        ids, _ = nn.dynamic_decode(dec, inits=[h0], max_step_num=5)
        got = np.asarray(ids._value)[0, :, 0]

        # manual greedy rollout
        h = h0
        tok = paddle.to_tensor(np.asarray([0], np.int64))
        want = []
        for _ in range(len(got)):
            out, h = cell(emb(tok), h)
            nxt = int(np.argmax(np.asarray(fc(out)._value)[0]))
            want.append(nxt)
            if nxt == 7:
                break
            tok = paddle.to_tensor(np.asarray([nxt], np.int64))
        np.testing.assert_array_equal(got[:len(want)], want)

    def test_tile_beam_merge_with_batch(self):
        x = paddle.to_tensor(np.arange(4, dtype=np.float32).reshape(2, 2))
        t = nn.BeamSearchDecoder.tile_beam_merge_with_batch(x, 3)
        assert t.shape == [6, 2]
        np.testing.assert_allclose(np.asarray(t._value)[0:3],
                                   np.tile(np.asarray([[0., 1.]]), (3, 1)))


class TestLossStability:
    def test_soft_margin_large_logits_finite(self):
        """Regression: log1p(exp(100)) overflowed; logaddexp is exact."""
        x = paddle.to_tensor(np.asarray([-100.0, 100.0], np.float32))
        y = paddle.to_tensor(np.asarray([1.0, -1.0], np.float32))
        got = float(F.soft_margin_loss(x, y))
        assert np.isfinite(got)
        np.testing.assert_allclose(got, 100.0, rtol=1e-5)


class TestSurfaceCompletion:
    def test_remaining_functional_surface(self):
        """The full reference nn.functional __all__ resolves here."""
        import os
        import re

        path = ("/root/reference/python/paddle/nn/functional/"
                "__init__.py")
        if not os.path.exists(path):
            pytest.skip("reference tree not mounted at /root/reference "
                        "(parity audit needs the reference checkout; "
                        "this container ships without it)")
        ref = open(path).read()
        names = set(re.findall(r"^\s+'(\w+)',", ref, re.M))
        missing = [n for n in sorted(names) if not hasattr(F, n)]
        assert missing == [], missing

    def test_inplace_variants_mutate(self):
        x = paddle.to_tensor(np.asarray([-1.0, 2.0], np.float32))
        y = F.relu_(x)
        assert y is x
        np.testing.assert_allclose(np.asarray(x._value), [0.0, 2.0])

    def test_log_sigmoid_stable(self):
        x = paddle.to_tensor(np.asarray([-100.0, 0.0], np.float32))
        out = np.asarray(F.log_sigmoid(x)._value)
        np.testing.assert_allclose(out, [-100.0, -np.log(2)], rtol=1e-5)

    def test_pairwise_distance_and_dice(self):
        a = paddle.to_tensor(np.asarray([[0.0, 3.0]], np.float32))
        b = paddle.to_tensor(np.asarray([[4.0, 0.0]], np.float32))
        d = float(F.pairwise_distance(a, b)._value[0])
        np.testing.assert_allclose(d, 5.0, rtol=1e-4)
        probs = paddle.to_tensor(np.asarray([[[0.9, 0.1]]], np.float32))
        lbl = paddle.to_tensor(np.asarray([[0]], np.int64))
        dl = float(F.dice_loss(probs, lbl))
        np.testing.assert_allclose(dl, 1 - 2 * 0.9 / (1.0 + 1.0),
                                   rtol=1e-3)

    def test_multi_margin_oracle(self):
        x = np.asarray([[0.1, 0.5, 0.2]], np.float32)
        got = float(F.multi_margin_loss(
            paddle.to_tensor(x),
            paddle.to_tensor(np.asarray([1], np.int64))))
        want = (max(0, 1 - 0.5 + 0.1) + max(0, 1 - 0.5 + 0.2)) / 3
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_gather_tree(self):
        ids = np.asarray([[[1, 2]], [[3, 4]]], np.int32)     # [T=2,B=1,K=2]
        parents = np.asarray([[[0, 0]], [[1, 0]]], np.int32)
        out = np.asarray(F.gather_tree(ids, parents)._value)
        # final beam0 came from parent 1 at t=1: path [2, 3]
        np.testing.assert_array_equal(out[:, 0, 0], [2, 3])
        np.testing.assert_array_equal(out[:, 0, 1], [1, 4])

    def test_rnnt_loss_two_frame_oracle(self):
        """Tiny exact oracle: T=2, U=1, V=2 — enumerate both alignments
        (emit@t0 + 2 blanks path structure) by hand."""
        logits = np.zeros((1, 2, 2, 2), np.float32)  # uniform: logp=-log2
        lab = np.asarray([[1]], np.int64)
        out = F.rnnt_loss(paddle.to_tensor(logits), paddle.to_tensor(lab),
                          paddle.to_tensor(np.asarray([2], np.int64)),
                          paddle.to_tensor(np.asarray([1], np.int64)),
                          reduction="none")
        got = float(np.asarray(out._value).ravel()[0])
        # alignments: (emit,blank,blank),(blank,emit,blank): each has
        # 3 uniform steps -> prob 2 * (1/2)^3 = 1/4 -> nll = log 4
        np.testing.assert_allclose(got, np.log(4.0), rtol=1e-4)


class TestPoolFixRegressions:
    def test_adaptive_3d_pools(self):
        x = paddle.to_tensor(RNG.randn(1, 2, 4, 6, 4).astype(np.float32))
        out = F.adaptive_avg_pool3d(x, 2)
        assert out.shape == [1, 2, 2, 2, 2]
        xv = np.asarray(x._value)
        np.testing.assert_allclose(
            np.asarray(out._value)[0, 0, 0, 0, 0],
            xv[0, 0, :2, :3, :2].mean(), rtol=1e-5)
        mx = F.adaptive_max_pool3d(x, [2, 3, 2])
        assert mx.shape == [1, 2, 2, 3, 2]
        np.testing.assert_allclose(
            np.asarray(mx._value)[0, 1, 1, 2, 1],
            xv[0, 1, 2:, 4:, 2:].max(), rtol=1e-5)

    def test_max_unpool_1d_3d_scatter(self):
        # pooled values land at their recorded flat positions, rest zero
        vals = paddle.to_tensor(
            np.asarray([[[5.0, 7.0]]], np.float32))       # [1, 1, 2]
        idx = paddle.to_tensor(np.asarray([[[1, 6]]], np.int64))
        u1 = F.max_unpool1d(vals, idx, 4)
        got = np.asarray(u1._value)[0, 0]
        assert u1.shape == [1, 1, 8]
        np.testing.assert_allclose(got[[1, 6]], [5.0, 7.0])
        assert got.sum() == 12.0
        with pytest.raises(ValueError, match="NCL"):
            F.max_unpool1d(vals, idx, 4, data_format="NLC")

        v3 = paddle.to_tensor(np.ones((1, 1, 1, 1, 1), np.float32))
        i3 = paddle.to_tensor(np.asarray(
            [[[[[7]]]]], np.int64))                       # flat pos 7
        u3 = F.max_unpool3d(v3, i3, 2)
        assert u3.shape == [1, 1, 2, 2, 2]
        assert np.asarray(u3._value).reshape(-1)[7] == 1.0
        with pytest.raises(ValueError, match="NCDHW"):
            F.max_unpool3d(v3, i3, 2, data_format="NDHWC")

    def test_zeropad2d_nhwc(self):
        """Regression: NHWC pad used to hit W+channels instead of H+W."""
        x = paddle.to_tensor(np.ones((1, 3, 3, 2), np.float32))
        out = F.zeropad2d(x, [1, 1, 2, 2], data_format="NHWC")
        assert out.shape == [1, 7, 5, 2]
        nchw = F.zeropad2d(
            paddle.to_tensor(np.ones((1, 2, 3, 3), np.float32)),
            [1, 1, 2, 2])
        assert nchw.shape == [1, 2, 7, 5]

    def test_multi_margin_weight_inside_pow(self):
        x = paddle.to_tensor(np.asarray([[0.1, 0.5, 0.2]], np.float32))
        y = paddle.to_tensor(np.asarray([1], np.int64))
        w = paddle.to_tensor(np.asarray([1.0, 2.0, 1.0], np.float32))
        got = float(F.multi_margin_loss(x, y, p=2, weight=w))
        z1, z2 = max(0, 1 - 0.5 + 0.1), max(0, 1 - 0.5 + 0.2)
        want = ((2 * z1) ** 2 + (2 * z2) ** 2) / 3  # (w*z)^p
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_rnnt_fastemit_raises(self):
        with pytest.raises(NotImplementedError, match="FastEmit"):
            F.rnnt_loss(paddle.to_tensor(np.zeros((1, 2, 2, 2),
                                                  np.float32)),
                        paddle.to_tensor(np.asarray([[1]], np.int64)),
                        paddle.to_tensor(np.asarray([2], np.int64)),
                        paddle.to_tensor(np.asarray([1], np.int64)),
                        fastemit_lambda=0.001)
