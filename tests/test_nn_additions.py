"""nn additions: unfold/fold layers, Unflatten, sequence_mask/zeropad2d,
soft-margin family losses, BeamSearchDecoder + dynamic_decode
(reference nn/layer/common.py, nn/functional/{common,extension,loss}.py,
nn/decode.py and their unittests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

RNG = np.random.RandomState(3)


class TestUnfoldFold:
    def test_unfold_matches_manual_patches(self):
        x = paddle.to_tensor(RNG.randn(1, 2, 4, 4).astype(np.float32))
        u = nn.Unfold(kernel_sizes=2, strides=2)(x)
        assert u.shape == [1, 2 * 2 * 2, 4]
        xv = np.asarray(x._value)
        # first block = x[:, :, 0:2, 0:2] flattened channel-major
        first = xv[0, :, 0:2, 0:2].reshape(-1)
        np.testing.assert_allclose(np.asarray(u._value)[0, :, 0], first,
                                   rtol=1e-6)

    def test_fold_inverts_unfold_on_disjoint_blocks(self):
        x = paddle.to_tensor(RNG.randn(1, 3, 4, 4).astype(np.float32))
        u = F.unfold(x, 2, strides=2)
        back = nn.Fold(output_sizes=[4, 4], kernel_sizes=2, strides=2)(u)
        np.testing.assert_allclose(np.asarray(back._value),
                                   np.asarray(x._value), rtol=1e-6)

    def test_unflatten(self):
        x = paddle.to_tensor(np.zeros((2, 12), np.float32))
        out = nn.Unflatten(1, [3, 4])(x)
        assert out.shape == [2, 3, 4]
        out = nn.Unflatten(-1, [2, 6])(x)
        assert out.shape == [2, 2, 6]


class TestNewLosses:
    def test_soft_margin_scalar_oracle(self):
        x = np.asarray([0.5, -2.0], np.float32)
        y = np.asarray([1.0, -1.0], np.float32)
        got = float(F.soft_margin_loss(paddle.to_tensor(x),
                                       paddle.to_tensor(y)))
        np.testing.assert_allclose(got, np.log1p(np.exp(-y * x)).mean(),
                                   rtol=1e-6)

    def test_multi_label_soft_margin_oracle(self):
        x = RNG.randn(4, 3).astype(np.float32)
        y = (RNG.rand(4, 3) > 0.5).astype(np.float32)
        got = float(F.multi_label_soft_margin_loss(
            paddle.to_tensor(x), paddle.to_tensor(y)))

        def sig(v):
            return 1.0 / (1.0 + np.exp(-v))

        ref = -(y * np.log(sig(x)) + (1 - y) * np.log(sig(-x)))
        np.testing.assert_allclose(got, ref.mean(axis=-1).mean(),
                                   rtol=1e-5)

    def test_npair_loss_grads(self):
        a = paddle.to_tensor(RNG.randn(4, 8).astype(np.float32))
        a.stop_gradient = False
        p = paddle.to_tensor(RNG.randn(4, 8).astype(np.float32))
        lab = paddle.to_tensor(np.asarray([0, 1, 0, 2], np.int64))
        loss = F.npair_loss(a, p, lab)
        loss.backward()
        assert np.isfinite(float(loss))
        assert a.grad is not None


class TestBeamSearchDecoder:
    def test_decodes_and_scores_order(self):
        paddle.seed(10)
        vocab, hidden = 12, 16
        emb = nn.Embedding(vocab, hidden)
        cell = nn.GRUCell(hidden, hidden)
        out_fc = nn.Linear(hidden, vocab)
        dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=1,
                                   beam_size=3, embedding_fn=emb,
                                   output_fn=out_fc)
        h0 = paddle.to_tensor(RNG.randn(2, hidden).astype(np.float32))
        ids, final = nn.dynamic_decode(dec, inits=[h0], max_step_num=6)
        got = np.asarray(ids._value)
        assert got.shape[0] == 2 and got.shape[2] == 3
        assert got.shape[1] <= 6
        assert (got >= 0).all() and (got < vocab).all()

    def test_greedy_equivalence_beam1(self):
        """beam_size=1 must follow the argmax chain of the cell."""
        paddle.seed(11)
        vocab, hidden = 8, 8
        emb = nn.Embedding(vocab, hidden)
        cell = nn.GRUCell(hidden, hidden)
        fc = nn.Linear(hidden, vocab)
        dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=7,
                                   beam_size=1, embedding_fn=emb,
                                   output_fn=fc)
        h0 = paddle.to_tensor(RNG.randn(1, hidden).astype(np.float32))
        ids, _ = nn.dynamic_decode(dec, inits=[h0], max_step_num=5)
        got = np.asarray(ids._value)[0, :, 0]

        # manual greedy rollout
        h = h0
        tok = paddle.to_tensor(np.asarray([0], np.int64))
        want = []
        for _ in range(len(got)):
            out, h = cell(emb(tok), h)
            nxt = int(np.argmax(np.asarray(fc(out)._value)[0]))
            want.append(nxt)
            if nxt == 7:
                break
            tok = paddle.to_tensor(np.asarray([nxt], np.int64))
        np.testing.assert_array_equal(got[:len(want)], want)

    def test_tile_beam_merge_with_batch(self):
        x = paddle.to_tensor(np.arange(4, dtype=np.float32).reshape(2, 2))
        t = nn.BeamSearchDecoder.tile_beam_merge_with_batch(x, 3)
        assert t.shape == [6, 2]
        np.testing.assert_allclose(np.asarray(t._value)[0:3],
                                   np.tile(np.asarray([[0., 1.]]), (3, 1)))


class TestLossStability:
    def test_soft_margin_large_logits_finite(self):
        """Regression: log1p(exp(100)) overflowed; logaddexp is exact."""
        x = paddle.to_tensor(np.asarray([-100.0, 100.0], np.float32))
        y = paddle.to_tensor(np.asarray([1.0, -1.0], np.float32))
        got = float(F.soft_margin_loss(x, y))
        assert np.isfinite(got)
        np.testing.assert_allclose(got, 100.0, rtol=1e-5)
