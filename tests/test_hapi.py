"""hapi Model API tests (reference python/paddle/tests/test_model.py)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.hapi import EarlyStopping, Model
from paddle_tpu.io import Dataset
from paddle_tpu.metric import Accuracy

RNG = np.random.RandomState(7)


class ToyDataset(Dataset):
    """Linearly separable 2-class problem."""

    def __init__(self, n=128):
        self.x = RNG.randn(n, 8).astype("float32")
        w = RNG.randn(8)
        self.y = (self.x @ w > 0).astype("int64")

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def _make_model():
    net = nn.Sequential(
        nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 2))
    m = Model(net)
    m.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=1e-2,
                                        parameters=net.parameters()),
        loss=nn.CrossEntropyLoss(),
        metrics=Accuracy())
    return m


class TestModelFit:
    def test_fit_reduces_loss_and_tracks_acc(self):
        m = _make_model()
        ds = ToyDataset(128)
        history = m.fit(ds, batch_size=32, epochs=4, verbose=0)
        assert len(history) == 4
        assert history[-1]["loss"] < history[0]["loss"]
        assert history[-1]["acc"] > 0.7

    def test_evaluate_and_predict(self):
        m = _make_model()
        ds = ToyDataset(64)
        m.fit(ds, batch_size=16, epochs=3, verbose=0)
        logs = m.evaluate(ds, batch_size=16, verbose=0)
        assert "loss" in logs and "acc" in logs
        preds = m.predict(ds, batch_size=16, stack_outputs=True)
        assert preds[0].shape == (64, 2)

    def test_fit_with_eval_data(self):
        m = _make_model()
        history = m.fit(ToyDataset(64), eval_data=ToyDataset(32),
                        batch_size=16, epochs=2, verbose=0)
        assert len(history) == 2

    def test_save_load_roundtrip(self, tmp_path):
        m = _make_model()
        ds = ToyDataset(64)
        m.fit(ds, batch_size=16, epochs=2, verbose=0)
        path = str(tmp_path / "ckpt" / "model")
        m.save(path)
        assert os.path.exists(path + ".pdparams")
        assert os.path.exists(path + ".pdopt")
        m2 = _make_model()
        m2.load(path)
        w1 = m.network.state_dict()
        w2 = m2.network.state_dict()
        for k in w1:
            np.testing.assert_allclose(w1[k].numpy(), w2[k].numpy())

    def test_early_stopping(self):
        m = _make_model()
        es = EarlyStopping(monitor="loss", patience=0, mode="min")
        # eval on every epoch; loss on a fixed eval set will plateau fast
        # with a large lr; patience=0 means stop on first non-improvement
        m.fit(ToyDataset(32), eval_data=ToyDataset(16), batch_size=16,
              epochs=50, verbose=0, callbacks=[es])
        assert m.stop_training  # stopped before 50 epochs

    def test_checkpoint_callback(self, tmp_path):
        m = _make_model()
        m.fit(ToyDataset(32), batch_size=16, epochs=2, verbose=0,
              save_dir=str(tmp_path / "ck"))
        assert os.path.exists(str(tmp_path / "ck" / "final.pdparams"))

    def test_summary(self, capsys):
        m = _make_model()
        info = m.summary()
        assert info["total_params"] == 8 * 32 + 32 + 32 * 2 + 2

    def test_network_computes_own_loss(self):
        """Model with loss=None: the network's output IS the loss."""

        class SelfLoss(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(4, 1)

            def forward(self, x, y):
                return ((self.lin(x) - y) ** 2).mean()

        net = SelfLoss()
        m = Model(net)
        m.prepare(optimizer=paddle.optimizer.SGD(
            learning_rate=0.05, parameters=net.parameters()))
        x = RNG.randn(64, 4).astype("float32")
        y = (x.sum(1, keepdims=True) * 0.3).astype("float32")
        batches = [((x[i:i + 16], y[i:i + 16]),) for i in range(0, 64, 16)]
        # network takes two inputs and returns loss; no separate labels
        l0 = m.train_batch([x[:16], y[:16]])["loss"]
        for _ in range(20):
            logs = m.train_batch([x[:16], y[:16]])
        assert logs["loss"] < l0
