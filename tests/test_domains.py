"""Tests for sparse / distribution / fft / signal domains.

Oracles: numpy/scipy-free closed forms. Reference analogs:
unittests/test_sparse_*.py, test_distribution_*.py, fft tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import distribution as D
from paddle_tpu import fft, signal, sparse

RNG = np.random.RandomState(5)


class TestSparse:
    def _coo(self):
        dense = np.zeros((4, 5), np.float32)
        dense[0, 1] = 2.0
        dense[2, 3] = -1.5
        dense[3, 0] = 4.0
        idx = np.array(np.nonzero(dense))
        vals = dense[tuple(idx)]
        return sparse.sparse_coo_tensor(idx, vals, dense.shape), dense

    def test_coo_roundtrip(self):
        st, dense = self._coo()
        assert st.nnz == 3
        np.testing.assert_allclose(st.to_dense().numpy(), dense)
        assert st.is_sparse_coo()

    def test_csr_roundtrip(self):
        st, dense = self._coo()
        csr = st.to_sparse_csr()
        assert csr.is_sparse_csr()
        np.testing.assert_allclose(csr.to_dense().numpy(), dense)
        back = csr.to_sparse_coo()
        np.testing.assert_allclose(back.to_dense().numpy(), dense)

    def test_csr_direct_construction(self):
        # [[0,2,0],[3,0,4]]
        csr = sparse.sparse_csr_tensor(
            [0, 1, 3], [1, 0, 2], [2.0, 3.0, 4.0], (2, 3))
        expect = np.array([[0, 2, 0], [3, 0, 4]], np.float32)
        np.testing.assert_allclose(csr.to_dense().numpy(), expect)

    def test_elementwise(self):
        st, dense = self._coo()
        np.testing.assert_allclose((st + st).to_dense().numpy(), 2 * dense)
        np.testing.assert_allclose((st - st).to_dense().numpy(), 0 * dense)
        np.testing.assert_allclose(
            sparse.relu(st).to_dense().numpy(), np.maximum(dense, 0))
        np.testing.assert_allclose(
            sparse.neg(st).to_dense().numpy(), -dense)

    def test_matmul(self):
        st, dense = self._coo()
        y = RNG.randn(5, 3).astype(np.float32)
        out = sparse.matmul(st, y)
        np.testing.assert_allclose(out.numpy(), dense @ y, rtol=1e-5,
                                   atol=1e-6)

    def test_masked_matmul(self):
        st, dense = self._coo()
        a = RNG.randn(4, 6).astype(np.float32)
        b = RNG.randn(6, 5).astype(np.float32)
        out = sparse.masked_matmul(a, b, st)
        full = a @ b
        expect = np.where(dense != 0, full, 0)
        np.testing.assert_allclose(out.to_dense().numpy(), expect,
                                   rtol=1e-5, atol=1e-5)


class TestDistribution:
    def test_normal_moments_and_logprob(self):
        paddle.seed(0)
        d = D.Normal(1.0, 2.0)
        s = d.sample([20000])
        assert abs(float(s.numpy().mean()) - 1.0) < 0.1
        assert abs(float(s.numpy().std()) - 2.0) < 0.1
        lp = d.log_prob(paddle.to_tensor(1.0))
        expect = -np.log(2.0) - 0.5 * np.log(2 * np.pi)
        np.testing.assert_allclose(float(lp), expect, rtol=1e-5)

    def test_kl_normal(self):
        p = D.Normal(0.0, 1.0)
        q = D.Normal(1.0, 2.0)
        kl = float(D.kl_divergence(p, q))
        expect = np.log(2.0) + (1 + 1) / (2 * 4) - 0.5
        np.testing.assert_allclose(kl, expect, rtol=1e-5)

    def test_uniform(self):
        paddle.seed(0)
        d = D.Uniform(2.0, 6.0)
        s = d.sample([10000]).numpy()
        assert s.min() >= 2.0 and s.max() < 6.0
        np.testing.assert_allclose(float(d.entropy()), np.log(4.0),
                                   rtol=1e-6)
        assert np.isneginf(float(d.log_prob(paddle.to_tensor(7.0))))

    def test_categorical(self):
        paddle.seed(0)
        d = D.Categorical(probs=np.array([0.1, 0.2, 0.7], np.float32))
        s = d.sample([20000]).numpy()
        freq = np.bincount(s, minlength=3) / len(s)
        np.testing.assert_allclose(freq, [0.1, 0.2, 0.7], atol=0.02)
        lp = float(d.log_prob(paddle.to_tensor(2)))
        np.testing.assert_allclose(lp, np.log(0.7), rtol=1e-4)

    def test_bernoulli_beta_dirichlet(self):
        paddle.seed(0)
        b = D.Bernoulli(probs=0.3)
        assert abs(float(b.sample([20000]).numpy().mean()) - 0.3) < 0.02
        be = D.Beta(2.0, 3.0)
        np.testing.assert_allclose(float(be.mean), 0.4, rtol=1e-6)
        s = be.sample([20000]).numpy()
        assert abs(s.mean() - 0.4) < 0.02
        dr = D.Dirichlet(np.array([1.0, 2.0, 3.0], np.float32))
        np.testing.assert_allclose(dr.mean.numpy(),
                                   [1 / 6, 2 / 6, 3 / 6], rtol=1e-5)

    def test_gamma_laplace_exponential(self):
        paddle.seed(0)
        g = D.Gamma(3.0, 2.0)
        np.testing.assert_allclose(float(g.mean), 1.5, rtol=1e-6)
        assert abs(float(g.sample([20000]).numpy().mean()) - 1.5) < 0.05
        la = D.Laplace(0.0, 1.0)
        lp = float(la.log_prob(paddle.to_tensor(0.0)))
        np.testing.assert_allclose(lp, -np.log(2.0), rtol=1e-5)
        e = D.Exponential(2.0)
        np.testing.assert_allclose(float(e.mean), 0.5, rtol=1e-6)

    def test_multinomial(self):
        paddle.seed(0)
        m = D.Multinomial(10, np.array([0.5, 0.5], np.float32))
        s = m.sample().numpy()
        assert s.sum() == 10


class TestFFT:
    def test_fft_matches_numpy(self):
        x = RNG.randn(16).astype(np.float32)
        out = fft.fft(paddle.to_tensor(x))
        np.testing.assert_allclose(out.numpy(), np.fft.fft(x), rtol=1e-4,
                                   atol=1e-4)

    def test_rfft_irfft_roundtrip(self):
        x = RNG.randn(32).astype(np.float32)
        spec = fft.rfft(paddle.to_tensor(x))
        back = fft.irfft(spec, n=32)
        np.testing.assert_allclose(back.numpy(), x, rtol=1e-4, atol=1e-5)

    def test_fft2_and_shift(self):
        x = RNG.randn(8, 8).astype(np.float32)
        out = fft.fft2(paddle.to_tensor(x))
        np.testing.assert_allclose(out.numpy(), np.fft.fft2(x), rtol=1e-3,
                                   atol=1e-4)
        sh = fft.fftshift(paddle.to_tensor(x))
        np.testing.assert_allclose(sh.numpy(), np.fft.fftshift(x))

    def test_fftfreq(self):
        np.testing.assert_allclose(fft.fftfreq(8, 0.5).numpy(),
                                   np.fft.fftfreq(8, 0.5))

    def test_fft_grad_flows(self):
        x = paddle.to_tensor(RNG.randn(16).astype(np.float32))
        x.stop_gradient = False
        spec = fft.rfft(x)
        # |X|^2 energy; real-valued loss of a complex intermediate
        energy = (spec * spec.conj()).real().sum()
        energy.backward()
        assert x.grad is not None
        assert np.isfinite(x.grad.numpy()).all()


class TestSignal:
    def test_stft_shape_and_content(self):
        n_fft, hop = 64, 16
        x = np.sin(2 * np.pi * 8 * np.arange(256) / 64).astype(np.float32)
        spec = signal.stft(paddle.to_tensor(x), n_fft=n_fft,
                           hop_length=hop, center=False)
        n_frames = 1 + (256 - n_fft) // hop
        assert list(spec.shape) == [n_fft // 2 + 1, n_frames]
        mag = np.abs(spec.numpy())
        # the sine at bin 8 dominates every frame
        assert (mag.argmax(axis=0) == 8).all()

    def test_stft_istft_roundtrip(self):
        x = RNG.randn(400).astype(np.float32)
        w = np.hanning(100).astype(np.float32)
        spec = signal.stft(paddle.to_tensor(x), n_fft=100, hop_length=25,
                           window=paddle.to_tensor(w), center=True)
        back = signal.istft(spec, n_fft=100, hop_length=25,
                            window=paddle.to_tensor(w), center=True,
                            length=400)
        np.testing.assert_allclose(back.numpy(), x, rtol=1e-3, atol=1e-3)

    def test_frame_overlap_add_inverse(self):
        x = RNG.randn(128).astype(np.float32)
        frames = signal.frame(paddle.to_tensor(x), 32, 32)  # no overlap
        assert frames.shape == [4, 32]
        back = signal.overlap_add(frames, 32)
        np.testing.assert_allclose(back.numpy(), x, rtol=1e-6)
