"""bench.py stale re-emit provenance: a multi-round photocopy chain
(BENCH_r05 was round 4's number re-emitted) must be visible from the
artifact alone via ``stale_generations`` + ``stale_since``."""
from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402


def _write_good(path, **extra):
    rec = {"metric": "llama_decoder_train_tokens_per_sec_per_chip",
           "value": 12345.6, "unit": "tokens/s",
           "measured_at": "2026-08-01T00:00:00Z", "backend": "tpu"}
    rec.update(extra)
    with open(path, "w") as f:
        json.dump(rec, f)
    return rec


class TestStaleChain:
    def test_generations_accumulate_across_reemits(self, tmp_path,
                                                   monkeypatch, capsys):
        last = tmp_path / "BENCH_LAST_GOOD.json"
        _write_good(str(last))
        monkeypatch.setattr(bench, "LAST_GOOD", str(last))

        rc = bench._emit_stale("tunnel wedged (test)")
        assert rc == 0
        out1 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out1["stale"] is True
        assert out1["stale_generations"] == 1
        assert out1["stale_since"] == "2026-08-01T00:00:00Z"
        assert out1["value"] == 12345.6

        # the chain survives a process restart: the incremented counter
        # was persisted back into LAST_GOOD
        rc = bench._emit_stale("still wedged (test)")
        assert rc == 0
        out2 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out2["stale_generations"] == 2
        assert out2["stale_since"] == "2026-08-01T00:00:00Z"
        assert out2["stale_reason"] == "still wedged (test)"
        persisted = json.loads(last.read_text())
        assert persisted["stale_generations"] == 2

    def test_fresh_record_has_no_stale_markers(self, tmp_path,
                                               monkeypatch, capsys):
        """A record that was never re-emitted carries none of the
        photocopy keys — their PRESENCE is the staleness signal."""
        last = tmp_path / "BENCH_LAST_GOOD.json"
        rec = _write_good(str(last))
        assert "stale" not in rec and "stale_generations" not in rec

    def test_no_last_good_is_a_hard_failure(self, tmp_path, monkeypatch):
        monkeypatch.setattr(bench, "LAST_GOOD",
                            str(tmp_path / "missing.json"))
        assert bench._emit_stale("nothing persisted (test)") == 3
