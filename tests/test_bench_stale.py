"""bench.py stale re-emit provenance: a multi-round photocopy chain
(BENCH_r05 was round 4's number re-emitted) must be visible from the
artifact alone via ``stale_generations`` + ``stale_since``."""
from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402


def _write_good(path, **extra):
    rec = {"metric": "llama_decoder_train_tokens_per_sec_per_chip",
           "value": 12345.6, "unit": "tokens/s",
           "measured_at": "2026-08-01T00:00:00Z", "backend": "tpu"}
    rec.update(extra)
    with open(path, "w") as f:
        json.dump(rec, f)
    return rec


class TestStaleChain:
    def test_generations_accumulate_across_reemits(self, tmp_path,
                                                   monkeypatch, capsys):
        last = tmp_path / "BENCH_LAST_GOOD.json"
        _write_good(str(last))
        monkeypatch.setattr(bench, "LAST_GOOD", str(last))

        rc = bench._emit_stale("tunnel wedged (test)")
        assert rc == 0
        out1 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out1["stale"] is True
        assert out1["stale_generations"] == 1
        assert out1["stale_since"] == "2026-08-01T00:00:00Z"
        assert out1["value"] == 12345.6

        # the chain survives a process restart: the incremented counter
        # was persisted back into LAST_GOOD
        rc = bench._emit_stale("still wedged (test)")
        assert rc == 0
        out2 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out2["stale_generations"] == 2
        assert out2["stale_since"] == "2026-08-01T00:00:00Z"
        assert out2["stale_reason"] == "still wedged (test)"
        persisted = json.loads(last.read_text())
        assert persisted["stale_generations"] == 2

    def test_fresh_record_has_no_stale_markers(self, tmp_path,
                                               monkeypatch, capsys):
        """A record that was never re-emitted carries none of the
        photocopy keys — their PRESENCE is the staleness signal."""
        last = tmp_path / "BENCH_LAST_GOOD.json"
        rec = _write_good(str(last))
        assert "stale" not in rec and "stale_generations" not in rec

    def test_no_last_good_is_a_hard_failure(self, tmp_path, monkeypatch):
        monkeypatch.setattr(bench, "LAST_GOOD",
                            str(tmp_path / "missing.json"))
        assert bench._emit_stale("nothing persisted (test)") == 3


# ---------------------------------------------------------------------------
# tools/perf_report.py --baseline must not diff against a photocopy
# ---------------------------------------------------------------------------

def _perf_report():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "perf_report_under_test",
        os.path.join(REPO, "tools", "perf_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestPerfReportStaleBaseline:
    """`perf_report.py --baseline` consumes the same BENCH_*.json
    artifacts bench.py stamps — a stale re-emit (BENCH_r04/r05 are
    photocopies of the 2026-07-31 probe) must be refused with its
    provenance named, never diffed as if it were a live number."""

    PAYLOAD = {"smoke": {"mfu": 0.4, "hbm_peak_bytes": 123},
               "jobs": {}}

    def _diff(self, baseline_path):
        import io

        out = io.StringIO()
        _perf_report().diff_baseline(self.PAYLOAD, str(baseline_path),
                                     out)
        return out.getvalue()

    def test_stale_markers_refuse_the_diff(self, tmp_path):
        p = tmp_path / "BENCH_stale.json"
        _write_good(str(p), mfu=0.39, stale=True,
                    stale_reason="tunnel wedged (test)",
                    stale_since="2026-07-31T01:04:37Z",
                    stale_generations=2)
        text = self._diff(p)
        assert "STALE re-emit" in text and "refusing to diff" in text
        assert "2026-07-31T01:04:37Z" in text
        assert "stale_generations   2" in text
        # no numeric comparison against the photocopy
        assert "->" not in text

    def test_driver_wrapper_parsed_record_detected(self, tmp_path):
        """BENCH_r*.json wraps the record under "parsed" (next to the
        raw child tail) — the stale markers must be found there too,
        the exact BENCH_r04/r05 shape."""
        p = tmp_path / "BENCH_r99.json"
        with open(p, "w") as f:
            json.dump({"n": 99, "rc": 0, "parsed": {
                "metric": "m", "value": 1.0, "mfu": 0.39,
                "measured_at": "2026-07-31T01:04:37Z",
                "stale": True, "stale_reason": "probe failed"}}, f)
        text = self._diff(p)
        assert "STALE re-emit" in text

    def test_fresh_baseline_still_diffs(self, tmp_path):
        p = tmp_path / "BENCH_fresh.json"
        _write_good(str(p), mfu=0.38)
        text = self._diff(p)
        assert "STALE" not in text
        assert "mfu" in text and "->" in text
