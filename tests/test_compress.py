"""Parity suite for quantized gradient communication
(paddle_tpu.distributed.compress).

Pins, per ISSUE acceptance:
- flag OFF: compiled-step HLO free of quantized-sync artifacts and
  byte-stable, eager wire frames byte-identical to the legacy format;
- flag ON: int8 path within tolerance (4-proc dp=2 x sharding=2 run in
  tests/compress_worker.py, >=3x comm-byte reduction via the
  comm_bytes registry / flight-recorder payload sizes);
- error-feedback residual pins the compiled loss trajectory to fp32
  over 50 steps;
- bucketing pins "number of reductions issued" via the flight recorder.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core import flags as fl
from paddle_tpu.distributed import compress
from paddle_tpu.kernels import quant

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "compress_worker.py")


@pytest.fixture
def qsync_flag():
    """Flag hygiene: every test leaves the global flag off."""
    yield
    fl.set_flags({"FLAGS_quantized_grad_sync": False,
                  "FLAGS_quantized_grad_sync_stochastic": False,
                  "FLAGS_grad_sync_bucket_mb": 4.0})


class TestQuantPrimitives:
    def test_roundtrip_within_half_ulp_per_block(self):
        rng = np.random.RandomState(0)
        # wide dynamic range across blocks — what block scaling is FOR
        x = (rng.randn(8, 1024) * np.exp(rng.randn(8, 1))) \
            .astype(np.float32)
        q, s = quant.quantize_int8_block(jnp.asarray(x), 256)
        xr = np.asarray(quant.dequantize_int8_block(q, s, block=256))
        blocks = x.reshape(8, 4, 256)
        half_ulp = np.abs(blocks).max(axis=-1, keepdims=True) / 127 * .5
        err = np.abs((x - xr).reshape(8, 4, 256))
        assert (err <= half_ulp + 1e-7).all()

    def test_zero_blocks_exact(self):
        x = jnp.zeros((2, 512), jnp.float32)
        q, s = quant.quantize_int8_block(x)
        assert np.asarray(
            quant.dequantize_int8_block(q, s)).sum() == 0.0

    def test_stochastic_rounding_unbiased(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(1, 256).astype(np.float32))
        key = jax.random.PRNGKey(0)
        acc = np.zeros((1, 256), np.float64)
        n = 300
        for i in range(n):
            q, s = quant.quantize_int8_block(
                x, 256, stochastic=True, key=jax.random.fold_in(key, i))
            acc += np.asarray(quant.dequantize_int8_block(q, s))
        ulp = float(np.abs(np.asarray(x)).max()) / 127
        bias = np.abs(acc / n - np.asarray(x)).max()
        # the mean of n dithered roundings concentrates ~ulp/sqrt(n)
        assert bias < 0.25 * ulp, (bias, ulp)

    def test_nonfinite_blocks_propagate_nan_not_silent_zero(self):
        """Overflow detectability (review-found): an inf gradient used
        to smear finite garbage, and a NaN gradient silently became 0 —
        masking AMP overflow detection. Non-finite blocks now carry
        scale NaN and dequantize to NaN on every rank."""
        for poison in (np.inf, np.nan):
            flat = np.ones(512, np.float32)
            flat[5] = poison
            q, s = compress.quantize_np(flat, 256)
            assert np.isnan(s[0])  # poisoned block flagged via scale
            assert np.isfinite(s[1])  # healthy block untouched
            deq = compress.dequantize_np(q, s, 256)
            assert np.isnan(deq[:256]).all()
            np.testing.assert_allclose(deq[256:], 1.0)
        # wire round trip keeps the poison visible
        bad = np.ones(2048, np.float32)
        bad[0] = np.inf
        out, _ = compress.wire_decode(
            compress.wire_encode(bad, compressed=True))
        assert np.isnan(out[:256]).all() and np.isfinite(out[256:]).all()
        # and the traced twin agrees
        xb = jnp.asarray(np.where(np.isfinite(bad[:512]), 1.0,
                                  np.nan)).reshape(2, 256)
        qj, sj = quant.quantize_int8_block(xb, 256)
        assert np.isnan(np.asarray(sj)[0, 0])
        assert np.isnan(np.asarray(
            quant.dequantize_int8_block(qj, sj))[0]).all()

    def test_np_twins_match_traced(self):
        rng = np.random.RandomState(2)
        flat = rng.randn(5000).astype(np.float32)
        qn, sn = compress.quantize_np(flat, 256)
        pad = np.pad(flat, (0, 5120 - 5000)).reshape(20, 256)
        qj, sj = quant.quantize_int8_block(jnp.asarray(pad), 256)
        np.testing.assert_array_equal(
            qn, np.asarray(qj).reshape(-1)[:5000])
        np.testing.assert_allclose(sn, np.asarray(sj).reshape(-1))
        np.testing.assert_allclose(
            compress.dequantize_np(qn, sn, 256),
            np.asarray(quant.dequantize_int8_block(qj, sj))
            .reshape(-1)[:5000])


class TestWireFormat:
    def test_uncompressed_frame_byte_identical_to_legacy(self):
        """Flag-off wire pin: the frame layout predates compression and
        every byte must stay put (mixed-version worlds decode it)."""
        import struct

        rng = np.random.RandomState(3)
        for arr in (rng.randn(8, 3).astype(np.float32),
                    rng.randint(0, 9, (4,)).astype(np.int64)):
            head = json.dumps({"d": arr.dtype.name,
                               "s": list(arr.shape)}).encode()
            legacy = struct.pack(">I", len(head)) + head + arr.tobytes()
            assert compress.wire_encode(arr) == legacy

    def test_flag_off_never_compresses(self, qsync_flag):
        big = np.random.RandomState(0).randn(4096).astype(np.float32)
        assert not compress.should_compress(big)
        fl.set_flags({"FLAGS_quantized_grad_sync": True})
        assert compress.should_compress(big)
        # ints and small payloads stay exact even with the flag on
        assert not compress.should_compress(
            np.arange(4096, dtype=np.int32))
        assert not compress.should_compress(
            np.zeros(512, np.float32))

    def test_compressed_frame_ratio_and_roundtrip(self):
        rng = np.random.RandomState(4)
        arr = (rng.randn(256, 64) * np.exp(rng.randn(256, 1))) \
            .astype(np.float32)
        plain = compress.wire_encode(arr)
        packed = compress.wire_encode(arr, compressed=True)
        assert len(plain) >= 3 * len(packed)
        assert compress.wire_is_compressed(packed)
        assert not compress.wire_is_compressed(plain)
        out, meta = compress.wire_decode(packed)
        assert out.shape == arr.shape and out.dtype == arr.dtype
        scale = np.abs(arr).max()
        assert np.abs(out - arr).max() <= scale / 127 + 1e-6

    def test_bf16_roundtrip(self):
        import ml_dtypes

        arr = np.random.RandomState(5).randn(64, 32) \
            .astype(ml_dtypes.bfloat16)
        out, _ = compress.wire_decode(
            compress.wire_encode(arr, compressed=True))
        assert out.dtype == arr.dtype
        assert np.abs(out.astype(np.float32)
                      - arr.astype(np.float32)).max() < 0.1


class TestBucketPlan:
    def test_threshold_coalescing(self):
        items = [("a", 30), ("b", 30), ("c", 30), ("d", 100), ("e", 10)]
        assert compress.plan_buckets(items, 64) == \
            [["a", "b"], ["c"], ["d"], ["e"]]

    def test_oversized_item_gets_own_bucket(self):
        items = [("big", 1000), ("s1", 5), ("s2", 5)]
        assert compress.plan_buckets(items, 64) == \
            [["big"], ["s1", "s2"]]

    def test_analytic_ring_bytes_ratio(self):
        fp = compress.ring_allreduce_bytes(1 << 20, 4, False)
        q8 = compress.ring_allreduce_bytes(1 << 20, 4, True)
        assert fp >= 3 * q8


needs8 = pytest.mark.skipif(jax.device_count() < 8,
                            reason="needs 8 virtual devices")


def _build_step(seed=7, lr=1e-2, zero_stage=0):
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.parallel.engine import CompiledTrainStep

    paddle.seed(seed)
    m = nn.Sequential(nn.Linear(16, 64), nn.Tanh(), nn.Linear(64, 8))
    o = paddle.optimizer.AdamW(learning_rate=lr,
                               parameters=m.parameters())
    return m, CompiledTrainStep(
        m, lambda out, y: F.cross_entropy(out, y), o,
        zero_stage=zero_stage)


def _batch(n=16):
    rng = np.random.RandomState(0)
    return (paddle.to_tensor(rng.rand(n, 16).astype(np.float32)),
            paddle.to_tensor(rng.randint(0, 8, n)))


@needs8
class TestCompiledQuantizedSync:
    @pytest.fixture(autouse=True)
    def mesh(self, qsync_flag):
        from paddle_tpu.distributed import mesh as pmesh

        pmesh.build_hybrid_mesh(dp=4, sharding=2)
        yield
        pmesh.set_mesh(None)

    def test_flag_off_hlo_has_no_quant_artifacts_and_is_stable(self):
        """The off-path pin: no all-to-all, no int8 payloads, and the
        HLO is build-to-build deterministic — the quantized machinery
        leaves zero residue when disabled."""
        x, y = _batch()
        _, s1 = _build_step()
        hlo1 = s1.lowered_hlo(x, y)
        assert "all-to-all" not in hlo1
        assert " s8[" not in hlo1
        _, s2 = _build_step()
        assert s2.lowered_hlo(x, y) == hlo1

    def test_flag_on_hlo_reduces_in_int8(self):
        fl.set_flags({"FLAGS_quantized_grad_sync": True})
        x, y = _batch()
        _, step = _build_step()
        hlo = step.lowered_hlo(x, y)
        assert "all-to-all" in hlo
        assert " s8[" in hlo
        assert step._qsync is not None
        axes, nranks, buckets = step._qsync
        assert nranks == 8 and set(axes) == {"dp", "sharding"}

    def test_error_feedback_pins_loss_trajectory_50_steps(self):
        x, y = _batch()
        _, ref = _build_step()
        ref_losses = [float(ref(x, y)) for _ in range(50)]
        fl.set_flags({"FLAGS_quantized_grad_sync": True})
        _, qs = _build_step()
        q_losses = [float(qs(x, y)) for _ in range(50)]
        np.testing.assert_allclose(q_losses, ref_losses, rtol=2e-2)
        # and it actually trained (not pinned by standing still)
        assert q_losses[-1] < 0.5 * q_losses[0]

    def test_bucketing_pins_reduction_count(self):
        # tiny threshold -> one bucket per param; big -> one bucket.
        # HLO all-to-all count is the compiled-path witness (the eager
        # witness — flight-recorder all_reduce count — is pinned by the
        # 4-proc worker)
        x, y = _batch()
        fl.set_flags({"FLAGS_quantized_grad_sync": True,
                      "FLAGS_grad_sync_bucket_mb": 1e-6})
        _, fine = _build_step()
        assert np.isfinite(float(fine(x, y)))  # triggers the build
        assert len(fine._qsync[2]) == 4  # W1, b1, W2, b2
        fl.set_flags({"FLAGS_grad_sync_bucket_mb": 4.0})
        _, fused = _build_step()
        assert float(fused(x, y)) > 0
        assert len(fused._qsync[2]) == 1

    def test_run_steps_quantized(self):
        fl.set_flags({"FLAGS_quantized_grad_sync": True})
        _, step = _build_step()
        rng = np.random.RandomState(1)
        xs = rng.rand(4, 16, 16).astype(np.float32)
        ys = rng.randint(0, 8, (4, 16))
        l1 = float(step.run_steps(paddle.to_tensor(xs),
                                  paddle.to_tensor(ys)))
        l2 = float(step.run_steps(paddle.to_tensor(xs),
                                  paddle.to_tensor(ys)))
        assert np.isfinite(l1) and np.isfinite(l2) and l2 < l1

    def test_sum_reduction_loss_declared_matches_exact(self):
        """Review-found: the quantized path combines PER-RANK losses,
        so a sum-reduction loss must be declared via loss_reduction
        ('mean' assumed otherwise) — psum replaces pmean and gradients
        keep their magnitude."""
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        from paddle_tpu.parallel.engine import CompiledTrainStep

        def build(reduction_arg):
            paddle.seed(9)
            m = nn.Sequential(nn.Linear(16, 32), nn.Tanh(),
                              nn.Linear(32, 4))
            o = paddle.optimizer.SGD(learning_rate=1e-3,
                                     parameters=m.parameters())
            loss = lambda out, y: F.cross_entropy(out, y,
                                                  reduction="sum")
            return CompiledTrainStep(m, loss, o,
                                     loss_reduction=reduction_arg)

        x, y = _batch()
        ref = build("sum")          # flag off: exact path
        ref_losses = [float(ref(x, y)) for _ in range(10)]
        fl.set_flags({"FLAGS_quantized_grad_sync": True})
        qs = build("sum")
        q_losses = [float(qs(x, y)) for _ in range(10)]
        np.testing.assert_allclose(q_losses, ref_losses, rtol=2e-2)

    def test_stochastic_rounding_path(self):
        fl.set_flags({"FLAGS_quantized_grad_sync": True,
                      "FLAGS_quantized_grad_sync_stochastic": True})
        x, y = _batch()
        _, step = _build_step()
        l0 = float(step(x, y))
        for _ in range(5):
            l1 = float(step(x, y))
        assert np.isfinite(l1) and l1 < l0

    def test_zero2_quantized_matches_stage0(self):
        x, y = _batch()
        fl.set_flags({"FLAGS_quantized_grad_sync": True})
        _, s0 = _build_step(zero_stage=0)
        _, s2 = _build_step(zero_stage=2)
        l0 = [float(s0(x, y)) for _ in range(5)]
        l2 = [float(s2(x, y)) for _ in range(5)]
        np.testing.assert_allclose(l2, l0, rtol=1e-2)

    def test_unsupported_mesh_falls_back_with_warning(self):
        from paddle_tpu.distributed import mesh as pmesh

        pmesh.build_hybrid_mesh(dp=4, mp=2)
        fl.set_flags({"FLAGS_quantized_grad_sync": True})
        x, y = _batch()
        _, step = _build_step()
        with pytest.warns(UserWarning, match="unsupported"):
            hlo = step.lowered_hlo(x, y)
        assert "all-to-all" not in hlo
        assert step._qsync is None

    def test_comm_bytes_gauges_published(self):
        from paddle_tpu import monitor

        fl.set_flags({"FLAGS_quantized_grad_sync": True})
        x, y = _batch()
        _, step = _build_step()
        float(step(x, y))
        metrics = monitor.snapshot()["metrics"]
        series = {tuple(sorted(s["labels"].items())): s["value"]
                  for s in metrics["grad_sync_bytes_per_step"]["series"]}
        fp = series[(("compressed", "false"),)]
        q8 = series[(("compressed", "true"),)]
        assert fp >= 3 * q8 > 0
        assert metrics["grad_sync_buckets"]["series"][0]["value"] == 1


class TestHybridOptimizerRoute:
    def test_flag_routes_dp_grad_sync_through_compressed_path(
            self, qsync_flag, monkeypatch):
        """The fused_allreduce_gradients analog must take the bucketed
        EF sync when the flag is on (review-found: a bare compressed
        all_reduce would drop sub-ulp grad mass with no residual)."""
        import paddle_tpu.distributed.compress as compress_mod
        from paddle_tpu import nn, optimizer
        from paddle_tpu.parallel.hybrid_optimizer import (
            HybridParallelOptimizer,
        )

        class FakePg:
            world_size = 2

        class FakeGroup:
            nranks = 2
            pg = FakePg()

        class FakeHcg:
            def get_data_parallel_group(self):
                return FakeGroup()

        calls = []
        monkeypatch.setattr(
            compress_mod, "sync_gradients_compressed",
            lambda params, group, residuals=None, **kw:
            calls.append((len(list(params)), residuals)))
        fl.set_flags({"FLAGS_quantized_grad_sync": True})
        lin = nn.Linear(2, 2)
        opt = HybridParallelOptimizer(
            optimizer.SGD(learning_rate=0.1,
                          parameters=lin.parameters()),
            hcg=FakeHcg(), strategy=None)
        lin(paddle.to_tensor(np.ones((1, 2), np.float32))) \
            .sum().backward()
        opt.step()
        opt.step()
        assert len(calls) == 2
        # residuals dict persists across steps (error feedback state)
        assert calls[0][1] is calls[1][1] is not None


class TestProbeRetry:
    """bench.py pre-flight: one transient probe failure must retry
    (with backoff) instead of re-emitting a stale photocopy."""

    def _bench(self):
        sys.path.insert(0, REPO)
        import bench

        return bench

    def test_retry_succeeds_after_transient_failure(self, monkeypatch):
        bench = self._bench()
        calls = []

        def fake_run(mode, timeout):
            calls.append(mode)
            if len(calls) == 1:
                return 1, ""  # transient wedge
            return 0, "PROBE_OK tpu\n"

        slept = []
        monkeypatch.setattr(bench, "_run_child", fake_run)
        monkeypatch.setattr(bench.time, "sleep",
                            lambda s: slept.append(s))
        assert bench._preflight_probe() == "tpu"
        assert calls == ["probe", "probe"]
        assert slept == [bench.PROBE_RETRY_BACKOFF_S]

    def test_two_failures_give_up(self, monkeypatch):
        bench = self._bench()
        monkeypatch.setattr(bench, "_run_child",
                            lambda mode, t: (None, ""))
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        assert bench._preflight_probe() is None

    def test_first_try_success_skips_backoff(self, monkeypatch):
        bench = self._bench()
        monkeypatch.setattr(bench, "_run_child",
                            lambda mode, t: (0, "PROBE_OK cpu\n"))
        monkeypatch.setattr(
            bench.time, "sleep",
            lambda s: (_ for _ in ()).throw(AssertionError("slept")))
        assert bench._preflight_probe() == "cpu"


class TestCompressed4Proc:
    """The acceptance run: 4 processes, dp=2 x sharding=2, int8 within
    tolerance of fp32 and >=3x fewer gradient comm bytes."""

    @pytest.fixture(scope="class")
    def cluster(self):
        sys.path.insert(0, os.path.join(REPO, "tests"))
        from dist_utils import free_ports

        port = free_ports(1)
        procs = []
        for rank in range(4):
            env = dict(os.environ)
            env.update({
                "PYTHONPATH": REPO + os.pathsep
                + env.get("PYTHONPATH", ""),
                "JAX_PLATFORMS": "cpu",
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": "4",
                "PADDLE_MASTER": "127.0.0.1:%d" % port,
            })
            env.pop("PALLAS_AXON_POOL_IPS", None)
            procs.append(subprocess.Popen(
                [sys.executable, WORKER], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True))
        results = {}
        for rank, p in enumerate(procs):
            try:
                out, err = p.communicate(timeout=300)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
            assert p.returncode == 0, (
                "rank %d rc=%d\nstdout:\n%s\nstderr:\n%s"
                % (rank, p.returncode, out[-2000:], err[-3000:]))
            line = [l for l in out.splitlines()
                    if l.startswith("COMPRESS_RESULT ")][0]
            results[rank] = json.loads(line[len("COMPRESS_RESULT "):])
        return results

    def test_int8_losses_within_tolerance(self, cluster):
        for rank, rec in cluster.items():
            fp = np.asarray(rec["fp32_losses"])
            q8 = np.asarray(rec["q8_losses"])
            np.testing.assert_allclose(q8, fp, rtol=5e-2,
                                       err_msg="rank %d" % rank)
            assert q8[-1] < q8[0], "rank %d did not train" % rank

    def test_all_ranks_identical_global_loss(self, cluster):
        base = cluster[0]["q8_losses"]
        for rank, rec in cluster.items():
            np.testing.assert_allclose(rec["q8_losses"], base,
                                       rtol=1e-9)

    def test_comm_bytes_at_least_3x_smaller(self, cluster):
        for rank, rec in cluster.items():
            fp_bytes = rec["fp32_bytes"]["false"]
            q8_bytes = rec["q8_bytes"]["true"]
            assert q8_bytes > 0, rank
            assert fp_bytes >= 3 * q8_bytes, (
                "rank %d: fp32 sync moved %d B but int8 moved %d B "
                "(< 3x reduction)" % (rank, fp_bytes, q8_bytes))

    def test_bucketing_pins_reductions_via_flight_recorder(self, cluster):
        for rank, rec in cluster.items():
            # 4 params -> 4 fp32 all_reduces; 2 buckets -> 2 compressed
            assert rec["fp32_allreduces_per_sync"] == 4, rank
            assert rec["q8_allreduces_per_sync"] == 2, rank
            assert rec["q8_wire_bytes_recorded"], rank

    def test_zero2_subgroup_training_within_tolerance(self, cluster):
        for rank, rec in cluster.items():
            fp = np.asarray(rec["zero2_fp32_losses"])
            q8 = np.asarray(rec["zero2_q8_losses"])
            assert np.isfinite(q8).all()
            np.testing.assert_allclose(q8, fp, rtol=5e-2,
                                       err_msg="rank %d" % rank)

    def test_max_reduction_stays_exact_under_flag(self, cluster):
        for rank, rec in cluster.items():
            assert rec.get("max_exact"), (
                "rank %d: op=max was lossy under the flag" % rank)

    def test_object_collectives_unaffected(self, cluster):
        for rank, rec in cluster.items():
            assert rec.get("object_collectives_ok"), rank

    def test_mismatch_validation_names_rank(self, cluster):
        for rank, rec in cluster.items():
            msg = rec["mismatch_error"]
            assert msg is not None, (
                "rank %d: strict all_gather let a shape mismatch "
                "through" % rank)
            assert "rank 1" in msg and "(3, 2)" in msg, msg
