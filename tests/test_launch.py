"""Launch / elastic / rpc / spawn tests.

Parity model: reference TestDistBase forks real localhost worker processes
(test_dist_base.py:1190); launch tests check env wiring; elastic tests mock
the registry (test_fleet_elastic_manager.py). Subprocess workers here are
tiny scripts that never import jax, so they start fast and never touch the
TPU tunnel.
"""
import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(cmd, **kw):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # force CPU: spawned workers must never dial the TPU tunnel (a wedged
    # tunnel turned these tests flaky)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=120, **kw)


class TestLaunchCLI:
    def test_single_node_two_procs(self, tmp_path):
        script = tmp_path / "worker.py"
        script.write_text(
            "import json, os, sys\n"
            "out = {k: os.environ.get(k) for k in\n"
            "       ('PADDLE_TRAINER_ID', 'PADDLE_TRAINERS_NUM',\n"
            "        'PADDLE_TRAINER_ENDPOINTS', 'PADDLE_JOB_ID')}\n"
            "open(sys.argv[1] + '/rank%s.json'\n"
            "     % os.environ['PADDLE_TRAINER_ID'], 'w').write(\n"
            "    json.dumps(out))\n")
        r = _run([sys.executable, "-m", "paddle_tpu.distributed.launch",
                  "--nproc_per_node", "2", "--log_dir",
                  str(tmp_path / "log"), "--job_id", "jtest",
                  str(script), str(tmp_path)])
        assert r.returncode == 0, r.stderr
        for rank in (0, 1):
            data = json.loads((tmp_path / ("rank%d.json" % rank)).read_text())
            assert data["PADDLE_TRAINER_ID"] == str(rank)
            assert data["PADDLE_TRAINERS_NUM"] == "2"
            assert len(data["PADDLE_TRAINER_ENDPOINTS"].split(",")) == 2
            assert data["PADDLE_JOB_ID"] == "jtest"
        # per-rank logs exist (reference workerlog.N naming)
        assert (tmp_path / "log" / "workerlog.0").exists()
        assert (tmp_path / "log" / "workerlog.1").exists()

    def test_failure_propagates(self, tmp_path):
        script = tmp_path / "bad.py"
        script.write_text("import sys; sys.exit(3)\n")
        r = _run([sys.executable, "-m", "paddle_tpu.distributed.launch",
                  "--nproc_per_node", "2", "--log_dir",
                  str(tmp_path / "log"), str(script)])
        assert r.returncode == 3

    def test_multi_node_rendezvous(self, tmp_path):
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        script = tmp_path / "worker.py"
        script.write_text(
            "import json, os, sys\n"
            "open(sys.argv[1] + '/rank%s.json'\n"
            "     % os.environ['PADDLE_TRAINER_ID'], 'w').write(json.dumps(\n"
            "    {k: os.environ.get(k) for k in\n"
            "     ('PADDLE_TRAINER_ID', 'PADDLE_NODE_RANK',\n"
            "      'PADDLE_TRAINERS_NUM', 'PADDLE_MASTER')}))\n")
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        launchers = [subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nnodes", "2", "--node_rank", str(n),
             "--master", "127.0.0.1:%d" % port,
             "--log_dir", str(tmp_path / ("log%d" % n)),
             "--job_id", "mn", str(script), str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True) for n in range(2)]
        outs = [p.communicate(timeout=120)[0] for p in launchers]
        assert all(p.returncode == 0 for p in launchers), outs
        for rank in (0, 1):
            data = json.loads((tmp_path / ("rank%d.json" % rank)).read_text())
            assert data["PADDLE_TRAINER_ID"] == str(rank)
            assert data["PADDLE_NODE_RANK"] == str(rank)
            assert data["PADDLE_TRAINERS_NUM"] == "2"

    def test_multi_node_requires_master(self, tmp_path):
        from paddle_tpu.distributed.launch import Controller, LaunchConfig

        ctl = Controller(LaunchConfig(nnodes=2, node_rank=0),
                         "nonexistent.py")
        with pytest.raises(ValueError, match="master"):
            ctl.build_pod()

    def test_elastic_restart(self, tmp_path):
        # worker exits 101 once (restart requested), then succeeds
        script = tmp_path / "elastic.py"
        script.write_text(
            "import os, sys\n"
            "if os.environ['PADDLE_RESTART_ROUND'] == '0':\n"
            "    sys.exit(101)\n"
            "sys.exit(0)\n")
        r = _run([sys.executable, "-m", "paddle_tpu.distributed.launch",
                  "--nproc_per_node", "1", "--max_restarts", "1",
                  "--log_dir", str(tmp_path / "log"), str(script)])
        assert r.returncode == 0, r.stderr


class TestElasticManager:
    def test_membership_watch(self):
        from paddle_tpu.distributed.elastic import (
            ElasticManager, ElasticStatus)
        from paddle_tpu.distributed.store import TCPStore

        store = TCPStore(is_master=True)
        try:
            os.environ["PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL"] = "1"
            try:
                # generous margins: heartbeat threads on a loaded CI
                # host can miss tight 0.1s/0.5s windows (observed flake)
                m0 = ElasticManager(store=store, job_id="ej", rank=0, np=2,
                                    heartbeat_interval=0.2, ttl=3.0)
                m1 = ElasticManager(store=store, job_id="ej", rank=1, np=2,
                                    heartbeat_interval=0.2, ttl=3.0)
            finally:
                del os.environ["PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL"]
            m0.register()
            m1.register()
            time.sleep(0.5)
            assert m0.alive_nodes() == [0, 1]
            assert m0.watch() == ElasticStatus.HOLD
            # node 1 dies -> heartbeat goes stale -> RESTART (ftl=1)
            m1.exit()
            deadline = time.time() + 10.0
            while time.time() < deadline and m0.alive_nodes() != [0]:
                time.sleep(0.2)
            assert m0.alive_nodes() == [0]
            assert m0.watch() == ElasticStatus.RESTART
            m0.exit()
        finally:
            store.close()


class TestSpawn:
    def test_spawn_two_procs(self, tmp_path):
        import paddle_tpu.distributed as dist

        out = str(tmp_path)
        dist.spawn(_spawn_target, args=(out,), nprocs=2)
        ranks = sorted(p.name for p in tmp_path.glob("rank*"))
        assert ranks == ["rank0", "rank1"]


def _spawn_target(out_dir):
    # runs in a spawned child: record the wired rank env
    rank = os.environ["PADDLE_TRAINER_ID"]
    assert os.environ["PADDLE_TRAINERS_NUM"] == "2"
    open(os.path.join(out_dir, "rank%s" % rank), "w").close()


class TestRPC:
    def test_rpc_two_workers(self, tmp_path):
        # pick a free port for the master store
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        script = tmp_path / "rpc_worker.py"
        script.write_text(
            "import os, sys\n"
            "sys.path.insert(0, %r)\n"
            "from paddle_tpu.distributed import rpc\n"
            "rank = int(sys.argv[1])\n"
            "rpc.init_rpc('worker%%d' %% rank, rank=rank, world_size=2,\n"
            "             master_endpoint='127.0.0.1:%d')\n"
            "infos = rpc.get_all_worker_infos()\n"
            "assert [w.name for w in infos] == ['worker0', 'worker1'], infos\n"
            "if rank == 0:\n"
            "    out = rpc.rpc_sync('worker1', pow, args=(2, 10))\n"
            "    assert out == 1024, out\n"
            "    fut = rpc.rpc_async('worker1', divmod, args=(7, 3))\n"
            "    assert fut.result(timeout=30) == (2, 1)\n"
            "rpc.shutdown()\n" % (REPO, port))
        env = {**os.environ,
               "PYTHONPATH": REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""),
               # force CPU: workers must never dial the TPU tunnel (a
               # wedged tunnel turned this test flaky)
               "JAX_PLATFORMS": "cpu"}
        env.pop("PALLAS_AXON_POOL_IPS", None)
        procs = [subprocess.Popen(
            [sys.executable, str(script), str(r)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
            for r in range(2)]
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=120)
            outs.append(out)
        assert all(p.returncode == 0 for p in procs), outs

    def test_rpc_errors_propagate(self):
        from paddle_tpu.distributed import rpc

        rpc.init_rpc("solo", rank=0, world_size=1,
                     master_endpoint="127.0.0.1:0")
        try:
            assert rpc.rpc_sync("solo", len, args=([1, 2, 3],)) == 3
            info = rpc.get_worker_info()
            assert info.name == "solo" and info.rank == 0
            with pytest.raises(TypeError):
                rpc.rpc_sync("solo", len, args=(1,))
        finally:
            rpc.shutdown()
