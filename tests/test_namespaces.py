"""Top-level namespace parity: tensor/version/sysconfig/reader/dataset/
cost_model/onnx (reference python/paddle/ top-level modules)."""
import itertools
import os

import numpy as np
import pytest

import paddle_tpu as paddle


class TestTensorNamespace:
    def test_module_groups(self):
        assert paddle.tensor.creation.to_tensor is paddle.to_tensor
        assert paddle.tensor.math.matmul is paddle.matmul
        assert paddle.tensor.manipulation.concat is paddle.concat
        assert paddle.tensor.linalg.einsum is paddle.linalg.einsum
        assert hasattr(paddle.tensor.logic, "equal")
        assert paddle.tensor.search is paddle.tensor.manipulation


class TestVersionSysconfig:
    def test_version(self, capsys):
        assert paddle.version.full_version == paddle.__version__
        paddle.version.show()
        assert "full_version" in capsys.readouterr().out
        assert paddle.version.tpu() and not paddle.version.cuda()

    def test_sysconfig_paths(self):
        inc = paddle.sysconfig.get_include()
        assert os.path.isdir(inc)
        assert os.path.exists(os.path.join(inc, "pt_capi.h"))
        assert paddle.sysconfig.get_lib().endswith("lib")


class TestReader:
    def _r(self, n=10):
        def reader():
            yield from range(n)

        return reader

    def test_shuffle_preserves_multiset(self):
        out = list(paddle.reader.shuffle(self._r(), 4)())
        assert sorted(out) == list(range(10))

    def test_chain_compose_firstn(self):
        c = paddle.reader.chain(self._r(3), self._r(2))
        assert list(c()) == [0, 1, 2, 0, 1]
        comp = paddle.reader.compose(self._r(3), self._r(3))
        assert list(comp()) == [(0, 0), (1, 1), (2, 2)]
        with pytest.raises(RuntimeError):
            list(paddle.reader.compose(self._r(3), self._r(2))())
        assert list(paddle.reader.firstn(self._r(), 4)()) == [0, 1, 2, 3]

    def test_map_buffered_cache_xmap(self):
        m = paddle.reader.map_readers(lambda a, b: a + b,
                                      self._r(4), self._r(4))
        assert list(m()) == [0, 2, 4, 6]
        assert sorted(paddle.reader.buffered(self._r(5), 2)()) == \
            list(range(5))
        cached = paddle.reader.cache(self._r(3))
        assert list(cached()) == list(cached()) == [0, 1, 2]
        x = paddle.reader.xmap_readers(lambda v: v * 2, self._r(4), 2, 4,
                                       order=True)
        assert list(x()) == [0, 2, 4, 6]


class TestDataset:
    def test_uci_housing_schema(self):
        feats, y = next(paddle.dataset.uci_housing.train()())
        assert feats.shape == (13,) and y.shape == (1,)
        assert len(paddle.dataset.uci_housing.feature_names) == 13
        train_n = sum(1 for _ in paddle.dataset.uci_housing.train()())
        test_n = sum(1 for _ in paddle.dataset.uci_housing.test()())
        assert (train_n, test_n) == (404, 102)


class TestCostModel:
    def test_profile_measure_runs(self):
        from paddle_tpu import static

        static.enable_static()
        try:
            main, startup = static.Program(), static.Program()
            with static.program_guard(main, startup):
                x = static.data("x", [2, 2], "float32")
                static.nn.fc(x, 2)
            cm = paddle.cost_model.CostModel()
            # startup must run with a feed-free program; measure main
            out = cm.profile_measure(startup_program=startup)
            assert "time" in out and out["time"] >= 0
        finally:
            static.disable_static()


class TestOnnx:
    def test_export_saves_stablehlo(self, tmp_path):
        import paddle_tpu.nn as nn

        m = nn.Linear(4, 2)
        prefix = str(tmp_path / "m")
        paddle.onnx.export(
            m, prefix,
            input_spec=[paddle.static.InputSpec([None, 4], "float32")])
        assert os.path.exists(prefix + ".pdmodel")
        loaded = paddle.jit.load(prefix)
        out = loaded(paddle.to_tensor(np.ones((2, 4), np.float32)))
        assert out.shape == [2, 2]

    def test_explicit_onnx_suffix_raises(self, tmp_path):
        import paddle_tpu.nn as nn

        with pytest.raises(RuntimeError, match="StableHLO"):
            paddle.onnx.export(nn.Linear(2, 2),
                               str(tmp_path / "m.onnx"))


class TestReaderEdgeCases:
    def test_compose_allows_none_samples(self):
        def r_vals():
            yield from [1, 2]

        def r_opt():
            yield from [None, 3]

        out = list(paddle.reader.compose(r_vals, r_opt)())
        assert out == [(1, None), (2, 3)]

    def test_buffered_abandoned_consumer_releases_thread(self):
        import threading

        before = threading.active_count()

        def big():
            yield from range(10000)

        for _ in range(5):
            list(paddle.reader.firstn(
                paddle.reader.buffered(big, 2), 1)())
        import time

        time.sleep(0.3)  # fill threads observe stop + exit
        assert threading.active_count() <= before + 1

    def test_xmap_unordered_bounded_window(self):
        out = sorted(paddle.reader.xmap_readers(
            lambda v: v * 2, lambda: iter(range(50)), 2, 4,
            order=False)())
        assert out == [v * 2 for v in range(50)]


class TestTensorAttribute:
    def test_attribute_module(self):
        x = paddle.to_tensor(np.asarray([[1.0, 2.0]], np.float32))
        assert int(paddle.tensor.attribute.rank(x)._value) == 2
        assert paddle.tensor.attribute.shape(x) == [1, 2]
        assert paddle.tensor.attribute.is_floating_point(x)
        assert not paddle.tensor.attribute.is_complex(x)
        c = paddle.to_tensor(np.asarray([1 + 2j], np.complex64))
        np.testing.assert_allclose(
            np.asarray(paddle.tensor.attribute.imag(c)._value), [2.0])


class TestReaderErrorPaths:
    def test_buffered_propagates_reader_exception(self):
        def bad():
            yield 1
            raise ValueError("boom")

        it = paddle.reader.buffered(bad, 2)()
        assert next(it) == 1
        with pytest.raises(ValueError, match="boom"):
            list(it)

    def test_buffered_exhausted_then_abandoned_no_leak(self):
        import threading
        import time

        before = threading.active_count()
        for _ in range(5):
            it = paddle.reader.buffered(lambda: iter(range(4)), 1)()
            next(it)
            it.close()
        time.sleep(0.3)
        assert threading.active_count() <= before + 1

    def test_xmap_ordered_is_lazy(self):
        def infinite():
            i = 0
            while True:
                yield i
                i += 1

        it = paddle.reader.xmap_readers(lambda v: v + 1, infinite, 2, 3,
                                        order=True)()
        assert [next(it) for _ in range(5)] == [1, 2, 3, 4, 5]


class TestFluidLayerEdge:
    def test_cross_entropy_ignore_index_and_1d_label(self):
        from paddle_tpu import fluid

        probs = paddle.to_tensor(
            np.asarray([[0.5, 0.5], [0.25, 0.75]], np.float32))
        label = paddle.to_tensor(np.asarray([[-100], [1]], np.int64))
        ce = np.asarray(fluid.layers.cross_entropy(
            probs, label, ignore_index=-100)._value)
        assert ce[0, 0] == 0.0
        np.testing.assert_allclose(ce[1, 0], -np.log(0.75), rtol=1e-6)
        # 1-D label of length 1 (batch-size-1 inference)
        one = fluid.layers.cross_entropy(
            paddle.to_tensor(np.asarray([[0.2, 0.8]], np.float32)),
            paddle.to_tensor(np.asarray([1], np.int64)))
        np.testing.assert_allclose(np.asarray(one._value)[0],
                                   -np.log(0.8), rtol=1e-6)

    def test_fill_constant_out_raises(self):
        from paddle_tpu import fluid

        with pytest.raises(ValueError, match="in place"):
            fluid.layers.fill_constant([1], "float32", 0.0,
                                       out=paddle.zeros([1]))


class TestDatasetIsolation:
    def test_reader_rows_are_private_copies(self):
        """Regression: the lru-cached array must not leak shared mutable
        rows — in-place consumer mutation cannot corrupt later epochs."""
        feats1, _ = next(paddle.dataset.uci_housing.train()())
        feats1 += 1000.0  # fluid-era scripts mutate rows in place
        feats2, _ = next(paddle.dataset.uci_housing.train()())
        assert feats2[0] < 500.0  # untouched by the first epoch's mutation

    def test_xmap_unordered_yields_as_completed(self):
        import time

        def r():
            yield from [0.2, 0.0]  # first sample is slow

        out = list(paddle.reader.xmap_readers(
            lambda v: (time.sleep(v), v)[1], r, 2, 2, order=False)())
        assert sorted(out) == [0.0, 0.2]
        assert out[0] == 0.0  # fast sample came out first
