"""ERNIE encoder family (BASELINE north star ERNIE-3.0-base): forward
shapes, MLM+SOP pretraining loss drops, mp-parallel compiled step.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import mesh as pmesh
from paddle_tpu.models.ernie import (
    ErnieConfig,
    ErnieForPretraining,
    ErnieForSequenceClassification,
    ErnieModel,
)
from paddle_tpu.parallel.engine import CompiledTrainStep


def _data(cfg, b=4, s=16, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, (b, s)).astype(np.int32)
    tt = rng.randint(0, cfg.type_vocab_size, (b, s)).astype(np.int32)
    return ids, tt, rng


class TestErnie:
    def test_forward_shapes(self):
        paddle.seed(0)
        cfg = ErnieConfig.tiny()
        m = ErnieModel(cfg)
        ids, tt, _ = _data(cfg)
        h, pooled = m(paddle.to_tensor(ids), paddle.to_tensor(tt))
        assert tuple(h.shape) == (4, 16, cfg.hidden_size)
        assert tuple(pooled.shape) == (4, cfg.hidden_size)

    def test_pretraining_loss_drops(self):
        paddle.seed(0)
        cfg = ErnieConfig.tiny()
        m = ErnieForPretraining(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=2e-3,
                                     parameters=m.parameters())
        ids, tt, rng = _data(cfg)
        masked = ids.copy().astype(np.int64)
        masked[:, ::2] = -100  # only odd positions scored
        sop = rng.randint(0, 2, (4,)).astype(np.int64)
        losses = []
        for _ in range(8):
            loss = m(paddle.to_tensor(ids), paddle.to_tensor(tt),
                     paddle.to_tensor(masked), paddle.to_tensor(sop))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] - 0.5, losses

    def test_sequence_classification(self):
        paddle.seed(0)
        cfg = ErnieConfig.tiny()
        m = ErnieForSequenceClassification(cfg, num_classes=3)
        ids, tt, rng = _data(cfg)
        logits = m(paddle.to_tensor(ids), paddle.to_tensor(tt))
        assert tuple(logits.shape) == (4, 3)

    def test_mp_compiled_step(self):
        pmesh.build_hybrid_mesh(dp=2, mp=4)
        paddle.seed(0)
        cfg = ErnieConfig.tiny(use_parallel=True)
        m = ErnieForPretraining(cfg)

        def loss_fn(out, masked):
            mlm, sop = out
            return F.cross_entropy(
                mlm.reshape([-1, cfg.vocab_size]), masked.reshape([-1]),
                ignore_index=-100)

        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        ids, tt, rng = _data(cfg)
        masked = ids.astype(np.int64)

        step = CompiledTrainStep(m, loss_fn, opt)
        loss = step(paddle.to_tensor(ids), paddle.to_tensor(tt),
                    paddle.to_tensor(masked))
        assert np.isfinite(float(loss))
        # mp sharding is real: q_proj weight carries the 'mp' spec
        spec = m.ernie.layers[0].attn.q_proj.weight._sharding_spec
        assert spec is not None and "mp" in str(spec)
