"""ERNIE encoder family (BASELINE north star ERNIE-3.0-base): forward
shapes, MLM+SOP pretraining loss drops, mp-parallel compiled step.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import mesh as pmesh
from paddle_tpu.models.ernie import (
    ErnieConfig,
    ErnieForPretraining,
    ErnieForSequenceClassification,
    ErnieModel,
)
from paddle_tpu.parallel.engine import CompiledTrainStep


def _data(cfg, b=4, s=16, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, (b, s)).astype(np.int32)
    tt = rng.randint(0, cfg.type_vocab_size, (b, s)).astype(np.int32)
    return ids, tt, rng


class TestErnie:
    def test_forward_shapes(self):
        paddle.seed(0)
        cfg = ErnieConfig.tiny()
        m = ErnieModel(cfg)
        ids, tt, _ = _data(cfg)
        h, pooled = m(paddle.to_tensor(ids), paddle.to_tensor(tt))
        assert tuple(h.shape) == (4, 16, cfg.hidden_size)
        assert tuple(pooled.shape) == (4, cfg.hidden_size)

    def test_pretraining_loss_drops(self):
        paddle.seed(0)
        cfg = ErnieConfig.tiny()
        m = ErnieForPretraining(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=2e-3,
                                     parameters=m.parameters())
        ids, tt, rng = _data(cfg)
        masked = ids.copy().astype(np.int64)
        masked[:, ::2] = -100  # only odd positions scored
        sop = rng.randint(0, 2, (4,)).astype(np.int64)
        losses = []
        for _ in range(8):
            loss = m(paddle.to_tensor(ids), paddle.to_tensor(tt),
                     paddle.to_tensor(masked), paddle.to_tensor(sop))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] - 0.5, losses

    def test_sequence_classification(self):
        paddle.seed(0)
        cfg = ErnieConfig.tiny()
        m = ErnieForSequenceClassification(cfg, num_classes=3)
        ids, tt, rng = _data(cfg)
        logits = m(paddle.to_tensor(ids), paddle.to_tensor(tt))
        assert tuple(logits.shape) == (4, 3)

    def test_mp_compiled_step(self):
        pmesh.build_hybrid_mesh(dp=2, mp=4)
        paddle.seed(0)
        cfg = ErnieConfig.tiny(use_parallel=True)
        m = ErnieForPretraining(cfg)

        def loss_fn(out, masked):
            mlm, sop = out
            return F.cross_entropy(
                mlm.reshape([-1, cfg.vocab_size]), masked.reshape([-1]),
                ignore_index=-100)

        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        ids, tt, rng = _data(cfg)
        masked = ids.astype(np.int64)

        step = CompiledTrainStep(m, loss_fn, opt)
        loss = step(paddle.to_tensor(ids), paddle.to_tensor(tt),
                    paddle.to_tensor(masked))
        assert np.isfinite(float(loss))
        # mp sharding is real: q_proj weight carries the 'mp' spec
        spec = m.ernie.layers[0].attn.q_proj.weight._sharding_spec
        assert spec is not None and "mp" in str(spec)


class TestFusedQKV:
    """fuse_qkv (the measured MXU narrow-matmul lever): fused projection
    must match the unfused attention exactly when seeded from the same
    weights."""

    def test_fused_matches_unfused(self):
        import numpy as np

        import paddle_tpu as paddle
        from paddle_tpu.models.ernie import ErnieConfig, ErnieModel

        cfg_u = ErnieConfig.tiny(hidden_dropout_prob=0.0)
        cfg_f = ErnieConfig.tiny(hidden_dropout_prob=0.0, fuse_qkv=True)
        paddle.seed(3)
        m_u = ErnieModel(cfg_u)
        paddle.seed(3)
        m_f = ErnieModel(cfg_f)
        # copy unfused q/k/v into the fused [h, 3h] projection
        import jax.numpy as jnp

        for lu, lf in zip(m_u.layers, m_f.layers):
            au, af = lu.attn, lf.attn
            # fused output reshapes [b,s,3h] -> [b,s,3,heads,hd]:
            # columns [0:h] are q, [h:2h] k, [2h:3h] v — plain concat
            af.qkv_proj.weight._value = jnp.concatenate(
                [au.q_proj.weight._value, au.k_proj.weight._value,
                 au.v_proj.weight._value], axis=1)
            af.qkv_proj.bias._value = jnp.concatenate(
                [au.q_proj.bias._value, au.k_proj.bias._value,
                 au.v_proj.bias._value])
        # remaining params copy BY NAME (the two trees differ in
        # structure; positional zip would misalign after the qkv gap)
        pu_by_name = dict(m_u.named_parameters())
        for nf, pf in m_f.named_parameters():
            if "qkv_proj" in nf:
                continue
            pf._value = pu_by_name[nf]._value
        m_u.eval()
        m_f.eval()
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(
            rng.randint(0, cfg_u.vocab_size, (2, 16)).astype(np.int32))
        seq_u, pooled_u = m_u(ids)
        seq_f, pooled_f = m_f(ids)
        np.testing.assert_allclose(np.asarray(seq_u.numpy()),
                                   np.asarray(seq_f.numpy()),
                                   rtol=1e-4, atol=1e-5)

    def test_fused_trains(self):
        import numpy as np

        import jax

        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F
        from paddle_tpu.distributed import mesh as pmesh
        from paddle_tpu.models.ernie import (
            ErnieConfig,
            ErnieForPretraining,
        )
        from paddle_tpu.parallel.engine import CompiledTrainStep

        pmesh.build_hybrid_mesh(dp=1, devices=jax.devices()[:1])
        cfg = ErnieConfig.tiny(fuse_qkv=True)
        paddle.seed(0)
        m = ErnieForPretraining(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())

        def loss_fn(out, labels):
            mlm, _ = out
            return F.cross_entropy(mlm.reshape([-1, cfg.vocab_size]),
                                   labels.reshape([-1]))

        step = CompiledTrainStep(m, loss_fn, opt)
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(
            rng.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32))
        first = float(step(ids, ids))
        for _ in range(4):
            last = float(step(ids, ids))
        assert np.isfinite(last) and last < first


class TestErnieFusedCE:
    """VERDICT round-5 #2: the ERNIE MLM head routed through the
    streaming fused lm_head+CE kernel under FLAGS_fused_lm_head_ce —
    with the mlm_head BIAS folded exactly (llama's lm_head has none),
    fused vs unfused losses must match."""

    def test_fused_path_engages_and_matches_eager(self):
        """Under a jit trace with the flag on, forward_head_loss takes
        the kernel path (not the silent fallback) and its value matches
        the materialized logits + cross_entropy computation."""
        import jax
        import jax.numpy as jnp

        from paddle_tpu.core import flags as fl
        from paddle_tpu.core.tensor import Tensor

        paddle.seed(4)
        cfg = ErnieConfig.tiny()
        m = ErnieForPretraining(cfg)
        m.eval()
        b, s = 8, 32  # T = 256 tiles DEFAULT_BLOCK_T
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (b, s)).astype(np.int32)
        masked = ids.astype(np.int64).copy()
        masked[:, ::3] = -100

        eager = float(m(paddle.to_tensor(ids),
                        masked_labels=paddle.to_tensor(masked)))

        fl.set_flags({"FLAGS_fused_lm_head_ce": True})
        engaged = []
        try:
            h, _ = m.ernie(paddle.to_tensor(ids))

            def f(hv, lbl):
                out = m.forward_head_loss(Tensor(hv), Tensor(lbl))
                engaged.append(out is not None)
                return out._value
            fused = float(jax.jit(f)(h._value, jnp.asarray(masked)))
        finally:
            fl.set_flags({"FLAGS_fused_lm_head_ce": False})
        assert engaged == [True]
        np.testing.assert_allclose(fused, eager, rtol=1e-5)

    def test_fused_flag_parity_compiled_training(self):
        """Three compiled AdamW steps, flag on vs off — losses must
        match (grads flow through the folded bias row too)."""
        import jax

        from paddle_tpu.core import flags as fl

        cfg = ErnieConfig.tiny()
        rng = np.random.RandomState(1)
        b, s = 8, 32
        ids = rng.randint(0, cfg.vocab_size, (b, s)).astype(np.int32)
        tt = rng.randint(0, cfg.type_vocab_size, (b, s)).astype(np.int32)
        masked = ids.astype(np.int64).copy()
        masked[:, ::2] = -100

        def run(fused):
            fl.set_flags({"FLAGS_fused_lm_head_ce": fused})
            try:
                pmesh.build_hybrid_mesh(dp=1, devices=jax.devices()[:1])
                paddle.seed(6)
                m = ErnieForPretraining(cfg)
                opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                             parameters=m.parameters())
                step = CompiledTrainStep(m, None, opt,
                                         labels_to_model=True)
                return [float(step(paddle.to_tensor(ids),
                                   paddle.to_tensor(tt),
                                   paddle.to_tensor(masked)))
                        for _ in range(3)]
            finally:
                fl.set_flags({"FLAGS_fused_lm_head_ce": False})

        np.testing.assert_allclose(run(True), run(False), rtol=2e-4)
