"""Sharded checkpointing + reshard-on-load + auto-checkpoint epochs
(reference group_sharded.py:179 save, auto_parallel dist_saver +
autoconvert reshard test, fluid auto_checkpoint.py).
"""
import os

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed import mesh as pmesh


class TestShardedSaveLoad:
    def test_roundtrip_preserves_values_and_spec(self, tmp_path):
        pmesh.build_hybrid_mesh(dp=2, mp=4)
        w = paddle.to_tensor(np.arange(32, dtype=np.float32).reshape(4, 8))
        w._sharding_spec = P(None, "mp")
        ckpt.save_state_dict({"w": w}, str(tmp_path / "ck"))
        loaded = ckpt.load_state_dict(str(tmp_path / "ck"))
        np.testing.assert_allclose(np.asarray(loaded["w"]._value),
                                   np.asarray(w._value))
        assert tuple(loaded["w"]._value.sharding.spec) == (None, "mp")

    def test_reshard_on_load_new_spec(self, tmp_path):
        pmesh.build_hybrid_mesh(dp=2, mp=4)
        w = paddle.to_tensor(np.random.RandomState(0).randn(8, 8)
                             .astype(np.float32))
        w._sharding_spec = P(None, "mp")
        ckpt.save_state_dict({"w": w}, str(tmp_path / "ck"))
        loaded = ckpt.load_state_dict(str(tmp_path / "ck"),
                                      shardings={"w": P("dp", None)})
        assert tuple(loaded["w"]._value.sharding.spec)[0] == "dp"
        np.testing.assert_allclose(np.asarray(loaded["w"]._value),
                                   np.asarray(w._value))

    def test_reshard_across_mesh_configs(self, tmp_path):
        # save under dp x mp, load under dp-only: 'mp' axis must drop
        pmesh.build_hybrid_mesh(dp=2, mp=4)
        w = paddle.to_tensor(np.ones((4, 8), np.float32))
        w._sharding_spec = P(None, "mp")
        ckpt.save_state_dict({"w": w}, str(tmp_path / "ck"))
        pmesh.build_hybrid_mesh(dp=8)
        loaded = ckpt.load_state_dict(str(tmp_path / "ck"))
        np.testing.assert_allclose(np.asarray(loaded["w"]._value), 1.0)

    def test_bf16_roundtrip(self, tmp_path):
        pmesh.build_hybrid_mesh(dp=8)
        w = paddle.to_tensor(np.ones((4,), np.float32)).astype("bfloat16")
        ckpt.save_state_dict({"w": w}, str(tmp_path / "ck"))
        loaded = ckpt.load_state_dict(str(tmp_path / "ck"))
        assert "bfloat16" in str(loaded["w"]._value.dtype)


class TestAutoCheckpoint:
    def test_resume_skips_completed_epochs(self, tmp_path):
        pmesh.build_hybrid_mesh(dp=8)
        paddle.seed(0)
        save_dir = str(tmp_path / "acp")

        def make():
            paddle.seed(0)
            m = nn.Linear(3, 3)
            return m

        m1 = make()
        ran = []
        r1 = ckpt.TrainEpochRange(5, "job", save_dir=save_dir, model=m1,
                                  max_keep=2)
        for epoch in r1:
            ran.append(epoch)
            # mutate weights each epoch so restore is observable
            m1.weight.set_value(np.full((3, 3), float(epoch), np.float32))
            if epoch == 2:
                break  # simulated crash after saving epochs 0..1
        assert ran == [0, 1, 2]
        # epoch 2 was NOT saved (break before range saved it)
        m2 = make()
        ran2 = []
        r2 = ckpt.TrainEpochRange(5, "job", save_dir=save_dir, model=m2,
                                  max_keep=2)
        assert r2.restored_epoch == 1
        np.testing.assert_allclose(np.asarray(m2.weight._value), 1.0)
        for epoch in r2:
            ran2.append(epoch)
        assert ran2 == [2, 3, 4]
        # retention: only max_keep newest checkpoints remain
        kept = sorted(d for d in os.listdir(save_dir)
                      if d.startswith("epoch_"))
        assert len(kept) == 2 and kept[-1] == "epoch_4"


class TestOptimizerResume:
    def test_global_step_and_moments_resume(self, tmp_path):
        pmesh.build_hybrid_mesh(dp=8)
        paddle.seed(0)
        m = nn.Linear(4, 2)
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=m.parameters())
        x = paddle.to_tensor(np.random.RandomState(0).randn(3, 4)
                             .astype(np.float32))
        for _ in range(3):
            loss = m(x).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        ckpt.save_model(m, opt, str(tmp_path / "ck"))
        paddle.seed(0)
        m2 = nn.Linear(4, 2)
        opt2 = paddle.optimizer.Adam(learning_rate=0.01,
                                     parameters=m2.parameters())
        ckpt.load_model(m2, opt2, str(tmp_path / "ck"))
        # step counter resumed — Adam bias correction continues, not
        # restarts (the silent-resume-bug regression)
        assert opt2._global_step == opt._global_step == 3
        np.testing.assert_allclose(np.asarray(m2.weight._value),
                                   np.asarray(m.weight._value))


class TestCompiledStepOptimizerCheckpoint:
    """optimizer.state_dict()/set_state_dict round-trips through
    CompiledTrainStep training (review-found gap: the functional slots
    lived only on the step object, so saved state was empty and resumes
    restarted Adam from zero moments)."""

    def test_save_resume_matches_uninterrupted(self):
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        from paddle_tpu.parallel.engine import CompiledTrainStep

        rng = np.random.RandomState(0)
        x = rng.randn(8, 4).astype(np.float32)
        y = rng.randn(8, 2).astype(np.float32)

        def build():
            paddle.seed(11)
            m = nn.Linear(4, 2)
            o = paddle.optimizer.Adam(learning_rate=0.05,
                                      parameters=m.parameters())
            return m, o

        # uninterrupted: 6 compiled steps
        m1, o1 = build()
        step1 = CompiledTrainStep(
            m1, lambda out, lbl: F.mse_loss(out, lbl), o1)
        for _ in range(6):
            loss_a = step1(paddle.to_tensor(x), paddle.to_tensor(y))

        # interrupted at 3: save model+opt state, rebuild, resume 3 more
        m2, o2 = build()
        step2 = CompiledTrainStep(
            m2, lambda out, lbl: F.mse_loss(out, lbl), o2)
        for _ in range(3):
            step2(paddle.to_tensor(x), paddle.to_tensor(y))
        model_sd = m2.state_dict()
        opt_sd = o2.state_dict()
        assert any("/" in k for k in opt_sd), \
            "optimizer state_dict empty after compiled training"
        assert int(opt_sd["global_step"]) == 3

        m3, o3 = build()
        m3.set_state_dict(model_sd)
        o3.set_state_dict(opt_sd)
        step3 = CompiledTrainStep(
            m3, lambda out, lbl: F.mse_loss(out, lbl), o3)
        for _ in range(3):
            loss_b = step3(paddle.to_tensor(x), paddle.to_tensor(y))

        np.testing.assert_allclose(float(loss_b), float(loss_a),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(m3.weight._value),
                                   np.asarray(m1.weight._value),
                                   rtol=1e-5, atol=1e-6)

    def test_set_state_dict_after_compile_takes_effect(self):
        """Restoring optimizer state AFTER CompiledTrainStep construction
        must reach the compiled path (advisor r4: it was silently
        ignored — the functional slots kept their compiled zeros)."""
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        from paddle_tpu.parallel.engine import CompiledTrainStep

        rng = np.random.RandomState(0)
        x = rng.randn(8, 4).astype(np.float32)
        y = rng.randn(8, 2).astype(np.float32)

        def build():
            paddle.seed(11)
            m = nn.Linear(4, 2)
            o = paddle.optimizer.Adam(learning_rate=0.05,
                                      parameters=m.parameters())
            return m, o

        m1, o1 = build()
        step1 = CompiledTrainStep(
            m1, lambda out, lbl: F.mse_loss(out, lbl), o1)
        for _ in range(6):
            loss_a = step1(paddle.to_tensor(x), paddle.to_tensor(y))

        m2, o2 = build()
        step2 = CompiledTrainStep(
            m2, lambda out, lbl: F.mse_loss(out, lbl), o2)
        for _ in range(3):
            step2(paddle.to_tensor(x), paddle.to_tensor(y))
        model_sd = m2.state_dict()
        opt_sd = o2.state_dict()

        # restore order deliberately inverted vs the other test: the
        # compiled step exists BEFORE set_state_dict is called
        m3, o3 = build()
        step3 = CompiledTrainStep(
            m3, lambda out, lbl: F.mse_loss(out, lbl), o3)
        m3.set_state_dict(model_sd)
        o3.set_state_dict(opt_sd)
        for _ in range(3):
            loss_b = step3(paddle.to_tensor(x), paddle.to_tensor(y))

        np.testing.assert_allclose(float(loss_b), float(loss_a),
                                   rtol=1e-5)

    def test_state_dict_snapshot_survives_donation(self):
        """A state_dict taken mid-training must stay readable after the
        next compiled step donates the live optimizer buffers (advisor
        r4: the sync hook mirrored the arrays without copying)."""
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        from paddle_tpu.parallel.engine import CompiledTrainStep

        rng = np.random.RandomState(0)
        x = rng.randn(8, 4).astype(np.float32)
        y = rng.randn(8, 2).astype(np.float32)
        paddle.seed(11)
        m = nn.Linear(4, 2)
        o = paddle.optimizer.Adam(learning_rate=0.05,
                                  parameters=m.parameters())
        step = CompiledTrainStep(
            m, lambda out, lbl: F.mse_loss(out, lbl), o, donate=True)
        for _ in range(2):
            step(paddle.to_tensor(x), paddle.to_tensor(y))
        sd = o.state_dict()
        snap = {k: v for k, v in sd.items() if "/" in k}
        assert snap
        # two more steps donate the buffers the snapshot was taken from
        for _ in range(2):
            step(paddle.to_tensor(x), paddle.to_tensor(y))
        for k, v in snap.items():
            arr = np.asarray(v._value)  # must not be a deleted buffer
            assert np.all(np.isfinite(arr)), k

    def test_pipeline_save_resume_matches_uninterrupted(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.distributed import mesh as pmesh
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.parallel.pipeline_parallel import (
            PipelinedTrainStep,
        )

        cfg = dict(vocab_size=64, hidden_size=16, intermediate_size=32,
                   num_hidden_layers=4, num_attention_heads=2,
                   max_position_embeddings=32, use_parallel=False)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 64, (8, 16)).astype(np.int32)
        labels = rng.randint(0, 64, (8, 16)).astype(np.int32)

        def loss_fn(logits, lbl):
            return F.cross_entropy(logits.reshape([-1, 64]),
                                   lbl.reshape([-1]))

        def build():
            pmesh.build_hybrid_mesh(dp=2, mp=1, pp=4)
            paddle.seed(21)
            m = LlamaForCausalLM(LlamaConfig(**cfg))
            o = paddle.optimizer.Adam(learning_rate=1e-3,
                                      parameters=m.parameters())
            return m, o

        m1, o1 = build()
        s1 = PipelinedTrainStep(m1, loss_fn, o1, n_micro=2)
        for _ in range(4):
            loss_a = s1(paddle.to_tensor(ids), paddle.to_tensor(labels))

        m2, o2 = build()
        s2 = PipelinedTrainStep(m2, loss_fn, o2, n_micro=2)
        for _ in range(2):
            s2(paddle.to_tensor(ids), paddle.to_tensor(labels))
        s2.sync_to_model()
        model_sd = m2.state_dict()
        opt_sd = o2.state_dict()
        assert any("/" in k for k in opt_sd), "pipeline opt state empty"
        assert int(opt_sd["global_step"]) == 2

        m3, o3 = build()
        m3.set_state_dict(model_sd)
        o3.set_state_dict(opt_sd)
        s3 = PipelinedTrainStep(m3, loss_fn, o3, n_micro=2)
        for _ in range(2):
            loss_b = s3(paddle.to_tensor(ids), paddle.to_tensor(labels))
        np.testing.assert_allclose(float(loss_b), float(loss_a),
                                   rtol=1e-4)


class TestResumeFidelityMidRunSteps:
    """ISSUE-7 satellite: a save_state_dict/load_state_dict round-trip
    taken MID-run_steps (between multi-step windows) resumes
    BIT-IDENTICAL to an uninterrupted run — params, optimizer state,
    step counter, and the RNG key all survive the disk round-trip (the
    model has dropout, so a lost RNG key would show up as diverged
    masks, not just a stale counter)."""

    K = 2           # steps per run_steps window

    def _build(self):
        paddle.seed(33)
        m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Dropout(0.2),
                          nn.Linear(16, 4))
        o = paddle.optimizer.Adam(learning_rate=1e-2,
                                  parameters=m.parameters())
        from paddle_tpu.parallel.engine import CompiledTrainStep

        return m, o, CompiledTrainStep(m, nn.CrossEntropyLoss(), o)

    def _window(self, w):
        rng = np.random.RandomState(900 + w)
        return (rng.randn(self.K, 8, 8).astype(np.float32),
                rng.randint(0, 4, (self.K, 8)).astype(np.int64))

    def test_roundtrip_resumes_bit_identical(self, tmp_path):
        from paddle_tpu.framework import random as prandom

        pmesh.build_hybrid_mesh(dp=8)
        # uninterrupted: 4 windows (8 steps)
        m1, o1, s1 = self._build()
        ref_losses = [float(s1.run_steps(*self._window(w)))
                      for w in range(4)]

        # interrupted after window 2: checkpoint to disk mid-run_steps
        m2, o2, s2 = self._build()
        for w in range(2):
            losses_head = float(s2.run_steps(*self._window(w)))
        ck = str(tmp_path / "mid")
        ckpt.save_model(m2, o2, ck)
        key, counter = prandom.get_rng_state()
        np.save(os.path.join(ck, "rng_key.npy"),
                np.asarray(jax.random.key_data(key)))
        with open(os.path.join(ck, "rng_counter"), "w") as f:
            f.write(str(counter))

        # fresh process-equivalent: new model/opt/step, load, resume
        m3, o3, s3 = self._build()
        ckpt.load_model(m3, o3, ck)
        arr = np.load(os.path.join(ck, "rng_key.npy"))
        with open(os.path.join(ck, "rng_counter")) as f:
            counter3 = int(f.read())
        prandom.set_rng_state(
            (jax.random.wrap_key_data(jax.numpy.asarray(arr)),
             counter3))
        assert s3._step_count == 4          # step counter round-tripped
        got_tail = [float(s3.run_steps(*self._window(w)))
                    for w in range(2, 4)]

        assert got_tail == ref_losses[2:], (got_tail, ref_losses)
        for (n1, t1), (n3, t3) in zip(
                sorted(m1.state_dict().items()),
                sorted(m3.state_dict().items())):
            assert n1 == n3
            np.testing.assert_array_equal(np.asarray(t1._value),
                                          np.asarray(t3._value),
                                          err_msg=n1)
        # optimizer accumulators identical too (Adam moments)
        sd1, sd3 = o1.state_dict(), o3.state_dict()
        assert int(sd3["global_step"]) == int(sd1["global_step"]) == 8
        for k in sd1:
            if hasattr(sd1[k], "_value") or isinstance(sd1[k],
                                                       np.ndarray):
                np.testing.assert_array_equal(
                    np.asarray(sd1[k]._value
                               if hasattr(sd1[k], "_value")
                               else sd1[k]),
                    np.asarray(sd3[k]._value
                               if hasattr(sd3[k], "_value")
                               else sd3[k]), err_msg=k)
