"""Sharded checkpointing + reshard-on-load + auto-checkpoint epochs
(reference group_sharded.py:179 save, auto_parallel dist_saver +
autoconvert reshard test, fluid auto_checkpoint.py).
"""
import os

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed import mesh as pmesh


class TestShardedSaveLoad:
    def test_roundtrip_preserves_values_and_spec(self, tmp_path):
        pmesh.build_hybrid_mesh(dp=2, mp=4)
        w = paddle.to_tensor(np.arange(32, dtype=np.float32).reshape(4, 8))
        w._sharding_spec = P(None, "mp")
        ckpt.save_state_dict({"w": w}, str(tmp_path / "ck"))
        loaded = ckpt.load_state_dict(str(tmp_path / "ck"))
        np.testing.assert_allclose(np.asarray(loaded["w"]._value),
                                   np.asarray(w._value))
        assert tuple(loaded["w"]._value.sharding.spec) == (None, "mp")

    def test_reshard_on_load_new_spec(self, tmp_path):
        pmesh.build_hybrid_mesh(dp=2, mp=4)
        w = paddle.to_tensor(np.random.RandomState(0).randn(8, 8)
                             .astype(np.float32))
        w._sharding_spec = P(None, "mp")
        ckpt.save_state_dict({"w": w}, str(tmp_path / "ck"))
        loaded = ckpt.load_state_dict(str(tmp_path / "ck"),
                                      shardings={"w": P("dp", None)})
        assert tuple(loaded["w"]._value.sharding.spec)[0] == "dp"
        np.testing.assert_allclose(np.asarray(loaded["w"]._value),
                                   np.asarray(w._value))

    def test_reshard_across_mesh_configs(self, tmp_path):
        # save under dp x mp, load under dp-only: 'mp' axis must drop
        pmesh.build_hybrid_mesh(dp=2, mp=4)
        w = paddle.to_tensor(np.ones((4, 8), np.float32))
        w._sharding_spec = P(None, "mp")
        ckpt.save_state_dict({"w": w}, str(tmp_path / "ck"))
        pmesh.build_hybrid_mesh(dp=8)
        loaded = ckpt.load_state_dict(str(tmp_path / "ck"))
        np.testing.assert_allclose(np.asarray(loaded["w"]._value), 1.0)

    def test_bf16_roundtrip(self, tmp_path):
        pmesh.build_hybrid_mesh(dp=8)
        w = paddle.to_tensor(np.ones((4,), np.float32)).astype("bfloat16")
        ckpt.save_state_dict({"w": w}, str(tmp_path / "ck"))
        loaded = ckpt.load_state_dict(str(tmp_path / "ck"))
        assert "bfloat16" in str(loaded["w"]._value.dtype)


class TestAutoCheckpoint:
    def test_resume_skips_completed_epochs(self, tmp_path):
        pmesh.build_hybrid_mesh(dp=8)
        paddle.seed(0)
        save_dir = str(tmp_path / "acp")

        def make():
            paddle.seed(0)
            m = nn.Linear(3, 3)
            return m

        m1 = make()
        ran = []
        r1 = ckpt.TrainEpochRange(5, "job", save_dir=save_dir, model=m1,
                                  max_keep=2)
        for epoch in r1:
            ran.append(epoch)
            # mutate weights each epoch so restore is observable
            m1.weight.set_value(np.full((3, 3), float(epoch), np.float32))
            if epoch == 2:
                break  # simulated crash after saving epochs 0..1
        assert ran == [0, 1, 2]
        # epoch 2 was NOT saved (break before range saved it)
        m2 = make()
        ran2 = []
        r2 = ckpt.TrainEpochRange(5, "job", save_dir=save_dir, model=m2,
                                  max_keep=2)
        assert r2.restored_epoch == 1
        np.testing.assert_allclose(np.asarray(m2.weight._value), 1.0)
        for epoch in r2:
            ran2.append(epoch)
        assert ran2 == [2, 3, 4]
        # retention: only max_keep newest checkpoints remain
        kept = sorted(d for d in os.listdir(save_dir)
                      if d.startswith("epoch_"))
        assert len(kept) == 2 and kept[-1] == "epoch_4"


class TestOptimizerResume:
    def test_global_step_and_moments_resume(self, tmp_path):
        pmesh.build_hybrid_mesh(dp=8)
        paddle.seed(0)
        m = nn.Linear(4, 2)
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=m.parameters())
        x = paddle.to_tensor(np.random.RandomState(0).randn(3, 4)
                             .astype(np.float32))
        for _ in range(3):
            loss = m(x).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        ckpt.save_model(m, opt, str(tmp_path / "ck"))
        paddle.seed(0)
        m2 = nn.Linear(4, 2)
        opt2 = paddle.optimizer.Adam(learning_rate=0.01,
                                     parameters=m2.parameters())
        ckpt.load_model(m2, opt2, str(tmp_path / "ck"))
        # step counter resumed — Adam bias correction continues, not
        # restarts (the silent-resume-bug regression)
        assert opt2._global_step == opt._global_step == 3
        np.testing.assert_allclose(np.asarray(m2.weight._value),
                                   np.asarray(m.weight._value))
