"""Structured error layer (VERDICT r2 #10; reference enforce.h):
negative paths assert error CLASS + structured PAYLOAD, not message
strings."""
from __future__ import annotations

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import enforce as errors


def _t(a):
    return paddle.to_tensor(np.asarray(a))


class TestTaxonomy:
    def test_typed_errors_subclass_builtins(self):
        # the reference's pybind mapping: typed error AND builtin
        assert issubclass(errors.InvalidArgumentError, ValueError)
        assert issubclass(errors.OutOfRangeError, IndexError)
        assert issubclass(errors.NotFoundError, KeyError)
        assert issubclass(errors.UnimplementedError, NotImplementedError)
        assert issubclass(errors.ExecutionTimeoutError, TimeoutError)
        for cls in errors.BUILTIN_TO_TYPED.values():
            assert issubclass(cls, errors.EnforceNotMet)
            assert issubclass(cls, RuntimeError)

    def test_enforce_payload(self):
        with pytest.raises(errors.InvalidArgumentError) as e:
            errors.enforce(False, "bad dim", hint="check shapes",
                           axis=2, rank=1)
        err = e.value
        assert err.code == "INVALID_ARGUMENT"
        assert err.hint == "check shapes"
        assert err.context == {"axis": 2, "rank": 1}
        assert "Error Message Summary" in str(err)

    def test_enforce_eq_and_shape_match(self):
        with pytest.raises(errors.InvalidArgumentError) as e:
            errors.enforce_eq(3, 4, what="degree")
        assert e.value.context["lhs"] == 3 and e.value.context["rhs"] == 4
        with pytest.raises(errors.InvalidArgumentError) as e:
            errors.enforce_shape_match((2, 3), (2, 4), what="weight")
        assert e.value.context["got_shape"] == (2, 3)
        assert e.value.context["expected_shape"] == (2, 4)
        errors.enforce_shape_match((2, 3), (-1, 3))  # wildcard ok


class TestDispatchEnrichment:
    def test_op_error_carries_op_and_shapes(self):
        with pytest.raises(errors.InvalidArgumentError) as e:
            paddle.concat([_t(np.zeros((2, 3), np.float32)),
                           _t(np.zeros((2, 4), np.float32))], axis=0)
        err = e.value
        assert err.op == "concat"
        assert (2, 3) in err.context["input_shapes"]
        assert (2, 4) in err.context["input_shapes"]

    def test_builtin_except_still_catches(self):
        # wrapping must never break `except ValueError` callers
        with pytest.raises(ValueError):
            paddle.concat([_t(np.zeros((2, 3), np.float32)),
                           _t(np.zeros((2, 4), np.float32))], axis=0)

    def test_enforce_not_met_gets_op_attached(self):
        with pytest.raises(errors.InvalidArgumentError) as e:
            paddle.vision.ops.roi_align(
                _t(np.zeros((2, 1, 4, 4), np.float32)),
                _t(np.zeros((2, 4), np.float32)),
                _t(np.array([1, 0], np.int32)), 2)
        assert e.value.op == "roi_align"

    def test_grad_path_enriches_too(self):
        x = _t(np.zeros((2, 3), np.float32))
        x.stop_gradient = False
        y = _t(np.zeros((2, 4), np.float32))
        y.stop_gradient = False
        with pytest.raises(errors.InvalidArgumentError) as e:
            paddle.concat([x, y], axis=0)
        assert e.value.op == "concat"


class TestNativeBoundary:
    def test_native_status_maps_to_typed(self):
        from paddle_tpu.distributed.ps import PsClient, PsServer

        srv = PsServer()
        try:
            with PsClient(port=srv.port) as cli:
                # pull from a table that does not exist: native -1
                with pytest.raises(errors.NotFoundError) as e:
                    cli.pull_sparse(99, [1], dim=4)
                assert e.value.context["status"] == -1
                # dim mismatch: native -4 -> InvalidArgument
                cli.create_sparse_table(0, 4, optimizer="sgd")
                with pytest.raises(errors.InvalidArgumentError) as e:
                    cli.pull_sparse(0, [1], dim=8)
                assert e.value.context["status"] == -4
        finally:
            srv.stop()


class TestVerbosityFlag:
    def test_call_stack_level_gates_context(self):
        err = errors.InvalidArgumentError("boom", op="matmul",
                                          got_shape=(2, 3))
        old = paddle.get_flags("FLAGS_call_stack_level")
        try:
            paddle.set_flags({"FLAGS_call_stack_level": 0})
            assert "got_shape" not in str(err)
            paddle.set_flags({"FLAGS_call_stack_level": 1})
            assert "got_shape" in str(err)
            assert "[Operator: matmul]" in str(err)
        finally:
            paddle.set_flags(old)

    def test_level2_includes_cause(self):
        old = paddle.get_flags("FLAGS_call_stack_level")
        try:
            paddle.set_flags({"FLAGS_call_stack_level": 2})
            try:
                try:
                    raise ValueError("inner boom")
                except ValueError as inner:
                    raise errors.InvalidArgumentError("outer") from inner
            except errors.InvalidArgumentError as err:
                assert "inner boom" in str(err)
        finally:
            paddle.set_flags(old)
