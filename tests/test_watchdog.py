"""paddle_tpu.monitor.watchdog: heartbeats, stall detection, /healthz +
/debugz endpoints, diagnostic bundles, cross-rank postmortems.

Covers the ISSUE-3 acceptance surface:
- disabled watchdog == zero native calls AND zero daemon threads while
  the instrumented hot paths (train step, serving engine, collectives)
  run;
- a forced stall produces a bundle (all-thread stacks, flight ring,
  metric snapshot, heartbeat ages) and /healthz flips ok -> stalled
  (HTTP 503) and back;
- a progressing loop under an enabled watchdog raises zero false
  positives;
- a deadlocked serving-engine thread is named with its stack;
- the multi-process forced stall (one rank sleeps between steps while
  peers wait in a collective): every surviving rank's postmortem names
  the stalled rank, shows the peers' in-flight collective gseq, and
  carries the sleeper's stack;
- tools/debug_bundle.py merges on-disk bundles into the same diagnosis.
"""
from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

import paddle_tpu  # noqa: F401  (forces the cpu test config first)
from paddle_tpu import monitor
from paddle_tpu.monitor import watchdog as wd

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

sys.path.insert(0, os.path.join(REPO, "tests"))
from dist_utils import free_port  # noqa: E402


@pytest.fixture(autouse=True)
def _watchdog_stopped():
    """Every test starts and ends with the watchdog off."""
    monitor.stop_watchdog()
    yield
    monitor.stop_watchdog()


def _wd_threads():
    return [t for t in threading.enumerate()
            if t.name == wd._THREAD_NAME]


class TestDisabledPath:
    def test_zero_daemon_threads_and_noop_beats(self):
        hb = monitor.heartbeat("t_wd_disabled")
        before = hb.beats
        hb.beat()
        with hb.busy("phase") as b:
            assert b is None          # the shared no-op context
        assert hb.beats == before
        assert not _wd_threads()
        assert not monitor.is_watchdog_running()

    def test_zero_native_calls_through_hot_paths(self, monkeypatch):
        """The tier-1 guard: with the watchdog off, the instrumented
        paths (heartbeat beats/brackets + a real collective through
        StoreProcessGroup's span) never touch the native trace lib —
        only the store wire itself (which predates the watchdog)."""
        from paddle_tpu.monitor import registry as mreg

        calls = []
        # arm the one native-touching path the monitor owns
        monkeypatch.setattr(mreg._state, "trace_bridge", True)
        monkeypatch.setattr(
            mreg._state, "_trace_fn",
            lambda name, v: calls.append((name, v)))
        mreg.disable()
        try:
            hb = monitor.heartbeat("t_wd_native")
            hb.beat()
            with hb.busy("phase", seq=1):
                pass
            # a real collective through the watchdog-bracketed span
            import numpy as np

            from paddle_tpu.distributed.process_group import \
                StoreProcessGroup
            from paddle_tpu.distributed.store import TCPStore

            with TCPStore("127.0.0.1", 0, is_master=True) as store:
                pg = StoreProcessGroup(store, 0, 1)
                pg.allreduce(np.ones((2,), np.float32))
            assert calls == []
            assert not _wd_threads()
        finally:
            mreg.enable(trace_bridge=False)

    def test_healthz_reports_disabled(self):
        p = wd.healthz_payload()
        assert p["status"] == "ok"
        assert p["watchdog"] == "disabled"


class TestStallDetection:
    def test_stall_fires_bundle_and_healthz_flips(self, tmp_path,
                                                  monkeypatch):
        monkeypatch.setenv("PT_MONITOR_DUMP_DIR", str(tmp_path))
        monitor.start_watchdog(stall_threshold_s=0.3,
                               poll_interval_s=0.05)
        assert len(_wd_threads()) == 1
        hb = monitor.heartbeat("t_wd_stall")
        with hb.busy("wedged.phase", step=7):
            deadline = time.time() + 5
            while time.time() < deadline and not list(
                    tmp_path.glob("watchdog_bundle_rank*.json")):
                time.sleep(0.05)
            p = wd.healthz_payload()
            assert p["status"] == "stalled"
            assert p["stalls"][0]["heartbeat"] == "t_wd_stall"
            assert p["stalls"][0]["phase"] == "wedged.phase"
            assert p["stalls"][0]["info"] == {"step": 7}
        # phase exited: healthz recovers
        assert wd.healthz_payload()["status"] == "ok"
        bundle_path = tmp_path / "watchdog_bundle_rank0.json"
        assert bundle_path.exists()
        b = json.loads(bundle_path.read_text())
        assert b["kind"] == "watchdog_bundle"
        assert b["verdict"] == "stalled"
        assert b["stalls"][0]["heartbeat"] == "t_wd_stall"
        # the bundle carries all four diagnostic surfaces
        assert any(s["name"] == "MainThread" for s in b["stacks"])
        assert "entries" in b["flight_recorder"]
        assert "watchdog_stalls_total" in b["metrics"]
        assert "t_wd_stall" in b["heartbeats"]

    def test_progressing_loop_no_false_positive(self, tmp_path,
                                                monkeypatch):
        monkeypatch.setenv("PT_MONITOR_DUMP_DIR", str(tmp_path))
        monitor.start_watchdog(stall_threshold_s=0.5,
                               poll_interval_s=0.05)
        hb = monitor.heartbeat("t_wd_progress")
        with hb.busy("long.window"):
            end = time.time() + 1.2       # > 2x the threshold
            while time.time() < end:
                hb.beat()                 # steady progress
                time.sleep(0.05)
        assert not list(tmp_path.glob("watchdog_bundle_rank*.json"))
        assert wd.healthz_payload()["status"] == "ok"

    def test_stall_refires_after_recovery(self, tmp_path, monkeypatch):
        """Episode dedupe must not permanently silence a heartbeat: a
        second distinct stall fires a second bundle."""
        monkeypatch.setenv("PT_MONITOR_DUMP_DIR", str(tmp_path))
        monitor.start_watchdog(stall_threshold_s=0.2,
                               poll_interval_s=0.05)
        hb = monitor.heartbeat("t_wd_refire")
        stalls = monitor.get_registry().get("watchdog_stalls_total")
        v0 = stalls.value
        start = v0
        for _ in range(2):
            with hb.busy("wedge"):
                deadline = time.time() + 5
                while time.time() < deadline \
                        and stalls.value == start:
                    time.sleep(0.05)
            start = stalls.value
        assert stalls.value >= v0 + 2

    def test_train_and_serving_paths_beat_under_watchdog(self):
        """The real instrumented paths progress cleanly (zero false
        positives) and advance their heartbeats."""
        import numpy as np

        import paddle_tpu as paddle
        from paddle_tpu import nn, optimizer
        from paddle_tpu.parallel.engine import CompiledTrainStep

        monitor.start_watchdog(stall_threshold_s=30,
                               poll_interval_s=0.5)
        hb = monitor.heartbeat("train_step")
        before = hb.beats
        net = nn.Sequential(nn.Linear(4, 4))
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=net.parameters())
        step = CompiledTrainStep(net, nn.MSELoss(), opt)
        x = paddle.to_tensor(np.zeros((8, 4), "float32"))
        step(x, x)
        assert hb.beats > before
        assert not hb.snapshot()["active_phases"]
        assert wd.healthz_payload()["status"] == "ok"


class TestHTTPEndpoints:
    def test_debugz_surface(self):
        srv = monitor.MetricsServer(port=0).start()
        try:
            base = "http://127.0.0.1:%d" % srv.port
            h = json.loads(urllib.request.urlopen(
                base + "/healthz").read())
            assert h["status"] == "ok"
            st = json.loads(urllib.request.urlopen(
                base + "/debugz/stacks").read())
            # this very test function is on the main thread's stack
            assert any("test_debugz_surface" in f["func"]
                       for s in st["stacks"] for f in s["frames"])
            fl = json.loads(urllib.request.urlopen(
                base + "/debugz/flight").read())
            assert "entries" in fl
            bu = json.loads(urllib.request.urlopen(
                base + "/debugz/bundle").read())
            assert bu["kind"] == "watchdog_bundle"
            assert bu["reason"] == "debugz"
        finally:
            srv.stop()

    def test_healthz_503_when_stalled(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PT_MONITOR_DUMP_DIR", str(tmp_path))
        monitor.start_watchdog(stall_threshold_s=0.2,
                               poll_interval_s=0.05)
        srv = monitor.MetricsServer(port=0).start()
        hb = monitor.heartbeat("t_wd_http_stall")
        try:
            base = "http://127.0.0.1:%d" % srv.port
            with hb.busy("wedge"):
                time.sleep(0.4)
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(base + "/healthz")
                assert ei.value.code == 503
                body = json.loads(ei.value.read())
                assert body["status"] == "stalled"
            h = json.loads(urllib.request.urlopen(
                base + "/healthz").read())
            assert h["status"] == "ok"
        finally:
            srv.stop()


class TestServingEngineDeadlock:
    def test_deadlocked_engine_thread_named_with_stack(self, tmp_path,
                                                       monkeypatch):
        """ISSUE-3 satellite: a serving engine thread wedged inside
        step() is a detectable stall whose bundle carries the blocked
        thread's stack."""
        import paddle_tpu as paddle
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.serving.engine import Engine

        monkeypatch.setenv("PT_MONITOR_DUMP_DIR", str(tmp_path))
        paddle.seed(0)
        cfg = LlamaConfig(vocab_size=32, hidden_size=16,
                          intermediate_size=32, num_hidden_layers=1,
                          num_attention_heads=2,
                          max_position_embeddings=32,
                          use_parallel=False)
        engine = Engine(LlamaForCausalLM(cfg), max_slots=1,
                        num_blocks=8, block_size=4)
        lock = threading.Lock()
        lock.acquire()

        def deadlocked_admit():
            with lock:                    # blocks until the test releases
                return None

        engine.scheduler.admit_next = deadlocked_admit
        monitor.start_watchdog(stall_threshold_s=0.3,
                               poll_interval_s=0.05)
        t = threading.Thread(target=engine.run, name="serving-loop")
        t.start()
        try:
            deadline = time.time() + 8
            bundle = None
            while time.time() < deadline and bundle is None:
                files = list(tmp_path.glob("watchdog_bundle_rank*.json"))
                if files:
                    bundle = json.loads(files[0].read_text())
                time.sleep(0.05)
            assert bundle is not None, "watchdog never fired"
            assert any(s["heartbeat"] == "serving_engine"
                       and s["phase"] == "serving.step"
                       for s in bundle["stalls"])
            # the deadlocked thread's stack is in the bundle, wedged in
            # the admit path
            loop_stacks = [s for s in bundle["stacks"]
                           if s["name"] == "serving-loop"]
            assert loop_stacks, bundle["stacks"]
            assert any("deadlocked_admit" in f["func"]
                       for f in loop_stacks[0]["frames"])
        finally:
            lock.release()
            t.join(timeout=30)
        assert not t.is_alive()


class TestDiagnoseBundles:
    def _bundle(self, rank, world=4, coll=None, stalls=(),
                hb_ages=None):
        hbs = {}
        if coll is not None:
            op, gseq, age = coll
            hbs["collectives"] = {
                "beats": 3, "last_beat": 0, "last_beat_age_s": age,
                "active_phases": [{
                    "phase": "collective.%s" % op,
                    "info": {"op": op, "gseq": gseq,
                             "group": "pg/default", "rank": rank,
                             "world_size": world},
                    "since": 100.0, "age_s": age}],
            }
        for name, age in (hb_ages or {}).items():
            hbs[name] = {"beats": 1, "last_beat": 0,
                         "last_beat_age_s": age, "active_phases": []}
        return {"kind": "watchdog_bundle", "rank": rank,
                "world_size": world, "verdict":
                "stalled" if stalls else "ok",
                "stalls": list(stalls), "heartbeats": hbs,
                "stacks": [], "flight_recorder": {}, "metrics": {}}

    def test_rank_between_steps_named(self):
        bundles = {r: self._bundle(r, coll=("all_reduce", 2, 10.0))
                   for r in (0, 1, 3)}
        bundles[2] = self._bundle(2, hb_ages={"collectives": 11.0})
        rep = monitor.diagnose_bundles(
            bundles, world_size=4,
            liveness={r: 0.1 for r in range(4)}, lease_s=5)
        assert rep["status"] == "stalled"
        assert rep["stalled_ranks"] == [2]
        assert rep["per_rank"][2]["state"] == "between-steps"
        assert rep["collective"]["gseq"] == 2
        assert rep["collective"]["op"] == "all_reduce"
        assert "rank 2" in rep["summary"]

    def test_rank_behind_in_collective_named(self):
        bundles = {r: self._bundle(r, coll=("all_reduce", 5, 8.0))
                   for r in range(3)}
        bundles[1] = self._bundle(1, coll=("all_reduce", 3, 8.0))
        rep = monitor.diagnose_bundles(
            bundles, world_size=3,
            liveness={r: 0.1 for r in range(3)}, lease_s=5)
        assert rep["status"] == "stalled"
        assert rep["stalled_ranks"] == [1]
        assert rep["per_rank"][1]["state"] == "in-collective"

    def test_dead_rank_by_lease_expiry(self):
        bundles = {r: self._bundle(r, world=3,
                                   coll=("all_reduce", 1, 9.0))
                   for r in (0, 1)}
        rep = monitor.diagnose_bundles(
            bundles, world_size=3,
            liveness={0: 0.2, 1: 0.3, 2: 60.0}, lease_s=5)
        assert rep["status"] == "stalled"
        assert rep["stalled_ranks"] == [2]
        assert rep["dead_ranks"] == [2]
        assert rep["per_rank"][2]["state"] == "dead"
        assert "DEAD" in rep["summary"]

    def test_all_waiting_same_seq_is_external(self):
        bundles = {r: self._bundle(r, world=2,
                                   coll=("all_gather", 4, 12.0))
                   for r in range(2)}
        rep = monitor.diagnose_bundles(
            bundles, world_size=2,
            liveness={0: 0.1, 1: 0.1}, lease_s=5)
        assert rep["status"] == "external-stall"
        assert rep["stalled_ranks"] == []

    def test_single_process_local_stall(self):
        bundles = {0: self._bundle(
            0, world=1,
            stalls=[{"heartbeat": "serving_engine",
                     "phase": "serving.step", "info": {},
                     "age_s": 9.0, "since": 1.0,
                     "threshold_s": 1.0}])}
        rep = monitor.diagnose_bundles(bundles, world_size=1,
                                       liveness={0: 0.1}, lease_s=5)
        assert rep["status"] == "stalled"
        assert rep["stalled_ranks"] == [0]


class TestForcedStallMultiProc:
    """ISSUE-3 acceptance: one rank sleeps between steps while peers
    wait in a collective; the watchdog postmortem names the stalled
    rank, shows the in-flight collective gseq of the waiters, and
    carries the sleeper's stack — and every rank exits 0 afterwards."""

    WORLD = 4
    STALL_RANK = 2

    @pytest.fixture(scope="class")
    def stall_run(self, tmp_path_factory):
        dump_dir = str(tmp_path_factory.mktemp("wd_dumps"))
        port = free_port()
        worker = os.path.join(REPO, "tests", "watchdog_stall_worker.py")
        procs = []
        for rank in range(self.WORLD):
            env = dict(os.environ)
            env.update({
                "PYTHONPATH": REPO + os.pathsep +
                env.get("PYTHONPATH", ""),
                "JAX_PLATFORMS": "cpu",
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(self.WORLD),
                "PADDLE_MASTER": "127.0.0.1:%d" % port,
                "PT_MONITOR_DUMP_DIR": dump_dir,
                "STALL_RANK": str(self.STALL_RANK),
                "STALL_SLEEP_S": "12",
                "WD_STALL_S": "1.5",
                "WD_GRACE_S": "4",
            })
            env.pop("PALLAS_AXON_POOL_IPS", None)
            procs.append(subprocess.Popen(
                [sys.executable, worker], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True))
        outs = []
        for rank, p in enumerate(procs):
            try:
                out, err = p.communicate(timeout=240)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
            outs.append((rank, p.returncode, out, err))
        return dump_dir, outs

    def test_all_ranks_recover_and_exit_clean(self, stall_run):
        _, outs = stall_run
        for rank, rc, out, err in outs:
            assert rc == 0, (
                "rank %d rc=%d\nstdout:\n%s\nstderr:\n%s"
                % (rank, rc, out[-2000:], err[-3000:]))
            assert "STALL_RUN_OK" in out, (rank, out)

    def test_postmortem_names_stalled_rank_with_stack(self, stall_run):
        dump_dir, _ = stall_run
        reports = sorted(glob.glob(os.path.join(
            dump_dir, "watchdog_postmortem_rank*.json")))
        assert reports, "no watchdog postmortem written"
        # a healthy detecting rank's report (rank 0 always is one here)
        path = os.path.join(dump_dir, "watchdog_postmortem_rank0.json")
        with open(path) as f:
            rep = json.load(f)
        assert rep["status"] == "stalled"
        assert rep["stalled_ranks"] == [self.STALL_RANK]
        assert rep["per_rank"][str(self.STALL_RANK)]["state"] \
            == "between-steps"
        # the waiters' in-flight collective: third allreduce = gseq 2
        assert rep["collective"]["op"] == "all_reduce"
        assert rep["collective"]["gseq"] == 2
        assert 0 in rep["collective"]["waiting_ranks"]
        # the sleeper's bundle rode along — with the guilty stack
        sleeper = rep["bundles"][str(self.STALL_RANK)]
        frames = json.dumps(sleeper["stacks"])
        assert "watchdog_stall_worker" in frames
        assert "time.sleep" in frames
        # and the detecting rank's own bundle shows it waiting at gseq 2
        detecting = rep["bundles"]["0"]
        colls = [p for s in detecting["heartbeats"].values()
                 for p in s["active_phases"]
                 if "gseq" in p.get("info", {})]
        assert any(p["info"]["gseq"] == 2 for p in colls)

    def test_debug_bundle_cli_merges_to_same_verdict(self, stall_run,
                                                     tmp_path):
        dump_dir, _ = stall_run
        assert glob.glob(os.path.join(dump_dir,
                                      "watchdog_bundle_rank*.json"))
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import debug_bundle as cli
        finally:
            sys.path.pop(0)
        out = tmp_path / "merged.json"
        rc = cli.main(["merge", "--dir", dump_dir, "--out", str(out),
                       "--world-size", str(self.WORLD)])
        assert rc == 1          # stalled verdict -> nonzero for scripting
        merged = json.loads(out.read_text())
        assert merged["kind"] == "watchdog_bundle_merged"
        assert merged["diagnosis"]["status"] == "stalled"
        assert merged["diagnosis"]["stalled_ranks"] == [self.STALL_RANK]
