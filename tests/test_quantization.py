"""Quantization tests (reference test_quant_aware / ptq unittests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.quantization import (
    PTQ,
    QAT,
    AbsMaxObserver,
    FakeQuanterWithAbsMax,
    MovingAverageAbsMaxObserver,
    QuantConfig,
    QuantedLinear,
    fake_quantize_dequantize,
)

RNG = np.random.RandomState(13)


class TestFakeQuant:
    def test_quantize_dequantize_error_bounded(self):
        x = RNG.randn(64).astype(np.float32)
        scale = float(np.abs(x).max())
        out = fake_quantize_dequantize(paddle.to_tensor(x), scale,
                                       bit_length=8)
        err = np.abs(out.numpy() - x).max()
        assert err <= scale / 127 + 1e-6

    def test_values_are_on_grid(self):
        x = RNG.randn(64).astype(np.float32)
        scale = float(np.abs(x).max())
        out = fake_quantize_dequantize(paddle.to_tensor(x), scale).numpy()
        grid = out / (scale / 127)
        np.testing.assert_allclose(grid, np.round(grid), atol=1e-4)

    def test_straight_through_gradient(self):
        x = paddle.to_tensor(RNG.randn(32).astype(np.float32))
        x.stop_gradient = False
        out = fake_quantize_dequantize(x, 3.0, bit_length=8)
        out.sum().backward()
        # STE: gradient is ~1 everywhere in range
        np.testing.assert_allclose(x.grad.numpy(), np.ones(32), rtol=1e-5)


class TestObservers:
    def test_absmax(self):
        ob = AbsMaxObserver()
        ob.observe(np.array([1.0, -3.0]))
        ob.observe(np.array([2.0]))
        assert ob.scale() == 3.0

    def test_ema(self):
        ob = MovingAverageAbsMaxObserver(moving_rate=0.5)
        ob.observe(np.array([4.0]))
        ob.observe(np.array([2.0]))
        assert ob.scale() == pytest.approx(3.0)


class TestQATPTQ:
    def _net(self):
        paddle.seed(0)
        return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))

    def test_qat_wraps_linears(self):
        net = self._net()
        q = QAT(QuantConfig()).quantize(net)
        wrapped = [m for m in q.sublayers() if isinstance(m, QuantedLinear)]
        assert len(wrapped) == 2
        # original untouched (not inplace)
        assert not any(isinstance(m, QuantedLinear) for m in net.sublayers())

    def test_qat_model_trains(self):
        net = self._net()
        q = QAT().quantize(net, inplace=True)
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=q.parameters())
        x = paddle.to_tensor(RNG.randn(32, 8).astype("float32"))
        y = paddle.to_tensor(RNG.randn(32, 4).astype("float32"))
        losses = []
        for _ in range(15):
            loss = ((q(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_qat_output_close_to_float(self):
        net = self._net()
        x = paddle.to_tensor(RNG.randn(16, 8).astype("float32"))
        ref = net(x).numpy()
        q = QAT().quantize(net)
        q.train()
        out = q(x).numpy()  # first pass observes then quantizes
        out = q(x).numpy()
        # int8 fake quant keeps outputs close
        assert np.abs(out - ref).max() < 0.15 * np.abs(ref).max() + 0.05

    def test_ptq_calibrate(self):
        net = self._net()
        ptq = PTQ()
        q = ptq.quantize(net)
        data = [RNG.randn(8, 8).astype("float32") for _ in range(4)]
        ptq.calibrate(q, data)
        assert not q.training
        x = paddle.to_tensor(RNG.randn(4, 8).astype("float32"))
        out = q(x)
        assert out.shape == [4, 4]


class TestPerChannel:
    """VERDICT r3 #6: per-channel weight scales + histogram observer
    (reference slim imperative qat channel_wise_abs_max default)."""

    def test_channel_observer_scale_shape(self):
        from paddle_tpu.quantization import ChannelWiseAbsMaxObserver

        obs = ChannelWiseAbsMaxObserver(channel_axis=0)
        w = np.stack([np.full((3, 3), 0.1, np.float32),
                      np.full((3, 3), 10.0, np.float32)])
        obs.observe(w)
        s = obs.scale()
        assert s.shape == (2, 1, 1)
        np.testing.assert_allclose(s[:, 0, 0], [0.1, 10.0])

    def test_per_channel_beats_per_tensor_on_skewed_weights(self):
        """A weight matrix whose output channels differ by 100x in
        magnitude: per-tensor quant crushes the quiet channels;
        per-channel keeps them."""
        from paddle_tpu.quantization import fake_quantize_dequantize

        rng = np.random.RandomState(0)
        w = rng.randn(16, 8).astype(np.float32)
        chan_scale = np.logspace(-2, 0, 8).astype(np.float32)
        w = w * chan_scale  # channel magnitudes span 0.01..1.0

        # per-tensor
        pt = np.asarray(fake_quantize_dequantize(
            paddle.to_tensor(w), float(np.abs(w).max())).numpy())
        # per-channel over axis 1
        s = np.abs(w).max(axis=0, keepdims=True)
        pc = np.asarray(fake_quantize_dequantize(
            paddle.to_tensor(w), s).numpy())
        err_pt = np.abs(pt - w).mean()
        err_pc = np.abs(pc - w).mean()
        assert err_pc < err_pt / 2.0, (err_pc, err_pt)

    def test_ptq_per_channel_accuracy_beats_per_tensor(self):
        """End-to-end PTQ on a small conv net with skewed channels:
        per-channel int8 output stays closer to float."""
        import paddle_tpu.nn as nn
        from paddle_tpu.quantization import PTQ, QuantConfig

        rng = np.random.RandomState(1)

        def build():
            paddle.seed(7)
            net = nn.Sequential(
                nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(),
                nn.Conv2D(8, 8, 3, padding=1), nn.ReLU(),
                nn.Flatten(), nn.Linear(8 * 8 * 8, 10))
            # skew conv output channels so per-tensor hurts
            with paddle.no_grad():
                w = np.asarray(net[0].weight.numpy())
                skew = np.logspace(-2, 0, w.shape[0]).astype(np.float32)
                net[0].weight.set_value(
                    w * skew.reshape(-1, 1, 1, 1))
            return net

        x = rng.rand(4, 3, 8, 8).astype(np.float32)
        xt = paddle.to_tensor(x)

        float_net = build()
        ref = np.asarray(float_net(xt).numpy())

        outs = {}
        for kind in ("channel_wise_abs_max", "abs_max"):
            net = build()
            q = PTQ(QuantConfig(weight_quantize_type=kind)).quantize(net)
            PTQ().calibrate(q, [x])
            outs[kind] = np.asarray(q(xt).numpy())
        err_pc = np.abs(outs["channel_wise_abs_max"] - ref).mean()
        err_pt = np.abs(outs["abs_max"] - ref).mean()
        assert err_pc < err_pt, (err_pc, err_pt)

    def test_hist_observer_percentile_cuts_outliers(self):
        from paddle_tpu.quantization import HistObserver

        obs = HistObserver(percentile=0.99)
        data = np.concatenate([np.random.RandomState(0).uniform(
            0, 1.0, 10000).astype(np.float32), [1000.0]])
        obs.observe(data)
        s = obs.scale()
        assert s < 10.0  # abs-max would be 1000
        assert s > 0.5

    def test_hist_observer_range_doubling(self):
        from paddle_tpu.quantization import HistObserver

        obs = HistObserver(percentile=1.0)
        obs.observe(np.array([0.5], np.float32))
        obs.observe(np.array([4.0], np.float32))  # forces rebinning x3
        s = obs.scale()
        assert 3.9 <= s <= 4.1

    def test_qat_trains_with_per_channel(self):
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        from paddle_tpu.quantization import QAT, QuantConfig

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                            nn.Linear(16, 4))
        q = QAT(QuantConfig()).quantize(net, inplace=True)
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=q.parameters())
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.rand(16, 8).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 4, (16,)).astype(np.int64))
        losses = []
        for _ in range(8):
            loss = F.cross_entropy(q(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0], losses

    def test_weight_scale_tracks_decaying_weights(self):
        """QAT weight quanter recomputes the scale from the LIVE weight
        (regression: a lifetime running max froze stale large scales as
        weights decayed)."""
        from paddle_tpu.quantization import FakeQuanterChannelWiseAbsMax

        q = FakeQuanterChannelWiseAbsMax(channel_axis=0)
        q.train()
        big = paddle.to_tensor(np.full((2, 4), 10.0, np.float32))
        small = paddle.to_tensor(np.full((2, 4), 0.1, np.float32))
        q(big)
        np.testing.assert_allclose(q.observer.scale().ravel(),
                                   [10.0, 10.0])
        q(small)
        np.testing.assert_allclose(q.observer.scale().ravel(),
                                   [0.1, 0.1])
        # eval freezes the scale (no re-observation)
        q.eval()
        q(big)
        np.testing.assert_allclose(q.observer.scale().ravel(),
                                   [0.1, 0.1])


class TestInt8Execution:
    """True int8 serving path (reference deploys quantized models via
    int8 kernels — slim save_quantized_model + inference int8; here an
    s8 x s8 -> s32 dot_general on the MXU)."""

    def test_int8_linear_matches_fake_quant(self):
        from paddle_tpu import nn
        from paddle_tpu.quantization import Int8Linear, QuantedLinear

        paddle.seed(0)
        lin = nn.Linear(16, 8)
        q = QuantedLinear(lin)
        q.eval()
        rng = np.random.RandomState(1)
        x = paddle.to_tensor(rng.randn(4, 16).astype(np.float32))
        # calibrate the act observer once
        q.train()
        q(x)
        q.eval()
        ref = q(x).numpy()
        obs = q.act_quanter.observer
        i8 = Int8Linear(lin, act_scale=float(obs.scale()))
        out = i8(x).numpy()
        # identical math: exact int32 accumulation vs fp32 sum of
        # exactly-representable integer products
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_dynamic_scale_close_to_float(self):
        from paddle_tpu import nn
        from paddle_tpu.quantization import Int8Linear

        paddle.seed(1)
        lin = nn.Linear(32, 4)
        rng = np.random.RandomState(2)
        x = paddle.to_tensor(rng.randn(8, 32).astype(np.float32))
        ref = lin(x).numpy()
        out = Int8Linear(lin)(x).numpy()
        err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-8)
        assert err < 0.05, err  # 8-bit relative error envelope

    def test_compiled_module_contains_s8_dot(self):
        import jax
        from paddle_tpu import nn
        from paddle_tpu.quantization import Int8Linear

        paddle.seed(2)
        i8 = Int8Linear(nn.Linear(16, 16))

        def fn(v):
            return i8(v)._value

        x = np.ones((4, 16), np.float32)
        hlo = jax.jit(fn).lower(x).compile().as_text()
        assert "s8" in hlo, "int8 operands absent from compiled module"

    def test_convert_to_int8_model(self):
        from paddle_tpu import nn
        from paddle_tpu.quantization import (
            Int8Linear,
            PTQ,
            convert_to_int8,
        )

        paddle.seed(3)
        model = nn.Sequential(nn.Linear(12, 24), nn.ReLU(),
                              nn.Linear(24, 4))
        rng = np.random.RandomState(3)
        X = rng.randn(64, 12).astype(np.float32)
        ref = model(paddle.to_tensor(X)).numpy()
        ptq = PTQ()
        q = ptq.quantize(model)
        ptq.calibrate(q, [X[i:i + 16] for i in range(0, 64, 16)])
        deploy = convert_to_int8(q)
        kinds = [type(m).__name__ for m in deploy.sublayers()]
        assert kinds.count("Int8Linear") == 2, kinds
        out = deploy(paddle.to_tensor(X)).numpy()
        # the contract: int8 execution reproduces the fake-quant
        # simulation it was converted from
        q.eval()
        sim = q(paddle.to_tensor(X)).numpy()
        rel_sim = np.abs(out - sim).max() / (np.abs(sim).max() + 1e-8)
        assert rel_sim < 0.02, rel_sim
        # and stays in the 8-bit envelope of the float model
        rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-8)
        assert rel < 0.2, rel
        # original model untouched (inplace=False)
        assert any(isinstance(m, nn.Linear)
                   for m in model.sublayers())

    def test_uncalibrated_convert_falls_back_to_dynamic(self):
        # review regression: an unobserved activation observer's 1e-8
        # placeholder must NOT be frozen as a static scale
        from paddle_tpu import nn
        from paddle_tpu.quantization import PTQ, convert_to_int8

        paddle.seed(4)
        model = nn.Sequential(nn.Linear(8, 8))
        x = paddle.to_tensor(
            np.random.RandomState(4).randn(4, 8).astype(np.float32))
        ref = model(x).numpy()
        q = PTQ().quantize(model)  # no calibrate()
        deploy = convert_to_int8(q)
        out = deploy(x).numpy()
        rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-8)
        assert rel < 0.1, rel  # dynamic path, not collapsed to ~0

    def test_quant_bits_flow_through_and_validate(self):
        from paddle_tpu import nn
        from paddle_tpu.quantization import (
            Int8Linear,
            QuantConfig,
            QAT,
            convert_to_int8,
        )

        paddle.seed(5)
        with pytest.raises(ValueError):
            Int8Linear(nn.Linear(4, 4), quant_bits=16)
        cfg = QuantConfig(quant_bits=4)
        q = QAT(cfg).quantize(nn.Sequential(nn.Linear(4, 4)))
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        q.train()
        q(x)
        deploy = convert_to_int8(q)
        i8 = [m for m in deploy.sublayers()
              if isinstance(m, Int8Linear)][0]
        assert i8.quant_bits == 4, i8.quant_bits

    def test_per_tensor_weight_scale_adopted(self):
        # review regression: abs_max (per-tensor) weight observers store
        # state in _state; their calibrated scale must be adopted, not
        # silently replaced with a per-channel recompute
        from paddle_tpu import nn
        from paddle_tpu.quantization import Int8Linear, QuantedLinear, \
            convert_to_int8

        paddle.seed(6)
        lin = nn.Linear(8, 4)
        q = QuantedLinear(lin, weight_quantize_type="abs_max")
        holder = nn.Sequential(q)
        x = paddle.to_tensor(
            np.random.RandomState(6).randn(4, 8).astype(np.float32))
        holder.train()
        holder(x)
        holder.eval()
        sim = holder(x).numpy()
        deploy = convert_to_int8(holder)
        i8 = [m for m in deploy.sublayers()
              if isinstance(m, Int8Linear)][0]
        assert np.ndim(np.asarray(i8._w_scale)) == 0 or \
            np.asarray(i8._w_scale).size == 1  # per-tensor adopted
        out = deploy(x).numpy()
        rel = np.abs(out - sim).max() / (np.abs(sim).max() + 1e-8)
        assert rel < 0.02, rel
        # and the source model was not mutated
        assert any(isinstance(m, QuantedLinear)
                   for m in holder.sublayers())

    def test_one_dim_input_keeps_shape(self):
        from paddle_tpu import nn
        from paddle_tpu.quantization import Int8Linear

        paddle.seed(7)
        lin = nn.Linear(6, 3)
        # per-channel scale in the observers' broadcast shape (1, out)
        w = np.asarray(lin.weight._value)
        ws = np.abs(w).max(axis=0, keepdims=True)  # (1, 3)
        i8 = Int8Linear(lin, w_scale=ws)
        out = i8(paddle.to_tensor(np.ones(6, np.float32)))
        assert out.shape == [3], out.shape

    def test_int8_conv2d_matches_fake_quant(self):
        from paddle_tpu import nn
        from paddle_tpu.quantization import Int8Conv2D, QuantedConv2D

        paddle.seed(8)
        conv = nn.Conv2D(3, 6, 3, padding=1, stride=2)
        q = QuantedConv2D(conv)
        rng = np.random.RandomState(8)
        x = paddle.to_tensor(rng.randn(2, 3, 8, 8).astype(np.float32))
        q.train()
        q(x)
        q.eval()
        ref = q(x).numpy()
        i8 = Int8Conv2D(conv,
                        act_scale=float(q.act_quanter.observer.scale()))
        out = i8(x).numpy()
        assert out.shape == ref.shape
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_convert_full_conv_model(self):
        from paddle_tpu import nn
        from paddle_tpu.quantization import PTQ, convert_to_int8

        paddle.seed(9)
        model = nn.Sequential(
            nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(),
            nn.Conv2D(8, 8, 3, padding=1), nn.ReLU(),
            nn.Flatten(), nn.Linear(8 * 8 * 8, 10))
        rng = np.random.RandomState(9)
        X = rng.rand(4, 3, 8, 8).astype(np.float32)
        ptq = PTQ()
        q = ptq.quantize(model)
        ptq.calibrate(q, [X])
        deploy = convert_to_int8(q)
        kinds = [type(m).__name__ for m in deploy.sublayers()]
        assert kinds.count("Int8Conv2D") == 2, kinds
        assert kinds.count("Int8Linear") == 1, kinds
        q.eval()
        sim = q(paddle.to_tensor(X)).numpy()
        out = deploy(paddle.to_tensor(X)).numpy()
        rel = np.abs(out - sim).max() / (np.abs(sim).max() + 1e-8)
        assert rel < 0.05, rel


class TestConvertAfterGenerate:
    def test_int8_generate_after_float_generate(self):
        """convert_to_int8 on a model that has already generated must not
        reuse the float model's compiled-generate cache: the module tree
        changed (Linear -> Int8Linear), so positional state binding
        against the old name list would mis-bind (review-found: the
        deep-copied cache produced a reshape crash; the cache key now
        carries the functional-state names)."""
        import numpy as np

        import paddle_tpu as paddle
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.quantization import PTQ, convert_to_int8

        cfg = LlamaConfig.tiny(use_parallel=False)
        paddle.seed(0)
        m = LlamaForCausalLM(cfg)
        m.eval()
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(
            rng.randint(0, cfg.vocab_size, (2, 12)).astype(np.int32))
        g_float = m.generate(ids, max_new_tokens=4)
        q = PTQ().quantize(m, inplace=False)
        q(ids)
        m8 = convert_to_int8(q)
        m8.eval()
        g_int8 = m8.generate(ids, max_new_tokens=4)
        assert np.asarray(g_int8.numpy()).shape == (2, 4)
        assert np.asarray(g_float.numpy()).shape == (2, 4)
