"""Quantization tests (reference test_quant_aware / ptq unittests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.quantization import (
    PTQ,
    QAT,
    AbsMaxObserver,
    FakeQuanterWithAbsMax,
    MovingAverageAbsMaxObserver,
    QuantConfig,
    QuantedLinear,
    fake_quantize_dequantize,
)

RNG = np.random.RandomState(13)


class TestFakeQuant:
    def test_quantize_dequantize_error_bounded(self):
        x = RNG.randn(64).astype(np.float32)
        scale = float(np.abs(x).max())
        out = fake_quantize_dequantize(paddle.to_tensor(x), scale,
                                       bit_length=8)
        err = np.abs(out.numpy() - x).max()
        assert err <= scale / 127 + 1e-6

    def test_values_are_on_grid(self):
        x = RNG.randn(64).astype(np.float32)
        scale = float(np.abs(x).max())
        out = fake_quantize_dequantize(paddle.to_tensor(x), scale).numpy()
        grid = out / (scale / 127)
        np.testing.assert_allclose(grid, np.round(grid), atol=1e-4)

    def test_straight_through_gradient(self):
        x = paddle.to_tensor(RNG.randn(32).astype(np.float32))
        x.stop_gradient = False
        out = fake_quantize_dequantize(x, 3.0, bit_length=8)
        out.sum().backward()
        # STE: gradient is ~1 everywhere in range
        np.testing.assert_allclose(x.grad.numpy(), np.ones(32), rtol=1e-5)


class TestObservers:
    def test_absmax(self):
        ob = AbsMaxObserver()
        ob.observe(np.array([1.0, -3.0]))
        ob.observe(np.array([2.0]))
        assert ob.scale() == 3.0

    def test_ema(self):
        ob = MovingAverageAbsMaxObserver(moving_rate=0.5)
        ob.observe(np.array([4.0]))
        ob.observe(np.array([2.0]))
        assert ob.scale() == pytest.approx(3.0)


class TestQATPTQ:
    def _net(self):
        paddle.seed(0)
        return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))

    def test_qat_wraps_linears(self):
        net = self._net()
        q = QAT(QuantConfig()).quantize(net)
        wrapped = [m for m in q.sublayers() if isinstance(m, QuantedLinear)]
        assert len(wrapped) == 2
        # original untouched (not inplace)
        assert not any(isinstance(m, QuantedLinear) for m in net.sublayers())

    def test_qat_model_trains(self):
        net = self._net()
        q = QAT().quantize(net, inplace=True)
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=q.parameters())
        x = paddle.to_tensor(RNG.randn(32, 8).astype("float32"))
        y = paddle.to_tensor(RNG.randn(32, 4).astype("float32"))
        losses = []
        for _ in range(15):
            loss = ((q(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_qat_output_close_to_float(self):
        net = self._net()
        x = paddle.to_tensor(RNG.randn(16, 8).astype("float32"))
        ref = net(x).numpy()
        q = QAT().quantize(net)
        q.train()
        out = q(x).numpy()  # first pass observes then quantizes
        out = q(x).numpy()
        # int8 fake quant keeps outputs close
        assert np.abs(out - ref).max() < 0.15 * np.abs(ref).max() + 0.05

    def test_ptq_calibrate(self):
        net = self._net()
        ptq = PTQ()
        q = ptq.quantize(net)
        data = [RNG.randn(8, 8).astype("float32") for _ in range(4)]
        ptq.calibrate(q, data)
        assert not q.training
        x = paddle.to_tensor(RNG.randn(4, 8).astype("float32"))
        out = q(x)
        assert out.shape == [4, 4]
