"""Quantization tests (reference test_quant_aware / ptq unittests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.quantization import (
    PTQ,
    QAT,
    AbsMaxObserver,
    FakeQuanterWithAbsMax,
    MovingAverageAbsMaxObserver,
    QuantConfig,
    QuantedLinear,
    fake_quantize_dequantize,
)

RNG = np.random.RandomState(13)


class TestFakeQuant:
    def test_quantize_dequantize_error_bounded(self):
        x = RNG.randn(64).astype(np.float32)
        scale = float(np.abs(x).max())
        out = fake_quantize_dequantize(paddle.to_tensor(x), scale,
                                       bit_length=8)
        err = np.abs(out.numpy() - x).max()
        assert err <= scale / 127 + 1e-6

    def test_values_are_on_grid(self):
        x = RNG.randn(64).astype(np.float32)
        scale = float(np.abs(x).max())
        out = fake_quantize_dequantize(paddle.to_tensor(x), scale).numpy()
        grid = out / (scale / 127)
        np.testing.assert_allclose(grid, np.round(grid), atol=1e-4)

    def test_straight_through_gradient(self):
        x = paddle.to_tensor(RNG.randn(32).astype(np.float32))
        x.stop_gradient = False
        out = fake_quantize_dequantize(x, 3.0, bit_length=8)
        out.sum().backward()
        # STE: gradient is ~1 everywhere in range
        np.testing.assert_allclose(x.grad.numpy(), np.ones(32), rtol=1e-5)


class TestObservers:
    def test_absmax(self):
        ob = AbsMaxObserver()
        ob.observe(np.array([1.0, -3.0]))
        ob.observe(np.array([2.0]))
        assert ob.scale() == 3.0

    def test_ema(self):
        ob = MovingAverageAbsMaxObserver(moving_rate=0.5)
        ob.observe(np.array([4.0]))
        ob.observe(np.array([2.0]))
        assert ob.scale() == pytest.approx(3.0)


class TestQATPTQ:
    def _net(self):
        paddle.seed(0)
        return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))

    def test_qat_wraps_linears(self):
        net = self._net()
        q = QAT(QuantConfig()).quantize(net)
        wrapped = [m for m in q.sublayers() if isinstance(m, QuantedLinear)]
        assert len(wrapped) == 2
        # original untouched (not inplace)
        assert not any(isinstance(m, QuantedLinear) for m in net.sublayers())

    def test_qat_model_trains(self):
        net = self._net()
        q = QAT().quantize(net, inplace=True)
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=q.parameters())
        x = paddle.to_tensor(RNG.randn(32, 8).astype("float32"))
        y = paddle.to_tensor(RNG.randn(32, 4).astype("float32"))
        losses = []
        for _ in range(15):
            loss = ((q(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_qat_output_close_to_float(self):
        net = self._net()
        x = paddle.to_tensor(RNG.randn(16, 8).astype("float32"))
        ref = net(x).numpy()
        q = QAT().quantize(net)
        q.train()
        out = q(x).numpy()  # first pass observes then quantizes
        out = q(x).numpy()
        # int8 fake quant keeps outputs close
        assert np.abs(out - ref).max() < 0.15 * np.abs(ref).max() + 0.05

    def test_ptq_calibrate(self):
        net = self._net()
        ptq = PTQ()
        q = ptq.quantize(net)
        data = [RNG.randn(8, 8).astype("float32") for _ in range(4)]
        ptq.calibrate(q, data)
        assert not q.training
        x = paddle.to_tensor(RNG.randn(4, 8).astype("float32"))
        out = q(x)
        assert out.shape == [4, 4]


class TestPerChannel:
    """VERDICT r3 #6: per-channel weight scales + histogram observer
    (reference slim imperative qat channel_wise_abs_max default)."""

    def test_channel_observer_scale_shape(self):
        from paddle_tpu.quantization import ChannelWiseAbsMaxObserver

        obs = ChannelWiseAbsMaxObserver(channel_axis=0)
        w = np.stack([np.full((3, 3), 0.1, np.float32),
                      np.full((3, 3), 10.0, np.float32)])
        obs.observe(w)
        s = obs.scale()
        assert s.shape == (2, 1, 1)
        np.testing.assert_allclose(s[:, 0, 0], [0.1, 10.0])

    def test_per_channel_beats_per_tensor_on_skewed_weights(self):
        """A weight matrix whose output channels differ by 100x in
        magnitude: per-tensor quant crushes the quiet channels;
        per-channel keeps them."""
        from paddle_tpu.quantization import fake_quantize_dequantize

        rng = np.random.RandomState(0)
        w = rng.randn(16, 8).astype(np.float32)
        chan_scale = np.logspace(-2, 0, 8).astype(np.float32)
        w = w * chan_scale  # channel magnitudes span 0.01..1.0

        # per-tensor
        pt = np.asarray(fake_quantize_dequantize(
            paddle.to_tensor(w), float(np.abs(w).max())).numpy())
        # per-channel over axis 1
        s = np.abs(w).max(axis=0, keepdims=True)
        pc = np.asarray(fake_quantize_dequantize(
            paddle.to_tensor(w), s).numpy())
        err_pt = np.abs(pt - w).mean()
        err_pc = np.abs(pc - w).mean()
        assert err_pc < err_pt / 2.0, (err_pc, err_pt)

    def test_ptq_per_channel_accuracy_beats_per_tensor(self):
        """End-to-end PTQ on a small conv net with skewed channels:
        per-channel int8 output stays closer to float."""
        import paddle_tpu.nn as nn
        from paddle_tpu.quantization import PTQ, QuantConfig

        rng = np.random.RandomState(1)

        def build():
            paddle.seed(7)
            net = nn.Sequential(
                nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(),
                nn.Conv2D(8, 8, 3, padding=1), nn.ReLU(),
                nn.Flatten(), nn.Linear(8 * 8 * 8, 10))
            # skew conv output channels so per-tensor hurts
            with paddle.no_grad():
                w = np.asarray(net[0].weight.numpy())
                skew = np.logspace(-2, 0, w.shape[0]).astype(np.float32)
                net[0].weight.set_value(
                    w * skew.reshape(-1, 1, 1, 1))
            return net

        x = rng.rand(4, 3, 8, 8).astype(np.float32)
        xt = paddle.to_tensor(x)

        float_net = build()
        ref = np.asarray(float_net(xt).numpy())

        outs = {}
        for kind in ("channel_wise_abs_max", "abs_max"):
            net = build()
            q = PTQ(QuantConfig(weight_quantize_type=kind)).quantize(net)
            PTQ().calibrate(q, [x])
            outs[kind] = np.asarray(q(xt).numpy())
        err_pc = np.abs(outs["channel_wise_abs_max"] - ref).mean()
        err_pt = np.abs(outs["abs_max"] - ref).mean()
        assert err_pc < err_pt, (err_pc, err_pt)

    def test_hist_observer_percentile_cuts_outliers(self):
        from paddle_tpu.quantization import HistObserver

        obs = HistObserver(percentile=0.99)
        data = np.concatenate([np.random.RandomState(0).uniform(
            0, 1.0, 10000).astype(np.float32), [1000.0]])
        obs.observe(data)
        s = obs.scale()
        assert s < 10.0  # abs-max would be 1000
        assert s > 0.5

    def test_hist_observer_range_doubling(self):
        from paddle_tpu.quantization import HistObserver

        obs = HistObserver(percentile=1.0)
        obs.observe(np.array([0.5], np.float32))
        obs.observe(np.array([4.0], np.float32))  # forces rebinning x3
        s = obs.scale()
        assert 3.9 <= s <= 4.1

    def test_qat_trains_with_per_channel(self):
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        from paddle_tpu.quantization import QAT, QuantConfig

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                            nn.Linear(16, 4))
        q = QAT(QuantConfig()).quantize(net, inplace=True)
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=q.parameters())
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.rand(16, 8).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 4, (16,)).astype(np.int64))
        losses = []
        for _ in range(8):
            loss = F.cross_entropy(q(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0], losses

    def test_weight_scale_tracks_decaying_weights(self):
        """QAT weight quanter recomputes the scale from the LIVE weight
        (regression: a lifetime running max froze stale large scales as
        weights decayed)."""
        from paddle_tpu.quantization import FakeQuanterChannelWiseAbsMax

        q = FakeQuanterChannelWiseAbsMax(channel_axis=0)
        q.train()
        big = paddle.to_tensor(np.full((2, 4), 10.0, np.float32))
        small = paddle.to_tensor(np.full((2, 4), 0.1, np.float32))
        q(big)
        np.testing.assert_allclose(q.observer.scale().ravel(),
                                   [10.0, 10.0])
        q(small)
        np.testing.assert_allclose(q.observer.scale().ravel(),
                                   [0.1, 0.1])
        # eval freezes the scale (no re-observation)
        q.eval()
        q(big)
        np.testing.assert_allclose(q.observer.scale().ravel(),
                                   [0.1, 0.1])
