"""paddle.hub (local hubconf repos) + paddle.batch reader combinator
(reference hapi/hub.py + batch.py and their unittests)."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestHub:
    def _repo(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "dependencies = ['numpy']\n"
            "def tiny_mlp(hidden=4):\n"
            "    '''A tiny MLP entrypoint.'''\n"
            "    import paddle_tpu.nn as nn\n"
            "    return nn.Linear(2, hidden)\n"
            "def _private():\n"
            "    pass\n")
        return str(tmp_path)

    def test_list_help_load(self, tmp_path):
        repo = self._repo(tmp_path)
        assert paddle.hub.list(repo, source="local") == ["tiny_mlp"]
        assert "tiny MLP" in paddle.hub.help(repo, "tiny_mlp",
                                             source="local")
        m = paddle.hub.load(repo, "tiny_mlp", source="local", hidden=6)
        assert m.weight.shape == [2, 6]

    def test_missing_entry_and_remote_source(self, tmp_path):
        repo = self._repo(tmp_path)
        with pytest.raises(RuntimeError, match="no callable"):
            paddle.hub.load(repo, "nope", source="local")
        with pytest.raises(RuntimeError, match="egress"):
            paddle.hub.list("user/repo", source="github")

    def test_missing_dependency(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "dependencies = ['definitely_not_installed_xyz']\n"
            "def f():\n    pass\n")
        with pytest.raises(RuntimeError, match="dependencies"):
            paddle.hub.list(str(tmp_path), source="local")


class TestBatch:
    def test_batching_and_drop_last(self):
        def reader():
            for i in range(7):
                yield i

        got = [b for b in paddle.batch(reader, 3)()]
        assert got == [[0, 1, 2], [3, 4, 5], [6]]
        got = [b for b in paddle.batch(reader, 3, drop_last=True)()]
        assert got == [[0, 1, 2], [3, 4, 5]]
        with pytest.raises(ValueError):
            paddle.batch(reader, 0)
