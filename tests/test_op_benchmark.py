"""Op-benchmark CI gate (reference tools/check_op_benchmark_result.py +
ci_op_benchmark.sh): comparator semantics + a tiny end-to-end run."""
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from op_benchmark import check_result, coverage_report  # noqa: E402


class TestCheckResult:
    def _base(self, **ops):
        return {"platform": "cpu", "ops": ops}

    def test_regression_fails_gate(self):
        ok, lines = check_result(self._base(matmul=1.30),
                                 self._base(matmul=1.00), tolerance=0.15)
        assert not ok
        assert any("REGRESSION" in l for l in lines)

    def test_within_tolerance_passes(self):
        ok, lines = check_result(self._base(matmul=1.10),
                                 self._base(matmul=1.00), tolerance=0.15)
        assert ok and not any("REGRESSION" in l for l in lines)

    def test_improvement_reported_not_failed(self):
        ok, lines = check_result(self._base(matmul=0.50),
                                 self._base(matmul=1.00))
        assert ok
        assert any("improved" in l for l in lines)

    def test_missing_op_fails(self):
        ok, lines = check_result(self._base(), self._base(matmul=1.0))
        assert not ok
        assert any("MISSING" in l for l in lines)

    def test_new_op_reported(self):
        ok, lines = check_result(self._base(gelu=0.1), self._base())
        assert ok
        assert any("new" in l for l in lines)

    def test_platform_mismatch_skips(self):
        cur = {"platform": "tpu", "ops": {"matmul": 9.9}}
        ok, lines = check_result(cur, self._base(matmul=1.0))
        assert ok
        assert any("platform mismatch" in l for l in lines)


class TestCoverageReport:
    """The anti-vacuous-pass satellite: rows with no baseline entry
    pass the regression gate vacuously and must be reported loudly
    (the committed TPU baseline guards 8 of 44 cases)."""

    def _base(self, **ops):
        return {"platform": "tpu", "ops": ops}

    def test_unguarded_rows_listed(self):
        ok, unguarded, lines = coverage_report(
            {"matmul", "gelu", "softmax"}, self._base(matmul=1.0))
        assert ok                      # informational without --strict
        assert unguarded == ["gelu", "softmax"]
        assert any("guards 1 of 3" in l for l in lines)
        assert sum("UNGUARDED" in l for l in lines) == 2
        assert any("vacuously" in l for l in lines)

    def test_strict_fails_on_gaps(self):
        ok, unguarded, lines = coverage_report(
            {"matmul", "gelu"}, self._base(matmul=1.0), strict=True)
        assert not ok
        assert unguarded == ["gelu"]
        assert any("FAILING" in l for l in lines)

    def test_full_coverage_passes_strict(self):
        ok, unguarded, lines = coverage_report(
            {"matmul"}, self._base(matmul=1.0), strict=True)
        assert ok and unguarded == []
        assert any("guards 1 of 1" in l for l in lines)

    def test_coverage_ignores_platform(self):
        """Unlike the timing gate, coverage compares NAMES — a
        platform-mismatched check must still scream about rows nobody
        guards anywhere."""
        base = {"platform": "tpu", "ops": {"matmul": 1.0}}
        ok, unguarded, _ = coverage_report({"matmul", "gelu"}, base,
                                           strict=True)
        assert not ok and unguarded == ["gelu"]

    def test_run_with_crashed_case_exits_nonzero(self, monkeypatch,
                                                 capsys):
        """A crashed case no longer kills the sweep, but `run` must
        stay loud about it (rc 1), not regress to silent success."""
        import op_benchmark as ob

        monkeypatch.setattr(ob, "run_bench", lambda out=None: {
            "platform": "cpu", "ops": {"matmul": 1.0},
            "failed": {"gelu": "RuntimeError('boom')"}})
        assert ob.main(["run"]) == 1
        assert "FAILED" in capsys.readouterr().out
        monkeypatch.setattr(ob, "run_bench", lambda out=None: {
            "platform": "cpu", "ops": {"matmul": 1.0}})
        assert ob.main(["run"]) == 0

    def test_update_strict_refuses_partial_baseline(self, tmp_path,
                                                    monkeypatch,
                                                    capsys):
        """update --strict-coverage must gate BEFORE writing: a
        mid-sweep crash cannot replace the committed baseline with a
        narrowed one."""
        import op_benchmark as ob

        baseline = tmp_path / "base.json"
        baseline.write_text(json.dumps(
            {"platform": "cpu", "ops": {"matmul": 1.0, "gelu": 2.0}}))
        monkeypatch.setattr(ob, "run_bench", lambda out=None: {
            "platform": "cpu", "ops": {"matmul": 1.1},
            "failed": {"gelu": "RuntimeError('boom')"}})
        rc = ob.main(["update", "--baseline", str(baseline),
                      "--strict-coverage"])
        assert rc == 1
        assert "NOT written" in capsys.readouterr().out
        # committed baseline untouched
        assert json.loads(baseline.read_text())["ops"] == {
            "matmul": 1.0, "gelu": 2.0}
        # non-strict update refuses too: pre-resilient-sweep behavior
        # was crash-before-write, and a silently narrowed baseline is
        # the vacuous-pass failure mode this gate exists to close
        rc = ob.main(["update", "--baseline", str(baseline)])
        assert rc == 1
        assert json.loads(baseline.read_text())["ops"] == {
            "matmul": 1.0, "gelu": 2.0}
        # without a crash the refresh goes through
        monkeypatch.setattr(ob, "run_bench", lambda out=None: {
            "platform": "cpu", "ops": {"matmul": 1.1, "gelu": 2.1}})
        rc = ob.main(["update", "--baseline", str(baseline),
                      "--strict-coverage"])
        assert rc == 0
        assert json.loads(baseline.read_text())["ops"] == {
            "matmul": 1.1, "gelu": 2.1}

    def test_committed_baseline_gap_is_visible(self):
        """The motivating case: the committed TPU baseline guards only
        the original 8 rows of the ~44-case sweep."""
        path = os.path.join(os.path.dirname(__file__), "..", "tools",
                            "op_bench_baseline.json")
        with open(path) as f:
            base = json.load(f)
        # stand-in for a full measured run: 44 case names
        measured = set(base["ops"]) | {"case_%d" % i for i in range(36)}
        ok, unguarded, lines = coverage_report(measured, base,
                                               strict=True)
        assert not ok
        assert len(unguarded) == 36
        assert any("guards %d of %d" % (len(base["ops"]), len(measured))
                   in l for l in lines)


class TestModelBenchmarkHarness:
    """tools/model_benchmark.py north-star rows execute end to end
    (reference ci_model_benchmark.sh analog). Fast rows only — the
    resnet/ernie compiles are covered by their own model tests."""

    def test_widedeep_and_allreduce_rows(self):
        import json
        import os
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        # never let the subprocess dial the TPU tunnel (repo convention:
        # tests/test_launch.py, __graft_entry__._cpu_mesh_env)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        if "host_platform_device_count" not in env.get("XLA_FLAGS", ""):
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                                + " --xla_force_host_platform_device_count=8"
                                ).strip()
        out = []
        for sub in ("widedeep", "allreduce"):
            proc = subprocess.run(
                [sys.executable, "tools/model_benchmark.py", sub,
                 "--iters", "2"],
                cwd=repo, env=env, capture_output=True, text=True,
                timeout=300)
            assert proc.returncode == 0, proc.stderr[-2000:]
            recs = [json.loads(l) for l in proc.stdout.splitlines()
                    if l.startswith("{")]
            assert recs and ("value" in recs[0] or "skipped" in recs[0]), \
                proc.stdout
            out += recs
        assert any(r.get("metric") == "widedeep_ps_examples_per_sec"
                   and r["value"] > 0 for r in out)
