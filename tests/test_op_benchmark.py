"""Op-benchmark CI gate (reference tools/check_op_benchmark_result.py +
ci_op_benchmark.sh): comparator semantics + a tiny end-to-end run."""
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from op_benchmark import check_result  # noqa: E402


class TestCheckResult:
    def _base(self, **ops):
        return {"platform": "cpu", "ops": ops}

    def test_regression_fails_gate(self):
        ok, lines = check_result(self._base(matmul=1.30),
                                 self._base(matmul=1.00), tolerance=0.15)
        assert not ok
        assert any("REGRESSION" in l for l in lines)

    def test_within_tolerance_passes(self):
        ok, lines = check_result(self._base(matmul=1.10),
                                 self._base(matmul=1.00), tolerance=0.15)
        assert ok and not any("REGRESSION" in l for l in lines)

    def test_improvement_reported_not_failed(self):
        ok, lines = check_result(self._base(matmul=0.50),
                                 self._base(matmul=1.00))
        assert ok
        assert any("improved" in l for l in lines)

    def test_missing_op_fails(self):
        ok, lines = check_result(self._base(), self._base(matmul=1.0))
        assert not ok
        assert any("MISSING" in l for l in lines)

    def test_new_op_reported(self):
        ok, lines = check_result(self._base(gelu=0.1), self._base())
        assert ok
        assert any("new" in l for l in lines)

    def test_platform_mismatch_skips(self):
        cur = {"platform": "tpu", "ops": {"matmul": 9.9}}
        ok, lines = check_result(cur, self._base(matmul=1.0))
        assert ok
        assert any("platform mismatch" in l for l in lines)


class TestModelBenchmarkHarness:
    """tools/model_benchmark.py north-star rows execute end to end
    (reference ci_model_benchmark.sh analog). Fast rows only — the
    resnet/ernie compiles are covered by their own model tests."""

    def test_widedeep_and_allreduce_rows(self):
        import json
        import os
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        # never let the subprocess dial the TPU tunnel (repo convention:
        # tests/test_launch.py, __graft_entry__._cpu_mesh_env)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        if "host_platform_device_count" not in env.get("XLA_FLAGS", ""):
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                                + " --xla_force_host_platform_device_count=8"
                                ).strip()
        out = []
        for sub in ("widedeep", "allreduce"):
            proc = subprocess.run(
                [sys.executable, "tools/model_benchmark.py", sub,
                 "--iters", "2"],
                cwd=repo, env=env, capture_output=True, text=True,
                timeout=300)
            assert proc.returncode == 0, proc.stderr[-2000:]
            recs = [json.loads(l) for l in proc.stdout.splitlines()
                    if l.startswith("{")]
            assert recs and ("value" in recs[0] or "skipped" in recs[0]), \
                proc.stdout
            out += recs
        assert any(r.get("metric") == "widedeep_ps_examples_per_sec"
                   and r["value"] > 0 for r in out)
