"""Op-benchmark CI gate (reference tools/check_op_benchmark_result.py +
ci_op_benchmark.sh): comparator semantics + a tiny end-to-end run."""
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from op_benchmark import check_result  # noqa: E402


class TestCheckResult:
    def _base(self, **ops):
        return {"platform": "cpu", "ops": ops}

    def test_regression_fails_gate(self):
        ok, lines = check_result(self._base(matmul=1.30),
                                 self._base(matmul=1.00), tolerance=0.15)
        assert not ok
        assert any("REGRESSION" in l for l in lines)

    def test_within_tolerance_passes(self):
        ok, lines = check_result(self._base(matmul=1.10),
                                 self._base(matmul=1.00), tolerance=0.15)
        assert ok and not any("REGRESSION" in l for l in lines)

    def test_improvement_reported_not_failed(self):
        ok, lines = check_result(self._base(matmul=0.50),
                                 self._base(matmul=1.00))
        assert ok
        assert any("improved" in l for l in lines)

    def test_missing_op_fails(self):
        ok, lines = check_result(self._base(), self._base(matmul=1.0))
        assert not ok
        assert any("MISSING" in l for l in lines)

    def test_new_op_reported(self):
        ok, lines = check_result(self._base(gelu=0.1), self._base())
        assert ok
        assert any("new" in l for l in lines)

    def test_platform_mismatch_skips(self):
        cur = {"platform": "tpu", "ops": {"matmul": 9.9}}
        ok, lines = check_result(cur, self._base(matmul=1.0))
        assert ok
        assert any("platform mismatch" in l for l in lines)
