"""Shared plumbing for forked multi-process distributed tests
(the reference TestDistBase harness analog)."""
from __future__ import annotations

import socket


def free_ports(n=1, host="127.0.0.1"):
    """Reserve n CONSECUTIVE free ports and return the first. Needed when
    a service derives sibling ports by offset (init_parallel_env puts the
    JAX coordinator on store-port + 1) — reserving only the base port
    leaves the sibling open to bind collisions."""
    for _ in range(64):
        socks = []
        try:
            s0 = socket.socket()
            s0.bind((host, 0))
            base = s0.getsockname()[1]
            socks.append(s0)
            ok = True
            for i in range(1, n):
                s = socket.socket()
                try:
                    s.bind((host, base + i))
                    socks.append(s)
                except OSError:
                    s.close()
                    ok = False
                    break
            if ok:
                return base
        finally:
            for s in socks:
                s.close()
    raise RuntimeError("could not reserve %d consecutive ports" % n)


def free_port(host="127.0.0.1"):
    return free_ports(1, host)
