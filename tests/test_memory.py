"""paddle_tpu.monitor.memory — the ISSUE-12 memory plane.

Covers the acceptance surface:
- ledger semantics: providers registered at engine construction
  (`FLAGS_monitor_memory` latched, the ptlint hot-path convention)
  report live bytes from array `nbytes`; `sample()` publishes
  `mem_device_bytes{component,job}` and isolates a dying provider to
  its own component;
- reconciliation: on the CPU backend the summed component bytes land
  within the documented tolerance of the `jax.live_arrays()` witness
  DELTA across engine construction;
- static-vs-transient split: `mem_hbm_headroom_bytes` = capacity −
  (static ledger + compiled transient peak), and the transient peak is
  the SAME donation-aware `executable_analysis` number `graph_report()`
  publishes (identity-pinned — no second hand-rolled estimate);
- OOM forensics: a forced `mem.oom` injection during a serving run
  writes `oom_postmortem_rank{r}.json` whose largest component is the
  KV pool, with KV occupancy in the context and the re-raise
  preserved; both train hot paths (`__call__`/`run_steps`) produce the
  same artifact; non-OOM failures write nothing;
- leak sentinel: a synthetic monotone-growth trace fires
  `perf_anomalies_total{kind="mem_leak"}` and flips /healthz degraded;
  a clean warmup and a sawtooth never fire;
- hard disabled-path pinning (PR-2/5/6 style): flags off = tracker
  None, zero native calls, zero new threads, zero `mem_*` registry
  series, `/debugz/memory` reports enabled:false;
- watchdog bundles embed the `mem_*` ring tails;
- tools/mem_snapshot.py: fresh artifact + the bench.py stale re-emit
  discipline.
"""
from __future__ import annotations

import gc
import importlib.util
import json
import os
import subprocess
import sys
import threading
import weakref

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor, serving
from paddle_tpu.monitor import memory as ptmem
from paddle_tpu.monitor import perf
from paddle_tpu.monitor import registry as mreg
from paddle_tpu.monitor import timeseries as ts
from paddle_tpu.resilience import faultinject as fi

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _mem_clean():
    """Every test starts and ends with the memory plane at its default
    (off) and no ledger/sentinel/anomaly state — later suites must see
    a pristine monitor."""
    _reset()
    yield
    _reset()


def _reset():
    fi.disable()
    fi._state.rules = []
    # drop the fault-counter samples this suite's injections created:
    # the resilience suite's disabled-path guard pins the counter
    # sample-free, and counters are process-global
    m = mreg.get_registry().get("faults_injected_total")
    if m is not None:
        for key in list(m._children):
            m.remove(*key)
    paddle.set_flags({"FLAGS_monitor_memory": False,
                      "FLAGS_perf_attribution": False,
                      "FLAGS_perf_sentinels": False,
                      "FLAGS_monitor_timeseries": False})
    ptmem.reset()
    perf.disable_sentinels()
    perf.reset()
    ts.disable()
    ts.clear()
    mreg.enable(trace_bridge=False)


def _tiny_engine(**kw):
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=4,
                      max_position_embeddings=64, use_parallel=False)
    model = LlamaForCausalLM(cfg)
    return serving.Engine(model, **kw)


def _tiny_step():
    import paddle_tpu.nn.functional as F
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.parallel.engine import CompiledTrainStep

    paddle.seed(0)
    cfg = LlamaConfig.tiny(use_parallel=False)
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())

    def loss_fn(logits, labels):
        return F.cross_entropy(
            logits.reshape([-1, cfg.vocab_size]),
            labels.reshape([-1]))

    step = CompiledTrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(
        0, cfg.vocab_size, (8, 16)).astype(np.int32))
    labels = paddle.to_tensor(rng.randint(
        0, cfg.vocab_size, (8, 16)).astype(np.int32))
    return step, ids, labels


# ---------------------------------------------------------------------------
# ledger
# ---------------------------------------------------------------------------

class TestLedger:
    def test_entry_forms_and_gauges(self):
        paddle.set_flags({"FLAGS_monitor_memory": True})
        arr = np.zeros((4, 8), dtype=np.float32)
        tr = ptmem.tracker("t_job", {
            "arrays": lambda: [("a", arr), ("b", 1024)],
            "dicts": lambda: {"entries": [
                {"tag": "c", "bytes": 100, "shape": [10],
                 "dtype": "int8"}], "detail": {"note": 1}},
        })
        assert tr is not None
        out = ptmem.sample()
        comps = out["components"]["t_job"]
        assert comps["arrays"]["bytes"] == arr.nbytes + 1024
        assert comps["dicts"]["bytes"] == 100
        assert comps["dicts"]["detail"] == {"note": 1}
        g = mreg.get_registry().get("mem_device_bytes")
        vals = dict(g.collect())
        assert vals[("arrays", "t_job")] == arr.nbytes + 1024
        assert vals[("dicts", "t_job")] == 100
        # top arrays carry tag/shape/dtype and sort by bytes
        top = out["top_arrays"][0]
        assert top["tag"] == "b" and top["bytes"] == 1024

    def test_provider_error_isolated(self):
        paddle.set_flags({"FLAGS_monitor_memory": True})

        def dying():
            raise ValueError("provider died")

        ptmem.tracker("t_job", {"ok": lambda: [("x", 7)],
                                "bad": dying})
        out = ptmem.sample()
        comps = out["components"]["t_job"]
        assert comps["ok"]["bytes"] == 7
        assert comps["bad"]["bytes"] == 0
        assert "ValueError" in comps["bad"]["error"]

    def test_reregistration_replaces_not_accumulates(self):
        paddle.set_flags({"FLAGS_monitor_memory": True})
        ptmem.register_component("c", lambda: [("x", 1)], job="t_job")
        ptmem.register_component("c", lambda: [("x", 2)], job="t_job")
        out = ptmem.sample()
        assert out["components"]["t_job"]["c"]["bytes"] == 2
        ptmem.unregister_component("c", job="t_job")
        assert "t_job" not in ptmem.sample()["components"]


# ---------------------------------------------------------------------------
# reconciliation + headroom (the acceptance math)
# ---------------------------------------------------------------------------

class TestReconciliationAndHeadroom:
    # CPU-backend slack on top of RECONCILE_TOLERANCE: paddle.seed /
    # engine construction create a few small untracked arrays (RNG
    # keys, block tables) next to the tracked pools
    SLACK = 256 << 10

    def test_serving_ledger_within_tolerance_of_witness_delta(self):
        paddle.set_flags({"FLAGS_monitor_memory": True})
        live_before = ptmem.allocator_stats()["live_bytes"]
        assert live_before is not None   # CPU backend: live_arrays
        eng = _tiny_engine(max_slots=2, num_blocks=256, block_size=4)
        out = ptmem.sample()
        rec = out["reconciliation"]
        assert rec["source"] == "live_arrays"
        delta = rec["live_bytes"] - live_before
        ledger = rec["ledger_bytes"]
        assert ledger > 0
        assert abs(delta - ledger) <= \
            ptmem.RECONCILE_TOLERANCE * ledger + self.SLACK, (
                delta, ledger)
        # the KV pool dominates this config, and its detail rows exist
        comps = out["components"]["serving"]
        assert comps["kv_pool"]["bytes"] > comps["model_params"]["bytes"]
        assert "pages_usable" in comps["kv_pool"]["detail"]
        assert eng._mem is not None

    def test_headroom_identity_and_matches_graph_report(
            self, monkeypatch):
        """mem_hbm_headroom_bytes = capacity − (static ledger +
        compiled transient peak), and the peak is the SAME
        donation-aware number graph_report() publishes for the llama
        fixture — identity-pinned so the repo cannot grow a second
        hand-rolled estimate."""
        monkeypatch.setenv("PT_MEM_CAPACITY_BYTES", str(2 << 30))
        paddle.set_flags({"FLAGS_monitor_memory": True,
                          "FLAGS_perf_attribution": True})
        step, ids, labels = _tiny_step()
        step(ids, labels)
        analysis = step.perf_analysis(ids, labels)
        peak = analysis["hbm_peak_bytes"]
        assert peak > 0
        out = ptmem.sample()
        row = out["jobs"]["train"]
        assert row["transient_peak_bytes"] == peak
        assert row["capacity_bytes"] == 2 << 30
        assert row["headroom_bytes"] == \
            (2 << 30) - row["ledger_bytes"] - peak
        g = mreg.get_registry().get("mem_hbm_headroom_bytes")
        assert dict(g.collect())[("train",)] == row["headroom_bytes"]
        # graph_report()'s cost row carries the identical peak
        rep = step.graph_report(ids, labels)
        costs = [(srep.get("cost") or {}).get("hbm_peak_bytes")
                 for srep in rep["steps"].values()]
        assert peak in costs, (peak, costs)
        # and memory.compiled_peak is definitionally that number
        assert ptmem.transient_peak("train")["bytes"] == peak

    def test_headroom_subtracts_full_ledger_across_jobs(
            self, monkeypatch):
        """Two jobs share ONE device: each job's headroom subtracts
        the FULL static ledger, not just its own slice — otherwise
        both would claim the other's bytes as free."""
        monkeypatch.setenv("PT_MEM_CAPACITY_BYTES", str(1 << 30))
        paddle.set_flags({"FLAGS_monitor_memory": True})
        ptmem.tracker("t_a", {"c": lambda: [("x", 100 << 20)]})
        ptmem.tracker("t_b", {"c": lambda: [("x", 50 << 20)]})
        jobs = ptmem.sample()["jobs"]
        want = (1 << 30) - (150 << 20)
        assert jobs["t_a"]["headroom_bytes"] == want
        assert jobs["t_b"]["headroom_bytes"] == want

    def test_dropped_engine_not_pinned_by_ledger(self):
        """The global ledger holds engines WEAKLY: discarding an
        engine must actually free its pools/params (a memory
        observability plane that leaks device memory would be
        self-parody); its components then report empty."""
        paddle.set_flags({"FLAGS_monitor_memory": True})
        eng = _tiny_engine(max_slots=2, num_blocks=32, block_size=4)
        assert ptmem.sample()["components"]["serving"]["kv_pool"][
            "bytes"] > 0
        wr = weakref.ref(eng)
        del eng
        gc.collect()
        assert wr() is None
        comps = ptmem.sample()["components"]["serving"]
        assert comps["kv_pool"]["bytes"] == 0
        assert comps["model_params"]["bytes"] == 0

    def test_no_capacity_no_fabricated_headroom(self, monkeypatch):
        monkeypatch.delenv("PT_MEM_CAPACITY_BYTES", raising=False)
        paddle.set_flags({"FLAGS_monitor_memory": True})
        ptmem.tracker("t_job", {"c": lambda: [("x", 10)]})
        row = ptmem.sample()["jobs"]["t_job"]
        # CPU allocator reports no bytes_limit: headroom absent
        assert row["capacity_bytes"] is None
        assert row["headroom_bytes"] is None
        g = mreg.get_registry().get("mem_hbm_headroom_bytes")
        assert ("t_job",) not in dict(g.collect())


# ---------------------------------------------------------------------------
# disabled-path pinning (PR-2/5/6 style)
# ---------------------------------------------------------------------------

class TestDisabledPathPinning:
    def test_flag_default_off(self):
        assert not paddle.get_flags(
            ["FLAGS_monitor_memory"])["FLAGS_monitor_memory"]
        assert not ptmem.is_enabled()

    def test_off_zero_native_zero_threads_zero_series(
            self, monkeypatch, tmp_path):
        """Flags off: engines latch tracker=None, the hot paths run,
        and the plane leaves NO trace — no native calls from ITS entry
        points, no new threads, no mem_* registry series, no sentinel,
        no postmortem machinery armed."""
        from paddle_tpu.core import native

        # the memory plane's own off-path entry points are native-free
        # (the engines' pre-existing profiler spans may use native —
        # that is not this plane's footprint)
        with monkeypatch.context() as m:
            m.setattr(native, "get_lib", lambda: pytest.fail(
                "disabled memory touched native lib"))
            assert ptmem.tracker("t_off", {"c": lambda: [("x", 1)]}) \
                is None
            assert ptmem.memory_payload()["enabled"] is False
            assert not ptmem.is_enabled()
        monkeypatch.setenv("PT_MONITOR_DUMP_DIR", str(tmp_path))
        threads_before = set(threading.enumerate())
        eng = _tiny_engine(max_slots=2, num_blocks=32, block_size=4)
        assert eng._mem is None
        r = eng.add_request([1, 2, 3], max_new_tokens=2)
        eng.run()
        assert eng.request_status(r)["state"] == "finished"
        step, ids, labels = _tiny_step()
        assert step._mem is None
        step(ids, labels)
        for name in ("mem_device_bytes", "mem_hbm_headroom_bytes",
                     "mem_unattributed_bytes",
                     "mem_oom_postmortems_total"):
            m = mreg.get_registry().get(name)
            assert m is None or list(m.collect()) == [], name
        assert ptmem._state.components == {}
        assert ptmem._state.sentinel is None
        assert set(threading.enumerate()) == threads_before
        assert not os.listdir(str(tmp_path))
        payload = ptmem.memory_payload()
        assert payload["enabled"] is False
        assert payload["components"] == {} and payload["jobs"] == {}


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

class TestOOMForensics:
    def test_looks_like_oom_classification(self):
        assert ptmem.looks_like_oom(
            RuntimeError("RESOURCE_EXHAUSTED: Out of memory while "
                         "trying to allocate 17179869184 bytes"))
        assert ptmem.looks_like_oom(ValueError("Allocation failure"))
        assert not ptmem.looks_like_oom(RuntimeError("shape mismatch"))
        fi.enable("mem.oom:error@1", seed=0)
        with pytest.raises(fi.InjectedFault) as ei:
            fi.fire("mem.oom")
        assert ptmem.looks_like_oom(ei.value)
        # a NON-mem injected fault is not OOM-shaped
        fi.disable()
        fi.enable("serving.step:error@1", seed=0)
        with pytest.raises(fi.InjectedFault) as ei:
            fi.fire("serving.step")
        assert not ptmem.looks_like_oom(ei.value)

    def test_serving_mem_oom_postmortem_names_kv_pool(
            self, monkeypatch, tmp_path):
        """THE acceptance path: a forced mem.oom during a serving run
        produces oom_postmortem_rank{r}.json whose largest-component
        attribution names the KV pool, with KV occupancy present and
        the re-raise preserved."""
        monkeypatch.setenv("PT_MONITOR_DUMP_DIR", str(tmp_path))
        paddle.set_flags({"FLAGS_monitor_memory": True})
        eng = _tiny_engine(max_slots=2, num_blocks=256, block_size=4)
        eng.add_request([1, 2, 3, 4, 5], max_new_tokens=8)
        # hit 1 passes (the request admits, prefills, decodes once —
        # pages live, occupancy > 0); hit 2 is the OOM
        fi.enable("mem.oom:error@2", seed=0)
        assert eng.step()
        with pytest.raises(fi.InjectedFault):   # re-raise preserved
            eng.step()
        path = os.path.join(str(tmp_path), "oom_postmortem_rank0.json")
        assert os.path.exists(path)
        with open(path) as f:
            post = json.load(f)
        assert post["kind"] == "oom_postmortem"
        assert post["injected"] is True
        comps = post["ledger"]["components"]["serving"]
        largest = max(comps, key=lambda n: comps[n]["bytes"])
        assert largest == "kv_pool", comps
        # top consumer named: a kv pool plane with shape/dtype
        top = post["ledger"]["top_arrays"][0]
        assert top["component"] == "kv_pool" and top["shape"]
        # KV occupancy present and live (the request held pages)
        assert post["context"]["kv_page_occupancy"] > 0
        assert post["context"]["kv_pages_used"] > 0
        # the admission decision ring made it into the artifact
        assert any(d["kind"] == "admit" for d in post["decisions"])
        c = mreg.get_registry().get("mem_oom_postmortems_total")
        assert dict(c.collect())[("serving",)] >= 1
        assert ptmem.memory_payload()["postmortems"]

    def test_train_step_and_run_steps_postmortem(
            self, monkeypatch, tmp_path):
        monkeypatch.setenv("PT_MONITOR_DUMP_DIR", str(tmp_path))
        paddle.set_flags({"FLAGS_monitor_memory": True})
        step, ids, labels = _tiny_step()
        step(ids, labels)
        fi.enable("mem.oom:error@1", seed=0)
        with pytest.raises(fi.InjectedFault):
            step(ids, labels)
        path = os.path.join(str(tmp_path), "oom_postmortem_rank0.json")
        assert os.path.exists(path)
        with open(path) as f:
            post = json.load(f)
        assert post["job"] == "train"
        comps = post["ledger"]["components"]["train"]
        assert set(comps) == {"model_params", "optimizer_slots",
                              "ef_residuals"}
        # adam: 2 fp32 slots per param — slots outweigh params
        assert comps["optimizer_slots"]["bytes"] > \
            comps["model_params"]["bytes"]
        assert post["context"]["step_count"] >= 1
        os.unlink(path)
        fi.disable()
        fi.enable("mem.oom:error@1", seed=0)
        stacked = (np.stack([ids.numpy()] * 2),
                   np.stack([labels.numpy()] * 2))
        with pytest.raises(fi.InjectedFault):
            step.run_steps(stacked)
        assert os.path.exists(path)

    def test_non_oom_failure_writes_no_postmortem(
            self, monkeypatch, tmp_path):
        monkeypatch.setenv("PT_MONITOR_DUMP_DIR", str(tmp_path))
        paddle.set_flags({"FLAGS_monitor_memory": True})
        eng = _tiny_engine(max_slots=2, num_blocks=32, block_size=4)
        r = eng.add_request([1, 2, 3], max_new_tokens=3)
        fi.enable("serving.prefill:error@1", seed=0)
        eng.run()   # poison path handles it; not OOM-shaped
        assert eng.request_status(r)["state"] == "failed"
        assert not os.listdir(str(tmp_path))


# ---------------------------------------------------------------------------
# leak sentinel
# ---------------------------------------------------------------------------

class TestLeakSentinel:
    def _grower(self):
        state = {"bytes": 0}

        def provider():
            return [("blob", state["bytes"])]

        return state, provider

    def test_monotone_growth_fires_and_degrades(self):
        paddle.set_flags({"FLAGS_monitor_memory": True})
        state, provider = self._grower()
        tr = ptmem.tracker("t_job", {"leaky": provider})
        assert tr is not None and ptmem._state.sentinel is not None
        for i in range(20):
            state["bytes"] = (i + 1) << 20   # +1 MiB per sample
            ptmem.sample()
        summ = perf.anomaly_summary()
        assert summ["counts"].get("mem_leak", 0) >= 1
        assert summ["degraded"] is True
        c = mreg.get_registry().get("perf_anomalies_total")
        assert dict(c.collect())[("mem_leak",)] >= 1
        ev = [e for e in summ["recent"] if e["kind"] == "mem_leak"]
        assert ev and ev[0]["detail"]["growth_bytes"] >= (1 << 20)

    def test_warmup_never_fires(self):
        """A clean warmup can never fire — even a monotone-growth
        warmup window (engine filling its pools at startup is growth,
        not a leak)."""
        paddle.set_flags({"FLAGS_monitor_memory": True})
        state, provider = self._grower()
        s = ptmem.MemLeakSentinel()
        ptmem.tracker("t_job", {"leaky": provider})
        for i in range(s.warmup):
            state["bytes"] = (i + 1) << 20
            ptmem.sample()
        assert perf.anomaly_summary()["counts"] == {}

    def test_sawtooth_never_fires(self):
        """Grow-release-grow (preemption reclaim, request churn) is
        load, not a leak: any single decreasing sample resets."""
        paddle.set_flags({"FLAGS_monitor_memory": True})
        state, provider = self._grower()
        ptmem.tracker("t_job", {"leaky": provider})
        for i in range(40):
            # rises 5 samples, drops on the 6th — window is 6
            state["bytes"] = ((i % 6) + 1) << 20
            ptmem.sample()
        assert perf.anomaly_summary()["counts"] == {}


# ---------------------------------------------------------------------------
# decision ring
# ---------------------------------------------------------------------------

class TestDecisionRing:
    def test_bounded_and_ordered(self):
        paddle.set_flags({"FLAGS_monitor_memory": True})
        for i in range(ptmem._DECISIONS_CAP + 20):
            ptmem.note_decision("serving", "admit", request=i)
        decs = ptmem._state.decisions
        assert len(decs) == ptmem._DECISIONS_CAP
        assert decs[-1]["request"] == ptmem._DECISIONS_CAP + 19
        stamps = [d["t_mono"] for d in decs]
        assert stamps == sorted(stamps)
        assert len(ptmem.recent_decisions(5)) == 5


# ---------------------------------------------------------------------------
# surfacing: watchdog bundle tails + payload
# ---------------------------------------------------------------------------

class TestSurfacing:
    def test_watchdog_bundle_embeds_mem_ring_tails(self):
        paddle.set_flags({"FLAGS_monitor_memory": True})
        ptmem.tracker("t_job", {"c": lambda: [("x", 123)]})
        ptmem.sample()
        bundle = monitor.build_bundle(reason="test")
        tail = bundle["timeseries_tail"]
        mem_series = [k for k in tail if k.startswith("mem_")]
        assert mem_series, list(tail)

    def test_payload_carries_sentinel_config_and_decisions(self):
        paddle.set_flags({"FLAGS_monitor_memory": True})
        ptmem.tracker("t_job", {"c": lambda: [("x", 5)]})
        ptmem.note_decision("t_job", "admit", request=1)
        p = ptmem.memory_payload()
        assert p["enabled"] is True
        assert p["leak_sentinel"]["series"] == "mem_device_bytes"
        assert p["decisions"][-1]["kind"] == "admit"
        assert "reconciliation" in p


# ---------------------------------------------------------------------------
# tools/mem_snapshot.py (battery row artifact)
# ---------------------------------------------------------------------------

def _load_mem_snapshot_mod():
    spec = importlib.util.spec_from_file_location(
        "t_mem_snapshot", os.path.join(REPO, "tools",
                                       "mem_snapshot.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestMemSnapshotTool:
    def test_stale_reemit_discipline(self, tmp_path):
        mod = _load_mem_snapshot_mod()
        out = str(tmp_path / "mem_snapshot.json")
        fresh = {"kind": "mem_snapshot", "version": 1, "ok": True,
                 "written_at": "2026-08-03T00:00:00Z",
                 "memory": {"enabled": True}}
        mod.write_artifact(out, fresh)
        # failed round: previous artifact re-emitted, marked stale
        got = mod.write_artifact(out, None, stale_reason="child died")
        assert got["stale"] is True
        assert got["stale_generations"] == 1
        assert got["stale_since"] == "2026-08-03T00:00:00Z"
        assert got["memory"] == {"enabled": True}
        # second failed round increments the generation chain
        got = mod.write_artifact(out, None, stale_reason="still dead")
        assert got["stale_generations"] == 2
        with open(out) as f:
            assert json.load(f)["stale_generations"] == 2

    def test_no_previous_artifact_writes_not_ok(self, tmp_path):
        mod = _load_mem_snapshot_mod()
        out = str(tmp_path / "mem_snapshot.json")
        got = mod.write_artifact(out, None, stale_reason="boom")
        assert got["ok"] is False and got["error"] == "boom"

    def test_cli_measures_and_commits(self, tmp_path):
        """End-to-end CPU smoke: the battery row's exact invocation
        writes a fresh ok artifact with a nonempty ledger and the
        compiled transient peak."""
        out = str(tmp_path / "mem_snapshot.json")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO + os.pathsep +
                   os.environ.get("PYTHONPATH", ""))
        env.pop("PALLAS_AXON_POOL_IPS", None)
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "mem_snapshot.py"),
             "--steps", "2", "--out", out],
            capture_output=True, text=True, env=env, timeout=540)
        assert r.returncode == 0, r.stdout + r.stderr
        with open(out) as f:
            snap = json.load(f)
        assert snap["ok"] is True and not snap.get("stale")
        assert snap["compiled_peak_bytes"] > 0
        mem = snap["memory"]
        assert mem["enabled"] is True
        comps = mem["components"]["train"]
        assert comps["model_params"]["bytes"] > 0
        assert comps["optimizer_slots"]["bytes"] > 0
        rec = mem["reconciliation"]
        assert rec["source"] == "live_arrays"
        assert rec["ledger_bytes"] > 0
