"""vision.transforms/ops/datasets + incubate + distribution surface
completions (reference vision/transforms, vision/ops.py, incubate/,
distribution/ remaining names)."""
import os
import re

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.incubate as incubate
from paddle_tpu import distribution as D
from paddle_tpu import vision

REF = "/root/reference/python/paddle"
_REF_GATE = pytest.mark.skipif(not os.path.isdir(REF),
                               reason="reference tree not mounted")


def _ref_all(path):
    src = open(REF + "/" + path).read()
    return sorted(set(re.findall(r"^\s+'(\w+)',", src, re.M)))


@_REF_GATE
class TestSurfaceGates:
    @pytest.mark.parametrize("mod,path", [
        ("transforms", "vision/transforms/__init__.py"),
        ("datasets", "vision/datasets/__init__.py"),
        ("models", "vision/models/__init__.py"),
    ])
    def test_vision_surfaces(self, mod, path):
        m = getattr(vision, mod)
        missing = [n for n in _ref_all(path) if not hasattr(m, n)]
        assert missing == [], missing

    def test_incubate_and_distribution(self):
        for mod, path in [(incubate, "incubate/__init__.py"),
                          (D, "distribution/__init__.py")]:
            missing = [n for n in _ref_all(path) if not hasattr(mod, n)]
            assert missing == [], missing


class TestTransforms:
    def _img(self):
        rng = np.random.RandomState(0)
        return (rng.rand(8, 8, 3) * 255).astype(np.uint8)

    def test_flips_crop_pad(self):
        img = self._img()
        np.testing.assert_array_equal(
            vision.transforms.hflip(img), img[:, ::-1])
        np.testing.assert_array_equal(
            vision.transforms.vflip(img), img[::-1])
        c = vision.transforms.crop(img, 1, 2, 3, 4)
        np.testing.assert_array_equal(c, img[1:4, 2:6])
        cc = vision.transforms.center_crop(img, 4)
        np.testing.assert_array_equal(cc, img[2:6, 2:6])
        p = vision.transforms.pad(img, 2)
        assert p.shape == (12, 12, 3)
        assert np.all(p[:2] == 0)

    def test_color_ops(self):
        img = self._img()
        b = vision.transforms.adjust_brightness(img, 2.0)
        assert b.dtype == np.uint8 and b.max() <= 255
        g = vision.transforms.to_grayscale(img)
        assert g.shape == (8, 8, 1)
        g3 = vision.transforms.to_grayscale(img, 3)
        assert g3.shape == (8, 8, 3)
        # hue shift of 0 is identity (within rounding)
        h0 = vision.transforms.adjust_hue(img, 0.0)
        assert np.abs(h0.astype(int) - img.astype(int)).max() <= 1
        with pytest.raises(ValueError):
            vision.transforms.adjust_hue(img, 0.9)

    def test_rotate_and_erase(self):
        img = np.zeros((8, 8, 1), np.float32)
        img[2, 2, 0] = 1.0
        r180 = vision.transforms.rotate(img, 180.0)
        # 180-degree rotation moves (2,2) to (5,5) (center-anchored)
        assert abs(float(r180[5, 5, 0]) - 1.0) < 0.2
        e = vision.transforms.erase(self._img(), 1, 1, 3, 3, 0)
        assert np.all(e[1:4, 1:4] == 0)

    def test_transform_classes_run(self):
        img = self._img()
        for t in [vision.transforms.ColorJitter(0.2, 0.2, 0.2, 0.1),
                  vision.transforms.Grayscale(3),
                  vision.transforms.Pad(1),
                  vision.transforms.RandomRotation(10),
                  vision.transforms.RandomErasing(prob=1.0),
                  vision.transforms.RandomResizedCrop(6),
                  vision.transforms.RandomPerspective(prob=1.0),
                  vision.transforms.Transpose()]:
            out = t(img)
            assert out is not None

    def test_compose_chain(self):
        chain = vision.transforms.Compose([
            vision.transforms.Pad(1),
            vision.transforms.RandomResizedCrop(6),
            vision.transforms.Transpose(),
        ])
        out = chain(self._img())
        assert out.shape == (3, 6, 6)

    def test_compose_chain_layout_stable_across_crop_draws(self):
        """Regression: Resize guessed CHW from `shape[0] in (1, 3)`
        alone, so a random crop of HEIGHT 3 — a (3, W, 3) HWC array —
        was resized channels-first and the chain's output layout
        flipped on ~6% of global-RNG draws (seed 22 was one). The
        layout guess now requires dim 2 to be non-channel-like too
        (transforms_extras._is_chw's rule)."""
        import random

        chain = vision.transforms.Compose([
            vision.transforms.Pad(1),
            vision.transforms.RandomResizedCrop(6),
            vision.transforms.Transpose(),
        ])
        state = random.getstate()
        try:
            for seed in (22, 31, 43, 57, 113):    # height-3 crop draws
                random.seed(seed)
                assert chain(self._img()).shape == (3, 6, 6), seed
        finally:
            random.setstate(state)

    def test_resize_ambiguous_three_row_image_is_hwc(self):
        """(3, W, 3) reads as HWC: resize scales rows/cols, keeping
        channels last."""
        arr = np.arange(3 * 5 * 3, dtype=np.float32).reshape(3, 5, 3)
        out = vision.transforms.resize(arr, (6, 6))
        assert out.shape == (6, 6, 3)

    def test_random_flips_flip_the_right_axes(self):
        """Regression: on HWC input, RandomHorizontalFlip reversed
        the CHANNEL axis (an RGB->BGR swap with zero flip) and
        RandomVerticalFlip reversed WIDTH. Horizontal = width axis,
        vertical = height axis, in every layout."""
        hwc = np.arange(4 * 5 * 3, dtype=np.float32).reshape(4, 5, 3)
        chw = np.transpose(hwc, (2, 0, 1)).copy()
        gray = np.arange(20, dtype=np.float32).reshape(4, 5)
        h = vision.transforms.RandomHorizontalFlip(prob=1.0)
        v = vision.transforms.RandomVerticalFlip(prob=1.0)
        assert np.array_equal(h(hwc), hwc[:, ::-1, :])
        assert np.array_equal(h(chw), chw[:, :, ::-1])
        assert np.array_equal(h(gray), gray[:, ::-1])
        assert np.array_equal(v(hwc), hwc[::-1])
        assert np.array_equal(v(chw), chw[:, ::-1, :])
        assert np.array_equal(v(gray), gray[::-1])


class TestVisionOps:
    def test_yolo_box_shapes(self):
        paddle.seed(0)
        na, C, H, W = 3, 4, 2, 2
        x = paddle.to_tensor(np.random.RandomState(0).randn(
            1, na * (5 + C), H, W).astype(np.float32))
        boxes, scores = vision.ops.yolo_box(
            x, paddle.to_tensor(np.asarray([[64, 64]], np.int32)),
            anchors=[10, 13, 16, 30, 33, 23], class_num=C,
            conf_thresh=0.01, downsample_ratio=32)
        assert boxes.shape == [1, na * H * W, 4]
        assert scores.shape == [1, na * H * W, C]

    def test_matrix_nms_decays_overlaps(self):
        boxes = np.asarray([[[0, 0, 10, 10], [0, 0, 10, 10],
                             [20, 20, 30, 30]]], np.float32)
        scores = np.asarray([[[0.9, 0.85, 0.8]]], np.float32)
        out, rois_num = vision.ops.matrix_nms(
            paddle.to_tensor(boxes), paddle.to_tensor(scores),
            score_threshold=0.1, post_threshold=0.0, nms_top_k=10,
            keep_top_k=10, background_label=-1)
        ov = np.asarray(out._value)
        # the exact-duplicate box decays to score 0 and is filtered by
        # post_threshold; winner + the far box survive
        assert int(np.asarray(rois_num._value)[0]) == 2
        top = ov[np.argsort(-ov[:, 1])]
        np.testing.assert_allclose(top[0, 1], 0.9, rtol=1e-5)
        np.testing.assert_allclose(top[1, 1], 0.8, rtol=1e-5)

    def test_psroi_pool(self):
        C_out, ph, pw = 2, 2, 2
        x = paddle.to_tensor(np.arange(
            1 * C_out * ph * pw * 4 * 4, dtype=np.float32)
            .reshape(1, C_out * ph * pw, 4, 4))
        boxes = paddle.to_tensor(np.asarray([[0, 0, 4, 4]], np.float32))
        out = vision.ops.psroi_pool(
            x, boxes, paddle.to_tensor(np.asarray([1], np.int32)),
            (ph, pw))
        assert out.shape == [1, C_out, ph, pw]

    def test_deform_layer_and_read_file(self, tmp_path):
        paddle.seed(1)
        m = vision.ops.DeformConv2D(2, 3, 3, padding=1)
        x = paddle.to_tensor(np.random.RandomState(2).randn(
            1, 2, 4, 4).astype(np.float32))
        offset = paddle.to_tensor(np.zeros((1, 18, 4, 4), np.float32))
        out = m(x, offset)
        assert out.shape == [1, 3, 4, 4]
        f = tmp_path / "blob.bin"
        f.write_bytes(b"\x01\x02\x03")
        r = vision.ops.read_file(str(f))
        np.testing.assert_array_equal(np.asarray(r._value), [1, 2, 3])


class TestDatasets:
    def test_dataset_folder(self, tmp_path):
        for cls in ("cat", "dog"):
            d = tmp_path / cls
            d.mkdir()
            for i in range(2):
                np.save(d / ("img%d.npy" % i),
                        np.full((2, 2), i, np.float32))
        ds = vision.datasets.DatasetFolder(str(tmp_path))
        assert len(ds) == 4
        img, label = ds[0]
        assert img.shape == (2, 2) and label == 0
        assert ds.classes == ["cat", "dog"]
        flat = vision.datasets.ImageFolder(str(tmp_path))
        assert len(flat) == 4

    def test_flowers_voc_synthetic(self):
        fl = vision.datasets.Flowers(mode="train", size=8)
        img, lbl = fl[0]
        assert img.shape == (3, 64, 64) and 0 <= lbl < 102
        voc = vision.datasets.VOC2012(mode="test", size=4)
        img, mask = voc[1]
        assert mask.shape == (64, 64)


class TestIncubateExtras:
    def test_lookahead_converges_and_syncs(self):
        import paddle_tpu.nn as nn

        paddle.seed(3)
        m = nn.Linear(4, 1)
        inner = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=m.parameters())
        opt = incubate.LookAhead(inner, alpha=0.5, k=2)
        X = np.random.RandomState(4).randn(16, 4).astype(np.float32)
        Y = X @ np.ones((4, 1), np.float32)
        first = None
        for i in range(10):
            loss = ((m(paddle.to_tensor(X))
                     - paddle.to_tensor(Y)) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            first = first if first is not None else float(loss)
        assert float(loss) < first

    def test_segment_alias_and_masked_softmax(self):
        x = paddle.to_tensor(np.asarray([[1.0, 2.0], [3.0, 4.0],
                                         [5.0, 6.0]], np.float32))
        seg = paddle.to_tensor(np.asarray([0, 0, 1], np.int64))
        s = incubate.segment_sum(x, seg)
        np.testing.assert_allclose(np.asarray(s._value),
                                   [[4.0, 6.0], [5.0, 6.0]])
        logits = paddle.to_tensor(np.zeros((1, 1, 3, 3), np.float32))
        out = incubate.softmax_mask_fuse_upper_triangle(logits)
        ov = np.asarray(out._value)[0, 0]
        np.testing.assert_allclose(ov[0], [1.0, 0.0, 0.0], atol=1e-6)
        np.testing.assert_allclose(ov[2], [1 / 3] * 3, rtol=1e-5)

    def test_identity_loss_and_unzip(self):
        x = paddle.to_tensor(np.asarray([1.0, 3.0], np.float32))
        assert float(incubate.identity_loss(x, "mean")) == 2.0
        lod = paddle.to_tensor(np.asarray([0, 1, 1, 2], np.int64))
        data = paddle.to_tensor(np.asarray([[5.0], [7.0]], np.float32))
        out = np.asarray(incubate.unzip(data, lod)._value)
        np.testing.assert_allclose(out, [[5.0], [0.0], [7.0]])


class TestDistributionExtras:
    def test_independent_sums_event_dims(self):
        base = D.Normal(loc=np.zeros((3, 2), np.float32),
                        scale=np.ones((3, 2), np.float32))
        ind = D.Independent(base, 1)
        v = paddle.to_tensor(np.zeros((3, 2), np.float32))
        lp = np.asarray(ind.log_prob(v)._value)
        assert lp.shape == (3,)
        base_lp = np.asarray(base.log_prob(v)._value)
        np.testing.assert_allclose(lp, base_lp.sum(-1), rtol=1e-6)

    def test_transformed_distribution_affine(self):
        class Affine:
            def forward(self, x):
                return x * 2.0 + 1.0

            def inverse(self, y):
                return (y - 1.0) / 2.0

            def forward_log_det_jacobian(self, x):
                import math

                return np.float32(math.log(2.0))

        base = D.Normal(loc=0.0, scale=1.0)
        td = D.TransformedDistribution(base, [Affine()])
        y = paddle.to_tensor(np.asarray([1.0], np.float32))
        lp = float(np.asarray(td.log_prob(y)._value).ravel()[0])
        # y=1 -> x=0: N(0,1).logpdf(0) - log 2
        want = -0.5 * np.log(2 * np.pi) - np.log(2.0)
        np.testing.assert_allclose(lp, want, rtol=1e-5)
        s = td.sample((4,))
        assert np.asarray(s._value).shape[0] == 4


class TestReviewRegressions:
    def test_matrix_nms_partial_overlap_decays(self):
        """Regression: compensate used the wrong axis, so PARTIAL
        overlaps (iou<1) were not suppressed at all."""
        boxes = np.asarray([[[0, 0, 10, 10], [0, 1, 10, 11],
                             [50, 50, 60, 60]]], np.float32)  # iou~0.82
        scores = np.asarray([[[0.9, 0.8, 0.7]]], np.float32)
        out, _ = vision.ops.matrix_nms(
            paddle.to_tensor(boxes), paddle.to_tensor(scores),
            score_threshold=0.1, post_threshold=0.0, nms_top_k=10,
            keep_top_k=10, background_label=-1)
        ov = np.asarray(out._value)
        by_score = dict()
        for row in ov:
            by_score[tuple(row[2:].tolist())] = row[1]
        overlap_score = by_score[(0.0, 1.0, 10.0, 11.0)]
        far_score = by_score[(50.0, 50.0, 60.0, 60.0)]
        assert overlap_score < 0.3          # decayed hard (was 0.8)
        np.testing.assert_allclose(far_score, 0.7, rtol=1e-5)

    def test_generate_proposals_v2_pixel_offset_changes_result(self):
        paddle.seed(5)
        rng = np.random.RandomState(6)
        scores = paddle.to_tensor(rng.rand(1, 2, 4, 4).astype(np.float32))
        deltas = paddle.to_tensor(
            (rng.randn(1, 8, 4, 4) * 0.1).astype(np.float32))
        img = paddle.to_tensor(np.asarray([[32.0, 32.0]], np.float32))
        anchors = paddle.to_tensor(
            rng.rand(4, 4, 2, 4).astype(np.float32) * 16)
        var = paddle.to_tensor(np.ones((4, 4, 2, 4), np.float32))
        a = vision.ops.generate_proposals_v2(
            scores, deltas, img, anchors, var, pixel_offset=False)
        b = vision.ops.generate_proposals_v2(
            scores, deltas, img, anchors, var, pixel_offset=True)
        assert not np.allclose(np.asarray(a[0]._value),
                               np.asarray(b[0]._value))

    def test_lookahead_state_dict_carries_slow_weights(self):
        import paddle_tpu.nn as nn

        paddle.seed(7)
        m = nn.Linear(2, 1)
        opt = incubate.LookAhead(
            paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=m.parameters()),
            alpha=0.5, k=5)
        x = paddle.to_tensor(np.ones((4, 2), np.float32))
        for _ in range(3):  # mid-cycle
            loss = (m(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        sd = opt.state_dict()
        assert sd["slow"][0] is not None  # slow anchor persisted

    def test_unzip_len_bounds_output(self):
        lod = paddle.to_tensor(np.asarray([0, 1, 1], np.int64))
        data = paddle.to_tensor(np.asarray([[5.0]], np.float32))
        out = np.asarray(incubate.unzip(data, lod, len=4)._value)
        assert out.shape == (4, 1)
        np.testing.assert_allclose(out[:, 0], [5.0, 0.0, 0.0, 0.0])

    def test_khop_sampler_eids_refuses(self):
        with pytest.raises(NotImplementedError, match="eids"):
            incubate.graph_khop_sampler(None, None, None, [2],
                                        return_eids=True)
