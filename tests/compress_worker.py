"""4-process dp=2 x sharding=2 worker for the quantized-communication
parity suite (tests/test_compress.py).

Phases (every rank runs all of them; rank 0 prints COMPRESS_RESULT):

1. **DataParallel sync, fp32 vs int8**: the same seeded MLP trains
   twice over the world group — flag off (per-param fp32 all_reduce)
   and flag on (bucketed compressed sync with error feedback). Records
   both loss trajectories, the ``comm_bytes_total{path=eager}``
   counter deltas per format (the >=3x acceptance assertion), the
   flight-recorder all_reduce count per sync (bucketing pin: buckets,
   not params), and that recorder entries carry ``wire_bytes``.

2. **ZeRO-2-style numpy training over subgroups**: grads
   reduce-scattered over the 'sharding' subgroup, chunk-allreduced over
   the 'dp' subgroup, params all-gathered back — fp32 vs compressed
   wire, loss sequences recorded for the tolerance check.

3. **Mismatch validation**: rank 1 passes a wrong-shaped tensor to the
   strict all_gather; every rank must get the clear error NAMING rank 1
   (validated on the self-describing frames before reassembly) instead
   of a cryptic stack() failure or hang.
"""
from __future__ import annotations

import json
import os
import sys

import numpy as np

N_STEPS = 10


def _snapshot_comm_bytes():
    from paddle_tpu.distributed import compress

    return {
        "false": compress.COMM_BYTES.labels(
            path="eager", compressed="false").value,
        "true": compress.COMM_BYTES.labels(
            path="eager", compressed="true").value,
    }


def _train_dp(paddle, dist, flag_on, seed=3):
    """One DataParallel training run over the world group; returns
    (losses, comm-bytes-delta dict, allreduce records per sync)."""
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu import monitor
    from paddle_tpu.core import flags as fl

    rank = dist.get_rank()
    nranks = dist.get_world_size()
    paddle.seed(seed)
    model = nn.Sequential(nn.Linear(64, 256), nn.Tanh(),
                          nn.Linear(256, 8))
    # the default Group("dp") has no process backend; the world group
    # carries the store pg that makes the eager sync real
    dp = paddle.DataParallel(
        model, group=dist.collective._get_default_group())
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=model.parameters())
    rng = np.random.RandomState(0)
    x = rng.rand(32, 64).astype(np.float32)
    y = rng.randint(0, 8, 32)
    shard = 32 // nranks
    xl = x[rank * shard:(rank + 1) * shard]
    yl = y[rank * shard:(rank + 1) * shard]

    fl.set_flags({"FLAGS_quantized_grad_sync": flag_on,
                  # small threshold so the 4 params coalesce into
                  # exactly 2 buckets: [W1] (64KiB) and [b1, W2, b2]
                  "FLAGS_grad_sync_bucket_mb": 0.0625})
    rec = monitor.get_flight_recorder()
    b0 = _snapshot_comm_bytes()
    losses = []
    sync_allreduces = None
    try:
        for step in range(N_STEPS):
            out = dp(paddle.to_tensor(xl))
            loss = F.cross_entropy(out, paddle.to_tensor(yl))
            loss.backward()
            n_rec0 = len(rec.entries())
            dp.sync_gradients()
            if sync_allreduces is None:
                entries = rec.entries()[n_rec0:]
                sync_allreduces = [e for e in entries
                                   if e["op"] == "all_reduce"]
            # the loss each rank reports is its LOCAL shard loss; make
            # it the global mean like the compiled step would
            gl = dist.collective._get_default_group().pg.allreduce(
                np.asarray(float(loss)), "avg")
            losses.append(float(gl))
            opt.step()
            opt.clear_grad()
    finally:
        fl.set_flags({"FLAGS_quantized_grad_sync": False})
    b1 = _snapshot_comm_bytes()
    delta = {k: b1[k] - b0[k] for k in b1}
    return losses, delta, sync_allreduces


def _train_zero2(dist, compressed, dp_group, sh_group, seed=11):
    """Numpy ZeRO-2-flavor training: batch split over all 4 ranks
    (dp x sharding is the data-parallel world), grads reduce-scattered
    over the sharding subgroup, each rank's owned chunk all-reduced
    over the dp subgroup, updated shards all-gathered back."""
    pg_sh = sh_group.pg
    pg_dp = dp_group.pg
    world = dist.get_world_size()
    rank = dist.get_rank()
    rng = np.random.RandomState(seed)
    W1 = (rng.randn(64, 64) * 0.1).astype(np.float32)
    W2 = (rng.randn(64, 8) * 0.1).astype(np.float32)
    X = rng.randn(32, 64).astype(np.float32)
    Y = rng.randn(32, 8).astype(np.float32)
    shard = 32 // world
    Xl = X[rank * shard:(rank + 1) * shard]
    Yl = Y[rank * shard:(rank + 1) * shard]
    nsh = pg_sh.world_size
    lr = 0.05
    losses = []
    residual = None
    for _ in range(N_STEPS):
        h = np.tanh(Xl @ W1)
        out = h @ W2
        diff = out - Yl
        loss_local = float((diff ** 2).mean())
        gout = 2.0 * diff / diff.size
        gW2 = h.T @ gout
        gh = gout @ W2.T
        gW1 = Xl.T @ (gh * (1.0 - h * h))
        flat = np.concatenate([gW1.reshape(-1), gW2.reshape(-1)]) \
            .astype(np.float32)
        pad = (-flat.size) % nsh
        flat = np.pad(flat, (0, pad))
        if compressed and residual is not None:
            flat = flat + residual
        if compressed:
            from paddle_tpu.distributed import compress

            q, s = compress.quantize_np(flat)
            residual = flat - compress.dequantize_np(q, s)
        # sharding-group reduce-scatter of the flat grad, then the
        # owned chunk rides the dp-group all-reduce: every rank ends
        # holding the WORLD-summed chunk it owns
        chunk = pg_sh.reduce_scatter(
            flat.reshape(nsh, -1), "sum", compressed=compressed)
        chunk = pg_dp.allreduce(chunk, "sum", compressed=compressed)
        chunk = chunk.reshape(-1) / world
        # update owned shard, gather shards back (param sync stays
        # fp32: compressing it is ZeRO-3 territory, not grad sync)
        upd = chunk * lr
        parts = pg_sh.allgather(upd, compressed=False)
        full = np.concatenate([p.reshape(-1) for p in parts])
        delta = full[:W1.size + W2.size]
        W1 -= delta[:W1.size].reshape(W1.shape)
        W2 -= delta[W1.size:].reshape(W2.shape)
        loss = float(pg_dp.allreduce(np.asarray(loss_local), "avg"))
        loss = float(pg_sh.allreduce(np.asarray(loss), "avg"))
        losses.append(loss)
    return losses


def main():
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    rank = int(os.environ["PADDLE_TRAINER_ID"])
    dist.init_parallel_env()
    assert dist.get_world_size() == 4

    result = {"rank": rank}

    # phase 1: DataParallel fp32 vs compressed
    fp_losses, fp_bytes, fp_recs = _train_dp(paddle, dist, False)
    q_losses, q_bytes, q_recs = _train_dp(paddle, dist, True)
    result.update({
        "fp32_losses": fp_losses,
        "q8_losses": q_losses,
        "fp32_bytes": fp_bytes,
        "q8_bytes": q_bytes,
        "fp32_allreduces_per_sync": len(fp_recs),
        "q8_allreduces_per_sync": len(q_recs),
        "q8_wire_bytes_recorded": all(
            e.get("wire_bytes", 0) > 0 for e in q_recs),
    })

    # phase 2: ZeRO-2 subgroup training. dp groups pair ranks with the
    # same sharding index; sharding groups pair ranks on the same dp
    # index (rank = dp_idx * 2 + sh_idx)
    dp_groups = [[0, 2], [1, 3]]
    sh_groups = [[0, 1], [2, 3]]
    my_dp = my_sh = None
    for ranks in dp_groups:
        g = dist.new_group(ranks=ranks)
        if rank in ranks:
            my_dp = g
    for ranks in sh_groups:
        g = dist.new_group(ranks=ranks)
        if rank in ranks:
            my_sh = g
    z_fp = _train_zero2(dist, False, my_dp, my_sh)
    z_q8 = _train_zero2(dist, True, my_dp, my_sh)
    result["zero2_fp32_losses"] = z_fp
    result["zero2_q8_losses"] = z_q8

    # phase 2b: object collectives ride the same store transport with
    # legitimately rank-varying payloads — the strict validation and
    # the compressed wire format must both leave them alone
    # (regression: np was not imported at collective.py module level,
    # so every multi-rank *_object call died with NameError)
    objs = []
    dist.all_gather_object(objs, {"rank": rank, "blob": "x" * (rank + 1)})
    assert [o["rank"] for o in objs] == [0, 1, 2, 3], objs
    carried = [{"seed": 42}] if rank == 2 else [None]
    dist.broadcast_object_list(carried, src=2)
    assert carried == [{"seed": 42}], carried
    result["object_collectives_ok"] = True

    # phase 2c: non-sum reductions stay EXACT even with the flag on
    # (review-found: per-rank rounding error neither averages out nor
    # re-enters via residuals for max/min/prod)
    from paddle_tpu.core import flags as fl

    fl.set_flags({"FLAGS_quantized_grad_sync": True})
    try:
        pg = dist.collective._get_default_group().pg
        vals = (np.linspace(0, 1, 4096).astype(np.float32)
                + 0.001 * rank)
        got = pg.allreduce(vals, "max")
        expect = np.linspace(0, 1, 4096).astype(np.float32) + 0.003
        result["max_exact"] = bool(np.array_equal(got, expect))
    finally:
        fl.set_flags({"FLAGS_quantized_grad_sync": False})

    # phase 3: strict all_gather shape-mismatch validation — rank 1
    # ships a deviant shape; EVERY rank must see the error naming it
    t = paddle.to_tensor(
        np.zeros((3, 2) if rank == 1 else (4, 2), np.float32))
    try:
        dist.all_gather(None, t)
        result["mismatch_error"] = None
    except ValueError as e:
        result["mismatch_error"] = str(e)
    dist.barrier()

    print("COMPRESS_RESULT " + json.dumps(result))
    sys.stdout.flush()


if __name__ == "__main__":
    main()
