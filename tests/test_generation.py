"""Compiled generation: static DecodeCache + one XLA while-loop.

Oracle: full re-forward over the growing sequence (no cache) — cached
decode must produce identical greedy tokens. Reference analog:
PaddleNLP GenerationMixin greedy/sampling over growing caches.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def _model(seed=0):
    paddle.seed(seed)
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      max_position_embeddings=64, use_parallel=False)
    return LlamaForCausalLM(cfg), cfg


class TestGenerate:
    def test_greedy_matches_full_reforward(self):
        m, cfg = _model()
        rng = np.random.RandomState(0)
        prompt = rng.randint(0, cfg.vocab_size, (2, 5)).astype(np.int32)
        out = m.generate(paddle.to_tensor(prompt), max_new_tokens=6)
        got = np.asarray(out._value)
        assert got.shape == (2, 6)

        # oracle: argmax over a full no-cache forward each step
        seq = prompt.copy()
        for t in range(6):
            logits = m(paddle.to_tensor(seq))
            nxt = np.argmax(np.asarray(logits._value)[:, -1, :], axis=-1)
            np.testing.assert_array_equal(got[:, t], nxt.astype(np.int32),
                                          err_msg="step %d" % t)
            seq = np.concatenate([seq, nxt[:, None].astype(np.int32)],
                                 axis=1)

    def test_eos_early_stop_pads(self):
        m, cfg = _model(seed=1)
        prompt = np.asarray([[1, 2, 3]], np.int32)
        # find the first greedily generated token and use it as "eos"
        first = int(np.asarray(
            m.generate(paddle.to_tensor(prompt),
                       max_new_tokens=1)._value)[0, 0])
        out = m.generate(paddle.to_tensor(prompt), max_new_tokens=5,
                         eos_token_id=first)
        got = np.asarray(out._value)[0]
        assert got[0] == first
        np.testing.assert_array_equal(got, np.full(5, first))  # eos-padded

    def test_sampling_modes_run(self):
        m, cfg = _model(seed=2)
        prompt = np.asarray([[4, 9]], np.int32)
        for kw in ({"do_sample": True, "top_k": 5},
                   {"do_sample": True, "top_p": 0.9},
                   {"do_sample": True, "temperature": 0.7, "top_k": 3,
                    "top_p": 0.95}):
            out = m.generate(paddle.to_tensor(prompt), max_new_tokens=4,
                             seed=7, **kw)
            got = np.asarray(out._value)
            assert got.shape == (1, 4)
            assert (got >= 0).all() and (got < cfg.vocab_size).all()

    def test_sampling_deterministic_per_seed(self):
        m, cfg = _model(seed=3)
        prompt = np.asarray([[4, 9, 2]], np.int32)
        a = np.asarray(m.generate(paddle.to_tensor(prompt),
                                  max_new_tokens=5, do_sample=True,
                                  top_k=8, seed=11)._value)
        b = np.asarray(m.generate(paddle.to_tensor(prompt),
                                  max_new_tokens=5, do_sample=True,
                                  top_k=8, seed=11)._value)
        c = np.asarray(m.generate(paddle.to_tensor(prompt),
                                  max_new_tokens=5, do_sample=True,
                                  top_k=8, seed=12)._value)
        np.testing.assert_array_equal(a, b)
        # this fixed model/seed pair is known to diverge; a broken seed
        # plumb (ignored seed arg) would make them equal
        assert not np.array_equal(a, c)


class TestCachedDecodeNumerics:
    def test_cached_logits_match_full_forward(self):
        """generate_step with the legacy tuple cache must agree with the
        uncached forward (end-aligned decode mask regression: the old
        path leaned on the fallback's end-aligned is_causal, which
        silently disagreed with the start-aligned flash kernel)."""
        m, cfg = _model(seed=4)
        rng = np.random.RandomState(1)
        seq = rng.randint(0, cfg.vocab_size, (1, 7)).astype(np.int32)

        full = np.asarray(m(paddle.to_tensor(seq))._value)

        # prefill on the first 4, then decode 3 tokens one at a time
        prefill, caches = m.generate_step(
            paddle.to_tensor(seq[:, :4]),
            [(jnp.zeros((1, 0, cfg.num_key_value_heads or 4,
                         cfg.hidden_size // cfg.num_attention_heads),
                        jnp.float32),) * 2
             for _ in range(cfg.num_hidden_layers)], 0)
        np.testing.assert_allclose(np.asarray(prefill._value),
                                   full[:, :4], rtol=1e-4, atol=1e-5)
        for t in range(4, 7):
            logits, caches = m.generate_step(
                paddle.to_tensor(seq[:, t:t + 1]), caches, t)
            np.testing.assert_allclose(
                np.asarray(logits._value)[:, 0], full[:, t],
                rtol=1e-4, atol=1e-5, err_msg="pos %d" % t)


class TestGPTGenerate:
    def test_greedy_matches_full_reforward(self):
        from paddle_tpu.models.gpt import GPTModel

        paddle.seed(5)
        m = GPTModel(vocab_size=64, hidden_size=32, num_layers=2,
                     num_heads=4, max_seq_len=64)
        rng = np.random.RandomState(2)
        prompt = rng.randint(0, 64, (2, 4)).astype(np.int32)
        got = np.asarray(m.generate(paddle.to_tensor(prompt),
                                    max_new_tokens=5)._value)
        assert got.shape == (2, 5)
        seq = prompt.copy()
        for t in range(5):
            logits = m(paddle.to_tensor(seq))
            nxt = np.argmax(np.asarray(logits._value)[:, -1, :], axis=-1)
            np.testing.assert_array_equal(got[:, t], nxt.astype(np.int32),
                                          err_msg="step %d" % t)
            seq = np.concatenate([seq, nxt[:, None].astype(np.int32)],
                                 axis=1)

    def test_generate_rejects_over_length(self):
        from paddle_tpu.models.gpt import GPTModel

        paddle.seed(6)
        m = GPTModel(vocab_size=32, hidden_size=16, num_layers=1,
                     num_heads=2, max_seq_len=8)
        prompt = np.zeros((1, 6), np.int32)
        with pytest.raises(ValueError, match="maximum sequence length"):
            m.generate(paddle.to_tensor(prompt), max_new_tokens=5)

    def test_gpt_block_rejects_legacy_tuple_cache(self):
        from paddle_tpu.models.gpt import GPTModel

        paddle.seed(7)
        m = GPTModel(vocab_size=32, hidden_size=16, num_layers=1,
                     num_heads=2, max_seq_len=16)
        bad = [(jnp.zeros((1, 0, 2, 8)), jnp.zeros((1, 0, 2, 8)))]
        with pytest.raises(TypeError, match="DecodeCache"):
            m.generate_step(paddle.to_tensor(np.zeros((1, 2), np.int32)),
                            bad, 0)

    def test_dropout_model_generates_clean_greedy(self):
        """generate() must run in eval mode: a train-mode dropout traced
        into the decode loop would corrupt logits (regression)."""
        from paddle_tpu.models.gpt import GPTModel

        paddle.seed(8)
        m = GPTModel(vocab_size=32, hidden_size=16, num_layers=1,
                     num_heads=2, max_seq_len=32, dropout=0.5)
        m.train()  # serving code often forgets eval(); generate handles it
        prompt = np.asarray([[3, 1, 4]], np.int32)
        got = np.asarray(m.generate(paddle.to_tensor(prompt),
                                    max_new_tokens=3)._value)
        assert m.training  # restored
        m.eval()
        seq = prompt.copy()
        for t in range(3):
            logits = m(paddle.to_tensor(seq))
            nxt = np.argmax(np.asarray(logits._value)[:, -1, :], axis=-1)
            np.testing.assert_array_equal(got[:, t], nxt.astype(np.int32))
            seq = np.concatenate([seq, nxt[:, None].astype(np.int32)],
                                 axis=1)

    def test_generate_jit_cache_reused(self):
        from paddle_tpu.models.gpt import GPTModel

        paddle.seed(9)
        m = GPTModel(vocab_size=32, hidden_size=16, num_layers=1,
                     num_heads=2, max_seq_len=32)
        p1 = paddle.to_tensor(np.asarray([[1, 2]], np.int32))
        m.generate(p1, max_new_tokens=2)
        assert len(m._generate_jit_cache) == 1
        m.generate(p1, max_new_tokens=2)  # same signature -> cache hit
        assert len(m._generate_jit_cache) == 1
        m.generate(p1, max_new_tokens=3)  # new signature
        assert len(m._generate_jit_cache) == 2


class TestBeamSearch:
    def _model(self):
        paddle.seed(13)
        cfg = LlamaConfig(vocab_size=32, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=4,
                          max_position_embeddings=64, use_parallel=False)
        return LlamaForCausalLM(cfg), cfg

    def test_single_step_beam_equals_greedy(self):
        """With max_new_tokens=1 the best beam IS the argmax token for
        any K — an exact invariant, not a seed accident."""
        m, cfg = self._model()
        prompt = paddle.to_tensor(
            np.random.RandomState(4).randint(0, 32, (2, 4)).astype(np.int32))
        greedy = np.asarray(m.generate(prompt, max_new_tokens=1)._value)
        for k in (2, 4):
            beam = np.asarray(m.generate(prompt, max_new_tokens=1,
                                         num_beams=k)._value)
            np.testing.assert_array_equal(beam, greedy)

    def test_beam_matches_exhaustive_oracle(self):
        """K >= vocab makes beam search EXACT over a 2-token horizon
        (every step-1 prefix survives): the returned pair must be the
        brute-force argmax over all vocab^2 continuations."""
        paddle.seed(17)
        cfg = LlamaConfig(vocab_size=8, hidden_size=16,
                          intermediate_size=32, num_hidden_layers=1,
                          num_attention_heads=2,
                          max_position_embeddings=32, use_parallel=False)
        m = LlamaForCausalLM(cfg)
        prompt = np.asarray([[3, 5]], np.int32)

        def logp_of(seq):
            logits = np.asarray(m(paddle.to_tensor(seq))._value)[0, -1]
            e = logits - logits.max()
            return e - np.log(np.exp(e).sum())

        best_score, best_pair = -np.inf, None
        lp1 = logp_of(prompt)
        for t1 in range(8):
            s1 = np.concatenate([prompt, [[t1]]], axis=1).astype(np.int32)
            lp2 = logp_of(s1)
            for t2 in range(8):
                sc = lp1[t1] + lp2[t2]
                if sc > best_score:
                    best_score, best_pair = sc, (t1, t2)

        out = np.asarray(m.generate(paddle.to_tensor(prompt),
                                    max_new_tokens=2, num_beams=8)._value)
        assert tuple(out[0]) == best_pair

    def test_beam_deterministic(self):
        m, cfg = self._model()
        prompt = paddle.to_tensor(np.asarray([[7, 3]], np.int32))
        a = np.asarray(m.generate(prompt, max_new_tokens=4,
                                  num_beams=4)._value)
        b = np.asarray(m.generate(prompt, max_new_tokens=4,
                                  num_beams=4)._value)
        np.testing.assert_array_equal(a, b)
        assert (a >= 0).all() and (a < cfg.vocab_size).all()

    def test_beam_eos_freezes(self):
        m, cfg = self._model()
        prompt = paddle.to_tensor(np.asarray([[1, 2, 3]], np.int32))
        first = int(np.asarray(m.generate(prompt, max_new_tokens=1,
                                          num_beams=3)._value)[0, 0])
        out = np.asarray(m.generate(prompt, max_new_tokens=5, num_beams=3,
                                    eos_token_id=first)._value)[0]
        assert out[0] == first
        np.testing.assert_array_equal(out, np.full(5, first))

    def test_sample_conflict_raises(self):
        m, cfg = self._model()
        with pytest.raises(ValueError, match="beam"):
            m.generate(paddle.to_tensor(np.zeros((1, 2), np.int32)),
                       num_beams=2, do_sample=True)

    def test_length_penalty_branch(self):
        """GNMT normalization path: alpha > 0 favors longer finished
        beams; the branch must at minimum trace, run, and stay within
        vocab (regression: path had zero coverage)."""
        m, cfg = self._model()
        prompt = paddle.to_tensor(np.asarray([[5, 9]], np.int32))
        for alpha in (0.6, -0.5):
            out = np.asarray(m.generate(prompt, max_new_tokens=4,
                                        num_beams=3, length_penalty=alpha,
                                        eos_token_id=0)._value)
            assert out.shape == (1, 4)
            assert (out >= 0).all() and (out < cfg.vocab_size).all()
        # alpha=0 must equal the unnormalized selection exactly
        a = np.asarray(m.generate(prompt, max_new_tokens=4,
                                  num_beams=3)._value)
        b = np.asarray(m.generate(prompt, max_new_tokens=4, num_beams=3,
                                  length_penalty=0.0)._value)
        np.testing.assert_array_equal(a, b)
