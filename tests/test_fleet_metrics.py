"""Distributed fleet metrics (reference fleet/metrics/metric.py over
framework/fleet/metrics.cc): per-trainer partials reduce to the global
metric. Single-process oracle tests + a 2-process run whose global AUC
must equal the single-process AUC over the union of the data."""
from __future__ import annotations

import os
import subprocess
import sys

import numpy as np

from dist_utils import free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bins(scores, labels, n=64):
    pos = np.zeros(n)
    neg = np.zeros(n)
    idx = np.clip((scores * n).astype(int), 0, n - 1)
    for i, y in zip(idx, labels):
        (pos if y else neg)[i] += 1
    return pos, neg


def _auc_oracle(scores, labels):
    order = np.argsort(-scores)
    y = np.asarray(labels)[order]
    tp = np.cumsum(y)
    fp = np.cumsum(1 - y)
    P, N = tp[-1], fp[-1]
    if P == 0 or N == 0:
        return 0.5
    # trapezoid over the ROC steps
    tpr = np.concatenate([[0], tp / P])
    fpr = np.concatenate([[0], fp / N])
    return float(np.trapezoid(tpr, fpr))


class TestSingleProcess:
    def test_auc_matches_rank_oracle(self):
        from paddle_tpu.distributed.fleet import metrics

        rng = np.random.RandomState(0)
        labels = rng.randint(0, 2, 512)
        scores = np.clip(labels * 0.35 + rng.rand(512) * 0.65, 0, 0.999)
        pos, neg = _bins(scores, labels, n=512)
        got = metrics.auc(pos, neg)
        want = _auc_oracle(scores, labels)
        assert abs(got - want) < 2e-2, (got, want)

    def test_degenerate_auc(self):
        from paddle_tpu.distributed.fleet import metrics

        assert metrics.auc(np.zeros(8), np.ones(8)) == 0.5

    def test_scalar_metrics(self):
        from paddle_tpu.distributed.fleet import metrics

        np.testing.assert_allclose(metrics.sum(np.arange(4.0)),
                                   np.arange(4.0))
        assert metrics.mae(np.array([6.0]), np.array([3.0])) == 2.0
        assert metrics.mse(np.array([12.0]), np.array([3.0])) == 4.0
        assert metrics.rmse(np.array([12.0]), np.array([3.0])) == 2.0
        assert metrics.acc(np.array([3.0]), np.array([4.0])) == 0.75


WORKER = r"""
import os, sys
sys.path.insert(0, %r)
import numpy as np
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.fleet import metrics

dist.init_parallel_env()
rank = dist.get_rank()
rng = np.random.RandomState(0)
labels = rng.randint(0, 2, 512)
scores = np.clip(labels * 0.35 + rng.rand(512) * 0.65, 0, 0.999)
half = slice(rank * 256, (rank + 1) * 256)          # disjoint shards
n = 512
pos = np.zeros(n); neg = np.zeros(n)
idx = np.clip((scores[half] * n).astype(int), 0, n - 1)
for i, y in zip(idx, labels[half]):
    (pos if y else neg)[i] += 1
print("AUC", metrics.auc(pos, neg))
print("ACC", metrics.acc(np.array([float((labels[half] == 1).sum())]),
                         np.array([256.0])))
""" % REPO


class TestTwoProcess:
    def test_global_auc_equals_union(self):
        from paddle_tpu.distributed.fleet import metrics

        port = free_port()
        procs = []
        for rank in range(2):
            env = dict(os.environ)
            env.update({
                "JAX_PLATFORMS": "cpu",
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": "2",
                "PADDLE_MASTER": "127.0.0.1:%d" % port,
            })
            env.pop("PALLAS_AXON_POOL_IPS", None)
            procs.append(subprocess.Popen(
                [sys.executable, "-c", WORKER], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
        outs = []
        for p in procs:
            try:
                outs.append(p.communicate(timeout=180))
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
        for p, (o, e) in zip(procs, outs):
            assert p.returncode == 0, e[-2000:]
        aucs = [float(o.split("AUC ")[1].split()[0]) for o, _ in outs]
        # both ranks see the same GLOBAL metric...
        assert abs(aucs[0] - aucs[1]) < 1e-9
        # ...equal to the single-process metric over the full data
        rng = np.random.RandomState(0)
        labels = rng.randint(0, 2, 512)
        scores = np.clip(labels * 0.35 + rng.rand(512) * 0.65, 0, 0.999)
        pos, neg = _bins(scores, labels, n=512)
        assert abs(aucs[0] - metrics.auc(pos, neg)) < 1e-9
        # global accuracy is the pooled fraction
        accs = [float(o.split("ACC ")[1].split()[0]) for o, _ in outs]
        assert abs(accs[0] - (labels == 1).mean()) < 1e-9
