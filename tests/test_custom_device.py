"""Custom-device plugin path (VERDICT r2 #7; reference
phi/backends/custom/fake_cpu_device.h + custom_device_test.cc): register
a fake PJRT backend under its own platform name, point set_device at it,
and run a real train step on the plugged backend.

Runs in a subprocess: plugin registration must precede any jax backend
initialization (frozen at first use — same constraint as the reference's
dlopen-at-framework-init), and the pytest process has long since
initialized the CPU backend.
"""
from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import sys
sys.path.insert(0, %r)

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.place import CustomPlace, register_fake_cpu_device

# 1. register BEFORE first backend use (the plugin-discovery contract)
place = register_fake_cpu_device("fake_cpu")
assert isinstance(place, CustomPlace)
assert paddle.device.get_all_custom_device_type() == ["fake_cpu"]
assert paddle.device.is_compiled_with_custom_device("fake_cpu")

# 2. set_device resolves the plugged backend's own devices
p = paddle.device.set_device("fake_cpu:0")
assert p.device_type == "custom:fake_cpu", p.device_type
import jax
dev = p.jax_device()
assert dev in jax.devices("fake_cpu"), (dev, jax.devices("fake_cpu"))

# 3. one real train step entirely on the plugged backend
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

paddle.seed(0)
model = nn.Linear(4, 2)
model.to(device="fake_cpu:0")
for prm in model.parameters():
    assert list(prm._value.devices())[0] in jax.devices("fake_cpu")
opt = paddle.optimizer.SGD(learning_rate=0.1,
                           parameters=model.parameters())
x = paddle.to_tensor(
    np.random.RandomState(0).randn(8, 4).astype(np.float32)).to(
        device="fake_cpu:0")
y = paddle.to_tensor(
    np.random.RandomState(1).randn(8, 2).astype(np.float32)).to(
        device="fake_cpu:0")
losses = []
for _ in range(5):
    loss = F.mse_loss(model(x), y)
    loss.backward()
    opt.step()
    opt.clear_grad()
    losses.append(float(loss))
assert losses[-1] < losses[0], losses
for prm in model.parameters():
    assert list(prm._value.devices())[0] in jax.devices("fake_cpu")
print("CUSTOM_DEVICE_OK", losses[0], losses[-1])
""" % REPO


def test_fake_pjrt_device_runs_train_step():
    env = {k: v for k, v in os.environ.items()
           if k not in ("PYTHONPATH", "PALLAS_AXON_POOL_IPS",
                        "PALLAS_AXON_REMOTE_COMPILE",
                        "AXON_LOOPBACK_RELAY")}
    # allow both the default cpu platform and the plugged one
    env["JAX_PLATFORMS"] = "cpu,fake_cpu"
    proc = subprocess.run([sys.executable, "-c", WORKER], cwd=REPO,
                          env=env, capture_output=True, text=True,
                          timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "CUSTOM_DEVICE_OK" in proc.stdout, proc.stdout


def test_register_after_init_raises():
    import pytest

    from paddle_tpu.core.place import register_custom_device_factory

    # this pytest process initialized jax long ago: registration must
    # refuse loudly instead of silently never taking effect
    import jax

    jax.devices()
    with pytest.raises(RuntimeError, match="after the JAX runtime"):
        register_custom_device_factory("late_dev", lambda: None)
