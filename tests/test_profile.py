"""paddle_tpu.monitor.profile — the ISSUE-13 continuous profiling plane.

Covers the acceptance surface:
- hard disabled-path pinning (PR-2/5/6/12 style): `FLAGS_monitor_profile`
  off ⇒ engines latch `step_hook()` = None, zero daemon threads, zero
  native calls from the plane's entry points, zero `profile_*` registry
  series, both debugz routes report disabled (route matrix in
  tests/test_debugz_routes.py);
- sampler overhead bound: at the default `PT_PROFILE_HZ` the sampler's
  self-time stays under 1% of wall on a busy process;
- folded-stack component attribution on a synthetic workload: a hot
  function whose name matches the `tokenize` component dominates the
  folded profile and the component shares;
- anomaly-triggered capture: a forced throughput-cliff sentinel run
  arms a one-shot window, the next hot steps produce a
  `profile_capture_<ts>/` artifact (manifest + folded host stacks whose
  component attribution names the synthetic hot component), and the
  cooldown defers — never drops — a second trigger;
- measured phase reconciliation: `profile_dispatch_seconds` /
  `profile_host_blocked_seconds` / `profile_host_gap_seconds` publish
  per hot step, mirror into /debugz/perf job rows, and
  tools/perf_report.py renders the measured-vs-analytic diff without
  fabricating an absent side;
- the profiler Xprof session guard: ptprof and a manual Profiler can
  never double-start_trace, and an owner cannot stop a window it did
  not start;
- watchdog bundles embed the sampler's time-weighted `profile_folded`;
- tools/profile_snapshot.py: --once CLI smoke + the bench.py stale
  re-emit discipline.
"""
from __future__ import annotations

import importlib.util
import io
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor, serving
from paddle_tpu.monitor import perf
from paddle_tpu.monitor import profile as pprof
from paddle_tpu.monitor import registry as mreg
from paddle_tpu.monitor import timeseries as ts

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROFILE_SERIES = ("profile_dispatch_seconds",
                  "profile_host_blocked_seconds",
                  "profile_host_gap_seconds",
                  "profile_samples_total",
                  "profile_captures_total")


@pytest.fixture(autouse=True)
def _prof_clean():
    """Every test starts and ends with the profiling plane at its
    default (off), no sampler thread, no capture state — later suites
    must see a pristine monitor."""
    _reset()
    yield
    _reset()


def _reset():
    from paddle_tpu.monitor import memory as ptmem
    from paddle_tpu.resilience import faultinject as fi

    fi.disable()
    fi._state.rules = []
    # drop fault-counter samples this suite's injections created (the
    # resilience suite pins the counter sample-free on its disabled
    # path, and counters are process-global — the test_memory hygiene)
    m = mreg.get_registry().get("faults_injected_total")
    if m is not None:
        for key in list(m._children):
            m.remove(*key)
    paddle.set_flags({"FLAGS_monitor_profile": False,
                      "FLAGS_monitor_memory": False,
                      "FLAGS_perf_attribution": False,
                      "FLAGS_perf_sentinels": False,
                      "FLAGS_monitor_timeseries": False})
    ptmem.reset()
    pprof.reset()
    perf.disable_sentinels()
    perf.reset()
    ts.disable()
    ts.clear()
    mreg.enable(trace_bridge=False)
    import paddle_tpu.profiler as ptprofiler

    with ptprofiler._xprof_lock:
        ptprofiler._xprof_owner = None


def _tiny_step():
    import paddle_tpu.nn.functional as F
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.parallel.engine import CompiledTrainStep

    paddle.seed(0)
    cfg = LlamaConfig.tiny(use_parallel=False)
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())

    def loss_fn(logits, labels):
        return F.cross_entropy(
            logits.reshape([-1, cfg.vocab_size]),
            labels.reshape([-1]))

    step = CompiledTrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(
        0, cfg.vocab_size, (8, 16)).astype(np.int32))
    labels = paddle.to_tensor(rng.randint(
        0, cfg.vocab_size, (8, 16)).astype(np.int32))
    return step, ids, labels


def _tiny_engine(**kw):
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=4,
                      max_position_embeddings=64, use_parallel=False)
    model = LlamaForCausalLM(cfg)
    return serving.Engine(model, **kw)


def _tokenizer_synthetic_hot(stop):
    """The synthetic hot component: the function NAME matches the
    `tokenize` attribution pattern, so samples landing here must be
    attributed to that component. The loop yields the GIL regularly —
    a pure spin can starve the sampler thread for seconds (CPython
    convoy effect) and flake the timing-based assertions; a sample
    taken mid-sleep still attributes here (time.sleep is C — this
    frame stays the Python leaf)."""
    x = 0
    while not stop.is_set():
        for _ in range(512):
            x = (x * 31 + 7) % 1000003
        time.sleep(0.0005)
    return x


def _run_hot_thread():
    stop = threading.Event()
    t = threading.Thread(target=_tokenizer_synthetic_hot, args=(stop,),
                         name="t-prof-hot", daemon=True)
    t.start()
    return stop, t


# ---------------------------------------------------------------------------
# disabled-path pinning (PR-2/5/6/12 style)
# ---------------------------------------------------------------------------

class TestDisabledPathPinning:
    def test_flag_default_off(self):
        assert not paddle.get_flags(
            ["FLAGS_monitor_profile"])["FLAGS_monitor_profile"]
        assert not pprof.is_enabled()

    def test_off_zero_native_zero_threads_zero_series(self, monkeypatch):
        from paddle_tpu.core import native

        with monkeypatch.context() as m:
            m.setattr(native, "get_lib", lambda: pytest.fail(
                "disabled profile plane touched native lib"))
            assert pprof.step_hook("t_off") is None
            assert pprof.start_sampler() is None
            assert pprof.arm_capture(reason="t_off") is False
            assert pprof.capture_window(steps=2) is False
            p = pprof.profile_payload()
            assert p["enabled"] is False and p["sampler"] is None
            assert "ptprof disabled" in pprof.folded_route_text()
            assert pprof.bundle_payload() is None
        threads_before = set(threading.enumerate())
        step, ids, labels = _tiny_step()
        assert step._prof is None
        step(ids, labels)
        eng = _tiny_engine(max_slots=2, num_blocks=32, block_size=4)
        assert eng._prof is None
        r = eng.add_request([1, 2, 3], max_new_tokens=2)
        eng.run()
        assert eng.request_status(r)["state"] == "finished"
        for name in PROFILE_SERIES:
            metric = mreg.get_registry().get(name)
            assert metric is None or list(metric.collect()) == [], name
        assert set(threading.enumerate()) == threads_before
        assert not pprof.sampler_running()
        assert pprof._state.pending == [] and pprof._state.window is None

    def test_on_anomaly_noop_while_off(self):
        assert pprof.on_anomaly("throughput_regression") is False
        assert pprof.on_stall() is False
        assert pprof.on_straggler([1]) is False
        assert pprof._state.pending == []


# ---------------------------------------------------------------------------
# sampler: overhead bound + component attribution
# ---------------------------------------------------------------------------

class TestSampler:
    def test_overhead_bound_at_default_hz(self):
        """THE overhead pin: at the default PT_PROFILE_HZ the sampler's
        own work stays under 1% of wall on a busy process."""
        paddle.set_flags({"FLAGS_monitor_profile": True})
        assert pprof._state.hz == pytest.approx(19.0)
        pprof.start_sampler()
        stop, t = _run_hot_thread()
        try:
            t0 = time.monotonic()
            with pprof._state.lock:
                base_self = pprof._state.self_time_s
                base_n = pprof._state.samples
            while time.monotonic() - t0 < 1.2:
                time.sleep(0.02)
            elapsed = time.monotonic() - t0
        finally:
            stop.set()
            t.join(timeout=5)
        with pprof._state.lock:
            self_dt = pprof._state.self_time_s - base_self
            n = pprof._state.samples - base_n
        assert n >= 5, n            # the sampler actually ran
        assert self_dt < 0.01 * elapsed, (self_dt, elapsed)
        payload = pprof.profile_payload()
        assert payload["sampler"]["overhead_share"] < 0.01

    def test_component_attribution_synthetic_workload(self):
        """A hot function whose name matches the tokenize pattern
        dominates the folded profile; the folded text carries the
        function name; counts land under the right component."""
        paddle.set_flags({"FLAGS_monitor_profile": True})
        pprof.start_sampler(hz=200)
        stop, t = _run_hot_thread()
        try:
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                comps = pprof.component_totals()
                if comps.get("tokenize", {}).get("samples", 0) >= 10:
                    break
                time.sleep(0.02)
        finally:
            stop.set()
            t.join(timeout=5)
        comps = pprof.component_totals()
        assert comps.get("tokenize", {}).get("samples", 0) >= 10, comps
        folded = pprof.folded_text()
        assert "_tokenizer_synthetic_hot" in folded
        # the hot thread's folded key leads with the thread name
        hot = [line for line in folded.splitlines()
               if line.startswith("t-prof-hot;")]
        assert hot, folded
        top = pprof.profile_payload()["top"]
        hot_rows = [r for r in top if r["component"] == "tokenize"]
        assert hot_rows and hot_rows[0]["count"] >= 10

    def test_stack_table_bounded(self):
        """Distinct-stack growth is capped: past PT_PROFILE_MAX_STACKS
        new stacks collapse into the overflow counter instead of
        growing without bound."""
        paddle.set_flags({"FLAGS_monitor_profile": True})
        with pprof._state.lock:
            pprof._state.max_stacks = 4
        pprof.start_sampler(hz=500)
        # churn distinct stacks by running distinct code objects
        fns = []
        ns = {}
        for i in range(8):
            exec("def _burn_%d(stop):\n"
                 "    x = 0\n"
                 "    while not stop.is_set():\n"
                 "        x = (x + %d) %% 99991\n" % (i, i + 1), ns)
            fns.append(ns["_burn_%d" % i])
        stop = threading.Event()
        threads = [threading.Thread(target=f, args=(stop,), daemon=True)
                   for f in fns]
        for t in threads:
            t.start()
        try:
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                with pprof._state.lock:
                    if pprof._state.overflow > 0:
                        break
                time.sleep(0.02)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)
        with pprof._state.lock:
            # cap + the bounded per-component overflow buckets
            real = [k for k in pprof._state.stacks
                    if not k.startswith("(overflow);")]
            assert len(real) <= 4
            assert len(pprof._state.stacks) <= \
                4 + len(pprof.COMPONENT_PATTERNS) + 1
            assert pprof._state.overflow > 0
            # saturated samples kept their component attribution
            assert any(k.startswith("(overflow);")
                       for k in pprof._state.stacks)


# ---------------------------------------------------------------------------
# measured phase reconciliation
# ---------------------------------------------------------------------------

class TestMeasuredPhases:
    def test_step_profiler_gauges_and_note_job_mirror(self):
        paddle.set_flags({"FLAGS_monitor_profile": True})
        sp = pprof.step_hook("t_job")
        assert sp is not None
        t0 = 100.0
        sp.step_begin()
        out = sp.step_end(t0, t0 + 0.5)
        assert out["dispatch_s"] == pytest.approx(0.5)
        assert out["gap_s"] == 0.0
        sp.step_begin()
        out = sp.step_end(t0 + 0.7, t0 + 0.8)
        assert out["gap_s"] == pytest.approx(0.2)   # 0.7 - prev end 0.5
        g = mreg.get_registry().get("profile_dispatch_seconds")
        assert dict(g.collect())[("t_job",)] == pytest.approx(0.1)
        g = mreg.get_registry().get("profile_host_gap_seconds")
        assert dict(g.collect())[("t_job",)] == pytest.approx(0.2)
        # mirrored into the /debugz/perf job row for perf_report
        row = perf.perf_payload()["jobs"]["t_job"]
        assert row["profile_dispatch_seconds"] == pytest.approx(0.1)
        assert row["profile_host_gap_seconds"] == pytest.approx(0.2)
        sp.note_phase("prefill", 0.05)
        sp.note_phase("prefill", 0.05)
        tot = pprof.job_totals()["t_job"]
        assert tot["steps"] == 2
        assert tot["phases"]["prefill"] == pytest.approx(0.1)

    def test_train_step_publishes_measured_split(self):
        paddle.set_flags({"FLAGS_monitor_profile": True})
        step, ids, labels = _tiny_step()
        assert step._prof is not None
        step(ids, labels)
        step(ids, labels)
        tot = pprof.job_totals()["train"]
        assert tot["steps"] == 2
        assert tot["dispatch_s"] > 0
        row = perf.perf_payload()["jobs"]["train"]
        for k in ("profile_dispatch_seconds",
                  "profile_host_blocked_seconds",
                  "profile_host_gap_seconds"):
            assert isinstance(row[k], float), k

    def test_serving_step_publishes_phases(self):
        paddle.set_flags({"FLAGS_monitor_profile": True})
        eng = _tiny_engine(max_slots=2, num_blocks=64, block_size=4)
        assert eng._prof is not None
        eng.add_request([1, 2, 3, 4], max_new_tokens=4)
        eng.run()
        tot = pprof.job_totals()["serving"]
        assert tot["steps"] >= 1
        assert tot["phases"].get("prefill", 0) > 0
        assert tot["phases"].get("decode", 0) > 0

    def test_perf_report_measured_vs_analytic_no_fabrication(self):
        spec = importlib.util.spec_from_file_location(
            "t_perf_report", os.path.join(REPO, "tools",
                                          "perf_report.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        both = {"jobs": {"train": {
            "phase_seconds": {"compute": 0.8, "comm": 0.1,
                              "host": 0.05},
            "comm_source": "analytic",
            "profile_dispatch_seconds": 0.7,
            "profile_host_blocked_seconds": 0.25,
            "profile_host_gap_seconds": 0.06,
        }}}
        buf = io.StringIO()
        mod.render_measured(both, buf)
        text = buf.getvalue()
        assert "exposed-comm residual" in text
        assert "delta" in text
        # measured only: the analytic side is ABSENT, not zero
        meas_only = {"jobs": {"train": {
            "profile_dispatch_seconds": 0.7,
            "profile_host_blocked_seconds": 0.25,
            "profile_host_gap_seconds": 0.06}}}
        buf = io.StringIO()
        mod.render_measured(meas_only, buf)
        assert "no diff fabricated" in buf.getvalue()
        assert "residual" not in buf.getvalue()
        # analytic only: the measured side is ABSENT, not zero
        analytic_only = {"jobs": {"train": {
            "phase_seconds": {"compute": 0.8, "comm": 0.1,
                              "host": 0.05}}}}
        buf = io.StringIO()
        mod.render_measured(analytic_only, buf)
        assert "no diff fabricated" in buf.getvalue()


# ---------------------------------------------------------------------------
# anomaly-triggered capture windows
# ---------------------------------------------------------------------------

class TestCaptureWindows:
    def test_throughput_cliff_arms_and_captures(self, monkeypatch,
                                                tmp_path):
        """THE acceptance path: a forced throughput-cliff sentinel run
        arms a capture window; the next hot steps finalize it into a
        profile_capture_<ts>/ artifact whose folded host stacks name
        the synthetic hot component; a second trigger inside the
        cooldown is deferred, never dropped."""
        monkeypatch.setenv("PT_MONITOR_DUMP_DIR", str(tmp_path))
        monkeypatch.setenv("PT_PROFILE_CAPTURE_STEPS", "2")
        paddle.set_flags({"FLAGS_monitor_profile": True})
        pprof.start_sampler(hz=200)
        pprof._state.cooldown_s = 3600.0
        perf.enable_sentinels()
        # compile OUTSIDE the window: the capture must be of the
        # anomalous steady-state steps, not a trace-time churn blob
        step, ids, labels = _tiny_step()
        step(ids, labels)
        # drop the compile-churn stacks so the (bounded) table has
        # room for the synthetic hot component's exact stack
        with pprof._state.lock:
            pprof._state.stacks = {}
            pprof._state.overflow = 0
        stop, t = _run_hot_thread()
        time.sleep(0.15)    # the hot thread's stack registers
        try:
            # synthetic throughput trace: healthy warmup, then the cliff
            for _ in range(12):
                ts.record("train_tokens_per_s", 100.0)
            ts.record("train_tokens_per_s", 1.0)
            counts = perf.anomaly_summary()["counts"]
            assert counts.get("throughput_regression", 0) >= 1
            assert len(pprof._state.pending) == 1
            assert pprof._state.pending[0]["reason"] == \
                "sentinel:throughput_regression"

            step(ids, labels)           # window opens on this step
            assert pprof._state.window is not None
            time.sleep(0.4)             # sampler sees the hot thread
            step(ids, labels)           # window closes (2 steps)
        finally:
            stop.set()
            t.join(timeout=5)
        assert pprof._state.window is None
        caps = pprof.profile_payload()["captures"]
        assert len(caps) == 1
        d = caps[0]["dir"]
        assert caps[0]["reason"] == "sentinel:throughput_regression"
        assert os.path.isdir(d) and d.startswith(str(tmp_path))
        with open(os.path.join(d, "manifest.json")) as f:
            manifest = json.load(f)
        assert manifest["kind"] == "profile_capture"
        assert manifest["steps"] == 2
        assert "train" in manifest["jobs"]
        # contents pinned: the component attribution of the window's
        # folded stacks names the synthetic hot component
        assert manifest["components"].get(
            "tokenize", {}).get("samples", 0) > 0, manifest["components"]
        with open(os.path.join(d, "folded_rank0.txt")) as f:
            folded = f.read()
        assert "_tokenizer_synthetic_hot" in folded
        c = mreg.get_registry().get("profile_captures_total")
        assert dict(c.collect())[
            ("sentinel:throughput_regression",)] == 1

        # cooldown pinned: a fresh trigger queues (defer-not-drop) and
        # does NOT open a window while the cooldown holds...
        assert pprof.arm_capture(reason="second")
        step(ids, labels)
        assert pprof._state.window is None
        assert len(pprof._state.pending) == 1
        # ...and fires as soon as the cooldown expires (host-only: the
        # ONE real Xprof window above already proved the device path)
        monkeypatch.setattr(pprof, "_xprof_begin",
                            lambda d: (False, "patched out"))
        pprof._state.last_capture_end = time.monotonic() - 7200.0
        step(ids, labels)
        assert pprof._state.window is not None \
            or len(pprof.profile_payload()["captures"]) == 2

    def test_anomaly_kind_filter(self):
        """Only profile-shaped sentinel kinds arm a window: a NaN loss
        has no timeline to capture, a cliff and a leak do."""
        paddle.set_flags({"FLAGS_monitor_profile": True})
        assert pprof.on_anomaly("nan_loss") is False
        assert pprof._state.pending == []
        assert pprof.on_anomaly("throughput_regression") is True
        assert pprof.on_anomaly("mem_leak") is True
        assert len(pprof._state.pending) == 2

    def test_max_captures_cap(self, monkeypatch, tmp_path):
        monkeypatch.setenv("PT_MONITOR_DUMP_DIR", str(tmp_path))
        paddle.set_flags({"FLAGS_monitor_profile": True})
        monkeypatch.setattr(pprof, "_xprof_begin",
                            lambda d: (False, "patched out"))
        pprof._state.cooldown_s = 0.0
        pprof._state.max_captures = 1
        sp = pprof.step_hook("t_job")
        for i in range(2):
            pprof.arm_capture(steps=1, reason="cap%d" % i)
            sp.step_begin()
            sp.step_end(float(i), float(i) + 0.01)
        assert len(pprof.profile_payload()["captures"]) == 1
        # past the cap the queue is drained, not grown forever
        assert pprof._state.pending == []

    def test_exception_mid_window_aborts_not_leaks(self, monkeypatch,
                                                   tmp_path):
        """A hot step raising mid-window (the reviewer's OOM scenario:
        the postmortem path re-raises) must CLOSE the window — partial
        artifact lands marked aborted, the one-window state clears, and
        the Xprof session owner is released, never leaked."""
        from paddle_tpu.resilience import faultinject as fi

        monkeypatch.setenv("PT_MONITOR_DUMP_DIR", str(tmp_path))
        paddle.set_flags({"FLAGS_monitor_profile": True,
                          "FLAGS_monitor_memory": True})
        monkeypatch.setattr(pprof, "_xprof_begin",
                            lambda d: (False, "patched out"))
        pprof._state.cooldown_s = 0.0
        eng = _tiny_engine(max_slots=2, num_blocks=64, block_size=4)
        eng.add_request([1, 2, 3], max_new_tokens=4)
        assert eng.step()                   # healthy step first
        pprof.arm_capture(steps=8, reason="pre_crash")
        fi.enable("mem.oom:error@1", seed=0)
        with pytest.raises(fi.InjectedFault):
            eng.step()
        assert pprof._state.window is None
        assert pprof._state.pending == []
        caps = pprof.profile_payload()["captures"]
        assert len(caps) == 1 and caps[0]["aborted"]
        with open(os.path.join(caps[0]["dir"], "manifest.json")) as f:
            manifest = json.load(f)
        assert manifest["aborted"] and "serving" in manifest["aborted"]
        import paddle_tpu.profiler as ptprofiler
        assert ptprofiler.xprof_session_owner() is None

    def test_stall_and_straggler_hooks_arm(self):
        paddle.set_flags({"FLAGS_monitor_profile": True})
        assert pprof.on_stall([{"heartbeat": "train_step",
                                "phase": "train.step",
                                "age_s": 61.0}]) is True
        assert pprof.on_straggler([2]) is True
        reasons = [p["reason"] for p in pprof._state.pending]
        assert reasons == ["watchdog_stall", "straggler"]
        assert pprof._state.pending[0]["detail"]["stalls"][0][
            "heartbeat"] == "train_step"


# ---------------------------------------------------------------------------
# Xprof session guard (the satellite on paddle_tpu/profiler)
# ---------------------------------------------------------------------------

class TestXprofSessionGuard:
    def test_busy_path_never_double_starts(self):
        import paddle_tpu.profiler as ptprofiler

        # claim the session by hand: a second owner's begin answers
        # False on the BUSY path without ever importing/starting jax
        with ptprofiler._xprof_lock:
            ptprofiler._xprof_owner = "manual"
        try:
            assert ptprofiler.xprof_session_begin(
                "ptprof", "/nonexistent") is False
            assert ptprofiler.xprof_session_owner() == "manual"
            # an owner cannot stop a window it did not start
            assert ptprofiler.xprof_session_end("ptprof") is False
            assert ptprofiler.xprof_session_owner() == "manual"
            # the holder can
            # (stop_trace itself may warn-once — that is the narrowed,
            # routed failure path, not a swallow)
            ptprofiler.xprof_session_end("manual")
            assert ptprofiler.xprof_session_owner() is None
        finally:
            with ptprofiler._xprof_lock:
                ptprofiler._xprof_owner = None

    def test_capture_degrades_host_only_when_session_busy(
            self, monkeypatch, tmp_path):
        """A manual profiler holding the Xprof session degrades a
        ptprof window to host-only — a capture still lands."""
        import paddle_tpu.profiler as ptprofiler

        monkeypatch.setenv("PT_MONITOR_DUMP_DIR", str(tmp_path))
        paddle.set_flags({"FLAGS_monitor_profile": True})
        pprof._state.cooldown_s = 0.0
        with ptprofiler._xprof_lock:
            ptprofiler._xprof_owner = "manual"
        try:
            mreg._warned.discard("profile.xprof_begin")
            sp = pprof.step_hook("t_job")
            pprof.arm_capture(steps=1, reason="busy_test")
            sp.step_begin()
            sp.step_end(0.0, 0.01)
        finally:
            with ptprofiler._xprof_lock:
                ptprofiler._xprof_owner = None
        caps = pprof.profile_payload()["captures"]
        assert len(caps) == 1 and caps[0]["xprof"] is False
        with open(os.path.join(caps[0]["dir"], "manifest.json")) as f:
            manifest = json.load(f)
        assert manifest["xprof"] is False
        assert "session held" in (manifest["xprof_error"] or "")


# ---------------------------------------------------------------------------
# surfacing: watchdog bundle + perf payload
# ---------------------------------------------------------------------------

class TestSurfacing:
    def test_watchdog_bundle_embeds_profile_folded(self):
        paddle.set_flags({"FLAGS_monitor_profile": True})
        pprof.start_sampler(hz=200)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with pprof._state.lock:
                if pprof._state.samples >= 3:
                    break
            time.sleep(0.02)
        bundle = monitor.build_bundle(reason="test")
        prof = bundle["profile_folded"]
        assert prof is not None
        assert prof["samples"] >= 3
        assert prof["folded"]
        assert "components" in prof

    def test_watchdog_bundle_profile_none_when_off(self):
        bundle = monitor.build_bundle(reason="test")
        assert bundle["profile_folded"] is None


# ---------------------------------------------------------------------------
# tools/profile_snapshot.py (battery row artifact)
# ---------------------------------------------------------------------------

def _load_snapshot_mod():
    spec = importlib.util.spec_from_file_location(
        "t_profile_snapshot", os.path.join(REPO, "tools",
                                           "profile_snapshot.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestProfileSnapshotTool:
    def test_stale_reemit_discipline(self, tmp_path):
        mod = _load_snapshot_mod()
        out = str(tmp_path / "profile_snapshot.json")
        fresh = {"kind": "profile_snapshot", "version": 1, "ok": True,
                 "written_at": "2026-08-03T00:00:00Z",
                 "profile": {"enabled": True}}
        mod.write_artifact(out, fresh)
        got = mod.write_artifact(out, None, stale_reason="child died")
        assert got["stale"] is True
        assert got["stale_generations"] == 1
        assert got["stale_since"] == "2026-08-03T00:00:00Z"
        assert got["profile"] == {"enabled": True}
        got = mod.write_artifact(out, None, stale_reason="still dead")
        assert got["stale_generations"] == 2
        with open(out) as f:
            assert json.load(f)["stale_generations"] == 2

    def test_no_previous_artifact_writes_not_ok(self, tmp_path):
        mod = _load_snapshot_mod()
        out = str(tmp_path / "profile_snapshot.json")
        got = mod.write_artifact(out, None, stale_reason="boom")
        assert got["ok"] is False and got["error"] == "boom"

    def test_cli_once_commits(self, tmp_path):
        """The --once spelling end-to-end: a fresh ok artifact with a
        live sampler summary, no train smoke paid."""
        out = str(tmp_path / "profile_snapshot.json")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO + os.pathsep +
                   os.environ.get("PYTHONPATH", ""))
        env.pop("PALLAS_AXON_POOL_IPS", None)
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "profile_snapshot.py"),
             "--once", "--window", "0.5", "--out", out],
            capture_output=True, text=True, env=env, timeout=540)
        assert r.returncode == 0, r.stdout + r.stderr
        with open(out) as f:
            snap = json.load(f)
        assert snap["ok"] is True and not snap.get("stale")
        assert snap["mode"] == "once"
        prof = snap["profile"]
        assert prof["enabled"] is True
        assert prof["sampler"]["samples"] >= 1
        assert prof["sampler"]["overhead_share"] < 0.01
