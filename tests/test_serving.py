"""Serving engine: continuous batching over a paged KV cache.

Oracle: ``GenerationMixin.generate`` greedy output for the same prompts
— the engine must reproduce it token-for-token under continuous
batching with slot reuse, mid-flight arrivals, and preemption.
Kernel oracle: ``masked_decode_attention`` (the dense decode path) —
the ragged paged-attention kernel gathers the same history through the
block table and must match to fp32 tolerance in interpret mode.
"""
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import serving
from paddle_tpu.models.generation import decode_mask, masked_decode_attention
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving.kernels.paged_attention import (
    paged_attention_kernel,
    paged_attention_reference,
)
from paddle_tpu.serving.kv_cache import BlockAllocator, PagedKVCache


@pytest.fixture(scope="module")
def llama():
    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      max_position_embeddings=64, use_parallel=False)
    return LlamaForCausalLM(cfg), cfg


def _greedy_ref(model, prompt, max_new_tokens, eos_token_id=None):
    """generate()'s greedy tokens, truncated at the first eos inclusive
    (the engine stops emitting after eos; generate eos-pads instead)."""
    out = model.generate(
        paddle.to_tensor(np.asarray([prompt], np.int32)),
        max_new_tokens=max_new_tokens, eos_token_id=eos_token_id)
    toks = np.asarray(out._value)[0].tolist()
    if eos_token_id is not None and eos_token_id in toks:
        toks = toks[:toks.index(eos_token_id) + 1]
    return toks


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------

class TestPagedAttentionKernel:
    def _random_paged(self, rng, s, h, hkv, d, bs, nb, mb, lens):
        """Scatter per-slot histories into pool pages; returns
        (q, k_pool, v_pool, block_tables, dense_k, dense_v)."""
        q = jnp.asarray(rng.randn(s, h, d), jnp.float32)
        kp = np.zeros((nb, bs, hkv, d), np.float32)
        vp = np.zeros((nb, bs, hkv, d), np.float32)
        bt = np.zeros((s, mb), np.int32)
        alloc = BlockAllocator(nb)
        max_len = mb * bs
        dk = np.zeros((s, max_len, hkv, d), np.float32)
        dv = np.zeros((s, max_len, hkv, d), np.float32)
        for i in range(s):
            L = lens[i]
            pages = alloc.alloc(-(-L // bs)) if L else []
            bt[i, :len(pages)] = pages
            hist_k = rng.randn(L, hkv, d).astype(np.float32)
            hist_v = rng.randn(L, hkv, d).astype(np.float32)
            dk[i, :L], dv[i, :L] = hist_k, hist_v
            for pos in range(L):
                kp[pages[pos // bs], pos % bs] = hist_k[pos]
                vp[pages[pos // bs], pos % bs] = hist_v[pos]
        return (q, jnp.asarray(kp), jnp.asarray(vp), bt,
                jnp.asarray(dk), jnp.asarray(dv))

    def test_parity_vs_masked_decode_attention(self):
        """Acceptance pin: interpret-mode Pallas kernel vs the dense
        decode path generation.py uses, <= 1e-5 fp32."""
        rng = np.random.RandomState(0)
        s, h, d, bs, nb, mb = 4, 4, 16, 4, 32, 8
        lens = [13, 32, 1, 7]
        q, kp, vp, bt, dk, dv = self._random_paged(
            rng, s, h, h, d, bs, nb, mb, lens)
        got = np.asarray(paged_attention_kernel(
            q, kp, vp, bt, np.asarray(lens, np.int32), interpret=True))
        for i in range(s):
            L = lens[i]
            # dense oracle: q is the token AT position L-1 over a cache
            # holding positions 0..L-1
            ref = masked_decode_attention(
                q[i][None, None], dk[i][None], dv[i][None],
                decode_mask(L - 1, 1, dk.shape[1]))
            ref = np.asarray(ref._value if hasattr(ref, "_value") else ref)
            np.testing.assert_allclose(got[i], ref[0, 0], atol=1e-5,
                                       err_msg="slot %d" % i)

    def test_kernel_matches_reference_gqa(self):
        """Pallas interpret vs the jnp gather fallback under GQA
        (pool stores 2 kv heads, q has 8)."""
        rng = np.random.RandomState(1)
        s, h, hkv, d, bs, nb, mb = 3, 8, 2, 16, 8, 16, 4
        lens = [9, 16, 3]
        q, kp, vp, bt, _, _ = self._random_paged(
            rng, s, h, hkv, d, bs, nb, mb, lens)
        a = np.asarray(paged_attention_kernel(
            q, kp, vp, bt, np.asarray(lens, np.int32), interpret=True))
        b = np.asarray(paged_attention_reference(
            q, kp, vp, bt, np.asarray(lens, np.int32)))
        np.testing.assert_allclose(a, b, atol=1e-5)

    def test_idle_slot_emits_finite_zero(self):
        """len-0 slots (idle) skip every page: output exactly 0 — and
        never NaN, which would poison the batched decode step."""
        rng = np.random.RandomState(2)
        q, kp, vp, bt, _, _ = self._random_paged(
            rng, 2, 4, 4, 16, 4, 8, 2, [5, 0])
        out = np.asarray(paged_attention_kernel(
            q, kp, vp, bt, np.asarray([5, 0], np.int32), interpret=True))
        assert np.isfinite(out).all()
        np.testing.assert_array_equal(out[1], 0.0)

    def test_trash_page_isolated(self):
        """Writes landing in page 0 (trash) must not change any live
        slot's attention output."""
        rng = np.random.RandomState(3)
        s, h, d, bs, nb, mb = 2, 4, 16, 4, 8, 2
        lens = [6, 4]
        q, kp, vp, bt, _, _ = self._random_paged(
            rng, s, h, h, d, bs, nb, mb, lens)
        base = np.asarray(paged_attention_kernel(
            q, kp, vp, bt, np.asarray(lens, np.int32), interpret=True))
        kp2 = kp.at[0].set(1e4)
        vp2 = vp.at[0].set(-1e4)
        noisy = np.asarray(paged_attention_kernel(
            q, kp2, vp2, bt, np.asarray(lens, np.int32), interpret=True))
        np.testing.assert_array_equal(base, noisy)


# ---------------------------------------------------------------------------
# engine vs generate parity
# ---------------------------------------------------------------------------

class TestEngineParity:
    def test_mixed_arrival_matches_generate(self, llama):
        """The acceptance workload: staggered prompt lengths, an early
        EOS, and arrivals mid-flight, through 2 slots with slot reuse —
        per-request tokens must exactly match generate()'s greedy output."""
        m, cfg = llama
        rng = np.random.RandomState(0)
        prompts = [rng.randint(0, cfg.vocab_size, (n,)).tolist()
                   for n in (5, 9, 3, 12, 7)]
        eng = serving.Engine(m, max_slots=2, num_blocks=64, block_size=4)
        # pick an eos that actually fires early for prompt[1]
        probe = _greedy_ref(m, prompts[1], 8)
        eos = probe[2]

        ids, plan = {}, []
        ids[0] = eng.add_request(prompts[0], max_new_tokens=6)
        ids[1] = eng.add_request(prompts[1], max_new_tokens=8,
                                 eos_token_id=eos)
        plan.append((0, 6, None))
        plan.append((1, 8, eos))
        eng.step()
        eng.step()
        # arrivals mid-flight, while slots are decoding
        ids[2] = eng.add_request(prompts[2], max_new_tokens=5)
        ids[3] = eng.add_request(prompts[3], max_new_tokens=4)
        plan.append((2, 5, None))
        plan.append((3, 4, None))
        eng.step()
        ids[4] = eng.add_request(prompts[4], max_new_tokens=6)
        plan.append((4, 6, None))
        while eng.step():
            pass

        for pi, mnt, e in plan:
            ref = _greedy_ref(m, prompts[pi], mnt, e)
            assert eng.output(ids[pi]) == ref, "request %d" % pi
        stats = eng.stats()
        assert stats["requests_finished"] == 5
        assert stats["decode_compiles"] == 1

    def test_slot_reuse_on_eos(self, llama):
        """More requests than slots: finished slots must be reclaimed
        (all requests complete) without growing the batch shape."""
        m, cfg = llama
        rng = np.random.RandomState(7)
        prompts = [rng.randint(0, cfg.vocab_size, (4 + i,)).tolist()
                   for i in range(6)]
        eng = serving.Engine(m, max_slots=2, num_blocks=64, block_size=4)
        ids = [eng.add_request(p, max_new_tokens=4) for p in prompts]
        outs = eng.run()
        for p, rid in zip(prompts, ids):
            assert outs[rid] == _greedy_ref(m, p, 4)
        assert eng.stats()["decode_compiles"] == 1


# ---------------------------------------------------------------------------
# edge cases (ISSUE satellite: exhaustion/preempt, zero-length, long
# prompt, compile-once under a staggered 20-request workload)
# ---------------------------------------------------------------------------

class TestServingEdgeCases:
    def test_preempt_requeue_bit_identical(self, llama):
        """Block-pool exhaustion preempts the youngest other request and
        requeues it by recompute — its final tokens must be bit-identical
        to an uncontended run."""
        m, cfg = llama
        rng = np.random.RandomState(1)
        prompts = [rng.randint(0, cfg.vocab_size, (n,)).tolist()
                   for n in (6, 8)]

        starved = serving.Engine(m, max_slots=2, num_blocks=7,
                                 block_size=4)
        sid = [starved.add_request(p, max_new_tokens=10) for p in prompts]
        souts = starved.run()
        assert starved.stats()["preemptions"] >= 1

        roomy = serving.Engine(m, max_slots=2, num_blocks=64, block_size=4)
        rid = [roomy.add_request(p, max_new_tokens=10) for p in prompts]
        routs = roomy.run()
        assert roomy.stats()["preemptions"] == 0

        for a, b in zip(sid, rid):
            assert souts[a] == routs[b]
        # the preempted request's metrics carry the count
        assert sum(starved.requests[i].metrics.preemptions
                   for i in sid) >= 1

    def test_zero_length_generation(self, llama):
        """max_new_tokens=0 finishes immediately: no slot, no pages, no
        decode step — but it still counts as finished."""
        m, _ = llama
        eng = serving.Engine(m, max_slots=2, num_blocks=16, block_size=4)
        rid = eng.add_request([1, 2, 3], max_new_tokens=0)
        assert not eng.has_work()
        assert eng.run() == {rid: []}
        assert eng.stats()["decode_steps"] == 0
        assert eng.stats()["requests_finished"] == 1
        assert eng.cache.allocator.free_blocks == 15  # nothing allocated

    def test_prefill_bucket_respects_block_table(self, llama):
        """Regression: with block_size < 8 and an unaligned
        max_model_len, the pow2 prefill bucket used to exceed
        ``MB * block_size`` — the pad scatter's clamped gather then
        overwrote the request's LAST REAL PAGE and decode silently
        diverged from generate()."""
        m, cfg = llama
        for seed in range(3):
            prompt = np.random.RandomState(seed).randint(
                0, cfg.vocab_size, (9,)).tolist()
            eng = serving.Engine(m, max_slots=1, num_blocks=16,
                                 block_size=4, max_model_len=11)
            assert (eng._bucket(9)
                    <= eng.cache.max_blocks_per_slot * eng.block_size)
            rid = eng.add_request(prompt, max_new_tokens=2)
            assert eng.run()[rid] == _greedy_ref(m, prompt, 2), seed

    def test_prompt_longer_than_block_size(self, llama):
        """A prompt spanning several pages prefills correctly (page
        boundaries inside the prompt)."""
        m, cfg = llama
        rng = np.random.RandomState(2)
        prompt = rng.randint(0, cfg.vocab_size, (11,)).tolist()  # 3 pages
        eng = serving.Engine(m, max_slots=1, num_blocks=16, block_size=4)
        rid = eng.add_request(prompt, max_new_tokens=5)
        assert eng.run()[rid] == _greedy_ref(m, prompt, 5)

    def test_oversized_request_rejected(self, llama):
        """A request that could never fit (pool or position table) is
        refused at add time, not deadlocked at schedule time."""
        m, _ = llama
        eng = serving.Engine(m, max_slots=1, num_blocks=4, block_size=4)
        with pytest.raises(ValueError):
            eng.add_request(list(range(10)), max_new_tokens=10)  # > pool
        eng2 = serving.Engine(m, max_slots=1, num_blocks=64, block_size=4)
        with pytest.raises(ValueError):
            eng2.add_request(list(range(40)), max_new_tokens=40)  # > 64 pos
        with pytest.raises(ValueError):
            eng2.add_request([], max_new_tokens=4)

    def test_compile_once_20_staggered_requests(self, llama):
        """jit-cache pin: a 20-request staggered workload (varying
        lengths, arrivals spread over the run) compiles the decode step
        EXACTLY once; prefill compiles once per length bucket."""
        m, cfg = llama
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, cfg.vocab_size,
                               (int(rng.randint(2, 14)),)).tolist()
                   for _ in range(20)]
        eng = serving.Engine(m, max_slots=4, num_blocks=64, block_size=4)
        it = iter(prompts)
        for p in [next(it) for _ in range(4)]:
            eng.add_request(p, max_new_tokens=int(rng.randint(2, 6)))
        pending = list(it)
        while eng.has_work() or pending:
            if pending:  # stagger: one arrival per engine step
                eng.add_request(pending.pop(0),
                                max_new_tokens=int(rng.randint(2, 6)))
            eng.step()
        stats = eng.stats()
        assert stats["requests_finished"] == 20
        assert stats["decode_compiles"] == 1, stats
        buckets = {eng._bucket(len(p)) for p in prompts}
        assert stats["prefill_compiles"] == len(buckets), stats

    def test_metrics_schema(self, llama):
        """Plain-dict metrics: per-request latency breakdown populated
        for a finished request; engine counters complete."""
        m, cfg = llama
        eng = serving.Engine(m, max_slots=1, num_blocks=16, block_size=4)
        rid = eng.add_request([3, 1, 4], max_new_tokens=4)
        eng.run()
        rm = eng.request_metrics(rid)
        assert set(rm) == {"queue_time_s", "ttft_s", "tpot_s", "e2e_s",
                           "prompt_tokens", "output_tokens", "preemptions",
                           "prefix_cached_tokens",
                           "prefix_cached_tokens_first"}
        assert rm["prompt_tokens"] == 3 and rm["output_tokens"] == 4
        for k in ("queue_time_s", "ttft_s", "tpot_s", "e2e_s"):
            assert rm[k] is not None and rm[k] >= 0
        es = eng.stats()
        for k in ("requests_in", "requests_finished", "preemptions",
                  "prefill_runs", "decode_steps", "output_tokens",
                  "decode_compiles", "prefill_compiles", "wall_s",
                  "throughput_tok_s", "slot_occupancy"):
            assert k in es
        assert es["requests_finished"] == 1
        assert 0 < es["slot_occupancy"] <= 1


# ---------------------------------------------------------------------------
# the external-cache hook on a second architecture (learned positions)
# ---------------------------------------------------------------------------

class TestGPTServing:
    def test_gpt_engine_matches_generate(self):
        from paddle_tpu.models.gpt import GPTModel

        paddle.seed(11)
        m = GPTModel(vocab_size=64, hidden_size=32, num_layers=2,
                     num_heads=4, max_seq_len=64)
        rng = np.random.RandomState(4)
        prompts = [rng.randint(0, 64, (n,)).tolist() for n in (4, 7, 10)]
        eng = serving.Engine(m, max_slots=2, num_blocks=32, block_size=4)
        ids = [eng.add_request(p, max_new_tokens=5) for p in prompts]
        outs = eng.run()
        for p, rid in zip(prompts, ids):
            assert outs[rid] == _greedy_ref(m, p, 5)
        assert eng.stats()["decode_compiles"] == 1


# ---------------------------------------------------------------------------
# donated-pools failure recovery
# ---------------------------------------------------------------------------

class TestDonatedPoolRecovery:
    """The compiled steps donate their input pools (donate_argnums) —
    a step that raises AFTER execution started leaves cache.pools
    pointing at DELETED buffers. The engine must detect that, reset
    the pool plane, and preempt-by-recompute every occupied slot:
    outputs stay bit-identical to a clean run and a one-step transient
    never becomes permanent engine death."""

    def _poison_after_dispatch(self, eng, attr):
        """Wrap a compiled step so its FIRST call runs the real jit
        (consuming the donated pools) and then raises — the
        post-dispatch failure mode fault injection (which fires before
        the call) cannot produce."""
        real = getattr(eng, attr)
        state = {"fired": False}

        def wrapper(*args):
            out = real(*args)
            if not state["fired"]:
                state["fired"] = True
                raise RuntimeError("post-dispatch transient")
            return out

        setattr(eng, attr, wrapper)
        return state

    def test_split_decode_recovers_bit_identical(self, llama):
        model, _cfg = llama
        rng = np.random.RandomState(9)
        prompts = [rng.randint(1, 64, (n,)).tolist() for n in (5, 9, 3)]
        clean = serving.Engine(model, max_slots=3, num_blocks=64,
                               block_size=4)
        ids = [clean.add_request(p, max_new_tokens=6) for p in prompts]
        want = clean.run()
        eng = serving.Engine(model, max_slots=3, num_blocks=64,
                             block_size=4)
        ids2 = [eng.add_request(p, max_new_tokens=6) for p in prompts]
        state = self._poison_after_dispatch(eng, "_decode")
        got = eng.run()
        assert state["fired"]
        assert [got[i] for i in ids2] == [want[i] for i in ids]
        st = eng.stats()
        assert st["preemptions"] >= 3     # every occupied slot requeued
        assert st["requests_finished"] == 3
        assert eng.cache.pools_alive()

    def test_recovery_requeue_preserves_fcfs_order(self, llama):
        """The recovery requeue uses appendleft in REVERSE slot order
        (the _on_decode_failure idiom) so the survivors re-admit
        strictly FCFS — earliest-admitted request back at the queue
        head, not the tail-end slot."""
        model, _cfg = llama
        eng = serving.Engine(model, max_slots=3, num_blocks=64,
                             block_size=4)
        rng = np.random.RandomState(11)
        ids = [eng.add_request(rng.randint(1, 64, (n,)).tolist(),
                               max_new_tokens=4) for n in (5, 7, 3)]
        eng.step()                        # admit + prefill all three
        for p in eng.cache.pools:         # simulate a post-dispatch
            p.k.delete()                  # failure consuming the
            p.v.delete()                  # donated pools
        eng._recover_consumed_pools()
        assert [r.id for r in eng.scheduler.queue] == ids
        assert eng.cache.pools_alive()

    def test_mixed_step_with_prefix_cache_recovers(self, llama):
        model, _cfg = llama
        paddle.set_flags({"FLAGS_serving_prefix_cache": True,
                          "FLAGS_serving_chunked_prefill": True})
        try:
            rng = np.random.RandomState(10)
            shared = rng.randint(1, 64, (8,)).tolist()
            prompts = [shared + rng.randint(1, 64, (n,)).tolist()
                       for n in (4, 6, 2)]
            clean = serving.Engine(model, max_slots=3, num_blocks=64,
                                   block_size=4)
            ids = [clean.add_request(p, max_new_tokens=6)
                   for p in prompts]
            want = clean.run()
            eng = serving.Engine(model, max_slots=3, num_blocks=64,
                                 block_size=4)
            ids2 = [eng.add_request(p, max_new_tokens=6)
                    for p in prompts]
            state = self._poison_after_dispatch(eng, "_mixed")
            got = eng.run()
            assert state["fired"]
            assert [got[i] for i in ids2] == [want[i] for i in ids]
            # the rebuilt prefix cache serves the fresh pools, not the
            # dead ones: the tree must be consistent with a live pool
            assert eng.cache.pools_alive()
            assert eng.stats()["decode_compiles"] == 1
        finally:
            paddle.set_flags({"FLAGS_serving_prefix_cache": False,
                              "FLAGS_serving_chunked_prefill": False})
