"""Trainer/DeviceWorker drivers + fleet datasets + FleetExecutor actor
runtime (reference framework/trainer.h, device_worker.h,
distributed/fleet/dataset/, distributed/fleet_executor/).
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.static as static
from paddle_tpu.distributed.fleet_executor import FleetExecutor, TaskNode
from paddle_tpu.framework.dataset import (
    InMemoryDataset,
    QueueDataset,
    RecordWriter,
)
from paddle_tpu.framework.trainer import (
    DistMultiTrainer,
    MultiTrainer,
    TrainerFactory,
)


def _write_records(path, n=32, seed=0):
    rng = np.random.RandomState(seed)
    with RecordWriter(path) as w:
        for i in range(n):
            x = rng.randn(4).astype(np.float32)
            y = np.asarray([x.sum()], np.float32)
            w.write_example((x, y))
    return path


class TestFleetDatasets:
    def test_queue_dataset_batches(self, tmp_path):
        f = _write_records(str(tmp_path / "a.rec"), n=10)
        ds = QueueDataset()
        ds.init(batch_size=4, thread_num=1, use_var=["x", "y"])
        ds.set_filelist([f])
        batches = list(ds.batches())
        assert sum(b["x"].shape[0] for b in batches) == 10
        assert batches[0]["x"].shape[1] == 4

    def test_in_memory_dataset_shuffle(self, tmp_path):
        f = _write_records(str(tmp_path / "a.rec"), n=16)
        ds = InMemoryDataset()
        ds.init(batch_size=16, thread_num=1, use_var=["x", "y"])
        ds.set_filelist([f])
        ds.load_into_memory()
        assert ds.get_memory_data_size() == 16
        first = next(iter(ds.batches()))["x"].copy()
        ds.local_shuffle(seed=3)
        second = next(iter(ds.batches()))["x"]
        assert first.shape == second.shape
        assert not np.allclose(first, second)
        # same multiset of rows
        np.testing.assert_allclose(np.sort(first.sum(1)),
                                   np.sort(second.sum(1)), rtol=1e-6)


class TestTrainFromDataset:
    def teardown_method(self, m):
        static.disable_static()

    def test_train_from_dataset_drops_loss(self, tmp_path):
        f = _write_records(str(tmp_path / "t.rec"), n=64)
        paddle.seed(0)
        static.enable_static()
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [-1, 4], "float32")
            y = static.data("y", [-1, 1], "float32")
            lin = nn.Linear(4, 1)
            loss = F.mse_loss(lin(x), y)
            opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=None)
            opt.minimize(loss)
        ds = InMemoryDataset()
        ds.init(batch_size=8, thread_num=2, use_var=[x, y])
        ds.set_filelist([f])
        ds.load_into_memory()
        exe = static.Executor()
        exe.run(startup)
        losses = []
        for _ in range(4):
            tr = exe.train_from_dataset(main, ds, fetch_list=[loss])
            losses.append(float(np.mean(tr.losses)))
        assert losses[-1] < losses[0], losses

    def test_trainer_factory(self):
        t = TrainerFactory().create_trainer("DistMultiTrainer",
                                            num_workers=3)
        assert isinstance(t, DistMultiTrainer)
        assert t.num_workers == 3


class TestDownpourWorker:
    def test_ps_pull_push_around_step(self):
        from paddle_tpu.distributed.ps.runtime import TheOnePSRuntime

        rt = TheOnePSRuntime()
        rt.create_sparse_table("emb", 4, optimizer="sgd", lr=1.0,
                               init_std=0.0)
        pulls, pushes = [], []

        def run_fn(batch):
            return batch

        def push_grads(slot, ids, rows, batch, out):
            pushes.append(ids.copy())
            return np.ones((ids.size, 4), np.float32)

        tr = DistMultiTrainer(num_workers=1)
        tr.initialize(run_fn=run_fn)
        tr.set_ps(rt, {"ids": "emb"}, push_grads)
        batches = [{"ids": np.array([1, 2], np.int64)},
                   {"ids": np.array([2, 3], np.int64)}]
        tr.run(iter(batches))
        assert len(pushes) == 2
        # id 2 was pushed twice with grad 1 and lr 1 -> row == -2
        np.testing.assert_allclose(rt.pull_sparse("emb", [2]),
                                   np.full((1, 4), -2.0))


class TestFleetExecutor:
    def test_linear_pipeline_order_and_results(self):
        fe = FleetExecutor.from_stages(
            [lambda x: x + 1, lambda x: x * 10],
            num_micro_batches=4,
            source_fn=lambda i: i)
        out = fe.run(timeout=30)
        assert out == [(i + 1) * 10 for i in range(4)]

    def test_diamond_graph(self):
        # source -> (a, b) -> join -> sink
        src = TaskNode(node_type="Source", task_id=0, max_run_times=3,
                       payload=lambda i: i)
        a = TaskNode(node_type="Compute", task_id=1, max_run_times=3,
                     payload=lambda x: x + 100)
        b = TaskNode(node_type="Compute", task_id=2, max_run_times=3,
                     payload=lambda x: x * 2)
        join = TaskNode(node_type="Compute", task_id=3, max_run_times=3,
                        payload=lambda u, v: (u, v))
        sink = TaskNode(node_type="Sink", task_id=4, max_run_times=3)
        for up, down in [(src, a), (src, b), (a, join), (b, join),
                         (join, sink)]:
            up.add_downstream_task(down.task_id)
            down.add_upstream_task(up.task_id)
        out = FleetExecutor([src, a, b, join, sink]).run(timeout=30)
        assert out == [(i + 100, i * 2) for i in range(3)]

    def test_timeout_raises(self):
        # a compute node with a missing upstream never fires
        src = TaskNode(node_type="Source", task_id=0, max_run_times=1,
                       payload=lambda i: i)
        c = TaskNode(node_type="Compute", task_id=1, max_run_times=1)
        sink = TaskNode(node_type="Sink", task_id=2, max_run_times=1)
        src.add_downstream_task(1)
        c.add_upstream_task(0)
        c.add_upstream_task(99)  # never sends
        c.add_downstream_task(2)
        sink.add_upstream_task(1)
        with pytest.raises(TimeoutError):
            FleetExecutor([src, c, sink]).run(timeout=1)


class TestReviewRegressions:
    def test_worker_error_propagates_without_deadlock(self):
        tr = MultiTrainer(num_workers=1)

        def bad(batch):
            raise ValueError("worker-boom")

        tr.initialize(run_fn=bad)
        with pytest.raises(ValueError, match="worker-boom"):
            tr.run(iter([{"x": i} for i in range(50)]))

    def test_diamond_binds_args_in_declaration_order(self):
        # upstream a has the LARGER task_id but is declared first
        src = TaskNode(node_type="Source", task_id=0, max_run_times=2,
                       payload=lambda i: i)
        a = TaskNode(node_type="Compute", task_id=7, max_run_times=2,
                     payload=lambda x: "A%d" % x)
        b = TaskNode(node_type="Compute", task_id=2, max_run_times=2,
                     payload=lambda x: "B%d" % x)
        join = TaskNode(node_type="Compute", task_id=3, max_run_times=2,
                        payload=lambda u, v: (u, v))
        sink = TaskNode(node_type="Sink", task_id=4, max_run_times=2)
        for up, down in [(src, a), (src, b)]:
            up.add_downstream_task(down.task_id)
            down.add_upstream_task(up.task_id)
        a.add_downstream_task(3)
        b.add_downstream_task(3)
        join.add_upstream_task(7)   # declared first -> first arg
        join.add_upstream_task(2)
        join.add_downstream_task(4)
        sink.add_upstream_task(3)
        out = FleetExecutor([src, a, b, join, sink]).run(timeout=30)
        assert out == [("A0", "B0"), ("A1", "B1")]

    def test_source_credit_bound(self):
        import threading
        import time as _time

        seen = []
        gate = threading.Event()

        def slow_stage(x):
            seen.append(x)
            gate.wait(0.2)
            return x

        fe = FleetExecutor.from_stages([slow_stage], num_micro_batches=8)
        # stage buffer size 2 (default credit): while the first batch is
        # in flight, at most `credit` tokens may have been emitted
        t = threading.Thread(target=fe.run, kwargs={"timeout": 30},
                             daemon=True)
        t.start()
        _time.sleep(0.05)
        assert len(seen) <= 2
        gate.set()
        t.join(30)


class TestCrossRankMessageBus:
    def test_pipeline_spans_two_processes(self):
        """Reference fleet_executor brpc MessageBus role: a 4-node
        pipeline split across two OS processes; interceptor messages
        (ready/ack) cross ranks over the TCP-store bus and the sink's
        completion releases both carriers."""
        import os
        import subprocess
        import sys

        from dist_utils import free_port

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        port = free_port()
        procs = []
        for rank in range(2):
            env = dict(os.environ)
            env.update({"FEXEC_RANK": str(rank), "FEXEC_PORT": str(port),
                        "FEXEC_MICRO": "5", "JAX_PLATFORMS": "cpu"})
            env.pop("PALLAS_AXON_POOL_IPS", None)
            procs.append(subprocess.Popen(
                [sys.executable,
                 os.path.join(repo, "tests", "fexec_worker.py")],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True))
        outs = []
        for p in procs:
            try:
                outs.append(p.communicate(timeout=120))
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
        for p, (o, e) in zip(procs, outs):
            assert p.returncode == 0, e[-2000:]
        assert "RANK0_DONE" in outs[0][0]
        # source i*10 -> stageA +1 -> stageB *2, in microbatch order
        assert "RESULTS [2, 22, 42, 62, 82]" in outs[1][0], outs[1][0]
