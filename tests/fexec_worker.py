"""Cross-rank FleetExecutor worker: a 2-stage pipeline split over two OS
processes, interceptor messages riding the MessageBus (TCP-store queues)
— the reference brpc-bus deployment shape (fleet_executor.cc +
message_bus.cc). Rank 0: Source(0) + stage A(1); rank 1: stage B(2) +
Sink(3)."""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from paddle_tpu.distributed.fleet_executor import (  # noqa: E402
    FleetExecutor,
    MessageBus,
    TaskNode,
)
from paddle_tpu.distributed.store import TCPStore  # noqa: E402


def main():
    rank = int(os.environ["FEXEC_RANK"])
    port = int(os.environ["FEXEC_PORT"])
    n_micro = int(os.environ.get("FEXEC_MICRO", "5"))
    store = TCPStore(port=port, is_master=(rank == 0))
    bus = MessageBus(store, rank)
    if rank == 0:
        src = TaskNode(node_type="Source", task_id=0,
                       max_run_times=n_micro, payload=lambda i: i * 10)
        a = TaskNode(node_type="Compute", task_id=1,
                     max_run_times=n_micro, payload=lambda x: x + 1)
        src.add_downstream_task(1)
        a.add_upstream_task(0)
        a.add_downstream_task(2)  # hosted on rank 1
        ex = FleetExecutor([src, a], bus=bus)
        ex.run(timeout=60)
        print("RANK0_DONE")
    else:
        b = TaskNode(node_type="Compute", task_id=2,
                     max_run_times=n_micro, payload=lambda x: x * 2)
        sink = TaskNode(node_type="Sink", task_id=3,
                        max_run_times=n_micro)
        b.add_upstream_task(1)  # hosted on rank 0
        b.add_downstream_task(3)
        sink.add_upstream_task(2)
        ex = FleetExecutor([b, sink], bus=bus)
        results = ex.run(timeout=60)
        print("RESULTS", results)
    store.close()


if __name__ == "__main__":
    main()
