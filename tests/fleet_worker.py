"""Worker for the multi-process fleet-telemetry acceptance test.

Every rank announces its metrics endpoint in the TCPStore
(monitor/fleet.py ``announce``), publishes train-shaped telemetry
(``train_step_seconds`` / ``train_steps_total`` / ``train_loss``) from
a synthetic step loop, and journals per-step spans. Rank 0 runs the
fleet collector. The scripted incidents:

- rank ``STRAGGLER_RANK`` runs every step ``SLOW_S`` instead of
  ``FAST_S`` — persistently slower than the fleet median, so the
  collector must flag it (``fleet_straggler_total{rank}``, named in
  ``/debugz/fleet``) while every rank is still stepping: no timeout,
  no stall, no watchdog involved;
- rank ``NAN_RANK`` publishes a NaN loss from step ``NAN_STEP`` — its
  local perf sentinel fires, its /healthz turns degraded, and the
  collector pulls a ``fleet_capture_<ts>/`` with bundles + journal
  tails from every rank;
- (ISSUE 18, ``STRAGGLER_RECOVER_STEP`` >= 0 + ``FLAGS_monitor_slo``)
  the straggler recovers mid-run: its steps turn fast again, the
  collector resolves the ``fleet/straggler/rank{r}`` incident, and
  every rank keeps publishing fast tail steps until rank 0 has
  observed the WHOLE lifecycle (flag -> capture -> resolve) in the
  merged /debugz/fleet/incidents timeline — recovery is only
  detectable against a live fleet pace.

Rank 0 prints the machine-checkable evidence lines the parent test
pins: STRAGGLER_FLAGGED (with the steps watermark at flag time),
FLEET_VERDICT (the /debugz/fleet payload fetched over real HTTP),
STRAGGLER_TOTAL, CAPTURES, FINAL_STEPS, INCIDENTS (the merged fleet
incident timeline over real HTTP). Every rank prints FLEET_OK and
exits 0 — the incidents leave telemetry, not corpses.

Spawned by tests/test_monitor_fleet.py with PADDLE_TRAINER_ID /
PADDLE_TRAINERS_NUM / PADDLE_MASTER / PT_MONITOR_DUMP_DIR and the
FLAGS_* env (monitor_fleet, perf_sentinels, monitor_timeseries,
monitor_trace, monitor_slo) set.
"""
from __future__ import annotations

import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def main():
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    world = int(os.environ["PADDLE_TRAINERS_NUM"])
    host, _, port = os.environ["PADDLE_MASTER"].partition(":")
    straggler_rank = int(os.environ.get("STRAGGLER_RANK", "2"))
    nan_rank = int(os.environ.get("NAN_RANK", "1"))
    nan_step = int(os.environ.get("NAN_STEP", "30"))
    steps = int(os.environ.get("STEPS", "45"))
    fast_s = float(os.environ.get("FAST_S", "0.08"))
    slow_s = float(os.environ.get("SLOW_S", "0.32"))
    recover_step = int(os.environ.get("STRAGGLER_RECOVER_STEP", "-1"))

    from paddle_tpu import monitor
    from paddle_tpu.monitor import fleet, perf, trace
    from paddle_tpu.monitor import memory as ptmem
    from paddle_tpu.distributed.process_group import (
        StoreProcessGroup,
        set_world_group,
    )
    from paddle_tpu.distributed.store import TCPStore

    assert fleet.is_enabled(), "FLAGS_monitor_fleet must be on"
    store = TCPStore(host or "127.0.0.1", int(port),
                     is_master=(rank == 0), timeout_s=180)
    store.barrier("boot", world, timeout_s=180)
    pg = StoreProcessGroup(store, rank, world)
    set_world_group(pg)

    url = fleet.announce(store, rank, world, job="train")
    assert url, "announce() returned no url with the flag on"
    print("ANNOUNCED rank=%d url=%s" % (rank, url), flush=True)

    # memory plane (ISSUE 12): a synthetic per-rank ledger so the
    # collector's /debugz/memory scrape populates the fleet table's
    # MEM/HEADROOM columns — distinct per rank so the parent test can
    # pin that each rank's own bytes surfaced (64 MiB + rank MiB)
    mem_bytes = (64 + rank) << 20
    if ptmem.is_enabled():
        tr = ptmem.tracker(
            "train", {"synthetic": lambda: [("blob", mem_bytes)]})
        assert tr is not None
        ptmem.note_transient_peak("train", 8 << 20, source="test")

    collector = None
    if rank == 0:
        collector = fleet.start_collector(
            store=store, world_size=world, rank=0,
            interval_s=0.25, straggler_factor=2.0,
            straggler_persist=2, capture_cooldown_s=1.5,
            http_timeout_s=10.0,
            capture_dir=os.environ["PT_MONITOR_DUMP_DIR"])
    # every rank waits until all endpoints are announced so the first
    # collector rounds see the whole fleet
    store.barrier("announced", world, timeout_s=180)

    reg = monitor.get_registry()
    step_hist = reg.get("train_step_seconds")
    steps_total = reg.get("train_steps_total")
    tok_rate = reg.get("train_tokens_per_s")
    loss_gauge = reg.get("train_loss")
    assert None not in (step_hist, steps_total, tok_rate, loss_gauge)

    straggler_flag_step = None
    for i in range(steps):
        sleep_s = fast_s
        if rank == straggler_rank and not (
                0 <= recover_step <= i):
            sleep_s = slow_s
        t0 = time.perf_counter()
        time.sleep(sleep_s)
        dt = time.perf_counter() - t0
        step_hist.observe(dt)
        steps_total.inc()
        tok_rate.set(128.0 / dt)
        loss = 2.0 - 0.01 * i
        if rank == nan_rank and i >= nan_step:
            loss = float("nan")
        loss_gauge.labels(job="train").set(loss)
        trace.record_train_step("train", i, dt, steps=1, tokens=128)
        if rank == 0 and straggler_flag_step is None \
                and collector._stragglers:
            straggler_flag_step = i
            # the run is demonstrably alive at flag time: record the
            # fleet's progress watermark, later pinned < FINAL_STEPS
            watermark = max(
                (st.get("steps_total") or 0)
                for st in collector._ranks.values())
            print("STRAGGLER_FLAGGED step=%d ranks=%s watermark=%d"
                  % (i, sorted(collector._stragglers), int(watermark)),
                  flush=True)
            # the verdict over real HTTP — what an operator (or the
            # ROADMAP item-2 router) would read
            with urllib.request.urlopen(url + "/debugz/fleet",
                                        timeout=10) as r:
                print("FLEET_VERDICT %s" % r.read().decode(),
                      flush=True)

    if rank == nan_rank:
        assert perf.is_degraded(), \
            "NaN loss did not trip the local sentinel"

    def _tail_step():
        # one fast step's worth of live telemetry: the collector can
        # only judge a recovery against a fleet that is still pacing
        t0 = time.perf_counter()
        time.sleep(fast_s)
        dt = time.perf_counter() - t0
        step_hist.observe(dt)
        steps_total.inc()
        tok_rate.set(128.0 / dt)

    slo_phase = recover_step >= 0
    if slo_phase:
        from paddle_tpu.monitor import incidents as ptinc
        assert ptinc.is_enabled(), \
            "FLAGS_monitor_slo must enable the incident table"

    if rank != 0 and slo_phase:
        # keep publishing until rank 0 has the whole lifecycle in hand
        while store.get("__slo/done", timeout_s=0.05) is None:
            _tail_step()

    if rank == 0:
        # settle: the collector needs (a) a round or two to see the
        # NaN rank's degradation and pull the capture, and (b) in the
        # ISSUE-18 recovery scenario, enough live rounds to watch the
        # straggler episode resolve in the merged incident timeline —
        # rank 0 keeps stepping so its own row stays live too
        skey = "fleet/straggler/rank%d" % straggler_rank
        deadline = time.monotonic() + (90 if slo_phase else 20)
        while time.monotonic() < deadline:
            caps = list(collector._captures)
            anomaly_seen = any(c["reason"] == "anomaly" for c in caps)
            if slo_phase:
                _tail_step()
                merged = fleet.fleet_incidents_payload()
                by_key = {}
                for inc in merged.get("incidents") or ():
                    by_key.setdefault(inc["key"], []).append(inc)
                straggler_resolved = any(
                    i.get("state") == "resolved"
                    for i in by_key.get(skey, ()))
                nan_seen = any(k.startswith("perf/nan_loss")
                               for k in by_key)
                if anomaly_seen and straggler_resolved and nan_seen:
                    break
            else:
                if anomaly_seen and collector._stragglers:
                    break
                time.sleep(0.25)
        total = 0
        m = reg.get("fleet_straggler_total")
        for key, v in m.collect():
            if key == (str(straggler_rank),):
                total = v
        print("STRAGGLER_TOTAL rank=%d value=%d"
              % (straggler_rank, int(total)), flush=True)
        print("CAPTURES %s" % json.dumps(
            [{"dir": c["dir"], "reason": c["reason"],
              "ranks": c["ranks"]} for c in collector._captures]),
            flush=True)
        final = max((st.get("steps_total") or 0)
                    for st in collector._ranks.values())
        print("FINAL_STEPS %d" % int(final), flush=True)
        # per-rank memory columns over real HTTP (ISSUE 12): the
        # parent test pins every rank's MEM/HEADROOM against its own
        # synthetic ledger
        with urllib.request.urlopen(url + "/debugz/fleet/ranks",
                                    timeout=10) as r:
            ranks = json.loads(r.read().decode())["ranks"]
        print("MEM_COLUMNS %s" % json.dumps(
            [{"rank": row["rank"],
              "mem_live_bytes": row.get("mem_live_bytes"),
              "mem_headroom_bytes": row.get("mem_headroom_bytes")}
             for row in ranks]), flush=True)
        with urllib.request.urlopen(url + "/metrics/fleet",
                                    timeout=10) as r:
            text = r.read().decode()
        assert 'train_steps_total{rank="0"}' in text, text[:400]
        print("FEDERATION_OK", flush=True)
        if slo_phase:
            # the merged fleet incident timeline over real HTTP (ISSUE
            # 18): dedup by id, episode lifecycle, capture causality —
            # then release the fleet's tail-step loops
            with urllib.request.urlopen(
                    url + "/debugz/fleet/incidents", timeout=10) as r:
                print("INCIDENTS %s" % r.read().decode(), flush=True)
            store.set("__slo/done", "1")

    store.barrier("done", world, timeout_s=180)
    if collector is not None:
        fleet.stop_collector()
    print("FLEET_OK rank=%d" % rank, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
