"""text / audio / geometric domain tests.

Oracles: numpy (segment ops, brute-force viterbi), librosa-style closed
forms for mel/DCT (reference unittests/test_audio_functions.py compares
against librosa; here the oracle is the direct formula).
"""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestSegmentOps:
    ids = np.array([0, 0, 1, 2, 2, 2], dtype=np.int64)
    x = np.arange(12, dtype=np.float32).reshape(6, 2)

    def test_segment_sum(self):
        out = paddle.geometric.segment_sum(
            paddle.to_tensor(self.x), paddle.to_tensor(self.ids))
        ref = np.stack([self.x[:2].sum(0), self.x[2:3].sum(0),
                        self.x[3:].sum(0)])
        np.testing.assert_allclose(out.numpy(), ref)

    def test_segment_mean_min_max(self):
        xt, it = paddle.to_tensor(self.x), paddle.to_tensor(self.ids)
        np.testing.assert_allclose(
            paddle.geometric.segment_mean(xt, it).numpy(),
            np.stack([self.x[:2].mean(0), self.x[2:3].mean(0),
                      self.x[3:].mean(0)]))
        np.testing.assert_allclose(
            paddle.geometric.segment_min(xt, it).numpy(),
            np.stack([self.x[:2].min(0), self.x[2:3].min(0),
                      self.x[3:].min(0)]))
        np.testing.assert_allclose(
            paddle.geometric.segment_max(xt, it).numpy(),
            np.stack([self.x[:2].max(0), self.x[2:3].max(0),
                      self.x[3:].max(0)]))

    def test_segment_min_int_empty_segments(self):
        # empty segments must yield 0, not the iinfo sentinel
        out = paddle.geometric.segment_min(
            paddle.to_tensor(np.array([3, 1], dtype=np.int32)),
            paddle.to_tensor(np.array([0, 0], dtype=np.int64)), out_size=3)
        assert out.numpy().tolist() == [1, 0, 0]
        out = paddle.geometric.segment_max(
            paddle.to_tensor(np.array([3, 1], dtype=np.int32)),
            paddle.to_tensor(np.array([0, 0], dtype=np.int64)), out_size=3)
        assert out.numpy().tolist() == [3, 0, 0]

    def test_segment_sum_grad(self):
        xt = paddle.to_tensor(self.x)
        xt.stop_gradient = False
        out = paddle.geometric.segment_sum(xt, paddle.to_tensor(self.ids))
        out.sum().backward()
        np.testing.assert_allclose(xt.grad.numpy(), np.ones_like(self.x))


class TestMessagePassing:
    # graph: 0->1, 0->2, 1->2
    src = np.array([0, 0, 1], dtype=np.int64)
    dst = np.array([1, 2, 2], dtype=np.int64)
    x = np.array([[1., 2.], [3., 4.], [5., 6.]], dtype=np.float32)

    def test_send_u_recv_sum(self):
        out = paddle.geometric.send_u_recv(
            paddle.to_tensor(self.x), paddle.to_tensor(self.src),
            paddle.to_tensor(self.dst), reduce_op="sum", out_size=3)
        ref = np.array([[0., 0.], [1., 2.], [4., 6.]], dtype=np.float32)
        np.testing.assert_allclose(out.numpy(), ref)

    def test_send_u_recv_mean_infers_size(self):
        out = paddle.geometric.send_u_recv(
            paddle.to_tensor(self.x), paddle.to_tensor(self.src),
            paddle.to_tensor(self.dst), reduce_op="mean")
        assert out.shape[0] == 3  # max(dst)+1
        np.testing.assert_allclose(out.numpy()[2], [2., 3.])

    def test_send_ue_recv(self):
        e = np.array([10., 20., 30.], dtype=np.float32)
        out = paddle.geometric.send_ue_recv(
            paddle.to_tensor(self.x), paddle.to_tensor(e),
            paddle.to_tensor(self.src), paddle.to_tensor(self.dst),
            message_op="add", reduce_op="sum", out_size=3)
        # dst2: (x0 + 20) + (x1 + 30) = [1+20+3+30, 2+20+4+30]
        np.testing.assert_allclose(out.numpy()[2], [54., 56.])

    def test_send_uv(self):
        out = paddle.geometric.send_uv(
            paddle.to_tensor(self.x), paddle.to_tensor(self.x),
            paddle.to_tensor(self.src), paddle.to_tensor(self.dst),
            message_op="mul")
        # edge 0: x[0] * x[1]
        np.testing.assert_allclose(out.numpy()[0], [3., 8.])

    def test_reindex_graph(self):
        x = paddle.to_tensor(np.array([10, 20], dtype=np.int64))
        neighbors = paddle.to_tensor(
            np.array([30, 10, 40, 20], dtype=np.int64))
        count = paddle.to_tensor(np.array([2, 2], dtype=np.int64))
        src, dst, nodes = paddle.geometric.reindex_graph(x, neighbors, count)
        np.testing.assert_array_equal(nodes.numpy(), [10, 20, 30, 40])
        np.testing.assert_array_equal(src.numpy(), [2, 0, 3, 1])
        np.testing.assert_array_equal(dst.numpy(), [0, 0, 1, 1])

    def test_sample_neighbors(self):
        # CSC: node0 neighbors [1,2,3], node1 neighbors [0]
        row = paddle.to_tensor(np.array([1, 2, 3, 0], dtype=np.int64))
        colptr = paddle.to_tensor(np.array([0, 3, 4, 4, 4], dtype=np.int64))
        nodes = paddle.to_tensor(np.array([0, 1], dtype=np.int64))
        nbr, cnt = paddle.geometric.sample_neighbors(
            row, colptr, nodes, sample_size=2)
        assert cnt.numpy().tolist() == [2, 1]
        assert set(nbr.numpy()[:2]).issubset({1, 2, 3})
        assert nbr.numpy()[2] == 0


class TestAudioFunctional:
    def test_hz_mel_roundtrip(self):
        import paddle_tpu.audio.functional as AF

        for htk in (False, True):
            for f in (60.0, 440.0, 8000.0):
                mel = AF.hz_to_mel(f, htk)
                back = AF.mel_to_hz(mel, htk)
                assert abs(back - f) / f < 1e-6, (f, htk)
        # tensor path matches scalar path
        freqs = paddle.to_tensor(np.array([60., 440., 8000.], np.float32))
        mels = AF.hz_to_mel(freqs, False).numpy()
        ref = [AF.hz_to_mel(float(f), False) for f in (60., 440., 8000.)]
        np.testing.assert_allclose(mels, ref, rtol=1e-5)

    def test_fbank_matrix(self):
        import paddle_tpu.audio.functional as AF

        fb = AF.compute_fbank_matrix(16000, 512, n_mels=40).numpy()
        assert fb.shape == (40, 257)
        assert np.all(fb >= 0)
        assert np.all(fb.sum(axis=1) > 0)  # every filter hits some bin

    def test_power_to_db(self):
        import paddle_tpu.audio.functional as AF

        x = paddle.to_tensor(np.array([1.0, 10.0, 100.0], np.float32))
        db = AF.power_to_db(x, top_db=None).numpy()
        np.testing.assert_allclose(db, [0.0, 10.0, 20.0], atol=1e-4)

    def test_power_to_db_top_db_jits(self):
        import paddle_tpu.audio.functional as AF
        from paddle_tpu import jit

        x = paddle.to_tensor(np.array([1.0, 10.0, 1e-6], np.float32))
        eager = AF.power_to_db(x, top_db=10.0).numpy()
        fn = jit.to_static(lambda t: AF.power_to_db(t, top_db=10.0))
        np.testing.assert_allclose(fn(x).numpy(), eager, rtol=1e-5,
                                   atol=1e-5)
        np.testing.assert_allclose(eager, [0.0, 10.0, 0.0], atol=1e-4)

    def test_create_dct_ortho(self):
        import paddle_tpu.audio.functional as AF

        d = AF.create_dct(13, 40).numpy()  # [40, 13]
        # orthonormal columns
        np.testing.assert_allclose(d.T @ d, np.eye(13), atol=1e-5)

    def test_get_window(self):
        import paddle_tpu.audio.functional as AF

        w = AF.get_window("hann", 16).numpy()
        assert len(w) == 16 and abs(w[0]) < 1e-12


class TestAudioFeatures:
    wav = np.sin(2 * np.pi * 440 * np.arange(4000) / 16000).astype(
        np.float32)

    def test_spectrogram_peak(self):
        from paddle_tpu.audio.features import Spectrogram

        sp = Spectrogram(n_fft=512, hop_length=256)
        out = sp(paddle.to_tensor(self.wav[None, :]))
        assert out.shape[1] == 257
        peak_bin = int(out.numpy()[0].mean(axis=1).argmax())
        expected = round(440 * 512 / 16000)
        assert abs(peak_bin - expected) <= 1

    def test_mel_and_mfcc_shapes(self):
        from paddle_tpu.audio.features import (LogMelSpectrogram, MFCC,
                                               MelSpectrogram)

        x = paddle.to_tensor(self.wav[None, :])
        mel = MelSpectrogram(sr=16000, n_fft=512, hop_length=256, n_mels=40)
        out = mel(x)
        assert out.shape[1] == 40
        lm = LogMelSpectrogram(sr=16000, n_fft=512, hop_length=256,
                               n_mels=40)(x)
        assert lm.shape[1] == 40
        mf = MFCC(sr=16000, n_mfcc=13, n_fft=512, hop_length=256,
                  n_mels=40)(x)
        assert mf.shape[1] == 13

    def test_wav_save_load_roundtrip(self, tmp_path):
        import paddle_tpu.audio as audio

        path = str(tmp_path / "t.wav")
        audio.save(path, paddle.to_tensor(self.wav[None, :]), 16000)
        info = audio.info(path)
        assert info.sample_rate == 16000
        assert info.num_samples == len(self.wav)
        wav2, sr = audio.load(path)
        assert sr == 16000
        np.testing.assert_allclose(wav2.numpy()[0], self.wav, atol=1e-3)

    def test_datasets(self):
        from paddle_tpu.audio.datasets import ESC50, TESS

        ds = TESS(mode="train", feat_type="raw", size=4, sample_rate=8000,
                  duration=0.25)
        w, label = ds[0]
        assert w.shape == (2000,) and 0 <= int(label) < 7
        ds2 = ESC50(mode="dev", feat_type="mfcc", size=2, sample_rate=8000,
                    duration=0.25, n_mfcc=13, n_fft=256, hop_length=128,
                    n_mels=24)
        feat, label = ds2[0]
        assert feat.shape[0] == 13


class TestViterbi:
    def _brute_force(self, pots, trans, length, bos_eos):
        import itertools

        N = pots.shape[-1]
        best, best_path = -np.inf, None
        for path in itertools.product(range(N), repeat=length):
            s = pots[0, path[0]]
            if bos_eos:
                s += trans[-1, path[0]]
            for t in range(1, length):
                s += trans[path[t - 1], path[t]] + pots[t, path[t]]
            if bos_eos:
                s += trans[path[-1], -2]
            if s > best:
                best, best_path = s, path
        return best, list(best_path)

    @pytest.mark.parametrize("bos_eos", [False, True])
    def test_matches_brute_force(self, bos_eos):
        rng = np.random.RandomState(0)
        B, T, N = 3, 5, 4
        pots = rng.randn(B, T, N).astype(np.float32)
        trans = rng.randn(N, N).astype(np.float32)
        lens = np.array([5, 3, 1], dtype=np.int64)
        scores, paths = paddle.text.viterbi_decode(
            paddle.to_tensor(pots), paddle.to_tensor(trans),
            paddle.to_tensor(lens), include_bos_eos_tag=bos_eos)
        for b in range(B):
            ref_s, ref_p = self._brute_force(pots[b], trans, int(lens[b]),
                                             bos_eos)
            assert abs(float(scores.numpy()[b]) - ref_s) < 1e-4
            assert paths.numpy()[b, :int(lens[b])].tolist() == ref_p

    def test_decoder_layer(self):
        rng = np.random.RandomState(1)
        trans = paddle.to_tensor(rng.randn(3, 3).astype(np.float32))
        dec = paddle.text.ViterbiDecoder(trans, include_bos_eos_tag=False)
        pots = paddle.to_tensor(rng.randn(2, 4, 3).astype(np.float32))
        lens = paddle.to_tensor(np.array([4, 2], dtype=np.int64))
        scores, paths = dec(pots, lens)
        assert scores.shape == [2] and paths.shape == [2, 4]


class TestTextDatasets:
    def test_imdb_imikolov(self):
        ds = paddle.text.Imdb(mode="train", size=8)
        doc, label = ds[0]
        assert doc.dtype == np.int64 and int(label) in (0, 1)
        ng = paddle.text.Imikolov(mode="train", window_size=3, size=4)
        tup = ng[0]
        assert len(tup) == 3

    def test_uci_housing(self):
        tr = paddle.text.UCIHousing(mode="train")
        te = paddle.text.UCIHousing(mode="test")
        x, y = tr[0]
        assert x.shape == (13,) and y.shape == (1,)
        assert len(tr) > len(te)

    def test_conll_movielens_wmt(self):
        c = paddle.text.Conll05st(size=2)
        sample = c[0]
        assert len(sample) == 9
        assert all(len(f) == len(sample[0]) for f in sample)
        m = paddle.text.Movielens(size=32)
        fields = m[0]
        assert len(fields) == 8 and fields[-1].dtype == np.float32
        w = paddle.text.WMT14(size=4)
        src, trg_in, trg_next = w[0]
        assert trg_in[0] == 0 and trg_next[-1] == 1
        assert len(trg_in) == len(trg_next)
