"""paddle_tpu.monitor.trace: span journal, exemplars, serving request
timelines, train-step spans, chrome round-trip.

Covers the ISSUE-6 acceptance surface:
- journal semantics: parent/child links, typed events, bounded traces
  and per-trace span rings, context-manager nesting;
- the hard disabled-path pinning (PR-2/5 style): FLAGS_monitor_trace
  off means zero journal allocations on the serving hot path, zero
  threads, zero native calls, and the registry exemplar hook slot
  stays None;
- the acceptance row: a forced p99-outlier request in a starved
  serving run resolves from its TTFT histogram exemplar to a complete
  span timeline — including a preempt/resume cycle — whose phase
  durations sum (+-5%) to its e2e latency;
- train-step spans whose child comm spans replay the flight-recorder
  brackets by sequence watermark (seq/gseq-linked);
- watchdog bundles embed the active (unfinished) spans;
- journal -> chrome-trace round-trip via tools/trace_merge.py
  --requests (span count + parentage preserved).
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.monitor import flight_recorder as frmod
from paddle_tpu.monitor import registry as mreg
from paddle_tpu.monitor import trace
from paddle_tpu.monitor import trace_merge as tmerge

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _trace_clean():
    """Every test starts AND ends with the journal at its default
    (off, empty) — neither earlier suites' leftovers nor ours leak."""
    paddle.set_flags({"FLAGS_monitor_trace": False})
    trace.disable()
    trace.clear()
    mreg.enable(trace_bridge=False)
    yield
    paddle.set_flags({"FLAGS_monitor_trace": False})
    trace.disable()
    trace.clear()
    mreg.enable(trace_bridge=False)


@pytest.fixture(scope="module")
def llama():
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      max_position_embeddings=64, use_parallel=False)
    return LlamaForCausalLM(cfg), cfg


# ---------------------------------------------------------------------------
# journal core
# ---------------------------------------------------------------------------

class TestJournalCore:
    def test_span_lifecycle_and_parentage(self):
        trace.enable()
        tid = trace.new_trace("request", request_id=7)
        root = trace.start_span("request", tid, kind="request")
        child = trace.start_span("prefill", tid, parent_id=root,
                                 kind="phase", slot=1)
        trace.add_event(child, "token", n=1, kv_pages_used=3)
        trace.end_span(child)
        trace.end_span(root, status="finished")
        tr = trace.get_trace(tid)
        assert tr["attrs"]["request_id"] == 7
        assert tr["open_spans"] == 0
        spans = {s["name"]: s for s in tr["spans"]}
        assert spans["prefill"]["parent_id"] == spans["request"]["span_id"]
        assert spans["prefill"]["attrs"]["slot"] == 1
        ev = spans["prefill"]["events"][0]
        assert ev["name"] == "token" and ev["attrs"]["kv_pages_used"] == 3
        assert spans["request"]["attrs"]["status"] == "finished"
        assert spans["request"]["t_end"] >= spans["request"]["t_start"]

    def test_span_context_manager_nests_parents(self):
        trace.enable()
        tid = trace.new_trace("job")
        with trace.exemplar_context(tid):
            with trace.span("outer") as outer:
                with trace.span("inner"):
                    pass
        tr = trace.get_trace(tid)
        inner = next(s for s in tr["spans"] if s["name"] == "inner")
        assert inner["parent_id"] == outer.span_id

    def test_trace_capacity_bounded_finished_evicted_first(self):
        trace.enable(capacity=4)
        open_tid = trace.new_trace("keepme")
        trace.start_span("open", open_tid)
        for i in range(10):
            t = trace.new_trace("r%d" % i)
            s = trace.start_span("a", t)
            trace.end_span(s)
        assert len(trace._state.traces) == 4
        # the trace with an open span survived the eviction sweep
        assert trace.get_trace(open_tid) is not None
        trace.enable(capacity=trace.DEFAULT_CAPACITY)

    def test_per_trace_span_ring_bounded(self):
        trace.enable(span_cap=8)
        tid = trace.new_trace("train")
        for i in range(30):
            s = trace.start_span("step", tid, step=i)
            trace.end_span(s)
        tr = trace.get_trace(tid)
        assert len(tr["spans"]) == 8
        # it is the TAIL that is kept
        assert tr["spans"][-1]["attrs"]["step"] == 29
        trace.enable(span_cap=trace.DEFAULT_SPANS_PER_TRACE)

    def test_phase_breakdown_sums_phase_spans(self):
        trace.enable()
        tid = trace.new_trace("request")
        t0 = trace.now()
        for name, dur in (("queue", 0.5), ("prefill", 0.25),
                          ("decode", 1.0), ("preempted", 0.125),
                          ("prefill", 0.25)):
            s = trace.start_span(name, tid, kind="phase", t=t0)
            trace.end_span(s, t=t0 + dur)
            t0 += dur
        ph = trace.phase_breakdown(tid)
        assert ph["queue"] == pytest.approx(0.5)
        assert ph["prefill"] == pytest.approx(0.5)      # both spans
        assert ph["decode"] == pytest.approx(1.0)
        assert ph["preempted"] == pytest.approx(0.125)
        assert trace.phase_breakdown("nope") is None


# ---------------------------------------------------------------------------
# disabled-path pinning (the acceptance gate)
# ---------------------------------------------------------------------------

class TestDisabledPathPinning:
    def test_flag_default_off_and_hook_slot_none(self):
        assert paddle.get_flags("FLAGS_monitor_trace") == \
            {"FLAGS_monitor_trace": False}
        assert not trace.is_enabled()
        assert mreg._state.ex_hook is None

    def test_disabled_emitters_are_noops(self):
        assert trace.new_trace("x") is None
        assert trace.start_span("s", "whatever") is None
        trace.end_span(None)
        trace.add_event(None, "e")
        assert trace.span("s") is trace._NOOP
        assert trace.exemplar_context("tid") is trace._NOOP
        assert trace.record_train_step("j", 1, 0.01) is None
        assert trace._state.traces == {}

    def test_serving_hot_path_zero_journal_zero_threads_zero_native(
            self, monkeypatch, llama):
        """Journal off: a full serving run allocates nothing into the
        journal, assigns no trace ids, starts no threads, and never
        touches the native lib from the trace path."""
        import paddle_tpu.profiler as profiler
        from paddle_tpu import serving
        from paddle_tpu.core import native

        # the pre-existing chrome-span bridge (serving/metrics.span ->
        # profiler.RecordEvent) probes the native lib and degrades on
        # failure by design — neutralize it with a regular exception so
        # the pytest.fail below only fires for NEW native touches
        class _NoNative:
            def __init__(self, *a, **kw):
                raise RuntimeError("no native lib in this test")

        monkeypatch.setattr(profiler, "RecordEvent", _NoNative)
        # ...as is the native trace-counter bridge (serving/metrics.
        # counter, active while the MONITOR is on) — also pre-existing
        monkeypatch.setattr("paddle_tpu.serving.metrics.counter",
                            lambda name, value: None)
        monkeypatch.setattr(
            native, "get_lib",
            lambda: pytest.fail("disabled trace touched the native lib"))
        mreg._state.trace_bridge = False
        threads_before = set(threading.enumerate())
        m, cfg = llama
        eng = serving.Engine(m, max_slots=2, num_blocks=32, block_size=4)
        rng = np.random.RandomState(0)
        rid = eng.add_request(rng.randint(0, 64, (5,)).tolist(),
                              max_new_tokens=4)
        eng.run()
        assert eng.requests[rid].trace_id is None
        assert eng.requests[rid].metrics.trace_id is None
        assert eng.request_trace(rid) == (None, None)
        assert trace._state.traces == {}
        assert trace._state.exemplars == {}
        assert mreg._state.ex_hook is None
        assert set(threading.enumerate()) == threads_before

    def test_disable_restores_boot_fast_path(self):
        trace.enable()
        assert mreg._state.ex_hook is not None
        trace.disable()
        assert mreg._state.ex_hook is None

    def test_flag_bootstrap_enables_in_subprocess(self):
        import subprocess

        p = subprocess.run(
            [sys.executable, "-c",
             "from paddle_tpu.monitor import trace, registry\n"
             "assert trace.is_enabled()\n"
             "assert registry._state.ex_hook is not None\n"
             "print('BOOT_OK')"],
            capture_output=True, text=True, timeout=120,
            env=dict(os.environ, FLAGS_monitor_trace="1",
                     JAX_PLATFORMS="cpu"), cwd=REPO)
        assert p.returncode == 0, p.stderr[-2000:]
        assert "BOOT_OK" in p.stdout


# ---------------------------------------------------------------------------
# exemplars
# ---------------------------------------------------------------------------

class TestExemplars:
    def test_histogram_observation_records_bucket_exemplar(self):
        trace.enable()
        h = monitor.histogram("t_trace_ex_seconds", buckets=(0.1, 1.0))
        tid = trace.new_trace("request")
        with trace.exemplar_context(tid):
            h.observe(0.5)
            h.observe(5.0)      # past the last bucket -> +Inf
        ex = trace.exemplars("t_trace_ex_seconds")
        assert ex["1.0"]["trace_id"] == tid
        assert ex["1.0"]["value"] == 0.5
        assert ex["+Inf"]["trace_id"] == tid
        # no context -> no exemplar recorded
        h.observe(0.05)
        assert "0.1" not in trace.exemplars("t_trace_ex_seconds")

    def test_labeled_series_exemplars_keyed_by_series_name(self):
        trace.enable()
        h = monitor.histogram("t_trace_ex_lbl_seconds",
                              labelnames=("k",), buckets=(1.0,))
        tid = trace.new_trace("request")
        with trace.exemplar_context(tid):
            h.labels(k="a").observe(0.5)
        ex = trace.exemplars('t_trace_ex_lbl_seconds{k="a"}')
        assert ex["1.0"]["trace_id"] == tid


# ---------------------------------------------------------------------------
# serving acceptance: exemplar -> timeline -> phase sum, with preemption
# ---------------------------------------------------------------------------

class TestServingTimelineAcceptance:
    def test_outlier_resolves_to_timeline_with_preempt_cycle(self, llama):
        """The acceptance row: a forced p99-outlier request's TTFT
        exemplar resolves to a complete span timeline — including a
        preempt/resume cycle — whose phase durations sum (+-5%) to its
        e2e latency."""
        from paddle_tpu import serving

        trace.enable()
        m, cfg = llama
        rng = np.random.RandomState(1)
        # starved pool geometry (the test_serving preempt idiom): B's
        # page growth exhausts the pool first and preempts A — so A,
        # the request we make the latency outlier, is also the one
        # that pays a preempt/recompute cycle
        eng = serving.Engine(m, max_slots=2, num_blocks=7, block_size=4)
        prompt_a = rng.randint(0, 64, (6,)).tolist()
        prompt_b = rng.randint(0, 64, (8,)).tolist()

        orig = eng._prefill_request
        slowed = []

        def slow_prefill(slot, req):
            # force the outlier: A's FIRST prefill (not the resume)
            # sleeps long enough to land its TTFT in a bucket of its
            # own among this test's observations
            if req.id == rid_a and not slowed:
                slowed.append(True)
                time.sleep(0.35)
            return orig(slot, req)

        eng._prefill_request = slow_prefill
        rid_a = eng.add_request(prompt_a, max_new_tokens=16)
        eng.step()      # A admitted + slow prefill + first decode
        # B arrives AFTER A's slow prefill so only A's TTFT carries the
        # forced outlier — the two must land in different buckets
        rid_b = eng.add_request(prompt_b, max_new_tokens=10)
        eng.run()

        assert eng.stats()["preemptions"] >= 1
        assert eng.requests[rid_a].metrics.preemptions >= 1

        # 1. the TTFT exemplar for the outlier's bucket names A's trace
        ma = eng.request_metrics(rid_a)
        assert ma["ttft_s"] >= 0.35
        tid_a = eng.requests[rid_a].trace_id
        ex = trace.exemplars("serving_ttft_seconds")
        from paddle_tpu.serving.metrics import _TTFT

        label = trace._bucket_label(_TTFT.buckets, ma["ttft_s"])
        assert ex[label]["trace_id"] == tid_a

        # 2. ...which resolves to a complete timeline with the
        # preempt/resume cycle: two prefill spans bracket a preempted
        # span, and the root request span closed "finished"
        tr = trace.get_trace(tid_a)
        names = [s["name"] for s in tr["spans"] if s["kind"] == "phase"]
        assert names.count("prefill") == 2
        assert "preempted" in names
        assert "queue" in names and "decode" in names
        root = next(s for s in tr["spans"] if s["kind"] == "request")
        assert root["attrs"]["status"] == "finished"
        assert root["attrs"]["preemptions"] >= 1
        assert tr["open_spans"] == 0

        # 3. phase durations sum to the e2e latency within 5%
        phases = trace.phase_breakdown(tid_a)
        assert set(phases) == {"queue", "prefill", "decode", "preempted"}
        assert sum(phases.values()) == \
            pytest.approx(ma["e2e_s"], rel=0.05)
        # B's timeline is complete too, without a preemption
        tid_b, phases_b = eng.request_trace(rid_b)
        assert sum(phases_b.values()) == \
            pytest.approx(eng.request_metrics(rid_b)["e2e_s"], rel=0.05)
        assert "preempted" not in phases_b

        # 4. token milestone events carry KV/slot occupancy
        decode = next(s for s in tr["spans"] if s["name"] == "decode")
        tokens = [e for e in decode["events"] if e["name"] == "token"]
        assert tokens
        assert tokens[0]["attrs"]["kv_pages_used"] > 0
        assert tokens[0]["attrs"]["slots_active"] >= 1
        # the scheduled event recorded admission-time pool state
        queue = next(s for s in tr["spans"] if s["name"] == "queue")
        sched = [e for e in queue["events"] if e["name"] == "scheduled"]
        assert sched and "kv_pages" in sched[0]["attrs"]

    def test_zero_length_request_traces_cleanly(self, llama):
        from paddle_tpu import serving

        trace.enable()
        m, _ = llama
        eng = serving.Engine(m, max_slots=2, num_blocks=16, block_size=4)
        rid = eng.add_request([1, 2, 3], max_new_tokens=0)
        tid, phases = eng.request_trace(rid)
        assert tid is not None
        tr = trace.get_trace(tid)
        assert tr["open_spans"] == 0
        root = next(s for s in tr["spans"] if s["kind"] == "request")
        assert root["attrs"]["status"] == "finished"
        assert root["attrs"]["output_tokens"] == 0


# ---------------------------------------------------------------------------
# train-step spans + flight-recorder-linked comm children
# ---------------------------------------------------------------------------

class TestTrainStepSpans:
    def test_comm_children_replay_flight_recorder_by_seq_watermark(self):
        trace.enable()
        fr = frmod.get_flight_recorder()
        fr.clear()
        # step 1 establishes the watermark (no comm attributed yet)
        trace.record_train_step("t_job", 1, 0.01)
        with fr.record("all_reduce", reduce_op="sum", shape=(4,),
                       dtype="float32", group="world",
                       strict_shape=True) as entry:
            time.sleep(0.002)
        entry["wire_bytes"] = 64
        trace.record_train_step("t_job", 2, 0.02)
        tid = trace._state.jobs["t_job"]["trace_id"]
        tr = trace.get_trace(tid)
        steps = [s for s in tr["spans"] if s["kind"] == "step"]
        assert [s["attrs"]["step"] for s in steps] == [1, 2]
        comm = [s for s in tr["spans"] if s["kind"] == "comm"]
        assert len(comm) == 1
        c = comm[0]
        # seq/gseq-linked: the SAME numbers a desync postmortem names
        assert c["attrs"]["seq"] == entry["seq"]
        assert c["attrs"]["gseq"] == entry["gseq"]
        assert c["attrs"]["group"] == "world"
        assert c["attrs"]["wire_bytes"] == 64
        assert c["parent_id"] == steps[1]["span_id"]
        assert c["t_start"] == entry["t_start"]
        assert c["t_end"] == entry["t_end"]
        # a third step with no new collectives adds no comm spans
        trace.record_train_step("t_job", 3, 0.01)
        tr = trace.get_trace(tid)
        assert len([s for s in tr["spans"] if s["kind"] == "comm"]) == 1

    def test_first_call_replays_own_window_by_wall_clock(self):
        """A one-shot workload (single run_steps call) has no previous
        seq watermark — its comm children come from the step's own
        wall window instead of being silently dropped."""
        trace.enable()
        fr = frmod.get_flight_recorder()
        fr.clear()
        t0 = time.time()
        with fr.record("all_reduce", shape=(4,), dtype="float32",
                       group="world", strict_shape=True):
            time.sleep(0.002)
        trace.record_train_step("t_oneshot", 1,
                                time.time() - t0 + 0.001)
        tid = trace._state.jobs["t_oneshot"]["trace_id"]
        tr = trace.get_trace(tid)
        comm = [s for s in tr["spans"] if s["kind"] == "comm"]
        assert len(comm) == 1 and comm[0]["attrs"]["op"] == "all_reduce"

    def test_compiled_train_step_emits_step_spans(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.parallel.engine import CompiledTrainStep

        trace.enable()
        paddle.seed(0)
        cfg = LlamaConfig.tiny(use_parallel=False)
        model = LlamaForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())

        def loss_fn(logits, labels):
            return F.cross_entropy(
                logits.reshape([-1, cfg.vocab_size]),
                labels.reshape([-1]))

        step = CompiledTrainStep(model, loss_fn, opt)
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(
            0, cfg.vocab_size, (8, 16)).astype(np.int32))
        labels = paddle.to_tensor(rng.randint(
            0, cfg.vocab_size, (8, 16)).astype(np.int32))
        step(ids, labels)
        step(ids, labels)
        tid = trace._state.jobs["train"]["trace_id"]
        tr = trace.get_trace(tid)
        steps = [s for s in tr["spans"] if s["kind"] == "step"]
        assert len(steps) == 2
        assert steps[-1]["attrs"]["tokens"] == 8 * 16
        assert steps[-1]["t_end"] is not None


# ---------------------------------------------------------------------------
# watchdog bundle embedding (satellite)
# ---------------------------------------------------------------------------

class TestBundleActiveSpans:
    def test_bundle_embeds_active_spans(self):
        trace.enable()
        tid = trace.new_trace("request", request_id=17)
        sid = trace.start_span("preempted", tid, kind="phase", slot=1)
        bundle = monitor.build_bundle("test")
        spans = bundle["active_spans"]
        assert any(s["span_id"] == sid and s["name"] == "preempted"
                   and s["trace_id"] == tid for s in spans)
        trace.end_span(sid)
        bundle = monitor.build_bundle("test")
        assert not any(s["span_id"] == sid
                       for s in bundle["active_spans"])

    def test_bundle_spans_empty_when_journal_off(self):
        bundle = monitor.build_bundle("test")
        assert bundle["active_spans"] == []


# ---------------------------------------------------------------------------
# chrome round-trip (CI/tooling satellite)
# ---------------------------------------------------------------------------

class TestChromeRoundTrip:
    def _journal(self, tmp_path):
        trace.enable()
        tid = trace.new_trace("request", request_id=1)
        root = trace.start_span("request", tid, kind="request")
        for phase in ("queue", "prefill", "decode"):
            s = trace.start_span(phase, tid, parent_id=root,
                                 kind="phase")
            trace.add_event(s, "token", n=1)
            trace.end_span(s)
        trace.end_span(root)
        path = str(tmp_path / "journal.json")
        journal = trace.write_journal(path)
        return path, journal, tid

    def test_journal_to_chrome_preserves_spans_and_parentage(
            self, tmp_path):
        path, journal, tid = self._journal(tmp_path)
        loaded = tmerge.load_journal(path)
        assert loaded["traces"].keys() == journal["traces"].keys()
        evs = tmerge.journal_events(loaded, clock="wall")
        xs = [e for e in evs if e["ph"] == "X"]
        assert len(xs) == 4                     # root + 3 phases
        root = next(e for e in xs if e["name"] == "request")
        for name in ("queue", "prefill", "decode"):
            child = next(e for e in xs if e["name"] == name)
            assert child["args"]["parent_id"] == \
                root["args"]["span_id"]
            assert child["tid"] == tid
        assert any(e["ph"] == "i" and e["name"] == "token" for e in evs)
        # monotonic alignment shifts by the journal's own clock anchor
        mono = tmerge.journal_events(loaded, clock="monotonic")
        anchor = loaded["clock_anchor"]
        shift_us = (anchor["monotonic"] - anchor["wall"]) * 1e6
        mroot = next(e for e in mono
                     if e["ph"] == "X" and e["name"] == "request")
        assert mroot["ts"] == pytest.approx(root["ts"] + shift_us)

    def test_trace_merge_cli_requests_mode(self, tmp_path):
        path, journal, tid = self._journal(tmp_path)
        out = str(tmp_path / "merged.json")
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import trace_merge as cli
        finally:
            sys.path.pop(0)
        rc = cli.main(["--out", out, "--requests", path,
                       "--requests-clock", "wall"])
        assert rc == 0
        merged = json.load(open(out))
        xs = [e for e in merged["traceEvents"] if e["ph"] == "X"]
        assert len(xs) == 4
        assert merged["metadata"]["extra_events"] == len(
            tmerge.journal_events(journal, clock="wall"))
        # parentage survives the full CLI round trip
        by_name = {e["name"]: e for e in xs}
        assert by_name["decode"]["args"]["parent_id"] == \
            by_name["request"]["args"]["span_id"]

    def test_load_journal_rejects_non_journal(self, tmp_path):
        p = tmp_path / "not_a_journal.json"
        p.write_text(json.dumps({"traceEvents": []}))
        with pytest.raises(ValueError):
            tmerge.load_journal(str(p))


# ---------------------------------------------------------------------------
# cross-process context (ISSUE 17): cid ids, traceparent, adoption
# ---------------------------------------------------------------------------

class TestCrossProcessContext:
    def test_trace_ids_minted_off_random_cid_not_pid(self):
        """The collision fix: ids come from a per-process RANDOM 64-bit
        cid, not the pid — pid-minted ids collide across hosts and
        recycle within one, silently fusing unrelated requests in
        fleet-merged journals."""
        trace.enable()
        cid = trace._state.cid
        assert len(cid) == 16
        int(cid, 16)                       # parses as hex
        assert cid != "%x" % os.getpid()
        assert cid != "%016x" % os.getpid()
        tid = trace.new_trace("request")
        assert tid.startswith(cid + ".")
        # two states (= two processes) mint from distinct id spaces
        assert trace._TraceState().cid != trace._TraceState().cid

    def test_journal_round_trips_cid(self, tmp_path):
        trace.enable()
        trace.new_trace("request")
        path = str(tmp_path / "journal.json")
        journal = trace.write_journal(path)
        assert journal["cid"] == trace._state.cid
        assert tmerge.load_journal(path)["cid"] == trace._state.cid

    def test_traceparent_format_parse_round_trip(self):
        tp = trace.format_traceparent("deadbeef.5", 11)
        assert tp == "pt1-deadbeef.5-b"
        assert trace.parse_traceparent(tp) == ("deadbeef.5", 11)
        # span-less context: the sender journals but had no span open
        assert trace.parse_traceparent(
            trace.format_traceparent("deadbeef.5")) == ("deadbeef.5",
                                                        None)
        # journal-off sender emits NO context field
        assert trace.format_traceparent(None) is None
        assert trace.format_traceparent(None, 11) is None
        # malformed/foreign input degrades to no-linkage, never raises
        for bad in (None, "", 7, "pt1", "pt2-x-1", "pt1--1",
                    "pt1-x-zz", "pt1-x-1-2"):
            assert trace.parse_traceparent(bad) == (None, None)

    def test_adopt_trace_registers_foreign_id_and_remote_parent(self):
        trace.enable()
        tid = trace.adopt_trace("feedface.3", "request", request_id=1)
        assert tid == "feedface.3"
        tr = trace.get_trace(tid)
        assert tr["attrs"]["adopted"] is True
        assert tr["attrs"]["request_id"] == 1
        # re-adoption merges attrs instead of duplicating the trace
        assert trace.adopt_trace(tid, "request", extra=2) == tid
        assert trace.get_trace(tid)["attrs"]["extra"] == 2
        assert len([t for t in trace._state.traces if t == tid]) == 1
        sid = trace.start_span("request", tid, kind="request",
                               remote_parent=42)
        trace.end_span(sid)
        span = trace.get_trace(tid)["spans"][-1]
        assert span["remote_parent"] == 42
        assert span["parent_id"] is None    # separate id spaces
        # the chrome export carries the linkage for the fleet merge
        evs = trace.chrome_events_from_journal(trace.dump())
        x = next(e for e in evs if e.get("ph") == "X"
                 and e["name"] == "request")
        assert x["args"]["remote_parent"] == 42

    def test_adopt_trace_disabled_or_none_noops(self):
        assert trace.adopt_trace("feedface.3", "request") is None
        trace.enable()
        assert trace.adopt_trace(None, "request") is None
        assert trace._state.traces == {}


# ---------------------------------------------------------------------------
# fleet-journal merge (ISSUE 17): router + replica journals, ONE trace
# ---------------------------------------------------------------------------

_FLEET_TID = "aaaaaaaaaaaaaaaa.0"


def _mk_span(sid, name, kind, t0, t1, parent=None, remote_parent=None,
             **attrs):
    s = {"span_id": sid, "trace_id": _FLEET_TID, "parent_id": parent,
         "name": name, "kind": kind, "t_start": t0, "t_end": t1,
         "attrs": dict(attrs), "events": []}
    if remote_parent is not None:
        s["remote_parent"] = remote_parent
    return s


def _mk_journal(cid, traces):
    return {"kind": "trace_journal", "version": 1, "pid": 1,
            "cid": cid, "written_at": "t",
            "clock_anchor": {"wall": 100.0, "monotonic": 50.0},
            "exemplars": {}, "traces": traces}


class TestFleetJournalMerge:
    """Synthetic router + replica journals reproducing the acceptance
    shape: attempt 1 dispatched to replica 0 (then killed), a reroute
    span naming the reason, attempt 2 finishing on replica 1 — all
    under ONE trace id, stitched on (trace_id, remote_parent)."""

    def _journals(self):
        router = _mk_journal("bbbbbbbbbbbbbbbb", {_FLEET_TID: {
            "trace_id": _FLEET_TID, "name": "fleet_request",
            "attrs": {"nonce": "n-1"}, "t_start": 10.0, "open_spans": 0,
            "spans": [
                _mk_span(0, "route", "request", 10.0, 14.0),
                _mk_span(1, "router_queue", "phase", 10.0, 10.5,
                         parent=0),
                _mk_span(2, "dispatch", "dispatch", 10.5, 10.6,
                         parent=0, nonce="n-1", replica=0,
                         outcome="accepted", attempt=1),
                _mk_span(3, "reroute", "reroute", 12.0, 12.0, parent=0,
                         reason="lease-evicted", from_rank=0),
                _mk_span(4, "dispatch", "dispatch", 12.1, 12.2,
                         parent=0, nonce="n-1", replica=1,
                         outcome="accepted", attempt=2),
                _mk_span(5, "settle", "settle", 14.0, 14.0, parent=0,
                         replica=1, status="finished"),
            ]}})
        victim = _mk_journal("cccccccccccccccc", {_FLEET_TID: {
            "trace_id": _FLEET_TID, "name": "request",
            "attrs": {"adopted": True}, "t_start": 10.5,
            "open_spans": 1,
            "spans": [_mk_span(0, "request", "request", 10.5, None,
                               remote_parent=2)]}})
        survivor = _mk_journal("dddddddddddddddd", {_FLEET_TID: {
            "trace_id": _FLEET_TID, "name": "request",
            "attrs": {"adopted": True}, "t_start": 12.1,
            "open_spans": 0,
            "spans": [_mk_span(0, "request", "request", 12.1, 14.0,
                               remote_parent=4)]}})
        return router, {0: victim, 1: survivor}

    def test_merge_prefixes_pids_and_stitches_flows(self):
        router, replicas = self._journals()
        evs = tmerge.merge_fleet_journals(router, replicas)
        pids = {e["pid"] for e in evs}
        assert "router/fleet_request" in pids
        assert "replica0/request" in pids and "replica1/request" in pids
        # one flow arrow per adopted replica span, dispatch -> request
        starts = [e for e in evs if e.get("ph") == "s"]
        finishes = [e for e in evs if e.get("ph") == "f"]
        assert len(starts) == 2 and len(finishes) == 2
        ids = {e["id"] for e in starts}
        assert ids == {"%s/2/r0" % _FLEET_TID, "%s/4/r1" % _FLEET_TID}
        assert {e["id"] for e in finishes} == ids
        # the arrow leaves the router track and lands on the replica's
        f1 = next(e for e in finishes
                  if e["id"] == "%s/4/r1" % _FLEET_TID)
        assert f1["pid"] == "replica1/request"
        s1 = next(e for e in starts
                  if e["id"] == "%s/4/r1" % _FLEET_TID)
        assert s1["pid"] == "router/fleet_request"
        assert s1["ts"] == pytest.approx(12.1 * 1e6)

    def test_merge_applies_clock_offsets_to_replica_events(self):
        router, replicas = self._journals()
        evs = tmerge.merge_fleet_journals(router, replicas,
                                          offsets={1: 0.5})
        # replica 1's clock runs 0.5s AHEAD of the router's: its spans
        # shift LEFT by 0.5s onto the router timebase
        x1 = next(e for e in evs if e.get("ph") == "X"
                  and e["pid"] == "replica1/request")
        assert x1["ts"] == pytest.approx((12.1 - 0.5) * 1e6)
        f1 = next(e for e in evs if e.get("ph") == "f"
                  and e["id"] == "%s/4/r1" % _FLEET_TID)
        assert f1["ts"] == pytest.approx((12.1 - 0.5) * 1e6)
        # router events never shift (it IS the timebase)
        xr = next(e for e in evs if e.get("ph") == "X"
                  and e["name"] == "route")
        assert xr["ts"] == pytest.approx(10.0 * 1e6)

    def test_fleet_trace_summary_orders_reroute_causality(self):
        router, _ = self._journals()
        summary = tmerge.fleet_trace_summary(router)
        row = summary[_FLEET_TID]
        assert row["nonce"] == "n-1"
        assert [d["replica"] for d in row["dispatches"]] == [0, 1]
        assert [d["outcome"] for d in row["dispatches"]] == \
            ["accepted", "accepted"]
        assert [r["reason"] for r in row["reroutes"]] == \
            ["lease-evicted"]
        assert row["reroutes"][0]["from_rank"] == 0
        # attempt 1 precedes the reroute precedes attempt 2
        assert row["dispatches"][0]["t_start"] \
            < row["reroutes"][0]["t_start"] \
            < row["dispatches"][1]["t_start"]

    def test_write_fleet_timeline_artifact(self, tmp_path):
        router, replicas = self._journals()
        path = str(tmp_path / "fleet_trace.json")
        doc = tmerge.write_fleet_timeline(path, router, replicas,
                                          offsets={1: 0.5},
                                          meta={"tool": "test"})
        on_disk = json.load(open(path))
        assert on_disk["kind"] == "fleet_trace"
        assert on_disk["requests"][_FLEET_TID]["reroutes"][0]["reason"] \
            == "lease-evicted"
        md = on_disk["metadata"]
        assert md["tool"] == "test"
        assert md["router_cid"] == "bbbbbbbbbbbbbbbb"
        assert md["replica_ranks"] == [0, 1]
        assert md["clock_offsets_s"] == {"1": 0.5}
        assert len(doc["traceEvents"]) == len(on_disk["traceEvents"])

    def test_trace_merge_cli_fleet_mode(self, tmp_path):
        router, replicas = self._journals()
        rp = str(tmp_path / "router.json")
        json.dump(router, open(rp, "w"))
        reps = []
        for r, j in replicas.items():
            p = str(tmp_path / ("replica%d.json" % r))
            json.dump(j, open(p, "w"))
            reps += ["--fleet-replica", "%d=%s" % (r, p)]
        out = str(tmp_path / "merged.json")
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import trace_merge as cli
        finally:
            sys.path.pop(0)
        rc = cli.main(["--out", out, "--fleet-router", rp,
                       "--fleet-offset", "1=0.5"] + reps)
        assert rc == 0
        merged = json.load(open(out))
        pids = {e.get("pid") for e in merged["traceEvents"]}
        assert "router/fleet_request" in pids
        assert "replica1/request" in pids
        assert any(e.get("ph") == "s" for e in merged["traceEvents"])
        # --fleet-replica without --fleet-router is an argparse error
        with pytest.raises(SystemExit):
            cli.main(["--out", out] + reps)
