"""/debugz route matrix: every registered route answers under every
monitor-flag disposition — all on AND all off — with pinned status
codes; off means "absent or empty", never a crash.

The fleet PR will route on these endpoints (drain-and-reschedule reads
/healthz, the router reads /debugz/perf), so the whole surface gets one
smoke matrix here instead of per-feature spot checks: `healthz`,
`metrics`, `metrics.json`, `stacks`, `flight`, `bundle`, `perf`,
`timeseries`, `trace` (+ the parametric `trace/{id}`).
"""
from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.monitor import fleet
from paddle_tpu.monitor import incidents as ptinc
from paddle_tpu.monitor import memory as ptmem
from paddle_tpu.monitor import perf
from paddle_tpu.monitor import profile as pprof
from paddle_tpu.monitor import registry as mreg
from paddle_tpu.monitor import slo as ptslo
from paddle_tpu.monitor import timeseries as ts
from paddle_tpu.monitor import trace
from paddle_tpu.monitor import watchdog as wd

# route -> (pinned status, body kind). These are the CONTRACT: a probe
# or router hardcodes them, so a refactor that changes one must show up
# here, not in production.
ROUTES = {
    "healthz": (200, "json"),
    "metrics": (200, "text"),
    "metrics.json": (200, "json"),
    "debugz/stacks": (200, "json"),
    "debugz/flight": (200, "json"),
    "debugz/bundle": (200, "json"),
    "debugz/perf": (200, "json"),
    "debugz/timeseries": (200, "json"),
    "debugz/trace": (200, "json"),
    "debugz/trace/journal": (200, "json"),
    "debugz/memory": (200, "json"),
    "debugz/profile": (200, "json"),
    "debugz/profile/folded": (200, "text"),
    "debugz/resilience": (200, "json"),
    "debugz/fleet": (200, "json"),
    "debugz/fleet/ranks": (200, "json"),
    "metrics/fleet": (200, "text"),
    "debugz/router": (200, "json"),
    "debugz/router/replicas": (200, "json"),
    "debugz/slo": (200, "json"),
    "debugz/incidents": (200, "json"),
    "debugz/fleet/incidents": (200, "json"),
    "debugz/replay": (200, "json"),
}

ALL_FLAGS = ("FLAGS_monitor_timeseries", "FLAGS_perf_attribution",
             "FLAGS_perf_sentinels", "FLAGS_monitor_trace",
             "FLAGS_monitor_fleet", "FLAGS_monitor_memory",
             "FLAGS_monitor_profile", "FLAGS_serving_fleet",
             "FLAGS_monitor_slo", "FLAGS_serving_replay")


@pytest.fixture()
def server():
    srv = monitor.MetricsServer(port=0).start()
    yield "http://127.0.0.1:%d" % srv.port
    srv.stop()


def _reset_monitor_state():
    from paddle_tpu.resilience import faultinject as _fi

    _fi.disable()
    _fi._state.rules = []
    paddle.set_flags({f: False for f in ALL_FLAGS})
    ptmem.reset()
    pprof.reset()
    perf.disable_sentinels()
    perf.reset()
    ptslo.disable()
    ptslo.clear()
    ptinc.disable()
    ptinc.clear()
    ts.disable()
    ts.clear()
    trace.disable()
    trace.clear()
    wd.stop_watchdog()
    fleet.stop_collector()
    fleet.clear_router_hook()
    # replay journal: reset WITHOUT importing it — the monitor plane
    # must stay importable with no serving (jax-heavy) modules loaded,
    # which is exactly the contract the /debugz/replay route keeps
    import sys as _sys
    _sreplay = _sys.modules.get("paddle_tpu.serving.replay")
    if _sreplay is not None:
        _sreplay.disable()
        _sreplay.clear()
    # drop router_*/replay_* series another suite's traffic may have
    # minted: the all-off matrix pins the families series-free
    for m in mreg.get_registry().metrics():
        if m.name.startswith(("router_", "replay_")):
            for store in ("_values", "_series"):
                for key in list(getattr(m, store, ()) or ()):
                    m.remove(*key)
    mreg.enable(trace_bridge=False)


@pytest.fixture(autouse=True)
def _clean():
    # reset BEFORE as well as after: the all-off matrix pins "watchdog
    # disabled / hooks None", which an earlier suite's leftovers (a
    # running watchdog, an enabled ring) would falsify
    _reset_monitor_state()
    yield
    _reset_monitor_state()


def _get(base, route):
    try:
        with urllib.request.urlopen("%s/%s" % (base, route),
                                    timeout=10) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _check_matrix(base):
    for route, (want_code, kind) in sorted(ROUTES.items()):
        code, body = _get(base, route)
        assert code == want_code, (route, code)
        if kind == "json":
            # every JSON route stays STRICT-parseable (no bare NaN)
            decoded = json.loads(
                body.decode(),
                parse_constant=lambda c: pytest.fail(
                    "%s emitted bare %s" % (route, c)))
            assert isinstance(decoded, dict)
        else:
            body.decode()


class TestRouteMatrixAllOff:
    def test_every_route_answers_with_flags_off(self, server):
        """All monitor feature flags at their defaults (off): every
        route still answers its pinned status — the payloads just say
        disabled/empty."""
        flags = paddle.get_flags(list(ALL_FLAGS))
        assert not any(flags.values())
        _check_matrix(server)
        # off == empty, pinned per subsystem:
        _, body = _get(server, "debugz/trace")
        p = json.loads(body.decode())
        assert p["enabled"] is False
        assert p["trace_count"] == 0 and p["exemplars"] == {}
        _, body = _get(server, "debugz/timeseries")
        assert json.loads(body.decode())["enabled"] is False
        _, body = _get(server, "debugz/perf")
        p = json.loads(body.decode())
        assert p["enabled"] == {"attribution": False,
                                "timeseries": False,
                                "sentinels": False}
        _, body = _get(server, "healthz")
        p = json.loads(body.decode())
        assert p["status"] == "ok" and p["watchdog"] == "disabled"
        _, body = _get(server, "debugz/memory")
        p = json.loads(body.decode())
        assert p["enabled"] is False
        assert p["components"] == {} and p["jobs"] == {}
        assert p["decisions"] == [] and p["postmortems"] == []
        _, body = _get(server, "debugz/profile")
        p = json.loads(body.decode())
        assert p["enabled"] is False
        assert p["sampler"] is None and p["jobs"] == {}
        assert p["captures"] == [] and p["top"] == []
        _, body = _get(server, "debugz/profile/folded")
        assert "ptprof disabled" in body.decode()
        # ...no sampler daemon thread exists with the flag off...
        import threading as _threading
        assert not [t for t in _threading.enumerate()
                    if t.name == pprof._THREAD_NAME]
        _, body = _get(server, "debugz/resilience")
        p = json.loads(body.decode())
        assert p["fault_injection"]["enabled"] is False
        _, body = _get(server, "debugz/fleet")
        p = json.loads(body.decode())
        assert p["enabled"] is False and p["collector"] is None
        _, body = _get(server, "debugz/fleet/ranks")
        p = json.loads(body.decode())
        assert p["enabled"] is False and p["ranks"] == []
        _, body = _get(server, "metrics/fleet")
        assert "not running" in body.decode()
        _, body = _get(server, "debugz/router")
        p = json.loads(body.decode())
        assert p == {"enabled": False, "router": None}
        _, body = _get(server, "debugz/router/replicas")
        p = json.loads(body.decode())
        assert p == {"enabled": False, "replicas": []}
        # SLO/incident plane off: disabled payloads, healthz stays
        # bit-identical (NO incidents_open key), zero slo_/incident_
        # series minted
        _, body = _get(server, "debugz/slo")
        p = json.loads(body.decode())
        assert p == {"enabled": False, "objectives": []}
        _, body = _get(server, "debugz/incidents")
        p = json.loads(body.decode())
        assert p == {"enabled": False, "open": [], "resolved": []}
        _, body = _get(server, "debugz/fleet/incidents")
        p = json.loads(body.decode())
        assert p == {"enabled": False, "incidents": []}
        _, body = _get(server, "healthz")
        assert "incidents_open" not in json.loads(body.decode())
        snap = mreg.get_registry().snapshot()
        for name, fam in snap.items():
            if name.startswith(("slo_", "incident_")):
                assert fam["series"] == [], name
        # replay journal off: the pinned disabled body — bit-identical
        # whether or not the serving package happens to be imported
        # (the route must not import it just to say "disabled") — and
        # zero replay_ series. The plane is thread-free by
        # construction on AND off: recording rides the engine's own
        # call stack (test_replay.py pins the engine-side path).
        _, body = _get(server, "debugz/replay")
        p = json.loads(body.decode())
        assert p == {"enabled": False, "requests": [], "dispatches": 0}
        for name, fam in mreg.get_registry().snapshot().items():
            if name.startswith("replay_"):
                assert fam["series"] == [], name
        # ...no collector / serving-fleet threads exist flags-off...
        import threading
        assert not [t for t in threading.enumerate()
                    if t.name == fleet._THREAD_NAME
                    or t.name.startswith("pt-sfleet")]
        # ...the serving-fleet router hook slot stayed None (the
        # route serves without ever importing the serving package)
        assert fleet._router_hook is None
        # ...and no router_* series materialized (registration is
        # series-free until a router/replica actually increments)
        snap = mreg.get_registry().snapshot()
        for name, fam in snap.items():
            if name.startswith("router_"):
                assert fam["series"] == [], name
        # ...and the registry hot-path hook slots stayed None
        assert mreg._state.ts_hook is None
        assert mreg._state.ex_hook is None

    def test_unknown_trace_id_404_not_crash(self, server):
        code, body = _get(server, "debugz/trace/no-such-trace")
        assert code == 404
        assert json.loads(body.decode())["error"] == "unknown trace"

    def test_unregistered_route_is_kv_404(self, server):
        code, _ = _get(server, "debugz/nope")
        assert code == 404


class TestRouteMatrixAllOn:
    def test_every_route_answers_with_flags_on(self, server):
        """Everything enabled at once (ring + sentinels + journal +
        watchdog thread) with live traffic: same pinned statuses, and
        the payloads carry the traffic."""
        paddle.set_flags({f: True for f in ALL_FLAGS})
        ts.enable()
        perf.enable_sentinels()
        ptslo.enable()
        trace.enable()
        wd.start_watchdog(stall_threshold_s=3600)
        fleet.start_collector(endpoints={0: server}, interval_s=0.1)
        monitor.gauge("t_routes_gauge").set(1.5)
        h = monitor.histogram("t_routes_seconds", buckets=(1.0,))
        tid = trace.new_trace("request", request_id=1)
        sid = trace.start_span("request", tid, kind="request")
        with trace.exemplar_context(tid):
            h.observe(0.5)
        trace.end_span(sid)
        perf.note_job("t_routes_job", tokens_per_s=10.0)
        ptmem.tracker("t_routes_job", {"c": lambda: [("x", 4096)]})
        sp = pprof.step_hook("t_routes_job")
        assert sp is not None
        t0 = time.monotonic()
        sp.step_begin()
        sp.step_end(t0, t0 + 0.01)

        _check_matrix(server)
        _, body = _get(server, "debugz/trace")
        p = json.loads(body.decode())
        assert p["enabled"] is True and p["trace_count"] >= 1
        assert p["exemplars"]["t_routes_seconds"]["1.0"]["trace_id"] \
            == tid
        code, body = _get(server, "debugz/trace/%s" % tid)
        assert code == 200
        p = json.loads(body.decode())
        assert p["trace_id"] == tid
        assert p["spans"][0]["name"] == "request"
        _, body = _get(server, "debugz/timeseries")
        p = json.loads(body.decode())
        assert p["enabled"] is True and "t_routes_gauge" in p["series"]
        _, body = _get(server, "debugz/perf")
        p = json.loads(body.decode())
        assert "t_routes_job" in p["jobs"]
        _, body = _get(server, "healthz")
        p = json.loads(body.decode())
        assert p["watchdog"] == "enabled" and p["status"] in (
            "ok", "degraded")
        _, body = _get(server, "debugz/memory")
        p = json.loads(body.decode())
        assert p["enabled"] is True
        assert p["components"]["t_routes_job"]["c"]["bytes"] == 4096
        assert "reconciliation" in p
        _, body = _get(server, "debugz/profile")
        p = json.loads(body.decode())
        assert p["enabled"] is True
        assert p["sampler"]["running"] is True
        assert p["jobs"]["t_routes_job"]["steps"] == 1
        _, body = _get(server, "debugz/profile/folded")
        assert "ptprof disabled" not in body.decode()
        _, body = _get(server, "metrics")
        assert "t_routes_gauge 1.5" in body.decode()
        # fleet routes carry the collector's fused self-scrape
        deadline = time.time() + 10
        while time.time() < deadline:
            if fleet.get_collector()._scrapes >= 1:
                break
            time.sleep(0.05)
        _, body = _get(server, "debugz/fleet")
        p = json.loads(body.decode())
        assert p["enabled"] is True
        assert p["collector"]["running"] is True
        _, body = _get(server, "debugz/fleet/ranks")
        p = json.loads(body.decode())
        assert [r["rank"] for r in p["ranks"]] == [0]
        assert p["ranks"][0]["ok"] is True
        _, body = _get(server, "metrics/fleet")
        assert 'rank="0"' in body.decode()
        _, body = _get(server, "debugz/trace/journal")
        p = json.loads(body.decode())
        assert p["kind"] == "trace_journal" and tid in p["traces"]
        # SLO/incident routes carry the live judge + table
        inc_id = ptinc.open("t_routes/incident", severity="ticket",
                            source="test", summary="route matrix")
        assert inc_id
        _, body = _get(server, "debugz/slo")
        p = json.loads(body.decode())
        assert p["enabled"] is True and p["objectives"]
        _, body = _get(server, "debugz/incidents")
        p = json.loads(body.decode())
        assert p["enabled"] is True
        assert [i["key"] for i in p["open"]] == ["t_routes/incident"]
        _, body = _get(server, "debugz/fleet/incidents")
        p = json.loads(body.decode())
        assert p["enabled"] is True
        assert any(i["key"] == "t_routes/incident"
                   for i in p["incidents"])
        # an open incident IS the degraded verdict while the plane is on
        _, body = _get(server, "healthz")
        p = json.loads(body.decode())
        assert p["status"] == "degraded" and p["incidents_open"] >= 1
        ptinc.resolve("t_routes/incident", reason="matrix done")
        # replay journal on: the route serves the live module payload
        # (capacity/entries/requests), not the pinned disabled stub
        from paddle_tpu.serving import replay as sreplay
        sreplay.enable()
        _, body = _get(server, "debugz/replay")
        p = json.loads(body.decode())
        assert p["enabled"] is True
        assert p["requests"] == [] and p["capacity"] >= 1
        # serving-fleet routes: flag on + a live (endpoint-mode)
        # router registered via the monitor hook
        from paddle_tpu.serving.fleet import Router
        router = Router(endpoints={0: "http://127.0.0.1:1"})
        try:
            _, body = _get(server, "debugz/router")
            p = json.loads(body.decode())
            assert p["enabled"] is True
            assert p["router"]["replicas"]["known"] == 1
            _, body = _get(server, "debugz/router/replicas")
            p = json.loads(body.decode())
            assert p["enabled"] is True
            assert [r["rank"] for r in p["replicas"]] == [0]
        finally:
            router.close()
        assert fleet._router_hook is None


class TestTraceFederation:
    """/debugz/trace/{id} ``federation`` matrix (ISSUE 17): pinned per
    FLAGS_serving_fleet x FLAGS_monitor_trace disposition — off means
    ``enabled: false`` with ZERO cross-replica fetches and no new
    threads; on means the replica fragments federate on demand."""

    def _local_trace(self):
        tid = trace.new_trace("fleet_request", nonce="n-1")
        sid = trace.start_span("route", tid, kind="request")
        trace.end_span(sid)
        return tid

    def test_trace_on_fleet_off_pins_disabled_zero_fetches(
            self, server):
        paddle.set_flags({"FLAGS_monitor_trace": True})
        trace.enable()
        tid = self._local_trace()

        # a hook whose fetch path fires despite the flag being off is
        # the contract bug this test exists to catch
        class _Boom:
            def trace_segments(self, _tid):
                pytest.fail("federation fetched with "
                            "FLAGS_serving_fleet off")

        fleet.set_router_hook(_Boom())
        try:
            import threading as _threading
            threads_before = set(_threading.enumerate())
            code, body = _get(server, "debugz/trace/%s" % tid)
            assert code == 200
            p = json.loads(body.decode())
            assert p["federation"] == {"enabled": False}
            # the 404-for-unknown contract is unchanged by federation
            code, _ = _get(server, "debugz/trace/no-such-trace")
            assert code == 404
            assert set(_threading.enumerate()) == threads_before
        finally:
            fleet.clear_router_hook()

    def test_fleet_on_trace_off_unknown_ids_404(self, server):
        paddle.set_flags({"FLAGS_serving_fleet": True})
        # journal off: no traces exist, so every id 404s — federation
        # never runs for a trace that cannot resolve locally
        code, body = _get(server, "debugz/trace/anything")
        assert code == 404
        assert json.loads(body.decode())["error"] == "unknown trace"

    def test_both_on_hook_without_segments_pins_empty(self, server):
        paddle.set_flags({"FLAGS_serving_fleet": True,
                          "FLAGS_monitor_trace": True})
        trace.enable()
        tid = self._local_trace()
        fleet.set_router_hook(object())     # duck-type: no
        try:                                # trace_segments attr
            _, body = _get(server, "debugz/trace/%s" % tid)
            p = json.loads(body.decode())
            assert p["federation"] == {"enabled": True, "segments": {}}
        finally:
            fleet.clear_router_hook()

    def test_both_on_unreachable_replica_degrades_to_error_stub(
            self, server):
        from paddle_tpu.serving.fleet import Router

        paddle.set_flags({"FLAGS_serving_fleet": True,
                          "FLAGS_monitor_trace": True})
        trace.enable()
        tid = self._local_trace()
        router = Router(endpoints={0: "http://127.0.0.1:1"})
        try:
            code, body = _get(server, "debugz/trace/%s" % tid)
            assert code == 200          # best-effort, never a crash
            p = json.loads(body.decode())
            fed = p["federation"]
            assert fed["enabled"] is True
            assert "error" in fed["segments"]["0"]
        finally:
            router.close()

    def test_both_on_federates_replica_fragments(self, server):
        """Endpoint-mode router pointing at a second in-process
        MetricsServer: the federation block carries that 'replica's'
        fragment, and the fragment is the LOCAL view (?local=1) — a
        fragment fetch never recurses into another fan-out."""
        from paddle_tpu.serving.fleet import Router

        paddle.set_flags({"FLAGS_serving_fleet": True,
                          "FLAGS_monitor_trace": True})
        trace.enable()
        tid = self._local_trace()
        replica_srv = monitor.MetricsServer(port=0).start()
        router = Router(endpoints={
            0: "http://127.0.0.1:%d" % replica_srv.port})
        try:
            code, body = _get(server, "debugz/trace/%s" % tid)
            assert code == 200
            p = json.loads(body.decode())
            fed = p["federation"]
            assert fed["enabled"] is True
            frag = fed["segments"]["0"]
            assert frag["trace_id"] == tid
            assert frag["spans"][0]["name"] == "route"
            # the fragment is local-only: no nested federation block
            assert "federation" not in frag
            # a router-submitted id resolves its nonce for attribution
            assert fed["nonce"] is None     # not router-submitted here
        finally:
            router.close()
            replica_srv.stop()
