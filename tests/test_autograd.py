"""Autograd engine tests (reference test_imperative_basic.py,
test_eager_* backward semantics)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def _f32(*shape):
    return np.random.RandomState(3).uniform(0.5, 1.5, shape).astype(np.float32)


class TestBackward:
    def test_scalar_backward(self):
        x = paddle.to_tensor(_f32(3, 4), stop_gradient=False)
        y = (x * x).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), 2 * x.numpy(), rtol=1e-5)

    def test_chain(self):
        x = paddle.to_tensor(_f32(3), stop_gradient=False)
        y = paddle.exp(paddle.log(x) * 2.0).sum()  # = sum(x^2)
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), 2 * x.numpy(), rtol=1e-4)

    def test_grad_accumulation(self):
        x = paddle.to_tensor(_f32(3), stop_gradient=False)
        (x * 2).sum().backward()
        (x * 3).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.full(3, 5.0), rtol=1e-5)

    def test_shared_input_fanout(self):
        x = paddle.to_tensor(_f32(3), stop_gradient=False)
        y = x * x + x * x  # x used twice in two ops
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), 4 * x.numpy(), rtol=1e-5)

    def test_stop_gradient(self):
        x = paddle.to_tensor(_f32(3), stop_gradient=False)
        y = paddle.to_tensor(_f32(3), stop_gradient=True)
        (x * y).sum().backward()
        assert y.grad is None
        np.testing.assert_allclose(x.grad.numpy(), y.numpy(), rtol=1e-5)

    def test_detach(self):
        x = paddle.to_tensor(_f32(3), stop_gradient=False)
        d = (x * 2).detach()
        assert d.stop_gradient
        z = (x * d).sum()
        z.backward()
        np.testing.assert_allclose(x.grad.numpy(), d.numpy(), rtol=1e-5)

    def test_non_scalar_backward_needs_grad(self):
        x = paddle.to_tensor(_f32(3), stop_gradient=False)
        y = x * 2
        with pytest.raises(RuntimeError):
            y.backward()
        y.backward(paddle.ones_like(y))
        np.testing.assert_allclose(x.grad.numpy(), np.full(3, 2.0), rtol=1e-5)

    def test_retain_graph(self):
        x = paddle.to_tensor(_f32(3), stop_gradient=False)
        y = (x * x).sum()
        y.backward(retain_graph=True)
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), 4 * x.numpy(), rtol=1e-5)

    def test_freed_graph_raises(self):
        x = paddle.to_tensor(_f32(3), stop_gradient=False)
        y = (x * x).sum()
        y.backward()
        with pytest.raises(RuntimeError):
            y.backward()

    def test_multi_output_op(self):
        x = paddle.to_tensor(_f32(4, 6), stop_gradient=False)
        parts = paddle.split(x, 2, axis=1)
        (parts[0].sum() * 2 + parts[1].sum()).backward()
        exp = np.concatenate([np.full((4, 3), 2.0), np.full((4, 3), 1.0)], 1)
        np.testing.assert_allclose(x.grad.numpy(), exp, rtol=1e-5)


class TestGradAPI:
    def test_grad_basic(self):
        x = paddle.to_tensor(_f32(3), stop_gradient=False)
        y = (x * x).sum()
        (gx,) = paddle.grad(y, x)
        np.testing.assert_allclose(gx.numpy(), 2 * x.numpy(), rtol=1e-5)
        assert x.grad is None  # paddle.grad must not pollute .grad

    def test_grad_intermediate(self):
        x = paddle.to_tensor(_f32(3), stop_gradient=False)
        h = x * 2
        y = (h * h).sum()
        (gh,) = paddle.grad(y, h, retain_graph=True)
        np.testing.assert_allclose(gh.numpy(), 2 * h.numpy(), rtol=1e-5)

    def test_grad_unused(self):
        x = paddle.to_tensor(_f32(3), stop_gradient=False)
        z = paddle.to_tensor(_f32(3), stop_gradient=False)
        y = (x * x).sum()
        with pytest.raises(RuntimeError):
            paddle.grad(y, [x, z], retain_graph=True)
        gx, gz = paddle.grad(y, [x, z], allow_unused=True)
        assert gz is None

    def test_no_grad_context(self):
        x = paddle.to_tensor(_f32(3), stop_gradient=False)
        with paddle.no_grad():
            y = x * x
        assert y.stop_gradient


class TestPyLayer:
    def test_pylayer(self):
        from paddle_tpu.autograd import PyLayer

        class Square(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x

            @staticmethod
            def backward(ctx, grad):
                (x,) = ctx.saved_tensor()
                return grad * x * 2

        x = paddle.to_tensor(_f32(3), stop_gradient=False)
        y = Square.apply(x)
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), 2 * x.numpy(), rtol=1e-5)
