"""Compiled text generation: tokenizer -> KV-cached decode -> detokenize.

Serving-path demo: BERT-style wordpiece tokenization over StringTensor
(host side), then GenerationMixin.generate — a jitted prefill plus the
whole decode loop as ONE XLA while-loop over static cache buffers.

    python examples/generate_text.py
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.text import BertTokenizer, FasterTokenizer

# toy whitespace-ish vocab; production swaps in a real vocab file
WORDS = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]", "the", "quick",
         "brown", "fox", "jump", "##s", "##ed", "over", "lazy", "dog",
         "run", "##ning", "!", "."]
VOCAB = {w: i for i, w in enumerate(WORDS)}


def main():
    paddle.seed(0)
    tok = FasterTokenizer(VOCAB, max_seq_len=16)
    ids, _ = tok(paddle.StringTensor(["the quick brown fox"]))
    print("prompt ids:", np.asarray(ids._value)[0].tolist())

    cfg = LlamaConfig(vocab_size=len(WORDS), hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, max_position_embeddings=64,
                      use_parallel=False)
    model = LlamaForCausalLM(cfg)  # untrained: tokens are arbitrary

    out = model.generate(ids, max_new_tokens=8, do_sample=True, top_k=5,
                         temperature=0.8, seed=7)
    gen = np.asarray(out._value)[0]
    bert = BertTokenizer(VOCAB)
    print("generated ids:", gen.tolist())
    print("generated tokens:", bert.convert_ids_to_tokens(gen))


if __name__ == "__main__":
    main()
