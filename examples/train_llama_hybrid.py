"""Hybrid-parallel Llama training: dp x mp x ZeRO in ONE compiled step.

The flagship distributed config (BASELINE.md "GPT/Llama TP+PP hybrid"):
every parallelism dimension enters as a sharding; XLA inserts and
overlaps the collectives. Run on 8 virtual CPU devices:

    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/train_llama_hybrid.py

or unchanged on a real TPU slice (the mesh maps onto ICI).
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import mesh
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.parallel.engine import CompiledTrainStep


def main(steps=10):
    import jax

    n = len(jax.devices())
    dp, mp, sharding = (2, 2, 2) if n >= 8 else (1, 1, 1)
    mesh.build_hybrid_mesh(dp=dp, mp=mp, sharding=sharding,
                           devices=jax.devices()[:dp * mp * sharding])
    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                      intermediate_size=256, num_hidden_layers=2,
                      num_attention_heads=4, max_position_embeddings=256,
                      use_parallel=True)
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=3e-4,
                                 parameters=model.parameters())

    def loss_fn(logits, labels):
        return F.cross_entropy(logits.reshape([-1, cfg.vocab_size]),
                               labels.reshape([-1]))

    # zero_stage=2: grads reduce-scattered + opt state sharded over
    # 'sharding'; stage 3 would shard the params themselves
    step = CompiledTrainStep(model, loss_fn, opt, zero_stage=2)
    rng = np.random.RandomState(0)
    batch, seq = 4 * dp * sharding, 64
    for i in range(steps):
        ids = paddle.to_tensor(
            rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
        labels = paddle.to_tensor(
            rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
        loss = step(ids, labels)
        print("step %d loss %.4f" % (i, float(loss)))
    # prove the q_proj weight is tensor-parallel sharded
    q = dict(model.named_parameters())[
        "llama.layers.0.self_attn.q_proj.weight"]
    print("q_proj sharding:", q._value.sharding.spec)
    return float(loss)


if __name__ == "__main__":
    main()
