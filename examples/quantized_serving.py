"""Quantized int8 serving end to end.

The reference deploy recipe (slim: train float -> PTQ calibrate ->
save_quantized_model -> int8 inference kernels) mapped TPU-native:
train float -> PTQ().quantize + calibrate -> convert_to_int8 (weights
frozen to s8, activations on calibrated scales; matmuls run s8 x s8 ->
s32 on the MXU at 2x the bf16 peak on v5e) -> serve.

    python examples/quantized_serving.py
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.quantization import PTQ, convert_to_int8


def make_data(n=512, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 1, 8, 8).astype(np.float32)
    # label: is the center patch brighter than the border?
    y = (x[:, 0, 2:6, 2:6].mean(axis=(1, 2))
         > x[:, 0].mean(axis=(1, 2))).astype(np.int64)
    return x, y


def build_model():
    return nn.Sequential(
        nn.Conv2D(1, 8, 3, padding=1), nn.ReLU(), nn.MaxPool2D(2),
        nn.Conv2D(8, 16, 3, padding=1), nn.ReLU(), nn.MaxPool2D(2),
        nn.Flatten(), nn.Linear(16 * 2 * 2, 2))


def accuracy(model, x, y, batch=128):
    hits = 0
    for i in range(0, len(x), batch):
        logits = model(paddle.to_tensor(x[i:i + batch]))
        hits += int((logits.numpy().argmax(1) == y[i:i + batch]).sum())
    return hits / len(x)


def main(train_steps=60, calib_batches=4):
    paddle.seed(0)
    x, y = make_data()
    model = build_model()
    opt = paddle.optimizer.Adam(learning_rate=2e-3,
                                parameters=model.parameters())
    for i in range(train_steps):
        lo = (i * 64) % len(x)
        xb = paddle.to_tensor(x[lo:lo + 64])
        yb = paddle.to_tensor(y[lo:lo + 64])
        loss = F.cross_entropy(model(xb), yb)
        loss.backward()
        opt.step()
        opt.clear_grad()
    model.eval()
    float_acc = accuracy(model, x, y)

    # PTQ: observe activation ranges on calibration batches, then freeze
    # everything into true int8 execution
    ptq = PTQ()
    q = ptq.quantize(model)
    ptq.calibrate(q, [x[i * 64:(i + 1) * 64] for i in range(calib_batches)])
    deploy = convert_to_int8(q)
    int8_acc = accuracy(deploy, x, y)
    print("float accuracy: %.3f | int8 accuracy: %.3f" %
          (float_acc, int8_acc))
    return float_acc, int8_acc


if __name__ == "__main__":
    main()
