"""GNN mini-batch training against the PS graph table.

The GraphSAGE pattern over the distributed graph service (reference
ps/table/common_graph_table.h + pscore graph ops): the server owns the
graph (adjacency + node features) and answers fixed-shape sampling
queries, so the device only ever compiles over dense [batch, k, dim]
tensors — no ragged structure reaches XLA. Two-hop neighborhood:
sample -> gather -> mean-aggregate -> concat -> dense layers.

    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= python examples/gnn_graphsage.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn as nn  # noqa: E402
import paddle_tpu.nn.functional as F  # noqa: E402
from paddle_tpu.distributed.ps import PsClient, PsServer  # noqa: E402


def build_two_community_graph(cli, n=200, dim=16, seed=0):
    """Two communities with dense intra-links and sparse cross-links;
    features carry a noisy community signal — the classic setting where
    neighbor aggregation beats a featurewise classifier."""
    rng = np.random.RandomState(seed)
    cli.create_graph_table(0, feat_dim=dim, seed=seed)
    labels = (np.arange(n) >= n // 2).astype(np.int32)
    src, dst = [], []
    for u in range(n):
        same = np.where(labels == labels[u])[0]
        other = np.where(labels != labels[u])[0]
        nbrs = np.concatenate([rng.choice(same, 8),
                               rng.choice(other, 1)])
        src += [u] * len(nbrs)
        dst += list(nbrs)
    cli.graph_add_edges(0, src, dst)
    feats = rng.randn(n, dim).astype(np.float32) * 1.0
    feats[:, 0] += (labels * 2 - 1) * 0.5  # weak signal, needs hops
    cli.graph_set_node_feat(0, np.arange(n), feats)
    return labels


class SageNet(nn.Layer):
    def __init__(self, dim, hidden=32):
        super().__init__()
        self.l1 = nn.Linear(2 * dim, hidden)
        self.l2 = nn.Linear(hidden, 2)

    def forward(self, self_f, agg_f):
        h = paddle.concat([self_f, agg_f], axis=-1)
        return self.l2(F.relu(self.l1(h)))


def sample_batch(cli, labels, batch_size=64, k=8, dim=16):
    ids = cli.graph_random_nodes(0, batch_size)
    nb = cli.graph_sample_neighbors(0, ids, k)
    valid = nb >= 0
    nf = cli.graph_get_node_feat(
        0, np.where(valid, nb, 0).reshape(-1)).reshape(
            batch_size, k, dim)
    mask = valid[..., None].astype(np.float32)
    agg = (nf * mask).sum(1) / np.maximum(mask.sum(1), 1.0)
    self_f = cli.graph_get_node_feat(0, ids)
    return (paddle.to_tensor(self_f), paddle.to_tensor(agg),
            paddle.to_tensor(labels[ids]))


def main():
    dim = 16
    srv = PsServer()
    try:
        with PsClient(port=srv.port) as cli:
            labels = build_two_community_graph(cli, dim=dim)
            paddle.seed(0)
            net = SageNet(dim)
            opt = paddle.optimizer.Adam(learning_rate=0.01,
                                        parameters=net.parameters())
            for step in range(60):
                self_f, agg, y = sample_batch(cli, labels, dim=dim)
                loss = F.cross_entropy(net(self_f, agg), y)
                loss.backward()
                opt.step()
                opt.clear_grad()
                if step % 20 == 0:
                    print("step %3d loss %.4f" % (step, float(loss)))
            # evaluate on every node
            ids = np.arange(len(labels))
            nb = cli.graph_sample_neighbors(0, ids, 8)
            valid = nb >= 0
            nf = cli.graph_get_node_feat(
                0, np.where(valid, nb, 0).reshape(-1)).reshape(
                    len(ids), 8, dim)
            m = valid[..., None].astype(np.float32)
            agg = (nf * m).sum(1) / np.maximum(m.sum(1), 1.0)
            logits = net(paddle.to_tensor(cli.graph_get_node_feat(0, ids)),
                         paddle.to_tensor(agg))
            pred = np.asarray(logits.numpy()).argmax(-1)
            acc = float((pred == labels).mean())
            print("full-graph accuracy: %.3f" % acc)
            assert acc > 0.8, acc
    finally:
        srv.stop()


if __name__ == "__main__":
    main()
