"""Dygraph training end to end: LeNet on (synthetic) MNIST.

The reference's hello-world config (SURVEY.md §7 step 3 minimum slice):
Dataset -> DataLoader -> Layer -> loss -> backward -> Adam -> lr schedule
-> save/load. Runs on CPU or a TPU chip unchanged.

    python examples/train_mnist.py
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


class LeNet(nn.Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 5, padding=2), nn.ReLU(), nn.MaxPool2D(2),
            nn.Conv2D(6, 16, 5), nn.ReLU(), nn.MaxPool2D(2))
        self.fc = nn.Sequential(
            nn.Linear(16 * 5 * 5, 120), nn.ReLU(),
            nn.Linear(120, 84), nn.ReLU(), nn.Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        return self.fc(paddle.flatten(x, 1))


def main(epochs=1, steps_per_epoch=30, batch_size=64,
         ckpt_path="/tmp/lenet.pdparams"):
    paddle.seed(0)
    model = LeNet()
    sched = paddle.optimizer.lr.CosineAnnealingDecay(
        learning_rate=1e-3, T_max=epochs * steps_per_epoch)
    opt = paddle.optimizer.Adam(learning_rate=sched,
                                parameters=model.parameters())
    rng = np.random.RandomState(0)
    for epoch in range(epochs):
        for step in range(steps_per_epoch):
            # synthetic batch (swap for paddle.vision.datasets.MNIST +
            # paddle.io.DataLoader with a real data directory)
            x = paddle.to_tensor(
                rng.randn(batch_size, 1, 28, 28).astype("float32"))
            y = paddle.to_tensor(
                rng.randint(0, 10, (batch_size,)).astype("int64"))
            loss = F.cross_entropy(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            sched.step()
            if step % 10 == 0:
                print("epoch %d step %d loss %.4f lr %.2e"
                      % (epoch, step, float(loss), sched.get_lr()))
    paddle.save(model.state_dict(), ckpt_path)
    model.set_state_dict(paddle.load(ckpt_path))
    print("saved + reloaded OK")
    return float(loss)


if __name__ == "__main__":
    main()
