"""Continuous-batching serving: paged KV cache + ONE compiled decode.

The production serving path (`paddle_tpu.serving`): requests with
different prompt lengths and budgets arrive while others are mid-
decode, stream through a fixed pool of KV pages, and share a single
jitted decode step — no shape changes, no recompiles, slots reused the
moment a request hits EOS or its token budget.

    python examples/continuous_batching.py
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import serving
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def main():
    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, max_position_embeddings=128,
                      use_parallel=False)
    model = LlamaForCausalLM(cfg)  # untrained: tokens are arbitrary

    eng = serving.Engine(model, max_slots=2, num_blocks=64, block_size=8)
    rng = np.random.RandomState(0)

    # two requests in flight...
    first = [eng.add_request(rng.randint(0, 128, (n,)).tolist(),
                             max_new_tokens=8) for n in (5, 11)]
    eng.step()
    # ...and two more arriving mid-decode — same compiled step serves all
    late = [eng.add_request(rng.randint(0, 128, (n,)).tolist(),
                            max_new_tokens=6) for n in (3, 7)]
    outs = eng.run()

    for rid in first + late:
        m = eng.request_metrics(rid)
        print("request %d: %d prompt -> %s (ttft %.1f ms)"
              % (rid, m["prompt_tokens"], outs[rid], m["ttft_s"] * 1e3))
    s = eng.stats()
    print("decode compiles: %d  (steps: %d, throughput %.0f tok/s)"
          % (s["decode_compiles"], s["decode_steps"],
             s["throughput_tok_s"]))
    return s


if __name__ == "__main__":
    main()
