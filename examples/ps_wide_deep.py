"""Parameter-server training: wide&deep with PS-held sparse embeddings.

The recommender path (reference the-one-PS): a server process owns the
sparse embedding table + dense slots; workers pull touched rows, compute
the dense part on-device, and push gradients back (async SGD). This demo
runs server and worker in one process against the in-process runtime;
tests/test_ps.py runs the same flow over real TCP worker processes.

    python examples/ps_wide_deep.py
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.ps.runtime import TheOnePSRuntime


def main(steps=20, n_slots=8, vocab=1000, dim=8):
    paddle.seed(0)
    rt = TheOnePSRuntime()
    table = rt.create_sparse_table("emb", dim, optimizer="adagrad", lr=0.05)
    deep = nn.Sequential(nn.Linear(n_slots * dim, 32), nn.ReLU(),
                         nn.Linear(32, 1))
    wide = nn.Linear(n_slots, 1)
    opt = paddle.optimizer.Adam(
        learning_rate=1e-3,
        parameters=deep.parameters() + wide.parameters())

    rng = np.random.RandomState(0)
    for i in range(steps):
        ids = rng.randint(0, vocab, (32, n_slots))
        y = (ids.sum(axis=1, keepdims=True) % 2).astype(np.float32)

        uniq, inv = np.unique(ids.reshape(-1), return_inverse=True)
        rows = np.asarray(table.pull(uniq.tolist()))      # PS pull
        emb = rows[inv].reshape(32, n_slots * dim)

        emb_t = paddle.to_tensor(emb.astype(np.float32))
        emb_t.stop_gradient = False
        wide_in = paddle.to_tensor((ids % 2).astype(np.float32))
        logit = deep(emb_t) + wide(wide_in)
        loss = F.binary_cross_entropy_with_logits(logit,
                                                  paddle.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()

        grad = np.asarray(emb_t.grad._value).reshape(-1, dim)  # PS push
        gsum = np.zeros((len(uniq), dim), np.float32)
        np.add.at(gsum, inv, grad)
        table.push(uniq.tolist(), gsum)
        if i % 5 == 0:
            print("step %d loss %.4f table rows %d"
                  % (i, float(loss), table.size()))
    return float(loss)


if __name__ == "__main__":
    main()
