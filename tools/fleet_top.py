"""htop-for-ranks: live per-rank fleet table from the collector view.

Scrapes every rank's metrics endpoint (monitor/fleet.py
FleetCollector, run in-process here — no server-side collector needed)
and renders the per-rank table: step, step time, tokens/s, MFU, HBM
peak, live memory + headroom (the /debugz/memory plane, round 14),
measured host-blocked share (the /debugz/profile plane, round 15),
comm share, serving-router replica count + affinity hit rate where a
rank hosts one (the /debugz/router plane, round 17), heartbeat age,
health verdict, straggler flag.

Endpoints come from one of:
  --endpoints URL[,URL...]   explicit list (rank = position, or R=URL)
  --store HOST:PORT --world N   discovery from the fleet TCPStore the
      ranks announced into (``__fleet/ep/rank{r}``, written by
      ``monitor.fleet.announce`` / ``init_parallel_env`` under
      ``FLAGS_monitor_fleet``)

Modes:
  (default)       live: redraw the table every --interval seconds
  --once          two scrapes --window apart (rates need a delta),
                  print the table, exit
  --json          print the machine-readable snapshot instead of the
                  table (scripts; implies --once unless live)
  --out PATH      write the fleet snapshot artifact. bench.py's
                  staleness discipline applies: if NOTHING answered
                  the scrape and PATH already holds a previous
                  snapshot, it is re-emitted marked ``stale: true``
                  (+ stale_generations/stale_since) instead of
                  silently photocopying — and the exit code is 3.

Usage:
  python tools/fleet_top.py --endpoints http://h1:9000,http://h2:9000
  python tools/fleet_top.py --store 127.0.0.1:6170 --world 4 --once --json
  python tools/fleet_top.py --store ... --world 4 --out tools/fleet_snapshot.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

from paddle_tpu.monitor import fleet  # noqa: E402
from paddle_tpu.monitor.watchdog import json_safe  # noqa: E402


def _fmt(v, spec="%s", dash="-"):
    if v is None:
        return dash
    try:
        return spec % v
    except (TypeError, ValueError):
        return str(v)


def _fmt_bytes(v):
    if v is None:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024 or unit == "TiB":
            return "%.1f%s" % (v, unit) if unit != "B" \
                else "%d%s" % (v, unit)
        v /= 1024.0
    return "-"


COLS = (
    ("RANK", 4, lambda r: _fmt(r.get("rank"), "%d")),
    ("STEP", 7, lambda r: _fmt(r.get("steps_total"), "%d")),
    ("BEHIND", 6, lambda r: _fmt(r.get("steps_behind"), "%d")),
    ("STEP_S", 8, lambda r: _fmt(r.get("step_time_s"), "%.3f")),
    ("TOK/S", 9, lambda r: _fmt(r.get("tokens_per_s"), "%.0f")),
    ("MFU", 6, lambda r: _fmt(r.get("mfu"), "%.3f")),
    ("HBM_PEAK", 9, lambda r: _fmt_bytes(r.get("hbm_peak_bytes"))),
    ("MEM", 9, lambda r: _fmt_bytes(r.get("mem_live_bytes"))),
    ("HEADROOM", 9, lambda r: _fmt_bytes(r.get("mem_headroom_bytes"))),
    ("HOSTBLK%", 8, lambda r: _fmt(
        r.get("profile_host_blocked_share") * 100 if isinstance(
            r.get("profile_host_blocked_share"), (int, float))
        else None, "%.1f")),
    ("COMM%", 6, lambda r: _fmt(
        r.get("comm_share") * 100 if isinstance(
            r.get("comm_share"), (int, float)) else None, "%.1f")),
    # serving-fleet router columns (blank unless the rank hosts a
    # Router — /debugz/router answers with a live hook there only)
    ("REPLICAS", 8, lambda r: _fmt(r.get("router_replicas"), "%d")),
    ("AFFIN%", 6, lambda r: _fmt(
        r.get("router_affinity_hit_rate") * 100 if isinstance(
            r.get("router_affinity_hit_rate"), (int, float))
        else None, "%.1f")),
    ("HB_AGE", 7, lambda r: _fmt(r.get("heartbeat_age_s"), "%.1f")),
    ("HEALTH", 9, lambda r: ("UNREACH" if not r.get("ok")
                             else (r.get("healthz") or "-"))),
    ("ANOM", 5, lambda r: _fmt(r.get("anomalies_total"), "%d")),
    ("STRAG", 5, lambda r: ("YES" if r.get("straggler") else "")),
    # SLO/incident columns (blank unless the rank runs
    # FLAGS_monitor_slo): worst objective's attainment %, worst
    # error-budget remaining %, open incident count
    ("SLO%", 6, lambda r: _fmt(
        r.get("slo_attainment_min") * 100 if isinstance(
            r.get("slo_attainment_min"), (int, float))
        else None, "%.1f")),
    ("BUDGET%", 7, lambda r: _fmt(
        r.get("slo_budget_min") * 100 if isinstance(
            r.get("slo_budget_min"), (int, float))
        else None, "%.1f")),
    ("INC", 4, lambda r: _fmt(r.get("incidents_open"), "%d")),
)


def render_table(rows, summary=None):
    lines = []
    hdr = "  ".join("%-*s" % (w, name) for name, w, _ in COLS)
    lines.append(hdr)
    lines.append("-" * len(hdr))
    for r in rows:
        lines.append("  ".join("%-*s" % (w, fn(r)[:w + 8])
                               for _, w, fn in COLS))
    if summary:
        strag = summary.get("stragglers") or {}
        caps = summary.get("captures") or ()
        lines.append("")
        lines.append(
            "scrapes=%s  ranks_ok=%s/%s  stragglers=%s  captures=%d"
            % (summary.get("collector", {}).get("scrapes"),
               len(summary.get("ranks_ok") or ()),
               summary.get("world_size"),
               ",".join(sorted(strag)) or "none", len(caps)))
        for c in caps[-2:]:
            lines.append("  capture[%s]: %s" % (c["reason"], c["dir"]))
    return "\n".join(lines)


def build_collector(args):
    endpoints = None
    store = None
    if args.endpoints:
        endpoints = {}
        for i, spec in enumerate(args.endpoints.replace(",", " ").split()):
            if "=" in spec and not spec.startswith("http"):
                r, _, u = spec.partition("=")
                endpoints[int(r)] = u
            else:
                endpoints[i] = spec
    elif args.store:
        from paddle_tpu.distributed.store import TCPStore

        host, _, port = args.store.partition(":")
        store = TCPStore(host or "127.0.0.1", int(port),
                         is_master=False, timeout_s=args.http_timeout + 5)
        if not args.world:
            sys.exit("--store needs --world N")
    else:
        sys.exit("need --endpoints or --store (see --help)")
    return fleet.FleetCollector(
        endpoints=endpoints, store=store, world_size=args.world,
        interval_s=args.interval, straggler_factor=args.factor,
        straggler_persist=args.persist, capture_dir=args.capture_dir,
        http_timeout_s=args.http_timeout)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="live per-rank fleet telemetry table")
    ap.add_argument("--endpoints", help="comma/space list of rank "
                                        "endpoint URLs (or R=URL)")
    ap.add_argument("--store", help="fleet TCPStore HOST:PORT to "
                                    "discover announced endpoints from")
    ap.add_argument("--world", type=int, default=0,
                    help="world size (required with --store)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="live-mode refresh seconds (default 2)")
    ap.add_argument("--window", type=float, default=1.0,
                    help="--once: delta window between the two scrapes")
    ap.add_argument("--once", action="store_true",
                    help="two scrapes, one table, exit")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable snapshot instead of a table")
    ap.add_argument("--out", help="write the fleet snapshot artifact "
                                  "(stale re-emit on a dead scrape)")
    ap.add_argument("--duration", type=float, default=0.0,
                    help="live mode: exit after this many seconds "
                         "(0 = until interrupted)")
    ap.add_argument("--factor", type=float, default=None,
                    help="straggler factor vs fleet median step time")
    ap.add_argument("--persist", type=int, default=None,
                    help="consecutive slow scrapes before flagging")
    ap.add_argument("--capture-dir", default=None,
                    help="where anomaly captures land "
                         "(default PT_MONITOR_DUMP_DIR)")
    ap.add_argument("--http-timeout", type=float, default=3.0)
    args = ap.parse_args(argv)

    c = build_collector(args)
    once = args.once or args.json or bool(args.out)
    try:
        if once:
            c.scrape_once()
            time.sleep(args.window)
            c.scrape_once()
            snap = fleet.snapshot_dict(c)
            if args.out:
                snap = fleet.write_snapshot_artifact(args.out,
                                                     collector=c)
                print("fleet_top: wrote %s (%d rank(s)%s)"
                      % (args.out, len(snap.get("ranks") or ()),
                         ", STALE re-emit" if snap.get("stale")
                         else ""), file=sys.stderr)
            if args.json:
                json.dump(json_safe(snap), sys.stdout,
                          indent=1, default=str)
                sys.stdout.write("\n")
            else:
                print(render_table(c.ranks_table(), c.summary()))
            return 3 if snap.get("stale") or not snap.get("ok") else 0
        deadline = (time.monotonic() + args.duration
                    if args.duration > 0 else None)
        while True:
            t0 = time.monotonic()
            c.scrape_once()
            sys.stdout.write("\x1b[2J\x1b[H")
            print("fleet_top  %s  interval=%.1fs"
                  % (time.strftime("%H:%M:%S"), args.interval))
            print(render_table(c.ranks_table(), c.summary()))
            sys.stdout.flush()
            if deadline is not None and time.monotonic() >= deadline:
                return 0
            time.sleep(max(args.interval - (time.monotonic() - t0), 0.05))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
