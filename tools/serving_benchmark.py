"""Serving-engine benchmark: continuous batching under Poisson traffic.

Synthetic open-loop workload (the serving analog of bench.py's training
headline): requests arrive by a seeded Poisson process with random
prompt/output lengths and stream through ``serving.Engine`` —
continuous batching, paged KV blocks, preemption under pool pressure.
Reports engine throughput (tok/s), TTFT/TPOT p50/p99, queue time,
preemption count and the compile-once counters to a JSON artifact.

Backend note (same discipline as tools/model_benchmark.py): runs on
whatever backend jax resolves — the real chip via the tunnel for
recorded numbers, CPU for plumbing checks. CPU numbers are throughput
of the jnp fallback kernel and are never recorded as baselines; the
tunnel_battery.sh serving row is the on-chip measurement.

Usage:
  python tools/serving_benchmark.py                  # tiny CPU smoke
  python tools/serving_benchmark.py --preset llama1b # on-chip row
  python tools/serving_benchmark.py --requests 64 --rate 8 \
      --out tools/serving_bench.json
  # resilience row: injected faults + queue bounds + deadlines —
  # reports shed/expired/failed counts and goodput under chaos
  python tools/serving_benchmark.py --fault-rate 0.1 --max-queue 16 \
      --deadline-s 10
  # fleet row (ISSUE 16): N forked engine replicas + the in-process
  # prefix-affinity router; phase A is the no-kill baseline, phase B
  # SIGKILLs one replica mid-run — zero accepted requests may be
  # lost, kill-phase p99 TTFT must stay within 2x of baseline, and
  # every survivor must still report decode_compiles == 1. Fleet runs
  # also trace end-to-end (ISSUE 17): the router journals its
  # queue/placement/dispatch/reroute spans, each replica adopts the
  # dispatch traceparent, and the merged clock-aligned timeline lands
  # in --fleet-trace-out; requests_detail rows carry trace_id plus the
  # per-hop breakdown (router queue vs dispatch attempts vs replica
  # phases)
  python tools/serving_benchmark.py --fleet 3 --kill-replica-at 4 \
      --shared-prefix-tokens 32 --out tools/serving_fleet_snapshot.json
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

PRESETS = {
    # geometry-only: weights are random (throughput, not quality)
    "tiny": dict(hidden_size=64, intermediate_size=128,
                 num_hidden_layers=2, num_attention_heads=4,
                 vocab_size=256, max_position_embeddings=256),
    "llama1b": dict(hidden_size=2048, intermediate_size=5504,
                    num_hidden_layers=22, num_attention_heads=16,
                    vocab_size=32000, max_position_embeddings=2048),
}


def _watchdog(seconds):
    def fire(signum, frame):
        sys.stderr.write("serving_benchmark watchdog: %ds, aborting\n"
                         % seconds)
        os._exit(3)

    signal.signal(signal.SIGALRM, fire)
    signal.alarm(seconds)


def _pow2_bucket(n):
    """Engine._bucket without the engine: next power of two >= 8. The
    fleet parent pre-warms every bucket its workload can hit on every
    replica so phase TTFTs never pay an in-window prefill compile."""
    p = 8
    while p < n:
        p *= 2
    return p


def _pct(values, q):
    import numpy as np

    return float(np.percentile(np.asarray(values, dtype=float), q)) \
        if values else None


def _pcts(values):
    """Aggregate percentile row (p50/p90/p99) for the JSON artifact."""
    return {"p50": _pct(values, 50), "p90": _pct(values, 90),
            "p99": _pct(values, 99)}


def _write_fleet_artifact(path, report, stale_reason=None,
                          kind="serving_fleet_snapshot"):
    """bench.py's staleness discipline for the fleet artifacts (the
    snapshot AND the merged fleet_trace timeline): a run that produced
    nothing re-emits the previous artifact of the same ``kind`` marked
    ``stale: true`` (+ stale_generations/stale_since) instead of
    silently photocopying — the battery row goes red (rc=3)."""
    if stale_reason is not None and os.path.exists(path):
        try:
            with open(path) as f:
                last = json.load(f)
        except (OSError, ValueError):
            last = None
        if last and last.get("kind") == kind:
            last["stale"] = True
            last["stale_reason"] = stale_reason
            last["stale_generations"] = \
                int(last.get("stale_generations", 0)) + 1
            last.setdefault("stale_since", last.get("measured_at"))
            report = last
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1, default=str)
        f.write("\n")
    os.replace(tmp, path)
    return report


def run_fleet(args):
    """--fleet N: fork N replica processes (tools/serving_router.py
    --replica), drive them through the in-process store-backed router,
    and measure the fleet headline: baseline (phase A) vs kill-one-
    replica-mid-run (phase B) TTFT, rerouted/lost counts, per-replica
    affinity hit rate, survivor decode_compiles."""
    import subprocess
    import urllib.request

    import numpy as np

    import jax

    from paddle_tpu.core import flags as ptflags
    from paddle_tpu.distributed.store import TCPStore
    from paddle_tpu.monitor import trace as mtrace
    from paddle_tpu.monitor import trace_merge as tm
    from paddle_tpu.serving.fleet import Router

    ptflags.set_flags({"FLAGS_serving_fleet": True})
    # fleet-wide tracing (on by default, the single-engine benchmark
    # discipline): the ROUTER journal records the dispatch half here;
    # each forked replica journals its engine half via the
    # FLAGS_monitor_trace env bootstrap, and the two merge into
    # tools/fleet_trace.json after the phases. Capacity covers both
    # phases plus warmups so early traces never get evicted.
    trace_cap = max(4 * args.requests + 128, 512)
    if not args.no_trace:
        mtrace.enable(capacity=trace_cap)

    def post_json(url, payload):
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.loads(r.read().decode())

    def get_json(url):
        with urllib.request.urlopen(url, timeout=10) as r:
            return json.loads(r.read().decode())

    def clock_offset(url, pings=5):
        """Replica wall clock minus local wall clock, NTP-style over
        /metrics.json (the monitor/fleet.py collector discipline:
        self-reported unix_time vs the local request midpoint, min-RTT
        sample wins) — the shift that clock-aligns the merged fleet
        timeline."""
        best_rtt, best_off = None, 0.0
        for _ in range(pings):
            t0 = time.time()    # ptlint: clock-ok — NTP offset probe
            m0 = time.monotonic()
            snap = get_json(url + "/metrics.json")
            t1 = time.time()    # ptlint: clock-ok — NTP offset probe
            rtt = time.monotonic() - m0
            if not isinstance(snap.get("unix_time"), (int, float)):
                return None
            if best_rtt is None or rtt < best_rtt:
                best_rtt = rtt
                best_off = float(snap["unix_time"]) - (t0 + t1) / 2.0
        return best_off

    launcher = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "serving_router.py")
    master = TCPStore(is_master=True)
    procs, announce, router = [], {}, None
    spt = args.shared_prefix_tokens
    vocab = PRESETS[args.preset]["vocab_size"]
    max_pos = PRESETS[args.preset]["max_position_embeddings"]
    rng = np.random.RandomState(args.seed)
    prefixes = [rng.randint(0, vocab, (spt,)).tolist()
                for _ in range(args.prefix_groups)] if spt else None

    def mk_workload():
        prompts = []
        for _ in range(args.requests):
            tail = rng.randint(
                0, vocab,
                (int(rng.randint(args.prompt_len[0],
                                 args.prompt_len[1] + 1)),)).tolist()
            head = prefixes[int(rng.randint(args.prefix_groups))] \
                if prefixes else []
            prompts.append(head + tail)
        new = [int(rng.randint(args.max_new[0], args.max_new[1] + 1))
               for _ in range(args.requests)]
        arrivals = np.cumsum(rng.exponential(1.0 / args.rate,
                                             args.requests))
        return prompts, new, arrivals

    def run_phase(name, kill_at=None):
        prompts, new, arrivals = mk_workload()
        nonces, killed = [], None
        start = time.perf_counter()
        nxt = 0
        while nxt < len(prompts) or (kill_at is not None
                                     and killed is None):
            now = time.perf_counter() - start
            if kill_at is not None and killed is None \
                    and now >= kill_at:
                # the victim is the live replica holding the most
                # unfinished work — the worst case for the
                # never-lose-a-request claim
                holding = {}
                for rq in router.requests():
                    if rq["state"] not in ("finished", "failed") \
                            and rq["rank"] is not None:
                        holding[rq["rank"]] = \
                            holding.get(rq["rank"], 0) + 1
                live = [r["rank"] for r in
                        router.replicas_debug_payload()
                        if r["state"] == "live"]
                killed = max(live,
                             key=lambda r: (holding.get(r, 0), -r)) \
                    if live else None
                if killed is not None:
                    procs[killed].kill()        # SIGKILL: no goodbye
            while nxt < len(prompts) and arrivals[nxt] <= now:
                nonces.append(router.submit(
                    prompts[nxt], max_new_tokens=new[nxt]))
                nxt += 1
            router.pump()
            time.sleep(0.002)
        settled = router.wait_all(timeout_s=args.fleet_wait_s)
        wall = time.perf_counter() - start
        reqs = [router.request(n) for n in nonces]
        ttft = [r["first_token_at"] - r["submitted_at"] for r in reqs
                if r["first_token_at"] is not None]
        lost = [r["nonce"] for r in reqs if r["state"] != "finished"]
        # per-request rows with the per-hop breakdown: router queue
        # (trace phase) vs dispatch attempts (every replica tried,
        # with outcome — a rerouted request reports BOTH attempts'
        # replicas) vs replica engine phases (from the result
        # payload's span summary). trace_id links each row to the
        # merged fleet timeline.
        detail = []
        for r in reqs:
            row = {
                "nonce": r["nonce"], "state": r["state"],
                "rank": r["rank"], "reroutes": r["reroutes"],
                "reroute_reasons": list(r["reroute_reasons"]),
                "attempt_ranks": list(r["attempt_ranks"]),
                "affinity": bool(r["affinity"]),
                "output_tokens": r["output_tokens"],
                "ttft_s": (round(r["first_token_at"]
                                 - r["submitted_at"], 6)
                           if r["first_token_at"] is not None
                           else None),
                "e2e_s": (round(r["finished_at"]
                                - r["submitted_at"], 6)
                          if r["finished_at"] is not None else None),
                "trace_id": r["trace_id"],
            }
            if r["trace_id"] is not None:
                pb = mtrace.phase_breakdown(r["trace_id"]) or {}
                row["hops"] = {
                    "router_queue_s": round(
                        pb.get("router_queue", 0.0), 6),
                    "dispatch_attempts": [dict(a)
                                          for a in r["attempts"]],
                    "replica_phases_s": (r["replica_trace"] or {}
                                         ).get("phases_s"),
                }
            detail.append(row)
        return {
            "phase": name, "requests": len(reqs),
            "settled": bool(settled), "wall_s": round(wall, 3),
            "ttft_s": _pcts(ttft),
            "finished": sum(r["state"] == "finished" for r in reqs),
            "lost": lost,
            "rerouted": sum(r["reroutes"] for r in reqs),
            "affinity_dispatches": sum(bool(r["affinity"])
                                       for r in reqs),
            "output_tokens": sum(r["output_tokens"] for r in reqs),
            "killed_rank": killed,
            "requests_detail": detail,
        }

    out = args.out
    try:
        for r in range(args.fleet):
            procs.append(subprocess.Popen(
                [sys.executable, launcher, "--replica",
                 "--rank", str(r),
                 "--store", "127.0.0.1:%d" % master.port,
                 "--preset", args.preset,
                 "--max-slots", str(args.max_slots),
                 "--num-blocks", str(args.num_blocks),
                 "--block-size", str(args.block_size),
                 "--seed", str(args.seed + r),
                 "--ttl-s", str(args.fleet_ttl_s),
                 "--heartbeat-s", "0.2"],
                stdout=subprocess.PIPE,
                # journal in the replica too (the trace.py env
                # bootstrap): its engine-half spans adopt the router's
                # traceparent and are pulled via /debugz/trace/journal
                # after the phases
                env=(dict(os.environ, FLAGS_monitor_trace="1",
                          PT_TRACE_CAPACITY=str(trace_cap))
                     if not args.no_trace else None)))
        for r, p in enumerate(procs):
            # one JSON line after Replica.start(): engine built, lease
            # registered, protocol served
            announce[r] = json.loads(p.stdout.readline().decode())
            print("replica %d up: %s" % (r, announce[r]["url"]),
                  flush=True)

        # per-replica compile warmup, straight to each replica's
        # enqueue endpoint (bypassing placement): every prefill bucket
        # the workload can hit + THE decode step, per replica, so
        # neither phase pays an in-window compile
        t0 = time.perf_counter()
        lo = args.prompt_len[0] + spt
        hi = args.prompt_len[1] + spt + args.max_new[1] - 1
        buckets = sorted({_pow2_bucket(n) for n in range(lo, hi + 1)})
        warm = []
        for r, info in announce.items():
            for i, b in enumerate(buckets):
                nonce = "warm-%d-%d" % (r, i)
                post_json(info["url"] + "/sfleet/enqueue",
                          {"nonce": nonce,
                           "prompt": [1] * min(b, max_pos - 4),
                           "max_new_tokens": 2})
                warm.append((info["url"], nonce))
        pending = list(warm)
        while pending:
            url, nonce = pending[0]
            st = get_json("%s/sfleet/result/%s" % (url, nonce))
            if st["state"] in ("finished", "failed", "shed",
                               "expired"):
                if st["state"] != "finished":
                    raise RuntimeError("warmup %s on %s: %r"
                                       % (nonce, url, st))
                pending.pop(0)
            else:
                time.sleep(0.05)
        warmup_s = time.perf_counter() - t0

        router = Router(store=TCPStore(port=master.port),
                        world_size=args.fleet,
                        block_size=args.block_size,
                        ttl_s=args.fleet_ttl_s)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            router.refresh_membership()
            router.scrape_loads()
            if router.debug_payload()["replicas"]["live"] \
                    == args.fleet:
                break
            time.sleep(0.05)
        else:
            raise RuntimeError(
                "only %r of %d replicas came live"
                % (router.debug_payload()["replicas"], args.fleet))

        baseline = run_phase("baseline")
        kill = run_phase("kill", kill_at=args.kill_replica_at) \
            if args.kill_replica_at is not None else None

        # merged fleet timeline: the router's journal (dispatch half)
        # + every SURVIVING replica's journal (engine half, pulled over
        # /debugz/trace/journal) + NTP-style clock offsets -> ONE
        # clock-aligned chrome trace with traceparent flow arrows. A
        # SIGKILLed victim's journal dies with it, but its attempt-1
        # evidence lives in the router's dispatch/reroute spans, so the
        # reroute causality chain survives the kill.
        trace_block = {"enabled": not args.no_trace}
        if not args.no_trace:
            replica_journals, offsets_s = {}, {}
            for r, info in announce.items():
                if procs[r].poll() is not None:
                    continue        # dead replica: journal lost
                try:
                    replica_journals[r] = get_json(
                        info["url"] + "/debugz/trace/journal")
                    off = clock_offset(info["url"])
                    if off is not None:
                        offsets_s[r] = off
                except (OSError, ValueError):
                    continue        # died mid-pull: same as dead
            doc = tm.write_fleet_timeline(
                args.fleet_trace_out, mtrace.dump(), replica_journals,
                offsets=offsets_s,
                meta={"tool": "serving_benchmark", "fleet": args.fleet,
                      "preset": args.preset,
                      "kill_replica_at_s": args.kill_replica_at,
                      "measured_at": time.strftime(
                          "%Y-%m-%dT%H:%M:%SZ", time.gmtime())})
            reqs_sum = doc.get("requests") or {}
            trace_block.update({
                "fleet_trace": args.fleet_trace_out,
                "router_traces": len(reqs_sum),
                "replica_journals": sorted(replica_journals),
                "clock_offsets_s": {r: round(o, 6)
                                    for r, o in offsets_s.items()},
                "rerouted_traces": sum(
                    1 for v in reqs_sum.values() if v["reroutes"]),
            })
            print("wrote", args.fleet_trace_out, flush=True)

        dbg = router.debug_payload()
        rows = router.replicas_debug_payload()
        killed_ranks = {p["killed_rank"] for p in (baseline, kill)
                        if p and p["killed_rank"] is not None}
        survivors = {
            r["rank"]: r["decode_compiles"] for r in rows
            if r["state"] != "evicted"
            and r["rank"] not in killed_ranks}
        lost = list(baseline["lost"]) + list(kill["lost"] if kill
                                             else [])
        ratio = None
        if kill and baseline["ttft_s"]["p99"] and \
                kill["ttft_s"]["p99"] is not None:
            ratio = round(kill["ttft_s"]["p99"]
                          / baseline["ttft_s"]["p99"], 3)
        report = {
            "kind": "serving_fleet_snapshot",
            "metric": "serving_fleet_kill_ttft_p99_ratio",
            "value": ratio,
            "backend": jax.default_backend(),
            "preset": args.preset,
            "fleet": args.fleet,
            "workload": {
                "requests_per_phase": args.requests,
                "poisson_rate": args.rate,
                "prompt_len": list(args.prompt_len),
                "max_new": list(args.max_new), "seed": args.seed,
                "shared_prefix_tokens": spt,
                "prefix_groups": args.prefix_groups if spt else 0,
                "max_slots": args.max_slots,
                "num_blocks": args.num_blocks,
                "block_size": args.block_size,
                "kill_replica_at_s": args.kill_replica_at,
                "ttl_s": args.fleet_ttl_s,
            },
            "warmup_compile_s": round(warmup_s, 3),
            "baseline": baseline,
            "kill": kill,
            "lost_requests": lost,
            "ttft_p99_ratio_within_2x": (ratio is not None
                                         and ratio <= 2.0),
            "survivor_decode_compiles": survivors,
            "trace": trace_block,
            "router": dbg,
            "replicas": rows,
            "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         time.gmtime()),
        }
        print(json.dumps({k: v for k, v in report.items()
                          if k not in ("replicas",)}), flush=True)
        _write_fleet_artifact(out, report)
        print("wrote", out, flush=True)
        if lost:
            sys.stderr.write("FAIL: %d accepted request(s) lost: %r\n"
                             % (len(lost), lost))
            return 5
        bad = {r: c for r, c in survivors.items() if c != 1}
        if bad:
            sys.stderr.write("FAIL: survivor decode_compiles != 1: "
                             "%r\n" % (bad,))
            return 4
        return 0
    except (RuntimeError, OSError, ValueError,
            json.JSONDecodeError) as e:
        sys.stderr.write("serving_benchmark --fleet failed: %r\n"
                         % (e,))
        _write_fleet_artifact(
            out, {"kind": "serving_fleet_snapshot", "ok": False,
                  "error": repr(e),
                  "measured_at": time.strftime(
                      "%Y-%m-%dT%H:%M:%SZ", time.gmtime())},
            stale_reason=repr(e))
        # the merged timeline rides the same staleness discipline: a
        # failed run re-emits the previous fleet_trace marked stale
        # rather than leaving a silently outdated artifact behind
        _write_fleet_artifact(
            args.fleet_trace_out,
            {"kind": "fleet_trace", "ok": False, "error": repr(e),
             "measured_at": time.strftime(
                 "%Y-%m-%dT%H:%M:%SZ", time.gmtime())},
            stale_reason=repr(e), kind="fleet_trace")
        return 3
    finally:
        if router is not None:
            router.close()
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
        master.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", choices=sorted(PRESETS), default="tiny")
    ap.add_argument("--requests", type=int, default=20)
    ap.add_argument("--rate", type=float, default=10.0,
                    help="Poisson arrival rate, requests/second")
    ap.add_argument("--prompt-len", type=int, nargs=2, default=(4, 24),
                    metavar=("LO", "HI"))
    ap.add_argument("--max-new", type=int, nargs=2, default=(4, 16),
                    metavar=("LO", "HI"))
    ap.add_argument("--max-slots", type=int, default=4)
    ap.add_argument("--num-blocks", type=int, default=128)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--watchdog", type=int, default=1100)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "serving_bench.json"))
    ap.add_argument("--monitor-out", default=None,
                    help="also dump the monitor registry snapshot (with "
                         "written_at metadata) to this JSON path")
    ap.add_argument("--fault-rate", type=float, default=0.0,
                    help="resilience chaos knob: probability of an "
                         "injected per-request prefill error (the "
                         "poison-request path); 0 = injection off")
    ap.add_argument("--fault-schedule", default=None,
                    help="raw fault schedule (resilience/faultinject "
                         "grammar, overrides --fault-rate), e.g. "
                         "'serving.prefill:error@p0.1;"
                         "serving.decode:delay=0.01@%%8'")
    ap.add_argument("--fault-seed", type=int, default=0)
    ap.add_argument("--deadline-s", type=float, default=None,
                    help="per-request queue-TTL: still waiting past "
                         "this -> terminal 'expired' status")
    ap.add_argument("--max-queue", type=int, default=None,
                    help="bounded admission queue: arrivals beyond it "
                         "are load-shed (counted, not enqueued)")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="enable FLAGS_serving_prefix_cache (radix "
                         "prefix cache over the paged KV pool)")
    ap.add_argument("--chunked-prefill", action="store_true",
                    help="enable FLAGS_serving_chunked_prefill (prompts "
                         "stream through the ONE mixed step in chunks)")
    ap.add_argument("--prefill-chunk", type=int, default=16,
                    help="chunk size for --chunked-prefill")
    ap.add_argument("--quant-kv", action="store_true",
                    help="enable FLAGS_serving_quant_kv (int8 block-"
                         "scaled KV pages + fp32 scale planes). "
                         "--num-blocks then names the FP32 pool the "
                         "byte budget could afford; the quantized run "
                         "gets the SAME bytes, which buy more pages — "
                         "the report's kv_capacity_headroom_vs_fp32")
    ap.add_argument("--quant-weights", action="store_true",
                    help="enable FLAGS_serving_quant_weights (weight-"
                         "only int8 block-scaled projection matmuls on "
                         "decode rows; prefill rows stay fp32)")
    ap.add_argument("--shared-prefix-tokens", type=int, default=0,
                    help="system-prompt traffic shape: every request's "
                         "prompt starts with one of --prefix-groups "
                         "shared prefixes of this many tokens (0 = "
                         "fully random prompts)")
    ap.add_argument("--prefix-groups", type=int, default=4,
                    help="number of distinct shared prefixes for "
                         "--shared-prefix-tokens")
    ap.add_argument("--slo", action="store_true",
                    help="judge the workload against the serving SLOs "
                    "(FLAGS_monitor_slo, latched before Engine "
                    "construction): per-objective attainment + budget "
                    "burn + burn-rate alerts land in the report")
    ap.add_argument("--profile", action="store_true",
                    help="FLAGS_monitor_profile: host sampling profiler "
                         "+ per-iteration dispatch/gap + prefill/decode "
                         "phase timers; arms a one-shot device-capture "
                         "window mid-run and reports host_blocked_s per "
                         "phase in the JSON")
    ap.add_argument("--record-out", default=None,
                    help="FLAGS_serving_replay: journal every measured "
                         "request (prompt ids, flag snapshot, weights "
                         "generation, output token hash) to this JSONL "
                         "path; tools/ptreplay.py run re-drives it and "
                         "diffs token-for-token")
    ap.add_argument("--replay", default=None,
                    help="replay a --record-out journal instead of "
                         "generating a workload: delegates to "
                         "tools/ptreplay.py (rebuilds the recorded "
                         "model + engine, re-drives every finished "
                         "request) and writes the divergence report to "
                         "--out; rc=2 on divergence")
    ap.add_argument("--no-trace", action="store_true",
                    help="skip the span journal (requests_detail rows "
                         "then carry no trace_id/phases_s breakdown)")
    ap.add_argument("--trace-out", default=None,
                    help="also write the span journal here "
                         "(tools/trace_merge.py --requests input)")
    ap.add_argument("--fleet", type=int, default=0,
                    help="serving-fleet mode: fork this many engine "
                         "replica processes (tools/serving_router.py "
                         "--replica) and drive them through the "
                         "in-process prefix-affinity router instead "
                         "of one local engine")
    ap.add_argument("--kill-replica-at", type=float, default=None,
                    help="fleet mode: SIGKILL one replica this many "
                         "seconds into the kill phase (phase B); the "
                         "router's TTL eviction + re-dispatch must "
                         "lose nothing")
    ap.add_argument("--fleet-ttl-s", type=float, default=2.0,
                    help="fleet mode: replica liveness lease TTL")
    ap.add_argument("--fleet-trace-out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "fleet_trace.json"),
        help="fleet mode: merged clock-aligned fleet timeline "
             "(router + surviving-replica journals stitched on "
             "traceparent; open in Perfetto)")
    ap.add_argument("--fleet-wait-s", type=float, default=300.0,
                    help="fleet mode: per-phase drain deadline")
    args = ap.parse_args()
    _watchdog(args.watchdog)
    if args.replay:
        # replay mode IS ptreplay: same entrypoint for record and
        # replay so CI rows and operators drive both through one tool
        import importlib.util

        p = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "ptreplay.py")
        spec = importlib.util.spec_from_file_location("ptreplay", p)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.run_replay(argparse.Namespace(
            journal=args.replay, out=args.out, full=False,
            matrix=False, against=None))
    if args.fleet > 0:
        return run_fleet(args)
    try:
        return _run_single(args)
    except Exception as e:
        # bench.py staleness discipline for the single-engine rows too
        # (battery serving/serving_prefix/serving_quant): a crashed run
        # re-emits the previous snapshot marked stale (rc=3) instead of
        # leaving a silently rotted photocopy behind
        import traceback
        traceback.print_exc()
        _write_fleet_artifact(
            args.out,
            {"kind": "serving_bench", "error": repr(e),
             "measured_at": time.strftime(
                 "%Y-%m-%dT%H:%M:%SZ", time.gmtime())},
            stale_reason=repr(e), kind="serving_bench")
        return 3


def _run_single(args):
    import numpy as np

    import jax

    import paddle_tpu as paddle
    from paddle_tpu import serving
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.monitor import trace as mtrace

    # span journal on by default for the benchmark (a measurement
    # tool): per-request phase attribution makes the preemption tax
    # visible per-request, not only in the aggregate counters. Capacity
    # sized to the workload so early requests never get evicted.
    if not args.no_trace:
        mtrace.enable(capacity=max(2 * args.requests + 64, 256))

    paddle.seed(args.seed)
    cfg = LlamaConfig(use_parallel=False, **PRESETS[args.preset])
    model = LlamaForCausalLM(cfg)

    rng = np.random.RandomState(args.seed)
    arrivals = np.cumsum(rng.exponential(1.0 / args.rate, args.requests))
    # shared-prefix traffic shape (--shared-prefix-tokens): the
    # millions-of-users workload — every request opens with one of G
    # shared system-prompt/few-shot headers, then a random tail of the
    # configured prompt length. The PREFIX CACHE should collapse
    # hit-request TTFT to roughly the tail's prefill cost.
    if args.shared_prefix_tokens > 0:
        prefixes = [rng.randint(0, cfg.vocab_size,
                                (args.shared_prefix_tokens,)).tolist()
                    for _ in range(args.prefix_groups)]
        group_of = [int(rng.randint(args.prefix_groups))
                    for _ in range(args.requests)]
        prompts = [prefixes[group_of[i]]
                   + rng.randint(0, cfg.vocab_size,
                                 (int(rng.randint(args.prompt_len[0],
                                                  args.prompt_len[1] + 1)),)
                                 ).tolist()
                   for i in range(args.requests)]
    else:
        prompts = [rng.randint(0, cfg.vocab_size,
                               (int(rng.randint(args.prompt_len[0],
                                                args.prompt_len[1] + 1)),)
                               ).tolist()
                   for _ in range(args.requests)]
    max_new = [int(rng.randint(args.max_new[0], args.max_new[1] + 1))
               for _ in range(args.requests)]

    from paddle_tpu.core import flags as ptflags

    from paddle_tpu.serving import replay as sreplay

    if args.record_out:
        # journal capacity sized like the trace journal: the measured
        # workload must never evict its own head
        sreplay.enable(capacity=max(2 * args.requests + 64, 256))
    ptflags.set_flags({
        # the record journal latches at Engine construction like every
        # tier-2 serving flag
        "FLAGS_serving_replay": bool(args.record_out),
        "FLAGS_serving_prefix_cache": bool(args.prefix_cache),
        "FLAGS_serving_chunked_prefill": bool(args.chunked_prefill),
        # serving-quant flags latch at Engine construction too — set
        # BEFORE the engine is built (PR-9 discipline)
        "FLAGS_serving_quant_kv": bool(args.quant_kv),
        "FLAGS_serving_quant_weights": bool(args.quant_weights),
        # ptprof latches at Engine construction like the tier-2 flags
        # — set BEFORE the engine is built
        "FLAGS_monitor_profile": bool(args.profile),
        # ptslo same discipline: the judge's ring listener must be
        # installed before the engine publishes its first sample
        "FLAGS_monitor_slo": bool(args.slo)})
    if args.slo:
        from paddle_tpu.monitor import slo as ptslo

        ptslo.enable()

    # equal-byte-budget sizing (--quant-kv): --num-blocks names the
    # fp32 pool a fixed HBM budget could afford. The quantized run
    # keeps the SAME byte budget and converts it into MORE pages —
    # per-page k+v bytes: fp32 = 2*4*bs*Hkv*D, int8+scales =
    # 2*(bs*Hkv*D + 4*bs*Hkv). The capacity headroom is the serving
    # payoff: later preemption onset and lower shed rate at the same
    # memory, reported as kv_capacity_headroom_vs_fp32 (>= 1.8 for any
    # realistic head_dim; 4D/(D+4) ~ 3.76x at D=64).
    kv_heads = cfg.num_key_value_heads or cfg.num_attention_heads
    head_dim = cfg.hidden_size // cfg.num_attention_heads
    fp32_page_bytes = 8 * args.block_size * kv_heads * head_dim
    quant_page_bytes = 2 * args.block_size * kv_heads * (head_dim + 4)
    num_blocks = args.num_blocks
    if args.quant_kv:
        num_blocks = max(args.num_blocks,
                         args.num_blocks * fp32_page_bytes
                         // quant_page_bytes)
    kv_headroom = num_blocks / args.num_blocks

    # resilience knobs are applied AFTER warmup (below): the compile
    # warmup enqueues one request per prefill bucket, and a deadline or
    # queue bound there would expire/reject buckets — pushing their
    # compiles into the measured window
    eng = serving.Engine(model, max_slots=args.max_slots,
                         num_blocks=num_blocks,
                         block_size=args.block_size,
                         prefill_chunk=args.prefill_chunk)

    # warmup: compile THE decode step plus every prefill bucket the
    # workload can hit, outside the measured window (compile time is
    # reported separately); one warmup request per bucket. Buckets go up
    # to prompt_hi + max_new_hi - 1, not prompt_hi: a preempted request
    # resumes with prompt + generated-so-far, and its re-prefill must
    # not pay an in-window compile either. Chunked prefill has NO
    # per-bucket prefills — one warm request traces the one mixed step.
    # With the prefix cache on, suffix prefills can be SHORTER than any
    # full prompt, so the bucket sweep starts at length 1.
    t0 = time.perf_counter()
    prompt_hi = (args.prompt_len[1] + args.shared_prefix_tokens)
    resume_hi = prompt_hi + args.max_new[1] - 1
    if args.chunked_prefill:
        n_warm = 1
        eng.add_request([1] * min(resume_hi, eng.max_model_len - 2),
                        max_new_tokens=2)
    else:
        lo = 1 if args.prefix_cache else args.prompt_len[0]
        buckets = sorted({eng._bucket(n) for n in
                          range(lo, resume_hi + 1)})
        n_warm = len(buckets)
        for b in buckets:
            warm_len = min(b, resume_hi, eng.max_model_len - 2)
            eng.add_request([1] * warm_len, max_new_tokens=2)
            if eng.prefix_cache is not None:
                # each warm request must be a FULL MISS: letting warm
                # request N hit request N-1's cached pages would shrink
                # its suffix into a lower bucket and leave the top
                # buckets uncompiled — an in-window jit later
                eng.run()
                eng.prefix_cache.clear()
    eng.run()
    if eng.prefix_cache is not None:
        # warmup prompts must not seed the measured workload's cache;
        # push the post-clear counters into the engine mirror so the
        # warmup snapshot below absorbs the clear's evictions
        eng.prefix_cache.clear()
        eng.metrics.on_prefix_stats(eng.prefix_cache.stats(),
                                    eng.cache.cow_clones)
    warmup_s = time.perf_counter() - t0
    if args.record_out:
        # warmup requests are shape probes, not workload: drop their
        # journal entries (keeping the engine capability snapshot and
        # model meta) so replay re-drives the measured window only
        sreplay.drop_entries()
    if args.slo:
        # warmup requests must not count against the measured
        # window's objectives (the warmup-vs-workload split every
        # other counter gets via the `base` snapshot below)
        ptslo.clear()
    base = eng.stats()     # counters up to here are warmup, not workload
    prof_base = None
    if args.profile:
        # ptprof totals snapshot: the measured window's per-phase host
        # seconds must exclude the compile warmup above
        from paddle_tpu.monitor import profile as pprof

        _pt = pprof.job_totals().get("serving") or {}
        prof_base = {"steps": _pt.get("steps", 0),
                     "dispatch_s": _pt.get("dispatch_s", 0.0),
                     "blocked_s": _pt.get("blocked_s", 0.0),
                     "gap_s": _pt.get("gap_s", 0.0),
                     "phases": dict(_pt.get("phases", {}))}
    eng.max_queue = args.max_queue
    eng.default_deadline_s = args.deadline_s

    # chaos: arm the injection framework AFTER warmup so the compile
    # window stays clean and every injected fault lands in the
    # measured workload (resilience/faultinject — seeded, so the same
    # arguments replay the same faults)
    fault_schedule = args.fault_schedule
    if fault_schedule is None and args.fault_rate > 0:
        fault_schedule = ("serving.prefill:error@p%g" % args.fault_rate)
    if fault_schedule:
        from paddle_tpu.resilience import faultinject as fi

        fi.enable(fault_schedule, seed=args.fault_seed)

    ids = []
    rejected = {}          # admission-shed reason -> count (no id)
    # pool-pressure trajectory: peak page occupancy overall and the
    # occupancy right BEFORE the first preemption/shed event — with
    # --quant-kv the same byte budget holds more pages, so pressure
    # (and the preemption tax) arrives later or never
    peak_occ = 0.0
    occ_at_first_pressure = None
    pressure_base = (eng.metrics.preemptions, eng.metrics.requests_shed)
    start = time.perf_counter()
    nxt = 0
    profile_armed = False
    while nxt < args.requests or eng.has_work():
        now = time.perf_counter() - start
        if args.profile and not profile_armed \
                and nxt >= args.requests // 2:
            # mid-run capture window: the Xprof artifact covers
            # steady-state steps, not the warmup or the tail drain
            from paddle_tpu.monitor import profile as pprof

            pprof.arm_capture(steps=8, reason="serving_benchmark")
            profile_armed = True
        while nxt < args.requests and arrivals[nxt] <= now:
            try:
                ids.append(eng.add_request(
                    prompts[nxt], max_new_tokens=max_new[nxt]))
            except serving.AdmissionError as e:
                rejected[e.reason] = rejected.get(e.reason, 0) + 1
            nxt += 1
        if eng.has_work():
            alloc = eng.cache.allocator
            occ = (1.0 - alloc.free_blocks
                   / max(alloc.usable_blocks, 1))
            peak_occ = max(peak_occ, occ)
            eng.step()
            if occ_at_first_pressure is None and (
                    (eng.metrics.preemptions,
                     eng.metrics.requests_shed) != pressure_base):
                # occupancy going INTO the step that first preempted
                # or shed — the onset point of pool pressure
                occ_at_first_pressure = occ
        elif nxt < args.requests:
            time.sleep(min(arrivals[nxt] - now, 0.05))
    wall = time.perf_counter() - start
    if fault_schedule:
        from paddle_tpu.resilience import faultinject as fi

        fault_state = fi.state()
        fi.disable()
    else:
        fault_state = None

    stats = eng.stats()
    # engine counters aggregate over the whole lifetime — subtract the
    # warmup snapshot so the artifact reports the measured window only
    meas_steps = stats["decode_steps"] - base["decode_steps"]
    occ_sum = (stats["slot_occupancy"] * stats["decode_steps"]
               - base["slot_occupancy"] * base["decode_steps"])
    meas_occupancy = occ_sum / meas_steps if meas_steps else 0.0
    per_req = []
    for r in ids:
        row = dict(eng.request_metrics(r), request_id=r)
        status = eng.request_status(r)
        row["status"] = status["state"]
        if status["reason"] is not None:
            row["status_reason"] = status["reason"]
        # trace id + per-request phase breakdown (queue / prefill /
        # decode / preempted seconds): the preemption tax attributable
        # per-request — a preempted request shows the recompute in its
        # own prefill/preempted phases, not only in the aggregate
        tid, phases = eng.request_trace(r)
        if tid is not None:
            row["trace_id"] = tid
            row["phases_s"] = {k: round(v, 6)
                               for k, v in sorted(phases.items())}
        # the replay-audit columns ride along unconditionally (the
        # hash is a pure function of the output ids): two bench
        # artifacts can be diffed for token drift without either run
        # having recorded a journal
        row["output_token_hash"] = sreplay.token_hash(eng.output(r))
        row["weights_generation"] = eng.weights_generation
        per_req.append(row)
    ttft = [m["ttft_s"] for m in per_req if m["ttft_s"] is not None]
    tpot = [m["tpot_s"] for m in per_req if m["tpot_s"] is not None]
    queue = [m["queue_time_s"] for m in per_req
             if m["queue_time_s"] is not None]
    out_tokens = sum(m["output_tokens"] for m in per_req)
    # TTFT split by prefix-cache outcome at the FIRST admission (TTFT
    # is set by the first token, so only that admission's match can
    # explain it — a preempted miss that re-hits its own pages on
    # resume stays a miss). The acceptance headline is p50 hit-TTFT
    # collapsing vs miss-TTFT on the shared-prefix shape.
    ttft_hit = [m["ttft_s"] for m in per_req
                if m["ttft_s"] is not None
                and m["prefix_cached_tokens_first"] > 0]
    ttft_miss = [m["ttft_s"] for m in per_req
                 if m["ttft_s"] is not None
                 and m["prefix_cached_tokens_first"] == 0]

    report = {
        "kind": "serving_bench",
        "metric": "serving_throughput_tok_s",
        "value": round(out_tokens / max(wall, 1e-9), 1),
        "unit": "tok/s",
        "backend": jax.default_backend(),
        "preset": args.preset,
        "workload": {
            "requests": args.requests, "poisson_rate": args.rate,
            "prompt_len": list(args.prompt_len),
            "max_new": list(args.max_new), "seed": args.seed,
            "max_slots": args.max_slots, "num_blocks": args.num_blocks,
            "block_size": args.block_size,
            "shared_prefix_tokens": args.shared_prefix_tokens,
            "prefix_groups": (args.prefix_groups
                              if args.shared_prefix_tokens else 0),
            "prefix_cache": bool(args.prefix_cache),
            "chunked_prefill": bool(args.chunked_prefill),
            "prefill_chunk": (args.prefill_chunk
                              if args.chunked_prefill else None),
            "quant_kv": bool(args.quant_kv),
            "quant_weights": bool(args.quant_weights),
        },
        "wall_s": round(wall, 3),
        "warmup_compile_s": round(warmup_s, 3),
        "output_tokens": out_tokens,
        "ttft_s": _pcts(ttft),
        "ttft_hit_s": _pcts(ttft_hit),
        "ttft_miss_s": _pcts(ttft_miss),
        "prefix_cache_hits": len(ttft_hit),
        "prefix_cache_hit_tokens_total": (stats["prefix_hit_tokens"]
                                          - base["prefix_hit_tokens"]),
        "prefix_cache_lookup_tokens_total": (
            stats["prefix_lookup_tokens"] - base["prefix_lookup_tokens"]),
        "prefix_cache_evictions": (stats["prefix_evictions"]
                                   - base["prefix_evictions"]),
        "cow_clones": stats["cow_clones"] - base["cow_clones"],
        "prefill_chunks": stats["prefill_chunks"] - base["prefill_chunks"],
        "tpot_s": _pcts(tpot),
        "queue_time_s": _pcts(queue),
        # serving-quant scoreboard: at the FIXED byte budget named by
        # --num-blocks, how many pages did the dtype buy, how late did
        # pool pressure arrive, and how much traffic was shed. The
        # acceptance headline is kv_capacity_headroom_vs_fp32 >= 1.8
        # with --quant-kv on.
        "quant": {
            "quant_kv": bool(args.quant_kv),
            "quant_weights": bool(args.quant_weights),
            "num_blocks_fp32_budget": args.num_blocks,
            "num_blocks_effective": num_blocks,
            "kv_page_bytes_fp32": fp32_page_bytes,
            "kv_page_bytes_quant": quant_page_bytes,
            "kv_capacity_headroom_vs_fp32": round(kv_headroom, 3),
            "peak_kv_page_occupancy": round(peak_occ, 4),
            "occupancy_before_first_pressure": (
                None if occ_at_first_pressure is None
                else round(occ_at_first_pressure, 4)),
            "shed_rate": round(
                stats["requests_shed"] / max(args.requests, 1), 4),
            "kv_quant_pages": stats.get("kv_quant_pages", 0),
            "quant_dequant_bytes": stats.get("quant_dequant_bytes", 0),
        },
        "preemptions": stats["preemptions"] - base["preemptions"],
        "decode_steps": meas_steps,
        "decode_compiles": stats["decode_compiles"],
        "prefill_compiles": stats["prefill_compiles"],
        "slot_occupancy": round(meas_occupancy, 4),
        "requests_finished": stats["requests_finished"] - n_warm,
        # resilience accounting: goodput (finished-request tokens only)
        # next to shed/expired/failed counts — under a fault schedule
        # the SLO question is "how much service survived the chaos"
        "goodput_tok_s": round(
            sum(m["output_tokens"] for m in per_req
                if m["status"] == "finished") / max(wall, 1e-9), 1),
        "requests_shed_total": stats["requests_shed"],
        "shed_by_reason": stats["shed_by_reason"],
        "rejected_at_admission": rejected,
        "fault_schedule": fault_schedule,
        "faults_injected": (
            None if fault_state is None else
            {r["rule"]: r["fired"] for r in fault_state["rules"]}),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        # raw per-request rows ride along with the aggregates so
        # distribution questions don't need a re-run
        "requests_detail": per_req,
    }
    if args.slo:
        # ptslo verdicts next to the goodput-vs-throughput gap: the
        # same artifact answers "how fast" AND "did it meet the SLO"
        from paddle_tpu.monitor import incidents as ptincidents

        spay = ptslo.payload()
        report["slo"] = {
            "enabled": spay.get("enabled", False),
            "window_scale": spay.get("window_scale"),
            "objectives": [
                {"objective": o.get("objective"),
                 "job": o.get("job"),
                 "threshold": o.get("threshold"),
                 "target": o.get("target"),
                 "samples": o.get("samples"),
                 "attainment": o.get("attainment"),
                 "budget_remaining_ratio":
                     o.get("budget_remaining_ratio"),
                 "burn_rate": o.get("burn_rate"),
                 "alerting": o.get("alerting")}
                for o in spay.get("objectives") or ()],
            "alerts_open": sorted(
                i["key"] for i in ptincidents.open_incidents()
                if i.get("source") == "slo"),
            "incidents_open": len(ptincidents.open_incidents()),
        }
    if args.profile:
        # measured host attribution (monitor/profile.py): per-phase
        # host seconds over the measured window (warmup subtracted),
        # the sampler's component shares, and any capture artifacts
        from paddle_tpu.monitor import profile as pprof

        ppay = pprof.profile_payload()
        tot = (ppay.get("jobs") or {}).get("serving") or {}
        pb = prof_base or {}
        report["profile"] = {
            "host_blocked_s": {
                k: round(v - pb.get("phases", {}).get(k, 0.0), 6)
                for k, v in sorted((tot.get("phases") or {}).items())},
            "dispatch_s_total": round(
                tot.get("dispatch_s", 0.0)
                - pb.get("dispatch_s", 0.0), 6),
            "gap_s_total": round(
                tot.get("gap_s", 0.0) - pb.get("gap_s", 0.0), 6),
            "steps": tot.get("steps", 0) - pb.get("steps", 0),
            "sampler": ppay.get("sampler"),
            "components": ppay.get("components"),
            "captures": [c["dir"] for c in ppay.get("captures") or ()],
            "pending_captures": ppay.get("pending_captures"),
        }
    print(json.dumps({k: v for k, v in report.items()
                      if k != "requests_detail"}), flush=True)
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    print("wrote", args.out, flush=True)
    if args.monitor_out:
        from paddle_tpu import monitor

        monitor.write_snapshot(args.monitor_out, meta={
            "tool": "serving_benchmark", "preset": args.preset,
            "backend": jax.default_backend(),
            "measured_at": report["measured_at"],
            "serving_throughput_tok_s": report["value"],
        })
        print("wrote", args.monitor_out, flush=True)
    if args.trace_out and not args.no_trace:
        mtrace.write_journal(args.trace_out)
        print("wrote", args.trace_out, flush=True)
    if args.record_out:
        # model meta makes the journal self-contained: ptreplay
        # rebuilds the exact weights from config kwargs + init seed
        # without ever importing this script
        sreplay.note_model({"preset": args.preset, "seed": args.seed,
                            "config": dict(PRESETS[args.preset])})
        head, jentries = sreplay.write_journal(args.record_out)
        print("wrote %s (%d journal entries, %d evictions)"
              % (args.record_out, len(jentries), head["evictions"]),
              flush=True)
    # contract check: the whole staggered workload must have reused ONE
    # compiled decode step (the engine's core shape-stability claim)
    if stats["decode_compiles"] != 1:
        sys.stderr.write("FAIL: decode compiled %d times (expected 1)\n"
                         % stats["decode_compiles"])
        return 4
    return 0


if __name__ == "__main__":
    sys.exit(main())
