"""Fleet telemetry battery row: the multi-proc train entry under the
fleet collector, emitting the committed ``tools/fleet_snapshot.json``.

Drives the EXISTING 2-process multihost train entry
(tests/multihost_worker.py — the same worker test_multihost.py golden-
pins) with ``FLAGS_monitor_fleet=1`` so each rank announces its
metrics endpoint in the TCPStore, while THIS process runs the fleet
collector standalone (a store client, no rank) — the "collector on any
rank or standalone" deployment — and writes the per-rank table +
aggregates as the battery artifact.

Staleness discipline (bench.py): if the multi-proc run fails or
nothing was scrapeable, the previous artifact is re-emitted marked
``stale: true`` (+ stale_generations/stale_since) instead of silently
photocopying, and the exit code is 3.

    python tools/fleet_battery.py [--steps 40] [--out tools/fleet_snapshot.json]
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

from paddle_tpu.monitor import fleet  # noqa: E402

# the consecutive-port reservation the multihost tests use (the store's
# +1 JAX-coordinator slot and the +10/+11 endpoint slots derive from
# the base) — ONE copy, in the dist test utils
from dist_utils import free_ports  # noqa: E402


def worker_env(rank, port):
    env = {k: v for k, v in os.environ.items()
           if k not in ("PYTHONPATH", "PALLAS_AXON_POOL_IPS",
                        "PALLAS_AXON_REMOTE_COMPILE",
                        "AXON_LOOPBACK_RELAY", "PALLAS_AXON_TPU_GEN",
                        "PADDLE_MASTER", "PADDLE_TRAINERS_NUM",
                        "PADDLE_TRAINER_ID", "PADDLE_NNODES",
                        "PADDLE_NODE_RANK")}
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "PADDLE_NNODES": "2",
        "PADDLE_NODE_RANK": str(rank),
        "PADDLE_TRAINERS_NUM": "2",
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_MASTER": "127.0.0.1:%d" % port,
        "PADDLE_CURRENT_ENDPOINT": "127.0.0.1:%d" % (port + 10 + rank),
        "FLAGS_monitor_fleet": "1",
        # the collector runs HERE (standalone store client), not on a
        # rank: -1 matches no trainer id
        "PT_FLEET_COLLECTOR_RANK": "-1",
    })
    return env


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="multi-proc train entry under the fleet collector")
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--out", default=os.path.join(
        REPO, "tools", "fleet_snapshot.json"))
    ap.add_argument("--interval", type=float, default=0.5)
    ap.add_argument("--timeout", type=float, default=600.0)
    args = ap.parse_args(argv)

    port = free_ports(12)
    worker = os.path.join(REPO, "tests", "multihost_worker.py")
    procs = [subprocess.Popen(
        [sys.executable, worker, str(args.steps)], cwd=REPO,
        env=worker_env(rank, port), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True) for rank in range(2)]

    collector = None
    stale_reason = None
    try:
        # dial the rank-0 worker's store once it is up (the workers are
        # busy importing jax for a while — keep retrying quietly)
        from paddle_tpu.distributed.store import TCPStore

        store = None
        deadline = time.monotonic() + min(args.timeout / 2, 240)
        while store is None and time.monotonic() < deadline:
            if procs[0].poll() is not None:
                break
            try:
                store = TCPStore("127.0.0.1", port, is_master=False,
                                 timeout_s=10)
            except RuntimeError:
                time.sleep(1.0)
        if store is None:
            stale_reason = "store never came up (worker died early?)"
        else:
            collector = fleet.FleetCollector(
                store=store, world_size=2, interval_s=args.interval,
                http_timeout_s=5.0).start()
        rcs = []
        for p in procs:
            try:
                out, err = p.communicate(timeout=args.timeout)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                out, err = p.communicate()
            rcs.append(p.returncode)
            sys.stderr.write(err[-2000:] + "\n" if rcs[-1] else "")
        if any(rc != 0 for rc in rcs):
            stale_reason = "multi-proc train entry failed (rcs=%s)" % rcs
    finally:
        if collector is not None:
            collector.stop()
    snap = fleet.write_snapshot_artifact(
        args.out, collector=collector, stale_reason=stale_reason)
    # red on ANY unusable artifact: stale re-emit, an explicit failure
    # reason, or a first-run snapshot with nothing scraped (ok=false)
    stale = bool(snap.get("stale")) or not snap.get("ok")
    print("fleet_battery: %s -> %s (ranks=%s steps=%s%s)"
          % ("STALE RE-EMIT" if stale else "ok", args.out,
             [r.get("rank") for r in snap.get("ranks") or ()],
             [r.get("steps_total") for r in snap.get("ranks") or ()],
             ", reason=%s" % stale_reason if stale_reason else ""))
    return 3 if stale or stale_reason else 0


if __name__ == "__main__":
    sys.exit(main())
