"""Continuous-profiling snapshot artifact: the tunnel battery's profile row.

Runs the bench-family decoder for a few compiled steps with ptprof ON
(``FLAGS_monitor_profile`` for the host sampler + measured dispatch/
blocked/gap timers, ``FLAGS_perf_attribution`` so the analytic
``perf_phase_seconds`` split exists to reconcile against) and commits
the /debugz/profile payload — sampler stats, component attribution,
top-K folded stacks, per-job measured phases — plus the measured-vs-
analytic diff inputs, as ``tools/profile_snapshot.json``. Committed in
the SAME battery window as the train rows, so the first live tunnel
window gets measured host-blocked time alongside the re-baselined MFU
(the BASELINE round-13 re-baseline note).

``--once`` skips the train smoke and just samples THIS process for a
short window — the host-only spelling for probing a box without paying
a compile.

Staleness discipline (bench.py / mem_snapshot): when the measurement
fails and a previous artifact exists, the previous artifact is
RE-EMITTED marked ``stale: true`` (+ ``stale_reason`` /
``stale_generations`` / ``stale_since``) and the exit code is 3 — a
photocopied profile must confess from the artifact itself.

Usage:
  python tools/profile_snapshot.py [--steps N] [--out tools/profile_snapshot.json]
  python tools/profile_snapshot.py --once        # host-only sample window
  python tools/profile_snapshot.py --json        # print payload too
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(HERE, ".."))

DEFAULT_OUT = os.path.join(HERE, "profile_snapshot.json")


def _watchdog(seconds=540):
    def fire(signum, frame):
        sys.stderr.write("profile_snapshot watchdog: %ds, aborting\n"
                         % seconds)
        os._exit(3)

    signal.signal(signal.SIGALRM, fire)
    signal.alarm(seconds)


def _base_snap(backend, mode):
    return {
        "kind": "profile_snapshot",
        "version": 1,
        "ok": True,
        "written_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                    time.gmtime()),
        "unix_time": time.time(),
        "pid": os.getpid(),
        "backend": backend,
        "mode": mode,
    }


def measure_once(window_s=0.8):
    """Host-only: start the sampler, keep this process busy for a short
    window, snapshot. No model, no compile — a bare-box probe."""
    import paddle_tpu as paddle
    from paddle_tpu.monitor import profile as pprof

    paddle.set_flags({"FLAGS_monitor_profile": True})
    pprof.start_sampler()
    t0 = time.monotonic()
    x = 0
    while time.monotonic() - t0 < float(window_s):
        x = (x + 1) % 1000003
    snap = _base_snap("host-only", "once")
    snap["profile"] = pprof.profile_payload()
    return snap


def measure(steps=5):
    """Bench-family decoder under ptprof + perf attribution; returns
    the snapshot dict (ok=True) carrying both sides of the
    measured-vs-analytic reconciliation."""
    import numpy as np
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import mesh as pmesh
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.monitor import perf
    from paddle_tpu.monitor import profile as pprof
    from paddle_tpu.parallel.engine import CompiledTrainStep

    paddle.set_flags({"FLAGS_monitor_profile": True,
                      "FLAGS_perf_attribution": True})
    pprof.start_sampler()
    on_tpu = jax.default_backend() != "cpu"
    pmesh.build_hybrid_mesh(dp=1, devices=jax.devices()[:1])
    paddle.seed(0)
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=768,
                          intermediate_size=2048, num_hidden_layers=12,
                          num_attention_heads=6,
                          max_position_embeddings=2048,
                          use_parallel=False, dtype="bfloat16")
        batch, seq = 8, 1024
    else:
        cfg = LlamaConfig.tiny(use_parallel=False)
        batch, seq = 2, 32
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())

    def loss_fn(logits, labels):
        return F.cross_entropy(
            logits.reshape([-1, cfg.vocab_size]), labels.reshape([-1]))

    step = CompiledTrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(
        0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(rng.randint(
        0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    for _ in range(max(int(steps), 1)):
        loss = step(ids, labels)
    final = float(loss)
    assert np.isfinite(final), final
    snap = _base_snap(jax.default_backend(), "smoke")
    snap["config"] = {"batch": batch, "seq": seq,
                      "steps": max(int(steps), 1),
                      "hidden": cfg.hidden_size,
                      "layers": cfg.num_hidden_layers}
    snap["final_loss"] = final
    snap["profile"] = pprof.profile_payload()
    # the analytic side of the reconciliation (perf.note_job rows carry
    # both the phase split and the mirrored profile_* measurements)
    snap["perf_jobs"] = (perf.perf_payload() or {}).get("jobs") or {}
    return snap


def write_artifact(path, snap=None, stale_reason=None):
    """Write the artifact with the stale re-emit discipline (the
    mem_snapshot/bench.py contract). Returns the dict written."""
    if snap is None or stale_reason is not None:
        reason = stale_reason or "measurement failed"
        last = None
        if os.path.exists(path):
            try:
                with open(path) as f:
                    last = json.load(f)
            except (OSError, ValueError):
                last = None
        if last and last.get("kind") == "profile_snapshot":
            last["stale"] = True
            last["stale_reason"] = reason
            last["stale_generations"] = \
                int(last.get("stale_generations", 0)) + 1
            last.setdefault("stale_since", last.get("written_at"))
            snap = last
        else:
            snap = {"kind": "profile_snapshot", "version": 1,
                    "ok": False, "error": reason,
                    "written_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                time.gmtime())}
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(snap, f, indent=1, default=str)
        f.write("\n")
    os.replace(tmp, path)
    return snap


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--once", action="store_true",
                    help="host-only sampler window, no train smoke")
    ap.add_argument("--window", type=float, default=0.8,
                    help="--once: sample window seconds")
    ap.add_argument("--out", default=DEFAULT_OUT,
                    help="artifact path (stale re-emit on failure)")
    ap.add_argument("--json", action="store_true",
                    help="print the snapshot JSON to stdout")
    a = ap.parse_args(argv)
    _watchdog()

    try:
        snap = measure_once(a.window) if a.once else measure(a.steps)
    except Exception as e:
        sys.stderr.write("profile_snapshot: measurement failed: %r\n"
                         % (e,))
        snap = write_artifact(a.out, None, stale_reason=repr(e))
        if a.json:
            print(json.dumps(snap, default=str))
        return 3
    write_artifact(a.out, snap)
    if a.json:
        print(json.dumps(snap, default=str))
    else:
        prof = snap["profile"]
        sampler = prof.get("sampler") or {}
        print("profile_snapshot: wrote %s (backend=%s, samples=%s, "
              "overhead=%.4f%%)"
              % (a.out, snap["backend"], sampler.get("samples"),
                 100 * (sampler.get("overhead_share") or 0.0)))
        for comp, row in sorted((prof.get("components") or {}).items()):
            print("  component %-12s %5.1f%%  (%d samples)"
                  % (comp, 100 * row["share"], row["samples"]))
        for job, tot in sorted((prof.get("jobs") or {}).items()):
            print("  job=%-8s steps=%d dispatch=%.4fs blocked=%.4fs "
                  "gap=%.4fs"
                  % (job, tot["steps"], tot["dispatch_s"],
                     tot["blocked_s"], tot["gap_s"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
