"""Fetch/merge watchdog diagnostic bundles across ranks.

Two sources, one merged artifact:

  # live: GET /debugz/bundle from each rank's fleet KV HTTP server
  python tools/debug_bundle.py fetch --endpoint host:port \
      [--endpoint host:port ...] --out merged.json

  # postmortem: merge the watchdog_bundle_rank*.json files a stalled
  # run left in its PT_MONITOR_DUMP_DIR
  python tools/debug_bundle.py merge --dir DUMP_DIR --out merged.json

The merged artifact is ``{bundles: {rank: bundle}, diagnosis: ...}``
where the diagnosis is monitor.watchdog.diagnose_bundles — the same
stalled/dead-rank naming the in-run cross-rank postmortem performs, so
an operator pulling bundles by hand and the watchdog's own gather agree
on the verdict.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from paddle_tpu.monitor.watchdog import (  # noqa: E402
    diagnose_bundles,
    summarize_postmortem,
)


def fetch_endpoint(endpoint, timeout_s=10.0):
    """GET /debugz/bundle from one rank's server; returns the bundle."""
    url = endpoint if "://" in endpoint else "http://" + endpoint
    with urllib.request.urlopen(url.rstrip("/") + "/debugz/bundle",
                                timeout=timeout_s) as r:
        return json.loads(r.read().decode())


def load_dir(dump_dir):
    """{rank: bundle} from watchdog_bundle_rank*.json files."""
    bundles = {}
    for path in sorted(glob.glob(os.path.join(
            dump_dir, "watchdog_bundle_rank*.json"))):
        m = re.search(r"rank(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                bundles[int(m.group(1))] = json.load(f)
        except (OSError, ValueError) as e:
            print("skipping %s: %s" % (path, e), file=sys.stderr)
    return bundles


def merge(bundles, world_size=None):
    if world_size is None:
        sizes = [b.get("world_size") for b in bundles.values()
                 if b.get("world_size")]
        world_size = max(sizes) if sizes else (
            max(bundles) + 1 if bundles else 0)
    diagnosis = diagnose_bundles(bundles, world_size)
    return {
        "kind": "watchdog_bundle_merged",
        "merged_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "world_size": world_size,
        "ranks": sorted(bundles),
        "diagnosis": diagnosis,
        "bundles": {str(r): b for r, b in sorted(bundles.items())},
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)
    f = sub.add_parser("fetch", help="GET /debugz/bundle from live ranks")
    f.add_argument("--endpoint", action="append", required=True,
                   help="host:port of a rank's fleet KV/metrics server "
                        "(repeatable)")
    f.add_argument("--timeout", type=float, default=10.0)
    f.add_argument("--out", required=True)
    f.add_argument("--world-size", type=int)
    m = sub.add_parser("merge", help="merge on-disk bundle files")
    m.add_argument("--dir", required=True,
                   help="PT_MONITOR_DUMP_DIR of the stalled run")
    m.add_argument("--out", required=True)
    m.add_argument("--world-size", type=int)
    a = ap.parse_args(argv)

    if a.cmd == "fetch":
        bundles = {}
        for ep in a.endpoint:
            try:
                b = fetch_endpoint(ep, a.timeout)
            except Exception as e:
                print("endpoint %s unreachable: %s" % (ep, e),
                      file=sys.stderr)
                continue
            bundles[int(b.get("rank", len(bundles)))] = b
    else:
        bundles = load_dir(a.dir)
    if not bundles:
        print("no bundles found", file=sys.stderr)
        return 2
    out = merge(bundles, a.world_size)
    with open(a.out, "w") as f:
        json.dump(out, f, indent=1, default=str)
        f.write("\n")
    print("merged %d bundle(s) -> %s" % (len(bundles), a.out))
    print(summarize_postmortem(out["diagnosis"]))
    return 0 if out["diagnosis"].get("status") in ("ok",
                                                   "inconclusive") else 1


if __name__ == "__main__":
    sys.exit(main())
