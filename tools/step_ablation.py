"""Train-step time attribution for the flagship bench config.

Parity motivation: the reference records per-op numbers next to its model
numbers (/root/reference/tools/ci_op_benchmark.sh:1 + op_tester.cc); this
tool answers the model-level question those leave open — *where does the
non-MXU time in a train step go* — by timing the step's components in
isolation at the exact bench shapes (bench.py 134M config by default,
--config llama1b for the weight-dominated one).

Each component is a jitted closure timed with the tunnel-safe recipe
(host scalar readback, never block_until_ready — BASELINE.md). Components
overlap deliberately (fwd is part of fwd+bwd); the table reports both raw
ms and the share of the full step, so the residual row ("other: XLA
fusion glue, layernorms, residual adds, weight update") is the step time
minus the big named pieces.

Usage: python tools/step_ablation.py [--config 134m|llama1b] [--out FILE]
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _watchdog(seconds=1500):
    def fire(signum, frame):
        sys.stderr.write("step_ablation watchdog: %ds, aborting\n" % seconds)
        os._exit(3)

    signal.signal(signal.SIGALRM, fire)
    signal.alarm(seconds)


def _time_ms(fn, sync, iters):
    """Median-free simple timing: warmup twice, time `iters` calls."""
    for _ in range(2):
        out = fn()
    sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    sync(out)
    return (time.perf_counter() - t0) * 1000.0 / iters


def _loop_time_ms(body, init, sync, inner, outer):
    """Per-iteration time of `body` amortized inside ONE jitted
    fori_loop call. Isolated per-call timing through the axon tunnel
    carries ~2-4 ms of host->tunnel dispatch per call, which swamps
    sub-ms components (the first committed 134m ablation measured
    attention at 4.47 ms/layer isolated vs ~0.75 ms in-step and went
    negative in the residual). The carry threads a data dependency so
    XLA cannot hoist the body out of the loop."""
    import jax

    looped = jax.jit(lambda c: jax.lax.fori_loop(0, inner, body, c))
    c = looped(init)
    sync(c)
    c = looped(init)
    sync(c)
    t0 = time.perf_counter()
    for _ in range(outer):
        c = looped(c)
    sync(c)
    return (time.perf_counter() - t0) * 1000.0 / (outer * inner)


class _Emitter:
    def __init__(self, out_path):
        self.rows = []
        self.out_path = out_path

    def __call__(self, name, ms, note=""):
        rec = {"component": name, "ms": round(ms, 2), "note": note}
        self.rows.append(rec)
        print(json.dumps(rec), flush=True)
        if self.out_path:
            # incremental write: a mid-run tunnel wedge (watchdog abort)
            # must not erase the components already measured
            with open(self.out_path, "w") as f:
                json.dump({"rows": self.rows, "partial": True}, f, indent=1)


def _dispatch_floor(emit, iters):
    import jax
    import jax.numpy as jnp

    tiny = jnp.zeros((8, 128), jnp.float32)
    disp_jit = jax.jit(lambda x: x + 1.0)
    ms = _time_ms(lambda: disp_jit(tiny),
                  lambda o: float(o[0, 0]), max(iters, 20))
    emit("dispatch_floor_per_call", ms,
         "host->device dispatch overhead; included once in full_step")
    return ms


def _forward_only(emit, model, ids_val, inner, outer, note):
    import jax.numpy as jnp

    names, vals = model.functional_state()
    state = dict(zip(names, vals))

    def fwd_fn(idsv):
        from paddle_tpu.core.dispatch import no_grad
        from paddle_tpu.core.tensor import Tensor

        with model.bind_state(list(state), [state[n] for n in state]):
            with no_grad():
                out = model(Tensor(idsv))
        out = out[0] if isinstance(out, tuple) else out
        return out._value

    def fwd_body(i, idsv):
        out = fwd_fn(idsv)
        # impossible predicate threads a dependency on the FULL output
        # into the next iteration without changing the input
        bump = (jnp.sum(out.astype(jnp.float32))
                > jnp.float32(1e30)).astype(idsv.dtype)
        return idsv + bump

    ms = _loop_time_ms(
        fwd_body, ids_val,
        lambda c: float(jnp.sum(c.reshape(-1)[:2].astype(jnp.float32))),
        inner, outer)
    emit("forward_only", ms, note)
    return ms


def _opt_update_only(emit, step, opt, inner, outer,
                     name="adamw_update_only"):
    import jax.numpy as jnp

    tr = {n: step._tensors[n]._value for n in step._trainable_names}
    gr = {n: jnp.ones_like(v) * 1e-6 for n, v in tr.items()}
    ost = step._opt_state
    first = step._trainable_names[0]

    def opt_body(i, carry):
        trc, stc = carry
        newp, news = opt.functional_apply(trc, gr, stc, step=1)
        return newp, news

    ms = _loop_time_ms(
        opt_body, (tr, ost),
        lambda c: float(jnp.sum(
            c[0][first].reshape(-1)[:1].astype(jnp.float32))),
        inner, outer)
    emit(name, ms, "elementwise, HBM-bound")
    return ms


def run_llama(args):
    import numpy as np
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import mesh as pmesh
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.parallel.engine import CompiledTrainStep

    on_tpu = jax.default_backend() != "cpu"
    iters = args.iters or (20 if on_tpu else 2)
    if not on_tpu:
        cfg = LlamaConfig.tiny(use_parallel=False)
        batch, seq = 2, 64
    elif args.config == "134m":
        cfg = LlamaConfig(vocab_size=32000, hidden_size=768,
                          intermediate_size=2048, num_hidden_layers=12,
                          num_attention_heads=6,
                          max_position_embeddings=2048,
                          use_parallel=False, dtype="bfloat16")
        batch, seq = 8, 1024
    else:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=5632, num_hidden_layers=16,
                          num_attention_heads=16,
                          max_position_embeddings=2048,
                          use_parallel=False, dtype="bfloat16",
                          recompute=True)
        batch, seq = 8, 1024

    pmesh.build_hybrid_mesh(dp=1, devices=jax.devices()[:1])
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())

    def loss_fn(logits, labels):
        return F.cross_entropy(logits.reshape([-1, cfg.vocab_size]),
                               labels.reshape([-1]))

    step = CompiledTrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))

    emit = _Emitter(args.out)
    rows = emit.rows

    inner = 16 if on_tpu else 2
    outer = max(2, iters // 4)

    disp_ms = _dispatch_floor(emit, iters)

    # 1. full train step (fwd + bwd + AdamW update)
    full_ms = _time_ms(lambda: step(ids, labels), lambda o: float(o), iters)
    emit("full_step", full_ms, "fwd+bwd+opt, the bench.py number")

    fwd_ms = _forward_only(emit, model, ids._value, inner, outer,
                           "inference pass; bwd ~= full - fwd - opt")

    # 2. flash attention fwd+bwd at the model's exact attention shape
    heads = cfg.num_attention_heads
    hd = cfg.hidden_size // heads
    q = jnp.asarray(rng.randn(batch, seq, heads, hd), jnp.bfloat16)

    from paddle_tpu.kernels.flash_attention import flash_attention

    def attn_loss(q, k, v):
        o = flash_attention(q, k, v, causal=True)
        return jnp.sum(o.astype(jnp.float32))

    attn_grad = jax.grad(attn_loss, argnums=(0, 1, 2))

    def attn_body(i, qc):
        dq, dk, dv = attn_grad(qc, qc, qc)
        # thread ALL three grads into the carry or XLA dead-code-
        # eliminates the dk/dv kernel out of the measurement
        dsum = (dq + dk + dv).astype(qc.dtype)
        return qc + dsum * jnp.asarray(1e-30, qc.dtype)

    attn_ms = _loop_time_ms(attn_body, q,
                            lambda c: float(c[0, 0, 0, 0]), inner, outer)
    emit("attention_fwd_bwd_per_layer", attn_ms,
         "x%d layers = %.2f ms" % (cfg.num_hidden_layers,
                                   attn_ms * cfg.num_hidden_layers))

    # 3. CE loss + lm_head matmul fwd+bwd (the vocab-sized tail)
    h = jnp.asarray(rng.randn(batch, seq, cfg.hidden_size), jnp.bfloat16)
    w = jnp.asarray(rng.randn(cfg.hidden_size, cfg.vocab_size),
                    jnp.bfloat16)
    lbl = jnp.asarray(labels._value)

    def head_loss(h, w):
        logits = (h @ w).reshape(-1, cfg.vocab_size).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, lbl.reshape(-1, 1),
                                   axis=-1)[:, 0]
        return jnp.mean(lse - gold)

    head_grad = jax.grad(head_loss, argnums=(0, 1))

    def head_body(i, hc):
        gh, gw = head_grad(hc, w)
        # gw (the [hidden, vocab] wgrad matmul) must feed the carry too,
        # or XLA removes the dominant backward matmul from the timing
        gw_tap = jnp.sum(gw.astype(jnp.float32)) * jnp.float32(1e-38)
        return (hc + gh.astype(hc.dtype) * jnp.asarray(1e-30, hc.dtype)
                + gw_tap.astype(hc.dtype))

    head_ms = _loop_time_ms(head_body, h,
                            lambda c: float(c[0, 0, 0]), inner, outer)
    emit("lm_head_plus_ce_fwd_bwd", head_ms, "vocab %d" % cfg.vocab_size)

    # 4. optimizer apply only (AdamW elementwise over all params)
    opt_ms = _opt_update_only(emit, step, opt, inner, outer)

    attn_total = attn_ms * cfg.num_hidden_layers
    resid = full_ms - disp_ms - attn_total - head_ms - opt_ms
    emit("residual_mlp_norms_rope_glue", resid,
         "full - dispatch - attention - head/CE - opt: MLP matmuls + "
         "RMSNorm + RoPE + residual adds + XLA glue; in-step fusion can "
         "make isolated component times differ from their in-step cost")
    summary = {"config": args.config, "backend": jax.default_backend(),
               "batch": batch, "seq": seq, "full_step_ms": round(full_ms, 2),
               "shares": {r["component"]: round(
                   (r["ms"] * (cfg.num_hidden_layers
                               if r["component"].endswith("per_layer")
                               else 1)) / full_ms, 3)
                   for r in rows if r["component"] != "full_step"}}
    print(json.dumps(summary), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"rows": rows, "summary": summary}, f, indent=1)
    return 0


def run_resnet50(args):
    """ResNet-50 attribution (VERDICT r4 #1): where do the ~87% of the
    chip go at 2,124 img/s? Components: layout (NHWC vs NCHW end-to-end
    — the conv relayout tax), forward, momentum update, head; residual
    is conv backward + BN glue."""
    import numpy as np
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import mesh as pmesh
    from paddle_tpu.parallel.engine import CompiledTrainStep
    from paddle_tpu.vision.models import resnet50

    on_tpu = jax.default_backend() != "cpu"
    iters = args.iters or (20 if on_tpu else 2)
    batch = 64 if on_tpu else 4
    size = 224 if on_tpu else 32
    pmesh.build_hybrid_mesh(dp=1, devices=jax.devices()[:1])
    emit = _Emitter(args.out)
    disp_ms = _dispatch_floor(emit, iters)

    rng = np.random.RandomState(0)
    y = paddle.to_tensor(rng.randint(0, 1000, (batch,)).astype(np.int32))

    def build(layout):
        paddle.seed(0)
        m = resnet50(num_classes=1000, data_format=layout)
        if on_tpu:
            m.to(dtype="bfloat16")
        o = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                      parameters=m.parameters())
        s = CompiledTrainStep(m, lambda lg, lb: F.cross_entropy(lg, lb), o)
        shape = ((batch, 3, size, size) if layout == "NCHW"
                 else (batch, size, size, 3))
        x = paddle.to_tensor(rng.rand(*shape).astype(np.float32) * 2 - 1)
        if on_tpu:
            x = x.astype("bfloat16")
        return m, o, s, x

    per_layout = {}
    for layout in ("NHWC", "NCHW"):
        m, o, s, x = build(layout)
        ms = _time_ms(lambda: s(x, y), lambda r: float(r), iters)
        per_layout[layout] = ms
        emit("full_step_%s" % layout.lower(), ms,
             "%.0f img/s" % (batch / ms * 1000.0))
    emit("layout_tax_nchw_minus_nhwc",
         per_layout["NCHW"] - per_layout["NHWC"],
         "relayout cost XLA inserts around NCHW convs")

    # components on the faster layout
    layout = min(per_layout, key=per_layout.get)
    model, opt, step, x = build(layout)
    full_ms = per_layout[layout]
    inner = 8 if on_tpu else 2
    outer = max(2, iters // 4)
    fwd_ms = _forward_only(emit, model, x._value, inner, outer,
                           "conv tower + head, inference pass")
    opt_ms = _opt_update_only(emit, step, opt, inner, outer,
                              "momentum_update_only")
    emit("residual_bwd_and_glue",
         full_ms - disp_ms - fwd_ms - opt_ms,
         "conv/BN backward + XLA glue (fwd is measured separately)")
    summary = {"config": "resnet50", "backend": jax.default_backend(),
               "batch": batch, "image_size": size, "layout": layout,
               "full_step_ms": round(full_ms, 2),
               "images_per_sec": round(batch / full_ms * 1000.0, 1),
               "per_layout_ms": {k: round(v, 2)
                                 for k, v in per_layout.items()}}
    print(json.dumps(summary), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"rows": emit.rows, "summary": summary}, f, indent=1)
    return 0


def run_ernie(args):
    """ERNIE-base attribution (VERDICT r4 #1): splits the 25%-MFU step
    into attention (12 heads x 64 head_dim, XLA path), the vocab-40000
    MLM head + CE, the dropout RNG tax (train-mode masks the llama
    config doesn't pay), embeddings, and the AdamW update."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import mesh as pmesh
    from paddle_tpu.models.ernie import ErnieConfig, ErnieForPretraining
    from paddle_tpu.parallel.engine import CompiledTrainStep

    on_tpu = jax.default_backend() != "cpu"
    iters = args.iters or (20 if on_tpu else 2)
    if on_tpu:
        cfg = ErnieConfig.base(fuse_qkv=not args.no_fuse)
        batch, seq = 16, 512
    else:
        cfg = ErnieConfig.tiny()
        batch, seq = 2, 64
    pmesh.build_hybrid_mesh(dp=1, devices=jax.devices()[:1])
    emit = _Emitter(args.out)
    paddle.seed(0)
    model = ErnieForPretraining(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())

    def loss_fn(out, labels):
        mlm, _sop = out
        return F.cross_entropy(mlm.reshape([-1, cfg.vocab_size]),
                               labels.reshape([-1]))

    step = CompiledTrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))

    inner = 16 if on_tpu else 2
    outer = max(2, iters // 4)
    disp_ms = _dispatch_floor(emit, iters)
    full_ms = _time_ms(lambda: step(ids, labels), lambda o: float(o), iters)
    emit("full_step", full_ms,
         "%.0f tok/s, fuse_qkv=%s" % (batch * seq / full_ms * 1000.0,
                                      getattr(cfg, "fuse_qkv", False)))
    fwd_ms = _forward_only(emit, model, ids._value, inner, outer,
                           "train-mode forward incl. dropout masks")

    # attention fwd+bwd at the exact shape (12 x 64: XLA path, not the
    # 128-head-dim Pallas kernel)
    heads = cfg.num_attention_heads
    hd = cfg.hidden_size // heads
    q = jnp.asarray(rng.randn(batch, seq, heads, hd),
                    jnp.bfloat16 if on_tpu else jnp.float32)

    def attn_loss(q, k, v):
        o = F.scaled_dot_product_attention(
            paddle.Tensor(q), paddle.Tensor(k), paddle.Tensor(v),
            is_causal=False)
        o = o._value if hasattr(o, "_value") else o
        return jnp.sum(o.astype(jnp.float32))

    attn_grad = jax.grad(attn_loss, argnums=(0, 1, 2))

    def attn_body(i, qc):
        dq, dk, dv = attn_grad(qc, qc, qc)
        dsum = (dq + dk + dv).astype(qc.dtype)
        return qc + dsum * jnp.asarray(1e-30, qc.dtype)

    attn_ms = _loop_time_ms(attn_body, q,
                            lambda c: float(c[0, 0, 0, 0]), inner, outer)
    emit("attention_fwd_bwd_per_layer", attn_ms,
         "x%d layers = %.2f ms" % (cfg.num_hidden_layers,
                                   attn_ms * cfg.num_hidden_layers))

    # MLM head + CE (hidden -> vocab 40000)
    h = jnp.asarray(rng.randn(batch, seq, cfg.hidden_size),
                    jnp.bfloat16 if on_tpu else jnp.float32)
    w = jnp.asarray(rng.randn(cfg.hidden_size, cfg.vocab_size), h.dtype)
    lbl = jnp.asarray(labels._value)

    def head_loss(h, w):
        logits = (h @ w).reshape(-1, cfg.vocab_size).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, lbl.reshape(-1, 1),
                                   axis=-1)[:, 0]
        return jnp.mean(lse - gold)

    head_grad = jax.grad(head_loss, argnums=(0, 1))

    def head_body(i, hc):
        gh, gw = head_grad(hc, w)
        gw_tap = jnp.sum(gw.astype(jnp.float32)) * jnp.float32(1e-38)
        return (hc + gh.astype(hc.dtype) * jnp.asarray(1e-30, hc.dtype)
                + gw_tap.astype(hc.dtype))

    head_ms = _loop_time_ms(head_body, h,
                            lambda c: float(c[0, 0, 0]), inner, outer)
    emit("mlm_head_plus_ce_fwd_bwd", head_ms, "vocab %d" % cfg.vocab_size)

    # dropout RNG tax: mask generation at the train-graph's shapes —
    # 2 masks/layer on [b, s, h] plus 1 on [b, s, ffn] worth of bits
    key0 = jax.random.PRNGKey(0)

    def drop_body(i, carry):
        key, acc = carry
        key, k1, k2 = jax.random.split(key, 3)
        m1 = jax.random.bernoulli(k1, 0.9, (batch, seq, cfg.hidden_size))
        m2 = jax.random.bernoulli(k2, 0.9, (batch, seq, cfg.hidden_size))
        acc = acc + jnp.sum(m1.astype(jnp.float32)) \
            + jnp.sum(m2.astype(jnp.float32))
        return key, acc

    drop_ms = _loop_time_ms(drop_body, (key0, jnp.float32(0)),
                            lambda c: float(c[1]), inner, outer)
    emit("dropout_masks_per_layer", drop_ms,
         "2 x [b,s,h] bernoulli; x%d layers = %.2f ms (llama pays 0)"
         % (cfg.num_hidden_layers, drop_ms * cfg.num_hidden_layers))

    opt_ms = _opt_update_only(emit, step, opt, inner, outer)
    attn_total = attn_ms * cfg.num_hidden_layers
    drop_total = drop_ms * cfg.num_hidden_layers
    emit("residual_ffn_ln_embed_glue",
         full_ms - disp_ms - attn_total - head_ms - drop_total - opt_ms,
         "ffn matmuls + layernorms + embeddings + XLA glue")
    summary = {"config": "ernie", "backend": jax.default_backend(),
               "batch": batch, "seq": seq,
               "fuse_qkv": bool(getattr(cfg, "fuse_qkv", False)),
               "full_step_ms": round(full_ms, 2),
               "tokens_per_sec": round(batch * seq / full_ms * 1000.0, 1)}
    print(json.dumps(summary), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"rows": emit.rows, "summary": summary}, f, indent=1)
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config",
                    choices=["134m", "llama1b", "resnet50", "ernie"],
                    default="134m")
    ap.add_argument("--iters", type=int, default=None)
    ap.add_argument("--out", default=None)
    ap.add_argument("--no-fuse", action="store_true",
                    help="ernie: disable the fused qkv projection")
    args = ap.parse_args()
    _watchdog()
    if args.config == "resnet50":
        return run_resnet50(args)
    if args.config == "ernie":
        return run_ernie(args)
    return run_llama(args)


if __name__ == "__main__":
    sys.exit(main())
