"""Perf report: MFU / phase split / HBM peak for a compiled train step.

Renders the monitor/perf.py attribution surface as a run report, from
one of three sources:

  # smoke: build the bench-family decoder, run a few compiled steps
  # with perf attribution + the time-series ring on, report (the
  # default; CPU-safe — a tiny config off-chip, the 110M bench config
  # on the real backend)
  python tools/perf_report.py [--steps N] [--json] [--out FILE]

  # live: GET /debugz/perf from a running rank's fleet KV HTTP server
  python tools/perf_report.py --endpoint host:port

  # artifact: render a previously-written payload JSON
  python tools/perf_report.py --in perf_report.json

``--baseline BENCH_*.json`` diffs the measured MFU / HBM peak against
a bench artifact's fields (bench.py emits ``mfu`` / ``hbm_peak_bytes``
as of this round); a baseline from before the perf round is reported
as such, never silently treated as zero. The battery
(tools/tunnel_battery.sh) runs the smoke + diff on-chip so the first
tunnel window captures a hardware-normalized MFU baseline
automatically.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _watchdog(seconds=900):
    def fire(signum, frame):
        sys.stderr.write("perf_report watchdog: %ds, aborting\n" % seconds)
        os._exit(3)

    signal.signal(signal.SIGALRM, fire)
    signal.alarm(seconds)


def smoke(steps=5):
    """Run the bench-family decoder under full perf instrumentation and
    return the /debugz/perf payload (+ a bench-style summary row)."""
    import numpy as np
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import mesh as pmesh
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.monitor import perf, timeseries
    from paddle_tpu.monitor import profile as pprofile
    from paddle_tpu.parallel.engine import CompiledTrainStep

    # ptprof next to the analytic attribution: the same smoke run
    # carries BOTH sides of the measured-vs-analytic diff below
    paddle.set_flags({"FLAGS_perf_attribution": True,
                      "FLAGS_monitor_profile": True})
    timeseries.enable()
    perf.enable_sentinels()
    pprofile.start_sampler()
    on_tpu = jax.default_backend() != "cpu"
    pmesh.build_hybrid_mesh(dp=1, devices=jax.devices()[:1])
    paddle.seed(0)
    if on_tpu:
        # the flagship bench config (bench.py): the MFU this prints IS
        # the hardware-normalized form of the headline tokens/s
        cfg = LlamaConfig(vocab_size=32000, hidden_size=768,
                          intermediate_size=2048, num_hidden_layers=12,
                          num_attention_heads=6,
                          max_position_embeddings=2048,
                          use_parallel=False, dtype="bfloat16")
        batch, seq = 8, 1024
    else:
        cfg = LlamaConfig.tiny(use_parallel=False)
        batch, seq = 2, 32
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())

    def loss_fn(logits, labels):
        return F.cross_entropy(
            logits.reshape([-1, cfg.vocab_size]), labels.reshape([-1]))

    step = CompiledTrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(
        0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(rng.randint(
        0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    loss = step(ids, labels)        # compile + first attribution
    float(loss)
    t0 = time.perf_counter()
    for _ in range(max(steps, 1)):
        loss = step(ids, labels)
    final = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final), final
    tokens_per_s = batch * seq * max(steps, 1) / dt
    payload = perf.perf_payload()
    payload["smoke"] = {
        "backend": jax.default_backend(),
        "batch": batch, "seq": seq, "steps": max(steps, 1),
        "tokens_per_s": round(tokens_per_s, 1),
        "final_loss": final,
    }
    # hardware-normalized bench fields over the steady-state window
    # (the per-step gauges cover the LAST step; this is the mean)
    payload["smoke"].update(perf.bench_fields(
        step._perf_attr.analysis if step._perf_attr else None,
        tokens_per_s=tokens_per_s, tokens_per_step=batch * seq))
    # host-sampler summary (component shares, top stacks) rides along
    # so the artifact answers "where did the host time go" too
    payload["profile"] = pprofile.profile_payload()
    return payload


def fetch(endpoint, timeout_s=10.0):
    url = endpoint if "://" in endpoint else "http://" + endpoint
    with urllib.request.urlopen(url.rstrip("/") + "/debugz/perf",
                                timeout=timeout_s) as r:
        return json.loads(r.read().decode())


def _fmt_bytes(n):
    if n is None:
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return "%.1f %s" % (n, unit)
        n /= 1024.0


def render(payload, out=sys.stdout):
    w = out.write
    jobs = payload.get("jobs") or {}
    machine = payload.get("machine") or {}
    smoke_row = payload.get("smoke")
    if smoke_row:
        w("== smoke run ==\n")
        for k in ("backend", "batch", "seq", "steps", "tokens_per_s",
                  "final_loss", "mfu", "model_flops_per_step",
                  "hbm_peak_bytes"):
            if k in smoke_row:
                w("  %-22s %s\n" % (k, smoke_row[k]))
    for job, r in sorted(jobs.items()):
        w("== perf: %s ==\n" % job)
        if "mfu" in r:
            w("  %-22s %.5f   (peak %.1f TFLOP/s)\n"
              % ("mfu", r["mfu"],
                 (r.get("peak_flops") or machine.get("peak_flops", 0))
                 / 1e12))
        if "model_flops_per_step" in r:
            w("  %-22s %.3e\n" % ("model_flops/step",
                                  r["model_flops_per_step"]))
        if "model_flops_per_s" in r:
            w("  %-22s %.3f\n" % ("model TFLOP/s",
                                  r["model_flops_per_s"] / 1e12))
        if "step_seconds" in r:
            w("  %-22s %.3f ms\n" % ("step time",
                                     r["step_seconds"] * 1e3))
        if "tokens_per_s" in r:
            w("  %-22s %.1f\n" % ("tokens/s", r["tokens_per_s"]))
        if "goodput_tokens_per_s" in r:
            w("  %-22s %.1f (throughput %.1f)\n"
              % ("goodput tok/s", r["goodput_tokens_per_s"],
                 r.get("throughput_tokens_per_s", 0.0)))
        if "kv_page_occupancy" in r:
            w("  %-22s %.3f\n" % ("kv page occupancy",
                                  r["kv_page_occupancy"]))
        share = r.get("phase_share")
        if share:
            w("  %-22s compute %.1f%%  comm %.1f%%  host %.1f%%"
              "  (comm source: %s)\n"
              % ("phase split", 100 * share.get("compute", 0),
                 100 * share.get("comm", 0), 100 * share.get("host", 0),
                 r.get("comm_source", "none")))
        if "hbm_peak_bytes" in r:
            note = (" (executable upper-bound estimate)"
                    if r.get("hbm_peak_is_estimate") else "")
            w("  %-22s %s%s\n" % ("hbm peak",
                                  _fmt_bytes(r["hbm_peak_bytes"]), note))
        if "loss" in r:
            w("  %-22s %s\n" % ("last loss", r["loss"]))
    anomalies = payload.get("anomalies") or {}
    counts = anomalies.get("counts") or {}
    w("== anomalies ==\n")
    if counts:
        w("  DEGRADED since %s: %s\n"
          % (anomalies.get("degraded_since"),
             ", ".join("%s x%d" % kv for kv in sorted(counts.items()))))
    else:
        w("  none\n")
    render_measured(payload, out)


def render_measured(payload, out=sys.stdout):
    """Measured-vs-analytic phase reconciliation (ISSUE 13): diff the
    ptprof dispatch/blocked/gap timers against the analytic
    ``perf_phase_seconds`` split per job. The analytic model becomes
    falsifiable here — and the exposed-comm residual (measured step −
    analytic compute) is the number ROADMAP item 4's overlap work is
    scored on. NEVER fabricates a side: a job missing the measured
    timers (FLAGS_monitor_profile off) or the analytic split
    (FLAGS_perf_attribution off) says so instead of diffing zeros."""
    w = out.write
    jobs = payload.get("jobs") or {}
    w("== measured vs analytic (ptprof) ==\n")
    if not jobs:
        w("  no jobs report either side\n")
        return
    for job, r in sorted(jobs.items()):
        meas = all(isinstance(r.get(k), (int, float)) for k in (
            "profile_dispatch_seconds", "profile_host_blocked_seconds",
            "profile_host_gap_seconds"))
        phases = r.get("phase_seconds") or {}
        analytic = bool(phases)
        if meas and analytic:
            md = r["profile_dispatch_seconds"]
            mb = r["profile_host_blocked_seconds"]
            mg = r["profile_host_gap_seconds"]
            step_meas = md + mb
            comp = float(phases.get("compute", 0.0))
            comm = float(phases.get("comm", 0.0))
            host = float(phases.get("host", 0.0))
            w("  %s:\n" % job)
            w("    step      measured %.6fs (dispatch %.6f + blocked "
              "%.6f)  analytic %.6fs (compute %.6f + comm %.6f)  "
              "delta %+.6fs\n"
              % (step_meas, md, mb, comp + comm, comp, comm,
                 step_meas - (comp + comm)))
            w("    host gap  measured %.6fs  analytic host %.6fs  "
              "delta %+.6fs\n" % (mg, host, mg - host))
            w("    exposed-comm residual %.6fs (measured step - "
              "analytic compute; analytic comm says %.6fs, source %s)"
              "\n" % (step_meas - comp, comm,
                      r.get("comm_source", "?")))
        elif meas:
            w("  %s: measured only (analytic phase split absent — "
              "FLAGS_perf_attribution off?); no diff fabricated\n"
              % job)
        elif analytic:
            w("  %s: analytic only (measured timers absent — "
              "FLAGS_monitor_profile off?); no diff fabricated\n"
              % job)
        else:
            w("  %s: neither side present\n" % job)


def render_graph(graph_path, out=sys.stdout):
    """Collective-count + donation-audit columns from the pthlo
    artifact (tools/graph_report.json, the battery's pthlo row): one
    report answers "is the comm schedule still what we shipped". Reads
    the artifact only — never re-lowers anything — and renders it with
    the analysis package's OWN formatter so these columns can never
    drift from pthlo's output. paddle_tpu/__init__ imports jax but
    analysis/ is stdlib-only, so a bare worker gets the ptlint.py
    stub-package trick."""
    w = out.write
    try:
        with open(graph_path) as f:
            graph = json.load(f)
    except (OSError, ValueError) as e:
        w("== graph report %s unreadable: %s ==\n" % (graph_path, e))
        return
    if graph.get("kind") != "pthlo_report":
        w("== %s is not a pthlo report ==\n" % graph_path)
        return
    if "paddle_tpu" not in sys.modules:
        import types

        _pkg = types.ModuleType("paddle_tpu")
        _pkg.__path__ = [os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "paddle_tpu")]
        sys.modules["paddle_tpu"] = _pkg
    from paddle_tpu.analysis.graph.runner import render_graph_text

    w("== graph report (%s) ==\n" % os.path.basename(graph_path))
    w(render_graph_text(graph))
    w("\n")


def diff_baseline(payload, baseline_path, out=sys.stdout):
    w = out.write
    try:
        with open(baseline_path) as f:
            base = json.load(f)
    except (OSError, ValueError) as e:
        w("== baseline %s unreadable: %s ==\n" % (baseline_path, e))
        return
    if isinstance(base, list):    # model_benchmark --out artifacts
        base = next((r for r in base if "mfu" in r), base[0] if base
                    else {})
    if isinstance(base, dict) and isinstance(base.get("parsed"), dict):
        # BENCH_r*.json driver wrapper: the measurement record rides
        # under "parsed" (next to the raw child tail)
        base = base["parsed"]
    if isinstance(base, dict) and (
            base.get("stale") or base.get("stale_generations")
            or base.get("stale_since")):
        # a photocopy re-emit (bench.py stale markers, ROADMAP:
        # BENCH_r04/r05 re-emitted the 2026-07-31 probe) is NOT a live
        # baseline — refuse the numeric diff instead of comparing
        # against a number that was never re-measured
        w("== baseline %s is a STALE re-emit — refusing to diff ==\n"
          % os.path.basename(baseline_path))
        w("  stale_reason        %s\n"
          % base.get("stale_reason", "unrecorded"))
        w("  stale_since         %s  (when the number was actually "
          "measured)\n"
          % base.get("stale_since", base.get("measured_at")))
        if base.get("stale_generations"):
            w("  stale_generations   %s  (consecutive photocopy "
            "re-emits)\n" % base["stale_generations"])
        w("  re-baseline on the next live tunnel window before "
          "trusting any delta against this artifact\n")
        return
    row = payload.get("smoke") or {}
    train = (payload.get("jobs") or {}).get("train") or {}
    cur_mfu = row.get("mfu", train.get("mfu"))
    cur_hbm = row.get("hbm_peak_bytes", train.get("hbm_peak_bytes"))
    w("== vs baseline %s ==\n" % os.path.basename(baseline_path))
    if "mfu" not in base:
        w("  baseline has no mfu field (pre-perf-round artifact; "
          "measured_at=%s) — this run seeds the MFU trajectory\n"
          % base.get("measured_at"))
    elif cur_mfu:
        delta = (cur_mfu / base["mfu"] - 1.0) * 100 if base["mfu"] else 0
        w("  mfu        %.5f -> %.5f  (%+.1f%%)\n"
          % (base["mfu"], cur_mfu, delta))
    if "hbm_peak_bytes" in base and cur_hbm:
        w("  hbm peak   %s -> %s\n"
          % (_fmt_bytes(base["hbm_peak_bytes"]), _fmt_bytes(cur_hbm)))
    for k in ("value", "measured_at", "backend"):
        if k in base:
            w("  baseline %-12s %s\n" % (k, base[k]))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group()
    src.add_argument("--endpoint",
                     help="host:port of a live rank (GET /debugz/perf)")
    src.add_argument("--in", dest="infile",
                     help="previously-written payload JSON")
    ap.add_argument("--smoke", action="store_true",
                    help="force the smoke run (the default source)")
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--json", action="store_true",
                    help="print the payload JSON instead of the report")
    ap.add_argument("--out", help="also write the payload JSON here")
    ap.add_argument("--baseline",
                    help="BENCH_*.json to diff mfu/hbm against")
    ap.add_argument("--graph", default=None,
                    help="pthlo artifact for the collective/donation "
                         "columns (default: tools/graph_report.json "
                         "when present; 'none' disables)")
    a = ap.parse_args(argv)
    _watchdog()

    if a.endpoint:
        payload = fetch(a.endpoint)
    elif a.infile:
        with open(a.infile) as f:
            payload = json.load(f)
    else:
        payload = smoke(a.steps)

    if a.out:
        with open(a.out, "w") as f:
            json.dump(payload, f, indent=1, default=str)
            f.write("\n")
    if a.json:
        print(json.dumps(payload, default=str))
    else:
        render(payload)
    if a.baseline:
        diff_baseline(payload, a.baseline)
    graph_path = a.graph
    if graph_path is None:
        default = os.path.join(os.path.dirname(os.path.abspath(
            __file__)), "graph_report.json")
        if os.path.exists(default):
            graph_path = default
    if graph_path and graph_path != "none" and not a.json:
        render_graph(graph_path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
