"""Scratch on-chip microbench for flash-attention variants (not shipped).

One dispatch runs `iters` iterations via lax.scan on-device, so tunnel
RPC overhead is amortized away.
"""
import functools
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.kernels.flash_attention import flash_attention

B, N, H, D = 8, 1024, 6, 128
ITERS = 20


def timeit(body, args, iters=ITERS, reps=3):
    """body: carry -> carry (device arrays). Times iters iterations
    inside one jitted scan; returns ms/iteration (min over reps)."""

    @jax.jit
    def run(c):
        def step(c, _):
            return body(c), ()
        c, _ = jax.lax.scan(step, c, None, length=iters)
        # scalar readback only — pulling full arrays through the tunnel
        # costs ~100ms and swamps the measurement
        return sum(jnp.sum(l.astype(jnp.float32))
                   for l in jax.tree_util.tree_leaves(c))

    s = run(args)  # compile+run
    _ = float(s)
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter()
        s = run(args)
        _ = float(s)
        best = min(best, time.perf_counter() - t0)
    return best / iters * 1000


def main():
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, N, H, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, N, H, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, N, H, D), jnp.bfloat16)

    def fwd(c):
        q, k, v = c
        o = flash_attention(q, k, v, causal=True)
        # feed output back in so scan iterations are serialized
        return (o, k, v)

    def fwdbwd(c):
        q, k, v = c

        def f(q, k, v):
            return (flash_attention(q, k, v, causal=True)
                    .astype(jnp.float32).sum())

        _, (dq, dk, dv) = jax.value_and_grad(f, argnums=(0, 1, 2))(q, k, v)
        return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))

    def tiny(c):
        return c + 1.0

    print("overhead    %.3f ms" %
          timeit(tiny, jnp.zeros((8, 128), jnp.float32), iters=100))
    print("fa fwd      %.3f ms" % timeit(fwd, (q, k, v), iters=100))
    print("fa fwd+bwd  %.3f ms" % timeit(fwdbwd, (q, k, v), iters=100))

    for sz in (4096, 8192):
        a = jnp.asarray(rng.randn(sz, sz), jnp.bfloat16)

        def mm(a):
            return a @ a

        t = timeit(mm, a, iters=100)
        print("mm %d^3   %.3f ms  -> %.1f TF/s" %
              (sz, t, 2 * sz**3 / (t / 1e3) / 1e12))


if __name__ == "__main__":
    main()
