"""ptreplay: re-drive a recorded serving workload and prove the tokens.

The record half (paddle_tpu/serving/replay.py, FLAGS_serving_replay)
journals every request an engine serves — prompt ids, sampling params,
the engine's latched flag snapshot, weights generation, and the output
token digest. This tool is the replay half: it rebuilds a REAL engine
(same model ctor path the benchmarks use — config kwargs + init seed
from the journal header's ``model`` meta), re-drives the journal
through it, and diffs token-for-token. Greedy decode is deterministic
per slot and the engine compiles ONE decode step, so replay costs no
recompiles (``decode_compiles == 1`` is re-checked here) and batching
order cannot change outputs.

Modes:
  run <journal>            digest-only divergence report (rolling token
                           hash per request); rc=2 on any divergence,
                           rc=4 if replay broke compile-once
    --full                 token-level diff: first diverging index +
                           both token tails per diverging request
    --matrix               replay across the prefix x chunked x
                           quant_kv x quant_weights flag matrix and
                           BISECT which axis introduces divergence: a
                           baseline (recorded-flags) divergence names
                           the ``weights`` axis (re-execution itself
                           disagrees — a perturbed/hot-swapped leaf),
                           a clean baseline with a diverging flip
                           names that flag axis
    --against <journal2>   diff two recordings pairwise (the canary
                           story: record on weights generation N,
                           record on N+1, diff — no engine rebuilt)
  smoke                    host-only CPU self-check for the battery
                           row: record a mixed tiny workload (prefix
                           hits + chunked prefill + quant-kv +
                           forced preempt/resume), require a
                           zero-divergence identity replay with
                           decode_compiles == 1, prove detection power
                           on a deliberately perturbed weight leaf
                           (and that --matrix names ``weights``), and
                           commit tools/replay_snapshot.json with the
                           stale re-emit discipline (rc=3 on failure)

Divergences count into ``replay_divergences_total{axis}`` and open a
``replay_divergence`` incident (evidence: the report path) when the
incident plane is on.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SMOKE_OUT = os.path.join(os.path.dirname(__file__),
                         "replay_snapshot.json")


def _first_divergence(a, b):
    """Index of the first differing token, or None if identical."""
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    if len(a) != len(b):
        return n
    return None


def _build_model(model_meta):
    """The benchmark's model ctor path: seed, then config kwargs. The
    seed reset makes weight init bit-reproducible — replay's whole
    premise."""
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    if not model_meta or "config" not in model_meta:
        raise SystemExit(
            "journal carries no model meta (record with "
            "serving_benchmark --record-out, or note_model() a "
            "{'config': {...}, 'seed': N} block before write_journal)")
    paddle.seed(int(model_meta.get("seed", 0)))
    cfg = LlamaConfig(use_parallel=False, **model_meta["config"])
    return LlamaForCausalLM(cfg)


def _perturb_one_leaf(model, scale=1.5):
    """Scale ONE projection weight leaf in place — the deliberate
    divergence the smoke row uses to prove the replay check has
    detection power (the ptcheck expected-finding discipline: a
    checker that cannot fail a broken run proves nothing)."""
    for name, p in model.named_parameters():
        v = p._value
        if getattr(v, "ndim", 0) == 2:
            p._value = v * scale
            return name
    raise RuntimeError("no 2-D weight leaf to perturb")


def replay_entries(head, entries, flags_override=None, full=False,
                   perturb=False):
    """Re-drive every finished entry through freshly built engines
    (one per recorded engine id, flags latched from the journal unless
    overridden) and return the divergence report block."""
    from paddle_tpu import serving
    from paddle_tpu.core import flags as ptflags

    replayable = [e for e in entries if e.get("state") == "finished"]
    skipped = {}
    for e in entries:
        if e.get("state") != "finished":
            skipped[e.get("state")] = skipped.get(e.get("state"), 0) + 1

    by_engine = {}
    for e in replayable:
        by_engine.setdefault(str(e.get("engine", 0)), []).append(e)

    divergences = []
    compiles = {}
    perturbed_leaf = None
    for eid, group in sorted(by_engine.items()):
        snap = (head.get("engines") or {}).get(eid) or {}
        flags = dict(snap.get("flags") or group[0]["flags"])
        if flags_override:
            flags.update(flags_override)
        caps = snap.get("caps") or {}
        # flags latch at Engine construction (PR-9): set BEFORE build
        ptflags.set_flags(flags)
        model = _build_model(head.get("model"))
        if perturb:
            perturbed_leaf = _perturb_one_leaf(model)
        eng = serving.Engine(
            model,
            max_slots=int(caps.get("max_slots", 4)),
            num_blocks=int(caps.get("num_blocks", 128)),
            block_size=int(caps.get("block_size", 16)),
            prefill_chunk=int(caps.get("prefill_chunk", 16)),
            max_model_len=caps.get("max_model_len"))
        rid_of = {}
        for e in group:
            # no deadline: replay determinism must not depend on the
            # replaying host's wall-clock speed
            rid = eng.add_request(e["prompt"],
                                  max_new_tokens=e["max_new_tokens"],
                                  eos_token_id=e.get("eos_token_id"))
            rid_of[rid] = e
        eng.run()
        for rid, e in rid_of.items():
            got = eng.output(rid)
            from paddle_tpu.serving.replay import token_hash

            got_hash = token_hash(got)
            want_hash = e.get("output_token_hash") \
                or token_hash(e.get("output") or ())
            if got_hash == want_hash:
                continue
            row = {"id": e["id"], "trace_id": e.get("trace_id"),
                   "engine": eid,
                   "recorded_hash": want_hash,
                   "replayed_hash": got_hash,
                   "weights_generation": e.get("weights_generation"),
                   "first_divergence": _first_divergence(
                       e.get("output") or [], got)}
            if full:
                row["recorded_tokens"] = e.get("output")
                row["replayed_tokens"] = got
            divergences.append(row)
        compiles[eid] = eng.stats()["decode_compiles"]
    return {
        "replayed": len(replayable),
        "skipped": skipped,
        "divergence_count": len(divergences),
        "divergences": divergences,
        "decode_compiles": compiles,
        "compile_once_ok": all(c == 1 for c in compiles.values()),
        "perturbed_leaf": perturbed_leaf,
    }


def matrix_bisect(head, entries, full=False, perturb=False):
    """Replay under the recorded flags, then once per flag axis with
    that ONE axis flipped. Bisection verdict: a baseline divergence
    names ``weights`` — the flags are identical to the recording, so
    re-execution itself disagrees, and the flag flips are skipped
    (every flip would inherit the same weight delta and prove
    nothing). A clean baseline with diverging flips names those flag
    axes; quant axes naming themselves is a finding about numerics
    (int8 KV / weight quantization are lossy), not a replay bug —
    only prefix and chunked are pinned token-identical by the repo's
    own tests."""
    from paddle_tpu.serving.replay import FLAG_AXES

    baseline = replay_entries(head, entries, full=full,
                              perturb=perturb)
    if baseline["divergence_count"]:
        return {
            "baseline_divergences": baseline["divergence_count"],
            "baseline": baseline,
            "axes": {},
            "bisected_axes": ["weights"],
        }
    axes = {}
    recorded_flags = {}
    for snap in (head.get("engines") or {}).values():
        recorded_flags.update(snap.get("flags") or {})
    for axis, flag in FLAG_AXES:
        flipped = not bool(recorded_flags.get(flag))
        res = replay_entries(head, entries,
                             flags_override={flag: flipped},
                             full=full, perturb=perturb)
        axes[axis] = {"flag": flag, "flipped_to": flipped,
                      "divergences": res["divergence_count"],
                      "compile_once_ok": res["compile_once_ok"]}
    return {
        "baseline_divergences": 0,
        "baseline": baseline,
        "axes": axes,
        "bisected_axes": [a for a, r in axes.items()
                          if r["divergences"]],
    }


def diff_journals(head_a, entries_a, head_b, entries_b, full=False):
    """Pairwise token diff of two recordings (--against): finished
    entries matched in admission order; a prompt mismatch marks the
    pair workload_mismatch instead of pretending it diverged."""
    fin_a = [e for e in entries_a if e.get("state") == "finished"]
    fin_b = [e for e in entries_b if e.get("state") == "finished"]
    pairs = min(len(fin_a), len(fin_b))
    divergences = []
    mismatches = 0
    for i in range(pairs):
        a, b = fin_a[i], fin_b[i]
        if a["prompt"] != b["prompt"] \
                or a["max_new_tokens"] != b["max_new_tokens"]:
            mismatches += 1
            continue
        if a.get("output_token_hash") == b.get("output_token_hash"):
            continue
        row = {"index": i, "id_a": a["id"], "id_b": b["id"],
               "hash_a": a.get("output_token_hash"),
               "hash_b": b.get("output_token_hash"),
               "weights_generation_a": a.get("weights_generation"),
               "weights_generation_b": b.get("weights_generation"),
               "first_divergence": _first_divergence(
                   a.get("output") or [], b.get("output") or [])}
        if full:
            row["tokens_a"] = a.get("output")
            row["tokens_b"] = b.get("output")
        divergences.append(row)
    return {
        "pairs": pairs,
        "unpaired": abs(len(fin_a) - len(fin_b)),
        "workload_mismatches": mismatches,
        "divergence_count": len(divergences),
        "divergences": divergences,
    }


def _note_divergences(report, out_path):
    """Feed the report's verdict into the metric/incident plane:
    replay_divergences_total{axis} + a replay_divergence incident with
    the report artifact as evidence."""
    from paddle_tpu.serving import replay as sreplay

    matrix = report.get("matrix")
    if matrix and matrix["bisected_axes"]:
        for axis in matrix["bisected_axes"]:
            n = (matrix["baseline_divergences"]
                 if axis == "weights"
                 else matrix["axes"][axis]["divergences"])
            sreplay.note_divergence(axis, max(n, 1), report=out_path)
    elif report.get("divergence_count"):
        sreplay.note_divergence("unknown", report["divergence_count"],
                                report=out_path)


def _write_report(path, report):
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1, default=str)
        f.write("\n")
    os.replace(tmp, path)


def run_replay(args):
    import jax

    from paddle_tpu.serving import replay as sreplay

    head, entries = sreplay.load_journal(args.journal)
    report = {
        "kind": "replay_report",
        "version": 1,
        "journal": args.journal,
        "backend": jax.default_backend(),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                     time.gmtime()),
        "recorded": head.get("requests"),
    }
    if args.against:
        head_b, entries_b = sreplay.load_journal(args.against)
        report["against"] = args.against
        report.update(diff_journals(head, entries, head_b, entries_b,
                                    full=args.full))
        compile_ok = True
    elif args.matrix:
        m = matrix_bisect(head, entries, full=args.full)
        report["matrix"] = m
        report["divergence_count"] = m["baseline_divergences"]
        report["divergences"] = m["baseline"]["divergences"]
        compile_ok = m["baseline"]["compile_once_ok"]
    else:
        report.update(replay_entries(head, entries, full=args.full))
        compile_ok = report["compile_once_ok"]
    _write_report(args.out, report)
    _note_divergences(report, args.out)
    print(json.dumps({k: v for k, v in report.items()
                      if k not in ("divergences", "matrix")}),
          flush=True)
    print("wrote", args.out, flush=True)
    if not compile_ok:
        sys.stderr.write("FAIL: replay broke compile-once "
                         "(decode_compiles != 1)\n")
        return 4
    if report.get("divergence_count"):
        axes = (report.get("matrix") or {}).get("bisected_axes")
        sys.stderr.write(
            "DIVERGED: %d request(s)%s — report: %s\n"
            % (report["divergence_count"],
               " (axes: %s)" % ",".join(axes) if axes else "",
               args.out))
        return 2
    return 0


def _smoke_record(tmpdir):
    """Record the smoke journal: tiny model, prefix + chunked +
    quant-kv on, shared-prefix prompts (cache hits) through a
    page-starved pool (forced preempt/resume) — the mixed workload
    the acceptance row names."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import serving
    from paddle_tpu.core import flags as ptflags
    from paddle_tpu.serving import replay as sreplay

    model_meta = {
        "preset": "replay_smoke", "seed": 0,
        "config": dict(vocab_size=64, hidden_size=32,
                       intermediate_size=64, num_hidden_layers=2,
                       num_attention_heads=4,
                       max_position_embeddings=96),
    }
    ptflags.set_flags({
        "FLAGS_serving_replay": True,
        "FLAGS_serving_prefix_cache": True,
        "FLAGS_serving_chunked_prefill": True,
        "FLAGS_serving_quant_kv": True,
        "FLAGS_serving_quant_weights": False})
    sreplay.clear()          # fresh journal; Engine latch auto-enables
    model = _build_model(model_meta)
    # page-starved pool: concurrent slots contend for pages so some
    # requests preempt and resume (recompute path) mid-journal
    eng = serving.Engine(model, max_slots=4, num_blocks=10,
                         block_size=8, prefill_chunk=8)
    rng = np.random.RandomState(0)
    shared = rng.randint(0, 64, (16,)).tolist()
    for i in range(12):
        prompt = (shared + rng.randint(0, 64, (4 + i % 5,)).tolist()
                  if i % 2 else
                  rng.randint(0, 64, (6 + i % 7,)).tolist())
        eng.add_request(prompt, max_new_tokens=6 + i % 6)
    eng.run()
    sreplay.note_model(model_meta)
    journal = os.path.join(tmpdir, "replay_smoke.jsonl")
    sreplay.write_journal(journal)
    stats = eng.stats()
    record = {
        "requests": 12,
        "preemptions": stats["preemptions"],
        "prefix_hit_tokens": stats["prefix_hit_tokens"],
        "decode_compiles": stats["decode_compiles"],
    }
    # replay must not re-record: drop the plane back off before the
    # replay engines are built
    ptflags.set_flags({"FLAGS_serving_replay": False})
    sreplay.disable()
    return journal, record


def run_smoke(args):
    """The tunnel_battery serving_replay row: record -> identity
    replay -> perturbed detection -> matrix bisect, committed as one
    artifact with the stale re-emit discipline (rc=3 on failure)."""
    import tempfile

    import jax

    report = None
    try:
        from paddle_tpu.serving import replay as sreplay

        with tempfile.TemporaryDirectory() as td:
            journal, record = _smoke_record(td)
            head, entries = sreplay.load_journal(journal)
            identity = replay_entries(head, entries)
            perturbed = replay_entries(head, entries, perturb=True)
            matrix = matrix_bisect(head, entries)
            # the acceptance bisect: a replaying host whose weights
            # drifted must have the matrix name the weights axis, not
            # blame a flag
            matrix_perturbed = matrix_bisect(head, entries,
                                             perturb=True)
            report = {
                "kind": "replay_snapshot",
                "backend": jax.default_backend(),
                "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                             time.gmtime()),
                "record": record,
                "identity": {
                    "divergences": identity["divergence_count"],
                    "replayed": identity["replayed"],
                    "compile_once_ok": identity["compile_once_ok"],
                },
                # detection power: a scaled weight leaf MUST diverge,
                # and the matrix MUST NOT blame a flag axis for it
                "perturbed": {
                    "leaf": perturbed["perturbed_leaf"],
                    "divergences": perturbed["divergence_count"],
                    "detected": perturbed["divergence_count"] > 0,
                },
                "matrix": {
                    "baseline_divergences":
                        matrix["baseline_divergences"],
                    "axes": {a: r["divergences"]
                             for a, r in matrix["axes"].items()},
                    "bisected_axes": matrix["bisected_axes"],
                },
                "matrix_perturbed": {
                    "bisected_axes": matrix_perturbed["bisected_axes"],
                },
            }
            # clean-journal matrix: baseline and the token-identity
            # axes (prefix, chunked) must not diverge; quant axes are
            # allowed to (lossy numerics is their finding to report).
            # perturbed-journal matrix: MUST bisect to weights.
            report["ok"] = bool(
                identity["divergence_count"] == 0
                and identity["compile_once_ok"]
                and record["preemptions"] > 0
                and record["prefix_hit_tokens"] > 0
                and report["perturbed"]["detected"]
                and matrix["baseline_divergences"] == 0
                and matrix["axes"]["prefix"]["divergences"] == 0
                and matrix["axes"]["chunked"]["divergences"] == 0
                and matrix_perturbed["bisected_axes"] == ["weights"])
    except Exception as e:
        sys.stderr.write("replay smoke failed: %r\n" % (e,))
        _reemit_stale(args.out, "smoke_failed: %r" % (e,))
        return 3
    _write_report(args.out, report)
    print(json.dumps(report), flush=True)
    print("wrote", args.out, flush=True)
    if not report["ok"]:
        _reemit_stale(args.out, None)    # artifact already fresh;
        return 2                         # the row goes red on content
    return 0


def _reemit_stale(path, stale_reason):
    """bench.py's staleness discipline: a failed smoke re-emits the
    previous artifact marked stale instead of photocopying silently."""
    if stale_reason is None or not os.path.exists(path):
        return
    try:
        with open(path) as f:
            last = json.load(f)
    except (OSError, ValueError):
        return
    if last.get("kind") != "replay_snapshot":
        return
    last["stale"] = True
    last["stale_reason"] = stale_reason
    last["stale_generations"] = int(last.get("stale_generations", 0)) + 1
    last.setdefault("stale_since", last.get("measured_at"))
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(last, f, indent=1, default=str)
        f.write("\n")
    os.replace(tmp, path)


def main():
    ap = argparse.ArgumentParser(
        description="deterministic serving record/replay audit")
    sub = ap.add_subparsers(dest="cmd", required=True)
    runp = sub.add_parser("run", help="replay a journal and diff")
    runp.add_argument("journal")
    runp.add_argument("--out", default="replay_report.json")
    runp.add_argument("--full", action="store_true",
                      help="token-level diff (first diverging index + "
                           "token tails), not just digests")
    runp.add_argument("--matrix", action="store_true",
                      help="replay across the flag matrix and bisect "
                           "the diverging axis")
    runp.add_argument("--against", default=None,
                      help="diff against a second journal instead of "
                           "re-executing (canary mode)")
    smokep = sub.add_parser("smoke", help="battery self-check row")
    smokep.add_argument("--out", default=SMOKE_OUT)
    args = ap.parse_args()
    if args.cmd == "smoke":
        return run_smoke(args)
    return run_replay(args)


if __name__ == "__main__":
    sys.exit(main())
