"""wide&deep PS-path saturation study (VERDICT r4 weak #6).

The single 15,198 ex/s point said nothing about WHERE the host PS path
binds or how it scales. This tool answers both, host-only (the PS path
is the host path — no TPU needed; the reference's PS exists precisely
to scale this, /root/reference/paddle/fluid/distributed/ps/README.md):

  1. component isolation at the bench shape (batch 512 x 8 slots x
     emb 16, vocab 100k): id generation, pull_sparse, push_sparse,
     dense fwd+bwd — each timed alone;
  2. worker scaling: N threads, each with its OWN PsClient connection
     (the native server is thread-per-connection, csrc/ps.cc:1114),
     hammering pull+push on the SAME table — aggregate ex/s vs N.

Writes tools/ps_saturation.json.

Usage: python tools/ps_saturation.py [--threads 1,2,4,8] [--iters 30]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

BATCH, N_SLOTS, EMB, VOCAB = 512, 8, 16, 100_000


def _mk_data(rng):
    import numpy as np

    ids = rng.randint(0, VOCAB, (BATCH, N_SLOTS)).astype(np.int64)
    y = rng.randint(0, 2, (BATCH,)).astype(np.float32)
    return ids, y


def components(cli, iters):
    import numpy as np

    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(2)
    rows = []

    def emit(name, per_iter_ms, note=""):
        rec = {"component": name, "ms_per_batch": round(per_iter_ms, 3),
               "examples_per_sec": round(BATCH / per_iter_ms * 1000.0, 1),
               "note": note}
        rows.append(rec)
        print(json.dumps(rec), flush=True)

    def timeit(fn, n=iters):
        fn()
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        return (time.perf_counter() - t0) * 1000.0 / n

    ids, y = _mk_data(rng)
    emit("id_generation", timeit(lambda: _mk_data(rng)),
         "synthetic feed parse (randint); real feed adds file IO")
    flat = ids.reshape(-1)
    emit("pull_sparse", timeit(lambda: cli.pull_sparse(0, flat)),
         "%d ids over TCP to the native table" % flat.size)
    pulled = cli.pull_sparse(0, flat)
    grads = np.asarray(pulled, np.float32) * 0.001
    emit("push_sparse", timeit(lambda: cli.push_sparse(0, flat, grads)),
         "adagrad update inside the table")

    w1 = jnp.asarray(np.random.RandomState(0).randn(
        N_SLOTS * EMB, 64).astype(np.float32) * 0.05)
    w2 = jnp.asarray(np.random.RandomState(1).randn(
        64, 1).astype(np.float32) * 0.05)
    emb = jnp.asarray(pulled.reshape(BATCH, N_SLOTS, EMB))
    yj = jnp.asarray(y)

    @jax.jit
    def dense(emb, w1, w2, y):
        def loss_fn(params):
            w1, w2 = params
            h = jax.nn.relu(emb.reshape(BATCH, -1) @ w1)
            logit = (h @ w2)[:, 0]
            return jnp.mean(jnp.maximum(logit, 0) - logit * y
                            + jnp.log1p(jnp.exp(-jnp.abs(logit))))

        return jax.value_and_grad(loss_fn)((w1, w2))

    def dense_once():
        loss, _ = dense(emb, w1, w2, yj)
        float(loss)

    emit("dense_fwd_bwd", timeit(dense_once),
         "MLP on %s backend" % jax.default_backend())
    return rows


def scaling(make_client, thread_counts, iters):
    import numpy as np

    out = []
    for n in thread_counts:
        counts = [0] * n
        stop = threading.Event()

        def worker(k):
            cli = make_client()
            rng = np.random.RandomState(100 + k)
            while not stop.is_set():
                ids, _ = _mk_data(rng)
                flat = ids.reshape(-1)
                rows = cli.pull_sparse(0, flat, dim=EMB)
                cli.push_sparse(0, flat,
                                np.asarray(rows, np.float32) * 0.001,
                                dim=EMB)
                counts[k] += 1

        threads = [threading.Thread(target=worker, args=(k,),
                                    daemon=True)
                   for k in range(n)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        time.sleep(max(2.0, iters / 10.0))
        stop.set()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        ex_s = sum(counts) * BATCH / dt
        rec = {"workers": n, "aggregate_examples_per_sec": round(ex_s, 1),
               "per_worker_examples_per_sec": round(ex_s / n, 1)}
        out.append(rec)
        print(json.dumps(rec), flush=True)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--threads", default="1,2,4,8")
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "ps_saturation.json"))
    args = ap.parse_args()

    from paddle_tpu.distributed.ps import PsClient, PsServer

    srv = PsServer()
    try:
        cli = PsClient(port=srv.port)
        cli.create_sparse_table(0, EMB, optimizer="adagrad", lr=0.05,
                                init_std=0.01)
        comp = components(cli, args.iters)
        sums = {r["component"]: r["ms_per_batch"] for r in comp}
        host_path = (sums.get("pull_sparse", 0)
                     + sums.get("push_sparse", 0))
        # binding attribution over HOST-path components only: in the
        # real config the dense step runs on the TPU (its CPU time here
        # is informational), so the PS path binds on table traffic
        binds = max(("pull_sparse", "push_sparse", "id_generation"),
                    key=lambda k: sums.get(k, 0))
        scale = scaling(lambda: PsClient(port=srv.port),
                        [int(x) for x in args.threads.split(",")],
                        args.iters)
        base = scale[0]["aggregate_examples_per_sec"]
        peak = max(r["aggregate_examples_per_sec"] for r in scale)
        report = {
            "shape": {"batch": BATCH, "slots": N_SLOTS, "emb_dim": EMB,
                      "vocab": VOCAB},
            # scaling on a 1-core host measures GIL/core contention, not
            # the table service; the reference's PS scales across
            # many-core hosts — read `scaling` against this count
            "host_cpu_count": os.cpu_count(),
            "components": comp,
            "binds_on": binds,
            "host_table_ms_per_batch": round(host_path, 3),
            "scaling": scale,
            "peak_aggregate_examples_per_sec": peak,
            "scaling_efficiency_at_max_workers": round(
                peak / (base * scale[-1]["workers"]), 3),
            "generated_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime()),
        }
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
            f.write("\n")
        print("wrote", args.out, flush=True)
    finally:
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
