#!/bin/bash
# On-chip measurement battery for a live tunnel window (round-5 VERDICT
# items 1, 2, 7): runs every pending measurement in priority order, each
# under its own timeout so one wedge cannot burn the window. Outputs are
# committed artifacts under tools/ + BENCH_LAST_GOOD via bench.py.
#
#   bash tools/tunnel_battery.sh [logdir]
#
# Priority: the flagship bench first (the driver-visible number), then
# the model rows, the op baseline, the ablations, serving int8, the
# continuous-batching serving row, 7B microbench.
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/battery_$(date -u +%H%M)}
mkdir -p "$LOG"
stamp() { date -u +%H:%M:%S; }

run() {  # run <name> <timeout> <cmd...>
  local name=$1 t=$2; shift 2
  echo "[$(stamp)] START $name" | tee -a "$LOG/battery.log"
  timeout "$t" "$@" > "$LOG/$name.out" 2>&1
  local rc=$?
  echo "[$(stamp)] DONE $name rc=$rc" | tee -a "$LOG/battery.log"
  tail -2 "$LOG/$name.out" | tee -a "$LOG/battery.log"
  return $rc
}

# 0a. static analysis: the invariant linter over the whole tree,
#     committed as an artifact. Host-only (stdlib, no accelerator) so
#     it runs before the tunnel probe — a red lint row must be visible
#     even in a window where the tunnel is wedged. Config comes from
#     [tool.ptlint] in pyproject.toml; rc!=0 means fresh findings or
#     stale baseline entries (tools/ptlint_report.json names them).
run ptlint 120 python tools/ptlint.py --out tools/ptlint_report.json

# 0b. compiled-graph analysis: pthlo lowers the registered fixtures
#     (train/pipeline/serving flag matrix) on 8 virtual CPU devices —
#     host-only like the ptlint row, it never touches the tunnel chip —
#     and runs the donation audit, collective-schedule contract check,
#     host-transfer/f64 lint and sharding report. rc!=0 means findings
#     or contract drift (tools/graph_report.json names them); the
#     committed artifact also feeds tools/perf_report.py's
#     collective/donation columns.
run pthlo 600 python tools/pthlo.py --check --out tools/graph_report.json

# 0c. protocol analysis: ptcheck DFS-explores the store/election/
#     barrier plane (real protocol code over an in-process SimStore on
#     a virtual clock) — host-only like the ptlint/pthlo rows, no
#     accelerator, no sockets, no real waiting. rc!=0 means a live
#     fixture produced a finding (the JSON carries a replayable
#     schedule string: `python tools/ptcheck.py --replay ...`) OR an
#     expected-finding regression fixture came back clean (the checker
#     lost the power its zeros rely on).
run ptcheck 300 python tools/ptcheck.py --out tools/ptcheck_report.json

# 0d. record/replay audit (ISSUE 20): ptreplay's self-check — record a
#     mixed tiny workload (prefix hits + chunked prefill + quant-kv +
#     forced preempt/resume) under FLAGS_serving_replay, then (a) the
#     identity replay must land ZERO divergences with
#     decode_compiles == 1, (b) a deliberately perturbed weight leaf
#     MUST be detected and the flag matrix must bisect it to the
#     `weights` axis, not blame a flag (the ptcheck expected-finding
#     discipline: a replay check that cannot fail a broken run proves
#     nothing), and (c) the clean matrix must keep the token-identity
#     axes (prefix, chunked) at zero. Host-only CPU like the 0a-0c
#     rows — determinism is a software property; the committed
#     artifact is tools/replay_snapshot.json (stale re-emit rc=3).
run serving_replay 900 env JAX_PLATFORMS=cpu \
    python tools/ptreplay.py smoke --out tools/replay_snapshot.json

# 0. pre-flight: bail fast if the tunnel is actually wedged
run probe 240 python bench.py --probe || { echo "tunnel wedged; abort"; exit 3; }

# Watchdog harness for the long train/serving rows: if a row hangs
# inside its timeout window, the in-process watchdog
# (paddle_tpu/monitor/watchdog.py) dumps a diagnostic bundle
# (all-thread stacks + flight ring + metrics) into $LOG and keeps a
# last-tick /healthz artifact there — a wedge leaves a diagnosis, not a
# bare `timeout` rc=124. Threshold 300s clears the worst-case compile.
wd() {  # wd <row-name> -> env-var prefix for a watchdog-monitored row
  echo "PT_WATCHDOG=1 PT_WATCHDOG_STALL_S=300 PT_MONITOR_DUMP_DIR=$LOG \
PT_WATCHDOG_HEALTHZ_OUT=$LOG/$1_healthz.json"
}

# 1. flagship number (single-step for vs_baseline + run_steps headline)
run bench 1500 env $(wd bench) python bench.py

# 1b. perf report: MFU / phase split / HBM peak of the bench-family
#     step under full attribution (FLAGS_perf_attribution + the
#     time-series ring + sentinels), diffed against the bench artifact
#     bench.py just refreshed — the first tunnel window after the perf
#     round captures an on-chip MFU baseline automatically
#     (tools/perf_report.json is the committed artifact).
run perf_report 900 python tools/perf_report.py --steps 10 --json \
    --out tools/perf_report.json --baseline BENCH_LAST_GOOD.json

# 1c. memory-plane snapshot (ISSUE 12): the per-component ledger +
#     allocator reconciliation + static-vs-transient headroom of the
#     SAME bench-family step under FLAGS_monitor_memory, committed as
#     tools/mem_snapshot.json. Runs inside the same window as the
#     train rows above so the headroom numbers date against a live
#     bench baseline; a failed child re-emits the previous artifact
#     marked stale (bench.py discipline) and the row goes red (rc=3).
run mem 600 env $(wd mem) python tools/mem_snapshot.py --steps 5 \
    --out tools/mem_snapshot.json

# 1d. continuous-profiling snapshot (ISSUE 13): host sampler component
#     attribution + MEASURED dispatch/blocked/gap step timers of the
#     SAME bench-family step under FLAGS_monitor_profile (+
#     FLAGS_perf_attribution for the analytic side), committed as
#     tools/profile_snapshot.json in the SAME window as the train rows
#     — the first live tunnel window gets measured host-blocked time
#     next to the re-baselined MFU (the round-13 re-baseline note).
#     tools/perf_report.py renders the measured-vs-analytic diff from
#     its own row above. Stale re-emit discipline on failure (rc=3).
run profile 600 env $(wd profile) python tools/profile_snapshot.py \
    --steps 5 --out tools/profile_snapshot.json

# 1e. SLO/incident snapshot (ISSUE 18): the SAME bench-family step
#     under FLAGS_monitor_slo — the objective judge runs over the
#     timeseries ring while the step trains, and the committed
#     tools/slo_snapshot.json carries the per-objective attainment /
#     error-budget / burn-rate verdicts plus the incident table. A
#     compliant run judges clean (no alert, empty table) — the
#     artifact proves the judge RAN. Stale re-emit on failure (rc=3).
run slo 600 env $(wd slo) python tools/slo_report.py --steps 5 \
    --out tools/slo_snapshot.json

# 2. north-star model rows (resnet both layouts, ernie fused, widedeep,
#    llama1b MFU row)
run model_resnet 1200 python tools/model_benchmark.py resnet50
run model_ernie 900 python tools/model_benchmark.py ernie_dp
run model_llama1b 1200 python tools/model_benchmark.py llama1b
run model_widedeep 600 python tools/model_benchmark.py widedeep

# 3. op baseline refresh: 44 rows (the reference-style CI gate).
#    --strict-coverage: a case that crashed mid-sweep leaves an
#    unguarded row and fails the battery row instead of silently
#    committing a baseline that guards only what happened to finish
run op_update 1800 python tools/op_benchmark.py update --strict-coverage

# 3b. eager collective wire benchmark: fp32 vs block-scaled int8
#     through the TCP store transport (the multi-host eager sync path;
#     distributed/compress.py). Wire bytes come from the comm_bytes
#     registry counters — the same series the acceptance tests assert.
run comm 600 python tools/comm_benchmark.py \
    --sizes 262144 1048576 4194304 --iters 5 \
    --out tools/comm_bench.json

# 4. step ablations (fixed grad threading; resnet layout tax; ernie
#    dropout/attention attribution)
run ablate_134m 1200 python tools/step_ablation.py --config 134m \
    --out tools/step_ablation_134m.json
run ablate_resnet 1500 python tools/step_ablation.py --config resnet50 \
    --out tools/step_ablation_resnet50.json
run ablate_ernie 1200 python tools/step_ablation.py --config ernie \
    --out tools/step_ablation_ernie.json

# 4b. flash kernel at head_dim 64 (ERNIE heads): compile probe + timing
#     vs the XLA fallback; if it compiles, re-run the ernie ablation
#     with the kernel routed in for an attributed comparison
run flash64 600 python tools/flash64_probe.py
if grep -q '"flash_d64_compiles": true' "$LOG/flash64.out" 2>/dev/null; then
  run ablate_ernie_flash64 1200 env FLAGS_flash_min_head_dim=64 \
      python tools/step_ablation.py --config ernie \
      --out tools/step_ablation_ernie_flash64.json
fi

# 4b2. dropout masks via the TPU hardware RNG (now that compiled steps
#      draw REAL per-step masks, the RNG tax is live — attribute it)
run ablate_ernie_rbg 1200 env FLAGS_dropout_rng_impl=rbg \
    python tools/step_ablation.py --config ernie \
    --out tools/step_ablation_ernie_rbg.json

# 4c. fused lm_head+CE kernel (measure child only — must not touch
#     BENCH_LAST_GOOD; parity is test-pinned, this is the timing)
run bench_fused_ce 1500 env FLAGS_fused_lm_head_ce=1 \
    python bench.py --measure

# 4d. fused qkv+mlp projections variant (tagged, measure child only)
run bench_fused_proj 1500 env BENCH_FUSE=1 python bench.py --measure
run bench_all_fused 1500 env BENCH_FUSE=1 FLAGS_fused_lm_head_ce=1 \
    python bench.py --measure

# 5. int8 serving row
run model_int8 1200 python tools/model_benchmark.py llama_int8

# 5b. continuous-batching serving row: paged KV + ragged paged-attention
#     decode under Poisson arrivals (tok/s, TTFT/TPOT p50/p90/p99,
#     preemptions -> committed JSON artifact). Also emits the monitor
#     registry snapshot with written_at metadata — the staleness witness
#     for this battery run (VERDICT r5: BENCH_r05 went stale silently;
#     a snapshot artifact dated by the run itself makes that detectable)
#     Runs under the watchdog: a serving-loop hang archives a bundle +
#     /healthz in $LOG instead of burning the window silently.
#     --profile (ISSUE 13): the row also carries measured per-phase
#     host seconds + an anomaly-style mid-run Xprof capture window.
#     --slo (ISSUE 18): the SLO judge watches the same run (latched
#     before Engine construction) and the artifact carries the
#     per-objective attainment + any burn-rate alerts that fired.
run serving 1200 env $(wd serving) \
    python tools/serving_benchmark.py --preset llama1b \
    --requests 64 --rate 8 --max-slots 8 --num-blocks 512 \
    --profile --slo \
    --out tools/serving_bench.json \
    --monitor-out tools/monitor_snapshot.json

# 5b2. serving tier-2 row (ISSUE 9): the SAME Poisson engine under the
#     system-prompt traffic shape (4 groups x 128 shared prefix tokens)
#     with the radix prefix cache + chunked prefill on — the artifact
#     reports TTFT split by cache hit/miss (acceptance: p50 hit-TTFT
#     <= 0.3x miss-TTFT), prefix_cache_hit_tokens_total, eviction/COW
#     counts and the chunk interleave, and still pins
#     decode_compiles == 1 (the mixed step is THE one compiled step).
#     Compare goodput-vs-throughput gap against the 5b row on the same
#     trace shape: the preemption tax should shrink (reclaim-before-
#     preempt). NOTE (re-baseline): BENCH_r04/r05 are stale photocopies
#     — run the bench + perf_report rows above in the same window so
#     these serving numbers diff against a LIVE baseline, not a rotted
#     one.
run serving_prefix 1200 env $(wd serving_prefix) \
    python tools/serving_benchmark.py --preset llama1b \
    --requests 64 --rate 8 --max-slots 8 --num-blocks 512 \
    --prefix-cache --chunked-prefill \
    --shared-prefix-tokens 128 --prefix-groups 4 \
    --out tools/serving_prefix_bench.json

# 5b3. serving quant row (ISSUE 19): the SAME shared-prefix shape as
#     5b2 with int8 block-scaled KV pages + weight-only int8 decode on
#     top (prefix cache + chunked prefill stay on — COW clones must
#     copy scale planes on-chip too). --num-blocks names the fp32 byte
#     budget; the quantized pool converts the same bytes into ~3.8x
#     the pages at head_dim=128, and the artifact's quant section
#     reports kv_capacity_headroom_vs_fp32 (acceptance: >= 1.8),
#     occupancy at first preemption/shed, and shed rate — compare
#     against the 5b2 row at the SAME --num-blocks to see pressure
#     arrive later. Still pins decode_compiles == 1 (rc=4): the
#     dequant-fused mixed step is THE one compiled step. Exercises the
#     quantized Mosaic paged-attention path (num_kv_heads*head_dim
#     tiling permitting) that CPU interpret tests can only approximate.
run serving_quant 1200 env $(wd serving_quant) \
    python tools/serving_benchmark.py --preset llama1b \
    --requests 64 --rate 8 --max-slots 8 --num-blocks 512 \
    --prefix-cache --chunked-prefill \
    --shared-prefix-tokens 128 --prefix-groups 4 \
    --quant-kv --quant-weights \
    --out tools/serving_quant_bench.json

# 5c. resilience serving row (ISSUE 7): the same engine under an
#     injected fault schedule + queue bound + deadlines — reports
#     goodput next to shed/expired/poison counts, proving graceful
#     degradation on-chip (requests fail individually, the engine and
#     its compile-once decode survive). Watchdog on like every long
#     row; the seeded schedule makes the chaos replayable.
run serving_resilience 1200 env $(wd serving_resilience) \
    python tools/serving_benchmark.py --preset llama1b \
    --requests 48 --rate 8 --max-slots 8 --num-blocks 512 \
    --fault-rate 0.1 --max-queue 32 --deadline-s 30 \
    --out tools/serving_resilience_bench.json

# 5c2. serving fleet row (ISSUE 16): 3 forked engine replicas + the
#     in-process prefix-affinity router over the fleet TCPStore, under
#     the shared-prefix Poisson shape. Phase A is the no-kill baseline;
#     phase B SIGKILLs the replica holding the most in-flight work
#     mid-run. Acceptance, enforced by exit codes: zero accepted
#     requests lost (rc=5), kill p99 TTFT ratio reported (within-2x
#     flag in the JSON), every survivor still decode_compiles == 1
#     (rc=4). A failed run re-emits the previous artifact marked stale
#     (rc=3) — bench.py's discipline. The row also commits the merged
#     fleet timeline (ISSUE 17): router + surviving-replica span
#     journals stitched on traceparent into tools/fleet_trace.json
#     (clock-aligned chrome trace + per-trace reroute-causality table);
#     the same stale re-emit discipline covers it on failure.
run serving_fleet 1500 env $(wd serving_fleet) \
    python tools/serving_benchmark.py --preset llama1b \
    --fleet 3 --kill-replica-at 4 \
    --requests 48 --rate 8 --max-slots 4 --num-blocks 256 \
    --shared-prefix-tokens 32 --prefix-groups 4 \
    --out tools/serving_fleet_snapshot.json \
    --fleet-trace-out tools/fleet_trace.json

# 5d. fleet telemetry row (ISSUE 8): the existing 2-process multihost
#     train entry under FLAGS_monitor_fleet — every rank announces its
#     metrics endpoint in the TCPStore, a STANDALONE collector scrapes
#     /metrics.json + /debugz/perf + /healthz from both ranks, fuses
#     them (counter sums, gauge spreads), and commits the per-rank
#     table + aggregates as tools/fleet_snapshot.json. A failed run
#     re-emits the previous artifact marked stale (bench.py's
#     discipline) and exits 3 — the battery row goes red instead of
#     photocopying a fleet table.
run fleet 900 python tools/fleet_battery.py --steps 40 \
    --out tools/fleet_snapshot.json

# 6. 7B-shape layer microbench (refines the pod projection)
run llama7b_micro 900 python tools/llama7b_plan.py --microbench

echo "[$(stamp)] battery complete; logs in $LOG" | tee -a "$LOG/battery.log"
