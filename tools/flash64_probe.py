"""On-chip probe: does this Mosaic build compile the flash kernel at
head_dim 64 (ERNIE/BERT heads)? The kernel is interpret-mode exact at 64
(tests/test_kernels.py); if this probe passes in a tunnel window, flip
FLAGS_flash_min_head_dim to 64 for the ERNIE configs (the ablation's
attention row then routes through the MXU kernel instead of the XLA
fallback).

Prints one JSON line {"flash_d64_compiles": bool, ...}.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    import numpy as np
    import jax
    import jax.numpy as jnp

    if jax.default_backend() == "cpu":
        print(json.dumps({"flash_d64_compiles": None,
                          "skipped": "needs the TPU chip"}))
        return 0
    from paddle_tpu.kernels.flash_attention import flash_attention

    rng = np.random.RandomState(0)
    b, n, h, d = 8, 512, 12, 64  # the ERNIE-base attention shape
    q = jnp.asarray(rng.randn(b, n, h, d), jnp.bfloat16)
    k = jnp.asarray(rng.randn(b, n, h, d), jnp.bfloat16)
    v = jnp.asarray(rng.randn(b, n, h, d), jnp.bfloat16)
    row = {"shape": [b, n, h, d]}
    try:
        grad = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(flash_attention(
                q, k, v, causal=False, interpret=False
            ).astype(jnp.float32)), argnums=(0, 1, 2)))
        g = grad(q, k, v)
        float(jnp.asarray(g[0]).astype(jnp.float32).sum())
        # time kernel vs XLA fallback at the same shape
        def t(fn):
            r = fn(q, k, v)
            float(jnp.asarray(r[0]).astype(jnp.float32).sum())
            t0 = time.perf_counter()
            for _ in range(10):
                r = fn(q, k, v)
            float(jnp.asarray(r[0]).astype(jnp.float32).sum())
            return (time.perf_counter() - t0) / 10 * 1e3

        from paddle_tpu.kernels.flash_attention import (
            _reference_attention,
        )

        def fallback(q, k, v):
            def fold(x):
                return jnp.swapaxes(x, 1, 2).reshape(b * h, n, d)

            return (jax.grad(lambda q_, k_, v_: jnp.sum(
                _reference_attention(fold(q_), fold(k_), fold(v_),
                                     1.0 / 8.0, False)
                .astype(jnp.float32)), argnums=(0, 1, 2))(q, k, v))

        fb = jax.jit(fallback)
        row.update({"flash_d64_compiles": True,
                    "kernel_ms": round(t(lambda *a: grad(*a)), 3),
                    "xla_fallback_ms": round(t(lambda *a: fb(*a)), 3)})
    except Exception as e:  # noqa: BLE001 — the probe's entire job
        row.update({"flash_d64_compiles": False,
                    "error": "%s: %s" % (type(e).__name__, str(e)[:300])})
    row["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    print(json.dumps(row), flush=True)
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "flash64_probe.json")
    with open(out, "w") as f:
        json.dump(row, f, indent=1)
        f.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
