"""Compile-level evidence for the Llama-7B hybrid-parallel north star.

BASELINE.json's "GPT/Llama-7B (TP+PP) tokens/sec/chip via Fleet" row
needs a v5p-64 pod; this environment has one tunneled v5e chip. This
tool produces the strongest artifact the environment permits
(VERDICT r4 next-round #3):

  1. AOT-compiles the REAL 7B training step — the same
     CompiledTrainStep / PipelinedTrainStep classes users run — over a
     virtual 64-device mesh (CPU backend, compile only, no execution)
     in two pod-shaped hybrid configs:
       A. tp8 x zero3-sharding8        (Megatron TP + full ZeRO-3)
       B. dp2 x sharding2 x tp8 x pp2  (TP+PP+DP hybrid, ZeRO-2 slots
          + reduce-scattered grads, per-layer remat, 1F1B ring)
  2. Records per-device memory from XLA's buffer assignment
     (compiled.memory_analysis(): argument/temp/peak bytes per device)
     and gates it against v5p per-chip HBM (95 GB).
  3. Counts the collectives XLA inserted (all-reduce for TP,
     reduce-scatter for ZeRO-2/3 grads, all-gather for ZeRO-3 params,
     collective-permute for the pp ring) as structural proof the
     sharding lowers to the intended communication pattern.
  4. Projects tokens/s/chip analytically from the measured sustained
     model-FLOPs throughput of this framework's largest on-chip run
     (953M at 99.3 TF/s, 50.4% MFU — MODEL_BENCH_r04.json) — labeled a
     PROJECTION, not a measurement.

No real weights are materialized for the heavy configs: parameters are
built zero-initialized (jax.random patched for construction speed),
optimizer slots enter the lowering as ShapeDtypeStructs, and the eager
device placement is skipped — XLA sees exactly the avals + shardings it
would see on a real pod. CPU-backend caveat: buffer assignment (fusion,
temp sizes) differs from the TPU backend, so temp/peak rows are
indicative; the argument-bytes rows (params + optimizer state + batch
per device) are exact sharding math.

Usage:
  python tools/llama7b_plan.py           # full artifact -> llama7b_plan.json
  python tools/llama7b_plan.py --quick   # 4-layer smoke of the harness
  python tools/llama7b_plan.py --microbench  # on-chip 7B-shape layer bench
                                             # (needs the TPU tunnel)
"""
from __future__ import annotations

import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
OUT = os.path.join(HERE, "llama7b_plan.json")
V5P_HBM_BYTES = 95e9
N_DEV = 64

_CHILD = "_LLAMA7B_PLAN_CHILD"


def reexec_cpu():
    """Child process with 64 virtual CPU devices and no TPU tunnel."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=%d" % N_DEV
        ).strip()
    env[_CHILD] = "1"
    os.execvpe(sys.executable, [sys.executable] + sys.argv, env)


def _patch_fast_init():
    """Zero-init params: PRNG generation of 6.7B elements on one CPU
    core is minutes; numerics are irrelevant for compile analysis."""
    import jax
    import jax.numpy as jnp

    def zeros(key, shape=(), dtype=jnp.float32, **kw):
        return jnp.zeros(shape, dtype)

    jax.random.normal = zeros
    jax.random.uniform = zeros
    jax.random.truncated_normal = (
        lambda key, lower, upper, shape=(), dtype=jnp.float32: jnp.zeros(
            shape, dtype))


def _struct_of_tree(tree):
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda v: jax.ShapeDtypeStruct(jnp.shape(v), jnp.result_type(v)),
        tree)


def _collective_counts(hlo_text):
    out = {}
    for op in ("all-reduce", "all-gather", "reduce-scatter",
               "collective-permute", "all-to-all"):
        # count op starts ("op-name" or "op-name-start"), not tuple refs
        out[op] = sum(hlo_text.count(" %s%s(" % (op, sfx))
                      + hlo_text.count(" = %s%s(" % (op, sfx))
                      for sfx in ("", "-start"))
        if out[op] == 0:
            out[op] = hlo_text.count("%s(" % op)
    return out


def _allreduce_feeds_dynamic_slice(text):
    """True when some dynamic-slice consumes (within two def-use hops
    through pass-through ops) the result of an all-reduce — the
    unfused reduce-scatter pattern."""
    import re

    producers = set()

    def consumes(args):
        # word-boundary match: %reshape.5 must not match %reshape.57
        return any(re.search(re.escape(p) + r"(?![\w.-])", args)
                   for p in producers)

    for m in re.finditer(
            r"(%[\w.-]+) = [^\n=]*\ball-reduce(?:-done)?\(", text):
        producers.add(m.group(1))
    for _ in range(2):  # follow pass-through ops a couple of hops
        grew = False
        for m in re.finditer(
                r"(%[\w.-]+) = [^\n=]*\b(?:get-tuple-element|reshape|"
                r"bitcast|copy|convert|transpose)\(([^)\n]*)\)", text):
            name, args = m.group(1), m.group(2)
            if name not in producers and consumes(args):
                producers.add(name)
                grew = True
        if not grew:
            break
    for m in re.finditer(r"dynamic-slice\(([^)\n]*)\)", text):
        if consumes(m.group(1)):
            return True
    # XLA fuses the slice: the consumer is then a `fusion(...)` whose
    # assigned name carries the fused op (e.g.
    # %dynamic-slice_transpose_fusion = fusion(%get-tuple-element...))
    for m in re.finditer(r"(%[\w.-]*slice[\w.-]*) = [^\n=]*\bfusion\("
                         r"([^)\n]*)\)", text):
        if consumes(m.group(2)):
            return True
    # Newer XLA CPU pipelines wrap partitioned bodies in call/fusion
    # ops (to_apply=/calls=%computation): a call consuming an
    # all-reduce result whose called computation TRANSITIVELY contains
    # a dynamic-slice is the same unfused reduce-scatter, one boundary
    # down.
    comps, cur, body = {}, None, []
    for line in text.splitlines():
        if cur is None:
            ms = re.match(r"\s*(?:ENTRY\s+)?(%[\w.-]+)\s*\([^\n]*\{\s*$",
                          line)
            if ms:
                cur, body = ms.group(1), []
        elif line.strip() == "}":
            comps[cur], cur = "\n".join(body), None
        else:
            body.append(line)
    refs = {n: set(re.findall(r"(?:to_apply|calls)=(%[\w.-]+)", b))
            for n, b in comps.items()}

    def has_ds(n, seen):
        if n in seen or n not in comps:
            return False
        seen.add(n)
        return ("dynamic-slice(" in comps[n]
                or any(has_ds(r, seen) for r in refs[n]))

    for m in re.finditer(r"= [^\n=]*\b(?:call|fusion)\(([^)\n]*)\)"
                         r"[^\n]*?(?:to_apply|calls)=(%[\w.-]+)", text):
        if consumes(m.group(1)) and has_ds(m.group(2), set()):
            return True
    return False


def _mem_row(compiled):
    from paddle_tpu.monitor import memory as ptmem

    ma = compiled.memory_analysis()
    row = {
        "argument_bytes_per_device": int(ma.argument_size_in_bytes),
        "output_bytes_per_device": int(ma.output_size_in_bytes),
        "temp_bytes_per_device": int(ma.temp_size_in_bytes),
        "alias_bytes_per_device": int(ma.alias_size_in_bytes),
    }
    # ONE peak number repo-wide (monitor/memory.py compiled_peak, the
    # same donation-aware executable_analysis the ledger/headroom math
    # and graph_report() cost rows consume): the real buffer-assignment
    # peak when jaxlib reports one, else args + temps + outputs net of
    # donation aliasing — an over-estimate (liveness overlap ignored),
    # flagged so hbm_fit readers don't mistake it for the scheduler's
    # real high-water mark.
    peak, is_estimate = ptmem.compiled_peak(compiled)
    if is_estimate:
        row["peak_is_upper_bound_estimate"] = True
    if peak is None:    # memory_analysis succeeded above, so this is
        peak = 0        # unreachable in practice — but never KeyError
    row["peak_bytes_per_device"] = int(peak)
    return row


def _model_and_sizes(cfg_kw, dtype="bfloat16"):
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(**cfg_kw)
    paddle.seed(0)
    t0 = time.monotonic()
    model = LlamaForCausalLM(cfg)
    model.to(dtype=dtype)
    n_params = sum(
        int(p.size) for _, p in model.named_parameters())
    print("model built: %.1fs, %d params (%.2fB)"
          % (time.monotonic() - t0, n_params, n_params / 1e9), flush=True)
    return cfg, model, n_params


def _abstract_opt(optimizer):
    """Route functional_init through ShapeDtypeStructs so slot zeros are
    never materialized (they only contribute avals to the lowering)."""
    import jax
    import jax.numpy as jnp

    def init(params_dict):
        return {
            name: [jax.ShapeDtypeStruct(jnp.shape(v), jnp.result_type(v))
                   for _ in optimizer._slots()]
            for name, v in params_dict.items()}

    optimizer.functional_init = init


def config_a(model, cfg, batch, seq):
    """tp8 x sharding8, ZeRO-3 via CompiledTrainStep."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import mesh as pmesh
    from paddle_tpu.parallel.engine import CompiledTrainStep

    pmesh.build_hybrid_mesh(mp=8, sharding=8)

    class AOTStep(CompiledTrainStep):
        def _shard_params(self):
            pass  # 64-way eager placement on one host would replicate

    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    _abstract_opt(opt)

    def loss_fn(logits, labels):
        return F.cross_entropy(
            logits.reshape([-1, cfg.vocab_size]), labels.reshape([-1]))

    step = AOTStep(model, loss_fn, opt, zero_stage=3)
    step._build()
    state_structs = _struct_of_tree(
        [step._tensors[n]._value for n in step._names])
    batch_structs = (jax.ShapeDtypeStruct((batch, seq), jnp.int32),
                     jax.ShapeDtypeStruct((batch, seq), jnp.int32))
    t0 = time.monotonic()
    lowered = step._compiled.lower(
        state_structs, step._opt_state, step._ef_state,
        jax.ShapeDtypeStruct((), jnp.int32),
        jax.ShapeDtypeStruct((), jnp.float32), jax.random.key(0),
        batch_structs)
    print("A lowered: %.1fs" % (time.monotonic() - t0), flush=True)
    t0 = time.monotonic()
    compiled = lowered.compile()
    print("A compiled: %.1fs" % (time.monotonic() - t0), flush=True)
    return compiled


def config_b(model, cfg, batch, seq, n_micro):
    """dp2 x sharding2 x tp8 x pp2, ZeRO-2, remat, 1F1B ring."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import mesh as pmesh
    from paddle_tpu.parallel import pipeline_parallel as pp_mod

    pmesh.build_hybrid_mesh(dp=2, mp=8, pp=2, sharding=2)

    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    _abstract_opt(opt)

    def loss_fn(logits, labels):
        return F.cross_entropy(
            logits.reshape([-1, cfg.vocab_size]), labels.reshape([-1]))

    # skip eager 64-way placement; jit in_shardings carry the layout
    real_put = jax.device_put
    jax.device_put = lambda x, *a, **k: x
    try:
        step = pp_mod.PipelinedTrainStep(
            model, loss_fn, opt, n_micro=n_micro, remat=True,
            zero_stage=2)
    finally:
        jax.device_put = real_put
    step._build()
    nb_structs = _struct_of_tree(
        [step.model.raw_state_tensors()[n]._value for n in step._nb_names])
    st_structs = _struct_of_tree(
        [step._stacked[s] for s in step.suffixes])
    batch_structs = (jax.ShapeDtypeStruct((batch, seq), jnp.int32),
                     jax.ShapeDtypeStruct((batch, seq), jnp.int32))
    t0 = time.monotonic()
    lowered = step._compiled.lower(
        nb_structs, st_structs, step._opt_state,
        jax.ShapeDtypeStruct((), jnp.int32),
        jax.ShapeDtypeStruct((), jnp.float32), jax.random.key(0),
        batch_structs)
    print("B lowered: %.1fs" % (time.monotonic() - t0), flush=True)
    t0 = time.monotonic()
    compiled = lowered.compile()
    print("B compiled: %.1fs" % (time.monotonic() - t0), flush=True)
    return compiled


def projection(n_params, seq, layers, hidden):
    """Tokens/s/chip projection from the measured sustained model-FLOPs
    throughput (NOT a measurement)."""
    # model FLOPs per token: 6N (fwd 2N + bwd 4N) + attention
    # 12*L*s*h per token (fwd+bwd of the s x s score/APV matmuls)
    attn = 12 * layers * seq * hidden
    flops_per_token = 6 * n_params + attn
    measured_tf = 99.3e12  # 953M run, MODEL_BENCH_r04.json, 50.4% MFU
    tok_chip = measured_tf / flops_per_token
    return {
        "method": "PROJECTION from measured 953M sustained throughput "
                  "(99.3 TF/s model FLOPs, 50.4% MFU on v5e; MFU rises "
                  "with model size so this is conservative for 7B)",
        "model_flops_per_token": flops_per_token,
        "assumed_sustained_model_tf_per_chip": 99.3,
        "projected_tokens_per_sec_per_chip": round(tok_chip, 1),
        "projected_tokens_per_sec_v5p64_pod": round(tok_chip * 64, 1),
        "is_measurement": False,
    }


def main():
    quick = "--quick" in sys.argv
    import jax

    assert jax.device_count() == N_DEV, jax.device_count()
    _patch_fast_init()

    layers = 4 if quick else 32
    seq = 512 if quick else 2048
    batch = 8 if quick else 16
    cfg_kw = dict(num_hidden_layers=layers,
                  max_position_embeddings=seq, use_parallel=True,
                  dtype="bfloat16", recompute=True,
                  fuse_attention_qkv=True, fuse_mlp=True)
    cfg, model, n_params = _model_and_sizes(cfg_kw)

    report = {
        "north_star": "BASELINE.json Llama-7B TP+PP hybrid tokens/s/chip",
        "generated_by": "tools/llama7b_plan.py",
        "quick": quick,
        "backend": "cpu (virtual %d-device mesh; compile-only)" % N_DEV,
        "caveat": "CPU-backend buffer assignment: argument bytes are "
                  "exact sharding math; temp/peak are indicative, the "
                  "TPU backend fuses differently",
        "model": {"hidden": cfg.hidden_size, "layers": layers,
                  "heads": cfg.num_attention_heads,
                  "ffn": cfg.intermediate_size,
                  "vocab": cfg.vocab_size, "seq": seq,
                  "batch_global": batch, "params": n_params,
                  "dtype": "bfloat16", "recompute": True},
        "configs": [],
    }

    # expected signatures: ZeRO-3's is the param all-gathers + TP
    # all-reduces (the grad combine's reduce-scatter-vs-AR choice is the
    # partitioner's on this backend); the pp hybrid must show the ring
    # collective-permutes and the ZeRO-2 AR->slice grad pattern
    for name, build, kw, expect in (
        ("tp8_zero3_sharding8", config_a, {},
         ["all-reduce", "all-gather"]),
        ("dp2_sharding2_tp8_pp2_zero2", config_b, {"n_micro": 4},
         ["all-reduce", "collective-permute", "reduce-scatter"]),
    ):
        t0 = time.monotonic()
        compiled = build(model, cfg, batch, seq, **kw)
        mem = _mem_row(compiled)
        text = compiled.as_text()
        colls = _collective_counts(text)

        def present(c):
            if colls.get(c, 0) > 0:
                return True
            # XLA's CPU SPMD pipeline lowers a reduce-scatter as
            # all-reduce + dynamic-slice when the combiner pass is off;
            # the TPU backend emits the fused op. Accept the pattern —
            # but only when a dynamic-slice actually CONSUMES an
            # all-reduce result (any dynamic-slice anywhere would make
            # the check vacuous: pp loops index with them constantly).
            if c == "reduce-scatter":
                return _allreduce_feeds_dynamic_slice(text)
            return False

        row = {
            "name": name,
            "memory": mem,
            "collectives": colls,
            "reduce_scatter_as_allreduce_plus_slice":
                colls.get("reduce-scatter", 0) == 0
                and _allreduce_feeds_dynamic_slice(text),
            "expected_collectives": expect,
            "expected_present": all(present(c) for c in expect),
            "hbm_fit": {
                "v5p_hbm_bytes": V5P_HBM_BYTES,
                "peak_fraction_of_v5p":
                    round(mem["peak_bytes_per_device"] / V5P_HBM_BYTES, 4),
                "fits": mem["peak_bytes_per_device"] < V5P_HBM_BYTES,
            },
            "wall_seconds": round(time.monotonic() - t0, 1),
        }
        report["configs"].append(row)
        print(json.dumps(row), flush=True)

    report["projection"] = projection(n_params, seq, layers,
                                      cfg.hidden_size)
    report["generated_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                           time.gmtime())
    out = OUT if not quick else OUT.replace(".json", "_quick.json")
    for a in sys.argv:  # --out=PATH: redirect (the live-gate test uses
        if a.startswith("--out="):  # a tmpdir, keeping the tree clean)
            out = a[len("--out="):]
    with open(out, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    print("wrote", out, flush=True)


def microbench():
    """On-chip microbench of 7B-shape components (one v5e chip through
    the tunnel): per-layer fwd+bwd at hidden 4096 / ffn 11008 and the
    lm_head+CE at vocab 32000. Refines the projection with measured
    7B-shape numbers when a tunnel window is open."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    assert jax.default_backend() != "cpu", "needs the TPU chip"
    sys.path.insert(0, REPO)
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    _patch_fast_init()
    # one decoder layer at exact 7B shapes (batch 1 x seq 2048 fits the
    # v5e 16GB easily; FLOPs/s at these K/N dims is what transfers)
    cfg = LlamaConfig(num_hidden_layers=1, max_position_embeddings=2048,
                      use_parallel=False, dtype="bfloat16",
                      fuse_attention_qkv=True, fuse_mlp=True)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.to(dtype="bfloat16")
    layer = model.llama.layers[0]
    sfx, vals = layer.functional_state()

    def layer_loss(vals_in, x):
        out = layer.functional_call(
            dict(zip(sfx, vals_in)), paddle.Tensor(x), state_names=sfx)
        return (out._value if hasattr(out, "_value") else out).astype(
            jnp.float32).sum()

    g = jax.jit(jax.grad(layer_loss, argnums=(0, 1)))
    x = jnp.zeros((1, 2048, 4096), jnp.bfloat16)
    r = g(list(vals), x)
    jax.tree_util.tree_map(
        lambda a: np.asarray(a[..., :1]) if hasattr(a, "shape") else a, r)
    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        r = g(list(vals), x)
    float(jnp.asarray(r[1]).astype(jnp.float32).sum())
    dt = (time.perf_counter() - t0) / iters
    n_layer_params = sum(int(np.prod(v.shape)) for v in vals)
    flops = 6 * n_layer_params * 2048 + 12 * 2048 * 4096 * 2048
    row = {"metric": "llama7b_layer_fwd_bwd_ms", "value": round(dt * 1e3, 2),
           "tokens": 2048, "layer_params": n_layer_params,
           "tf_per_s": round(flops / dt / 1e12, 1),
           "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime())}
    print(json.dumps(row), flush=True)
    # fold into the committed plan if present
    try:
        with open(OUT) as f:
            rep = json.load(f)
        rep.setdefault("microbench", []).append(row)
        with open(OUT, "w") as f:
            json.dump(rep, f, indent=1)
            f.write("\n")
    except OSError:
        pass


if __name__ == "__main__":
    if "--microbench" in sys.argv:
        sys.path.insert(0, REPO)
        microbench()
    elif os.environ.get(_CHILD) != "1":
        reexec_cpu()
    else:
        sys.path.insert(0, REPO)
        main()
