"""On-chip numerics check: Pallas kernel vs XLA reference path (not shipped)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.kernels import flash_attention as fa

B, N, H, D = 2, 1024, 2, 128
rng = np.random.RandomState(0)
q = jnp.asarray(rng.randn(B, N, H, D), jnp.bfloat16)
k = jnp.asarray(rng.randn(B, N, H, D), jnp.bfloat16)
v = jnp.asarray(rng.randn(B, N, H, D), jnp.bfloat16)


def loss_kernel(q, k, v):
    o = fa.flash_attention(q, k, v, causal=True)
    return (o.astype(jnp.float32) ** 2).mean()


def loss_ref(q, k, v):
    b, n, h, d = q.shape

    def fold(x):
        return jnp.swapaxes(x, 1, 2).reshape(b * h, x.shape[1], d)

    o = fa._reference_attention(fold(q), fold(k), fold(v),
                                1.0 / np.sqrt(d), True)
    o = jnp.swapaxes(o.reshape(b, h, n, d), 1, 2)
    return (o.astype(jnp.float32) ** 2).mean()


for name, f in [("kernel", loss_kernel), ("ref", loss_ref)]:
    l, g = jax.jit(jax.value_and_grad(f, argnums=(0, 1, 2)))(q, k, v)
    print(name, float(l),
          [float(jnp.abs(x.astype(jnp.float32)).mean()) for x in g])

lk, gk = jax.jit(jax.value_and_grad(loss_kernel, argnums=(0, 1, 2)))(q, k, v)
lr, gr = jax.jit(jax.value_and_grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
print("loss rel err", abs(float(lk) - float(lr)) / abs(float(lr)))
for a, b_, nm in zip(gk, gr, "qkv"):
    a = np.asarray(a, np.float32)
    b_ = np.asarray(b_, np.float32)
    denom = np.abs(b_).mean() + 1e-8
    print("d%s: mean abs diff %.3e (rel %.3e)" %
          (nm, np.abs(a - b_).mean(), np.abs(a - b_).mean() / denom))
