"""Calibrate the auto-parallel cost model against measured step times.

Parity: reference auto_parallel/tuner/profiler.py — run candidate
configs for real, feed the measurements back into the cost model
(VERDICT r3 #3: the analytic constants were asserted, never measured).

Measures CompiledTrainStep wall time for a matrix of model shapes x
mesh factorizations on whatever backend jax resolves (the 8-device
virtual CPU mesh in CI; a pod slice on real hardware), fits the
planner's two machine constants (effective flops, effective link
bandwidth) by least squares over the planner's own linear features,
and writes tools/cost_model_calibration.json.

Usage: python tools/calibrate_cost_model.py [--iters N] [--out FILE]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def measure_plan(plan, cfg_kw, batch, seq, iters=3):
    """Build the tiny-llama model under the given mesh factorization and
    time a compiled train step. Returns (stats_dict, seconds)."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import mesh as pmesh
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.parallel.engine import CompiledTrainStep

    mesh_kw = {k: v for k, v in plan.items() if v > 1 or k == "dp"}
    pmesh.build_hybrid_mesh(**mesh_kw)
    paddle.seed(0)
    cfg = LlamaConfig.tiny(**cfg_kw)
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())

    def loss_fn(logits, labels):
        return F.cross_entropy(logits.reshape([-1, cfg.vocab_size]),
                               labels.reshape([-1]))

    zero = 1 if plan.get("sharding", 1) > 1 else 0
    step = CompiledTrainStep(model, loss_fn, opt, zero_stage=zero)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    loss = step(ids, labels)
    float(loss)  # compile + sync
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        loss = step(ids, labels)
        float(loss)
        times.append(time.perf_counter() - t0)
    stats = _stats_for(cfg, batch, seq, model)
    return stats, float(np.median(times))


def _stats_for(cfg, batch, seq, model):
    """program_stats equivalent computed from the model config (the
    planner scores on the same four aggregates)."""
    import numpy as np

    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    flops = 6.0 * n_params * batch * seq  # fwd+bwd matmul flops
    return {
        "flops": flops,
        "param_bytes": n_params * 4,
        "act_bytes": batch * seq * cfg.hidden_size * 4,
        "n_layers": cfg.num_hidden_layers,
    }


DEFAULT_PLANS = [
    {"dp": 8, "mp": 1, "pp": 1, "sharding": 1},
    {"dp": 4, "mp": 2, "pp": 1, "sharding": 1},
    {"dp": 2, "mp": 4, "pp": 1, "sharding": 1},
    {"dp": 1, "mp": 4, "pp": 1, "sharding": 2},
    {"dp": 4, "mp": 1, "pp": 1, "sharding": 2},
]

DEFAULT_SHAPES = [
    (dict(hidden_size=64, intermediate_size=128, num_hidden_layers=2),
     8, 64),
    (dict(hidden_size=128, intermediate_size=256, num_hidden_layers=2),
     8, 64),
    (dict(hidden_size=128, intermediate_size=256, num_hidden_layers=4),
     8, 128),
]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(__file__), "cost_model_calibration.json"))
    args = ap.parse_args()

    import jax

    from paddle_tpu.distributed.auto_parallel.planner import MeshPlanner

    n_dev = jax.device_count()
    samples = []
    for cfg_kw, batch, seq in DEFAULT_SHAPES:
        for plan in DEFAULT_PLANS:
            total = plan["dp"] * plan["mp"] * plan["pp"] * plan["sharding"]
            if total != n_dev:
                continue
            try:
                stats, t = measure_plan(plan, cfg_kw, batch, seq,
                                        args.iters)
            except Exception as e:  # keep calibrating the other cells
                print(json.dumps({"plan": plan, "error": repr(e)[:200]}),
                      flush=True)
                continue
            samples.append({"stats": stats, "plan": plan,
                            "n_devices": n_dev, "measured": t})
            print(json.dumps({"plan": plan, "shape": cfg_kw,
                              "measured_ms": round(t * 1e3, 2)}),
                  flush=True)
    planner = MeshPlanner(hbm_bytes=1e12)
    fit = planner.calibrate(samples)
    result = {
        "backend": jax.default_backend(),
        "n_devices": n_dev,
        "n_samples": len(samples),
        "eff_flops": fit["eff_flops"],
        "bw": fit["bw"],
        "residual": fit["residual"],
        "samples": [{"plan": s["plan"], "measured": s["measured"]}
                    for s in samples],
    }
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps({"calibrated": True, **{k: result[k] for k in
                                             ("eff_flops", "bw",
                                              "residual")}}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
