#!/usr/bin/env python
"""ptlint CLI — run the paddle_tpu invariant linter over the tree.

    python tools/ptlint.py [paths...]            # lint (default paths
                                                 # from [tool.ptlint])
    python tools/ptlint.py --json                # JSON report on stdout
    python tools/ptlint.py --out report.json     # JSON artifact (the
                                                 # tunnel-battery row)
    python tools/ptlint.py --write-baseline      # re-grandfather the
                                                 # current flag/trace/
                                                 # thread findings
    python tools/ptlint.py --rules clock,metric  # subset of passes

Exit codes: 0 = clean (fresh findings all grandfathered, no stale
baseline entries), 1 = fresh findings or stale baseline, 2 = usage.

Config lives in ``[tool.ptlint]`` in pyproject.toml (paths, exclude,
baseline path, per-pass tables) so CI needs no flags. Stdlib-only:
runs on a bare worker without jax/numpy.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

if "paddle_tpu" not in sys.modules:
    # paddle_tpu/__init__.py imports jax; the analysis subpackage is
    # pure stdlib. Register a stub parent so a bare CI worker (no jax)
    # can still run the lint row.
    import types

    _pkg = types.ModuleType("paddle_tpu")
    _pkg.__path__ = [os.path.join(_REPO, "paddle_tpu")]
    sys.modules["paddle_tpu"] = _pkg

from paddle_tpu.analysis import (  # noqa: E402
    Baseline, Project, load_config, render_json, render_text, run)
from paddle_tpu.analysis.runner import (  # noqa: E402
    BASELINE_ELIGIBLE, RULES)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="ptlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help="dirs/files to lint (default: [tool.ptlint] "
                         "paths, else 'paddle_tpu tools')")
    ap.add_argument("--root", default=None,
                    help="repo root (default: the tools/ parent)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of: %s"
                         % ",".join(RULES))
    ap.add_argument("--json", action="store_true",
                    help="JSON report on stdout instead of text")
    ap.add_argument("--out", default=None,
                    help="also write the JSON report to this path")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default from [tool.ptlint])")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report ALL findings "
                         "as fresh)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current %s findings as the new "
                         "baseline and exit 0"
                         % "/".join(BASELINE_ELIGIBLE))
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".."))
    config = load_config(root)
    if args.paths:
        # Resolve CLI paths against root first, then CWD; a path that
        # exists in neither is a usage error — silently scanning zero
        # files would make a typo'd invocation report "clean".
        paths = []
        for p in args.paths:
            if os.path.exists(os.path.join(root, p)):
                paths.append(p)
                continue
            cand = os.path.abspath(p)
            if not os.path.exists(cand):
                ap.error("path %r not found under root %s or cwd"
                         % (p, root))
            rel = os.path.relpath(cand, root)
            if rel.split(os.sep)[0] == os.pardir:
                ap.error("path %r is outside root %s — pass --root"
                         % (p, root))
            paths.append(rel)
    else:
        paths = config.get("paths") or ["paddle_tpu", "tools"]
    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            ap.error("unknown rule(s) %s (have: %s)"
                     % (unknown, ",".join(RULES)))
    project = Project(root, paths=paths,
                      exclude=tuple(config.get("exclude", ())),
                      config=config)
    baseline_path = args.baseline or config.get("baseline")
    if baseline_path and not os.path.isabs(baseline_path):
        baseline_path = os.path.join(root, baseline_path)

    if args.write_baseline:
        if rules is not None:
            ap.error("--write-baseline cannot be combined with "
                     "--rules: the baseline is written whole, and a "
                     "subset run would silently drop every other "
                     "rule's grandfathered entries")
        findings, _, _ = run(project, rules=rules, baseline=None)
        keep = [f for f in findings if f.rule in BASELINE_ELIGIBLE]
        if not baseline_path:
            ap.error("--write-baseline needs a baseline path "
                     "(--baseline or [tool.ptlint] baseline)")
        Baseline.from_findings(keep).write(baseline_path)
        dropped = len(findings) - len(keep)
        print("ptlint: wrote %d grandfathered finding(s) to %s"
              % (len(keep), os.path.relpath(baseline_path, root)))
        if dropped:
            print("ptlint: %d finding(s) in non-grandfatherable rules "
                  "(clock/metric/silent-except) NOT written — fix or "
                  "pragma them" % dropped)
        return 0

    baseline = None
    if baseline_path and not args.no_baseline:
        baseline = Baseline.load(baseline_path)
    findings, stale, counts = run(project, rules=rules,
                                  baseline=baseline)
    report = render_json(
        findings, stale, counts,
        meta={"root": root, "paths": list(paths),
              "rules": rules or list(RULES),
              "baseline": (os.path.relpath(baseline_path, root)
                           if baseline_path else None),
              "files_scanned": len(project.files)})
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1)
            f.write("\n")
    if args.json:
        json.dump(report, sys.stdout, indent=1)
        sys.stdout.write("\n")
    else:
        print(render_text(findings, stale, counts))
    fresh = [f for f in findings if not f.grandfathered]
    return 1 if (fresh or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
