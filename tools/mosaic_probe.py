"""Probe which bf16 dot forms this Mosaic build compiles (not shipped)."""
import functools
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
import numpy as np

BQ, BK, D = 512, 512, 128


def probe(name, kernel, shapes, out_shape):
    try:
        f = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(out_shape, jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)
                      for _ in shapes],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        )
        args = [jnp.ones(s, jnp.bfloat16) for s in shapes]
        r = jax.jit(f)(*args)
        np.asarray(r).ravel()[0]
        print("OK  ", name)
    except Exception as e:
        msg = str(e).split("\n")[0][:150]
        print("FAIL", name, "--", msg)


def k_nt(a_ref, b_ref, o_ref):
    # a [BQ, D] @ b [BK, D]^T : contracting (1,1) — "transposed rhs"
    o_ref[...] = jax.lax.dot_general(
        a_ref[...], b_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)


def k_nn(a_ref, b_ref, o_ref):
    # a [BQ, D] @ b [D, BK] : contracting (1,0) — plain matmul
    o_ref[...] = jax.lax.dot_general(
        a_ref[...], b_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def k_tn(a_ref, b_ref, o_ref):
    # a [BQ, D]^T... contracting (0,0): [D, BQ]x[BQ... -> a^T @ b
    o_ref[...] = jax.lax.dot_general(
        a_ref[...], b_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def k_mixed(a_ref, b_ref, o_ref):
    # bf16 x fp32-from-exp: p (computed fp32, cast bf16) @ v bf16
    s = jax.lax.dot_general(
        a_ref[...], b_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    p = jnp.exp(s - 1.0).astype(jnp.bfloat16)
    o_ref[...] = jax.lax.dot_general(
        p, b_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


probe("nt bf16 (1,1)", k_nt, [(BQ, D), (BK, D)], (BQ, BK))
probe("nn bf16 (1,0)", k_nn, [(BQ, D), (D, BK)], (BQ, BK))
probe("tn bf16 (0,0)", k_tn, [(D, BQ), (D, BK)], (BQ, BK))
probe("nt+cast+nn chained", k_mixed, [(BQ, D), (BK, D)], (BQ, D))
