"""Serving-fleet launcher: host the router, or be one replica.

Two modes over paddle_tpu/serving/fleet (FLAGS_serving_fleet is set
here — launchers own flag setup, the library refuses without it):

Router mode (default): joins the fleet TCPStore, watches the replica
announcements (``__sfleet/replica/{r}``), and serves the client API on
its own MetricsServer —

    POST /sfleet/submit          {prompt, max_new_tokens, ...} -> {nonce}
    GET  /sfleet/status/{nonce}  request progress / tokens when finished
    GET  /debugz/router          replica + affinity + request counters
    GET  /debugz/router/replicas per-replica table

Replica mode (``--replica``): the worker process the benchmark forks
(and a multi-host launcher runs one-per-host). Builds the preset model
+ ``serving.Engine``, wraps it in ``fleet.Replica`` — which announces
the endpoint in the store, heartbeats the liveness lease, and serves
the enqueue/result/load protocol until SIGTERM (handled as a graceful
deregister) or SIGKILL (the crash the router's TTL eviction exists
for).

Usage:
  python tools/serving_router.py --store 127.0.0.1:6170 --world 2
  python tools/serving_router.py --replica --rank 0 \
      --store 127.0.0.1:6170 --preset tiny
  # storeless router over fixed endpoints (no fleet store):
  python tools/serving_router.py --endpoints http://h1:9100,http://h2:9100
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

from serving_benchmark import PRESETS  # noqa: E402


def _store_from(spec, timeout_s=10.0):
    from paddle_tpu.distributed.store import TCPStore

    host, _, port = spec.partition(":")
    return TCPStore(host or "127.0.0.1", int(port), is_master=False,
                    timeout_s=timeout_s)


def run_replica(args):
    import paddle_tpu as paddle
    from paddle_tpu import serving
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving.fleet import Replica

    paddle.seed(args.seed)
    cfg = LlamaConfig(use_parallel=False, **PRESETS[args.preset])
    model = LlamaForCausalLM(cfg)
    eng = serving.Engine(model, max_slots=args.max_slots,
                         num_blocks=args.num_blocks,
                         block_size=args.block_size)
    eng.max_queue = args.max_queue
    store = _store_from(args.store) if args.store else None
    rep = Replica(eng, args.rank, store=store, port=args.port,
                  ttl_s=args.ttl_s,
                  heartbeat_interval_s=args.heartbeat_s,
                  meta={"preset": args.preset, "pid": os.getpid()})
    stop = {"sig": None}

    def _term(signum, frame):
        stop["sig"] = signum

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    rep.start()
    # announce on stdout for the forking parent (benchmark): one JSON
    # line, then serve until a signal lands
    print(json.dumps({"rank": rep.rank, "url": rep.url,
                      "generation": rep.generation,
                      "pid": os.getpid()}), flush=True)
    while stop["sig"] is None:
        time.sleep(0.1)
    rep.stop(deregister=True)
    return 0


def run_router(args):
    from paddle_tpu.monitor.exporter import MetricsServer
    from paddle_tpu.serving.fleet import Router

    endpoints = None
    store = None
    if args.endpoints:
        endpoints = {}
        for i, spec in enumerate(
                args.endpoints.replace(",", " ").split()):
            if "=" in spec and not spec.startswith("http"):
                r, _, u = spec.partition("=")
                endpoints[int(r)] = u
            else:
                endpoints[i] = spec
    elif args.store:
        if not args.world:
            sys.exit("--store needs --world N")
        store = _store_from(args.store)
    else:
        sys.exit("need --store or --endpoints (see --help)")
    router = Router(store=store, world_size=args.world,
                    endpoints=endpoints, block_size=args.block_size,
                    ttl_s=args.ttl_s, http_timeout_s=args.http_timeout)
    srv = MetricsServer(args.port)
    router.install_routes(srv)
    srv.start()
    router.start(interval_s=args.interval)
    stop = {"sig": None}

    def _term(signum, frame):
        stop["sig"] = signum

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    print(json.dumps({"router": "http://127.0.0.1:%d" % srv.port,
                      "pid": os.getpid()}), flush=True)
    while stop["sig"] is None:
        time.sleep(0.2)
    router.close()
    srv.stop()
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="serving-fleet router / replica launcher")
    ap.add_argument("--replica", action="store_true",
                    help="run ONE engine replica instead of the router")
    ap.add_argument("--rank", type=int, default=0)
    ap.add_argument("--store", help="fleet TCPStore HOST:PORT")
    ap.add_argument("--world", type=int, default=0,
                    help="router: expected replica count")
    ap.add_argument("--endpoints",
                    help="router: fixed replica URLs (storeless mode), "
                         "comma/space list, or R=URL pairs")
    ap.add_argument("--port", type=int, default=0,
                    help="HTTP port (0 = ephemeral, printed on stdout)")
    ap.add_argument("--preset", choices=sorted(PRESETS), default="tiny")
    ap.add_argument("--max-slots", type=int, default=4)
    ap.add_argument("--num-blocks", type=int, default=128)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--max-queue", type=int, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--ttl-s", type=float, default=3.0)
    ap.add_argument("--heartbeat-s", type=float, default=0.5)
    ap.add_argument("--interval", type=float, default=0.05,
                    help="router pump interval")
    ap.add_argument("--http-timeout", type=float, default=2.0)
    args = ap.parse_args(argv)

    from paddle_tpu.core import flags as ptflags

    ptflags.set_flags({"FLAGS_serving_fleet": True})
    if args.replica:
        return run_replica(args)
    return run_router(args)


if __name__ == "__main__":
    sys.exit(main())
