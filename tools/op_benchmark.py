"""Per-op micro-benchmark runner + regression gate.

Parity: reference op-benchmark CI tooling —
/root/reference/paddle/fluid/operators/benchmark/op_tester.cc (config-driven
op timing), /root/reference/tools/ci_op_benchmark.sh +
check_op_benchmark_result.py (compare against a stored baseline, fail the
gate on regression).

TPU shape: each case times a jitted op body looped on-device via lax.scan
(amortizes dispatch; see tools/ perf notes in BASELINE.md), subtracting
measured empty-body overhead. Baselines are committed JSON; `check`
compares a fresh run and fails on >tolerance slowdowns.

Usage:
  python tools/op_benchmark.py run  [--out FILE]      # measure
  python tools/op_benchmark.py check --baseline FILE [--tolerance 0.15]
  python tools/op_benchmark.py update --baseline FILE # refresh baseline
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _cases():
    """Benchmark config (reference op_tester's config files): op name ->
    (build_args, body). Shapes sized for the v5e bench model family on
    TPU; scaled down 8x on CPU so the CI-plumbing run stays fast
    (baselines are per-platform — cross-platform numbers never compare)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.RandomState(0)
    scale = 1 if jax.default_backend() == "tpu" else 8

    def t(*shape, dtype=jnp.bfloat16):
        shape = tuple(max(s // scale, 1) if s >= 1024 else s for s in shape)
        return jnp.asarray(rng.randn(*shape), dtype)

    cases = {}

    def case(name, args, body):
        cases[name] = (args, body)

    case("matmul_8192x768x768",
         (t(8192, 768), t(768, 768)),
         lambda a, b: (a @ b, None)[0])
    case("matmul_8192x768x32000",
         (t(8192, 768), t(768, 32000)),
         lambda a, b: a @ b)
    case("softmax_8192x32000",
         (t(8192, 32000, dtype=jnp.float32),),
         lambda x: jax.nn.softmax(x, axis=-1))
    case("layer_norm_8192x768",
         (t(8192, 768, dtype=jnp.float32),),
         lambda x: (x - x.mean(-1, keepdims=True))
         / jnp.sqrt(x.var(-1, keepdims=True) + 1e-5))
    case("gelu_8192x2048",
         (t(8192, 2048),),
         jax.nn.gelu)
    case("flash_attention_8x1024x6x128", None, None)  # built below
    case("reduce_sum_8192x32000",
         (t(8192, 32000, dtype=jnp.float32),),
         lambda x: x.sum(axis=-1))
    case("transpose_8192x768",
         (t(8192, 768),),
         lambda x: x.T.copy() if hasattr(x.T, "copy") else jnp.swapaxes(
             x, 0, 1))

    from paddle_tpu.kernels.flash_attention import flash_attention

    q = t(8, 1024, 6, 128)
    cases["flash_attention_8x1024x6x128"] = (
        (q, t(8, 1024, 6, 128), t(8, 1024, 6, 128)),
        lambda q, k, v: flash_attention(q, k, v, causal=True))
    return cases


def _time_case(args, body, iters=None, reps=3):
    """ms/iteration via on-device scan loop minus empty-body overhead."""
    import jax
    import jax.numpy as jnp

    if iters is None:
        iters = 30 if jax.default_backend() == "tpu" else 5

    def loop(fn):
        # chain iterations through a scalar perturbation so XLA cannot
        # hoist the loop-invariant body out of the scan
        @jax.jit
        def run_loop(a):
            def step(c, _):
                out = fn(*[x + 0 * c if jnp.issubdtype(x.dtype, jnp.floating)
                           else x for x in a])
                first = jax.tree_util.tree_leaves(out)[0]
                return jnp.sum(first.astype(jnp.float32)) * 1e-30, None

            c, _ = jax.lax.scan(step, jnp.zeros((), jnp.float32), None,
                                length=iters)
            return c

        return run_loop

    run_loop = loop(body)
    s = run_loop(args)
    float(s)  # compile + settle
    best = 1e30
    for _ in range(reps):
        t0 = time.perf_counter()
        s = run_loop(args)
        float(s)
        best = min(best, time.perf_counter() - t0)
    return best / iters * 1000.0


def run_bench(out_path=None):
    import jax

    results = {"platform": jax.default_backend(), "ops": {}}
    cases = _cases()
    # measured empty-loop overhead to subtract
    import jax.numpy as jnp

    overhead = _time_case((jnp.zeros((8, 128)),), lambda x: x + 1.0,
                          iters=50)
    results["overhead_ms"] = round(overhead, 4)
    for name, (args, body) in sorted(cases.items()):
        ms = _time_case(args, body)
        results["ops"][name] = round(max(ms - overhead, 1e-4), 4)
        print("%-36s %8.3f ms" % (name, results["ops"][name]))
    if out_path:
        with open(out_path, "w") as f:
            json.dump(results, f, indent=1, sort_keys=True)
        print("wrote", out_path)
    return results


def check_result(current, baseline, tolerance=0.15):
    """Gate logic (reference check_op_benchmark_result.py): fail when an
    op is >tolerance slower than baseline ON THE SAME PLATFORM; report
    speedups informationally. Returns (ok, report_lines)."""
    lines = []
    ok = True
    if current.get("platform") != baseline.get("platform"):
        lines.append("SKIP: platform mismatch (%s vs baseline %s) — "
                     "baselines are per-platform"
                     % (current.get("platform"), baseline.get("platform")))
        return True, lines
    for name, base_ms in sorted(baseline.get("ops", {}).items()):
        cur_ms = current.get("ops", {}).get(name)
        if cur_ms is None:
            ok = False
            lines.append("MISSING %s (in baseline, not measured)" % name)
            continue
        ratio = cur_ms / base_ms if base_ms else float("inf")
        if ratio > 1.0 + tolerance:
            ok = False
            lines.append("REGRESSION %-36s %.3f -> %.3f ms (%.0f%%)"
                         % (name, base_ms, cur_ms, (ratio - 1) * 100))
        elif ratio < 1.0 - tolerance:
            lines.append("improved   %-36s %.3f -> %.3f ms" %
                         (name, base_ms, cur_ms))
    for name in sorted(set(current.get("ops", {})) -
                       set(baseline.get("ops", {}))):
        lines.append("new        %-36s %.3f ms"
                     % (name, current["ops"][name]))
    return ok, lines


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("cmd", choices=["run", "check", "update"])
    ap.add_argument("--out")
    ap.add_argument("--baseline",
                    default=os.path.join(os.path.dirname(__file__),
                                         "op_bench_baseline.json"))
    ap.add_argument("--tolerance", type=float, default=0.15)
    a = ap.parse_args()
    if a.cmd == "run":
        run_bench(a.out)
        return 0
    if a.cmd == "update":
        run_bench(a.baseline)
        return 0
    cur = run_bench(None)
    with open(a.baseline) as f:
        base = json.load(f)
    ok, lines = check_result(cur, base, a.tolerance)
    print("\n".join(lines) or "all ops within tolerance")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
