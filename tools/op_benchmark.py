"""Per-op micro-benchmark runner + regression gate.

Parity: reference op-benchmark CI tooling —
/root/reference/paddle/fluid/operators/benchmark/op_tester.cc (config-driven
op timing), /root/reference/tools/ci_op_benchmark.sh +
check_op_benchmark_result.py (compare against a stored baseline, fail the
gate on regression).

TPU shape: each case times a jitted op body looped on-device via lax.scan
(amortizes dispatch; see tools/ perf notes in BASELINE.md), subtracting
measured empty-body overhead. Baselines are committed JSON; `check`
compares a fresh run and fails on >tolerance slowdowns.

Usage:
  python tools/op_benchmark.py run  [--out FILE]      # measure
  python tools/op_benchmark.py check --baseline FILE [--tolerance 0.15]
  python tools/op_benchmark.py update --baseline FILE # refresh baseline

Both `check` and `update` print a COVERAGE summary (how many of the
measured cases the baseline actually guards) and list every UNGUARDED
row — a case with no baseline entry passes the gate vacuously, which is
how the committed TPU baseline quietly guarded only 8 of 44 cases.
`--strict-coverage` turns any unguarded row into a nonzero exit (the
tunnel battery's update row runs with it, so a partial refresh can
never masquerade as a full one).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _cases():
    """Benchmark config (reference op_tester's config files): op name ->
    (build_args, body). ~40 rows, one per op family feeding the
    north-star configs (llama decoder, ResNet-50, ERNIE-base, the
    optimizer/infra paths) — the breadth the reference gate guards
    (/root/reference/tools/ci_op_benchmark.sh:1). Shapes sized for the
    v5e bench models on TPU; scaled down 8x on CPU so the CI-plumbing
    run stays fast (baselines are per-platform — cross-platform numbers
    never compare; the original 8 rows keep their pre-expansion CPU
    shrink rule so the committed TPU baseline's names stay stable)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    rng = np.random.RandomState(0)
    scale = 1 if jax.default_backend() == "tpu" else 8

    def t(*shape, dtype=jnp.bfloat16):
        shape = tuple(max(s // scale, 1) if s >= 1024 else s for s in shape)
        return jnp.asarray(rng.randn(*shape), dtype)

    def s(*shape, dtype=jnp.bfloat16):
        """Aggressive CPU shrink (any dim >= 64) for the heavy new rows."""
        shape = tuple(max(d // scale, 1) if d >= 64 else d for d in shape)
        return jnp.asarray(rng.randn(*shape), dtype)

    cases = {}

    def case(name, args, body):
        cases[name] = (args, body)

    def fwd_bwd(fn, argnums=(0,)):
        def run(*args):
            return jax.value_and_grad(
                lambda *a: jnp.sum(fn(*a).astype(jnp.float32)),
                argnums=argnums)(*args)
        return run

    # -- original 8 rows (names/shapes frozen for baseline continuity) --
    case("matmul_8192x768x768",
         (t(8192, 768), t(768, 768)),
         lambda a, b: (a @ b, None)[0])
    case("matmul_8192x768x32000",
         (t(8192, 768), t(768, 32000)),
         lambda a, b: a @ b)
    case("softmax_8192x32000",
         (t(8192, 32000, dtype=jnp.float32),),
         lambda x: jax.nn.softmax(x, axis=-1))
    case("layer_norm_8192x768",
         (t(8192, 768, dtype=jnp.float32),),
         lambda x: (x - x.mean(-1, keepdims=True))
         / jnp.sqrt(x.var(-1, keepdims=True) + 1e-5))
    case("gelu_8192x2048",
         (t(8192, 2048),),
         jax.nn.gelu)
    case("flash_attention_8x1024x6x128", None, None)  # built below
    case("reduce_sum_8192x32000",
         (t(8192, 32000, dtype=jnp.float32),),
         lambda x: x.sum(axis=-1))
    case("transpose_8192x768",
         (t(8192, 768),),
         lambda x: x.T.copy() if hasattr(x.T, "copy") else jnp.swapaxes(
             x, 0, 1))

    from paddle_tpu.kernels.flash_attention import flash_attention

    q = t(8, 1024, 6, 128)
    cases["flash_attention_8x1024x6x128"] = (
        (q, t(8, 1024, 6, 128), t(8, 1024, 6, 128)),
        lambda q, k, v: flash_attention(q, k, v, causal=True))

    # -- llama-7B matmul shapes (MXU saturation at K/N >= 4096) --
    case("matmul_4096x4096x4096",
         (s(4096, 4096), s(4096, 4096)),
         lambda a, b: a @ b)
    case("matmul_mlp7b_4096x4096x11008",
         (s(4096, 4096), s(4096, 11008)),
         lambda a, b: a @ b)
    case("int8_matmul_8192x768x768",
         (jnp.asarray(rng.randint(-127, 127, (8192 // scale, 768)),
                      jnp.int8),
          jnp.asarray(rng.randint(-127, 127, (768, 768)), jnp.int8)),
         lambda a, b: lax.dot_general(
             a, b, (((1,), (0,)), ((), ())),
             preferred_element_type=jnp.int32))

    # -- ResNet-50 conv path (NCHW as the framework's conv lowers it) --
    dn = ("NCHW", "OIHW", "NCHW")
    case("conv2d_stem_7x7s2_64x3x224",
         (s(64, 3, 224, 224), s(64, 3, 7, 7)),
         lambda x, w: lax.conv_general_dilated(
             x, w, (2, 2), [(3, 3), (3, 3)], dimension_numbers=dn))
    case("conv2d_3x3_64x128x28",
         (s(64, 128, 28, 28), s(128, 128, 3, 3)),
         lambda x, w: lax.conv_general_dilated(
             x, w, (1, 1), "SAME", dimension_numbers=dn))
    case("conv2d_1x1_64x256x56_to512",
         (s(64, 256, 56, 56), s(512, 256, 1, 1)),
         lambda x, w: lax.conv_general_dilated(
             x, w, (1, 1), "VALID", dimension_numbers=dn))
    case("conv2d_fwd_bwd_3x3_64x128x28",
         (s(64, 128, 28, 28), s(128, 128, 3, 3)),
         fwd_bwd(lambda x, w: lax.conv_general_dilated(
             x, w, (1, 1), "SAME", dimension_numbers=dn),
             argnums=(0, 1)))
    case("batch_norm_train_64x128x28",
         (s(64, 128, 28, 28, dtype=jnp.float32),
          s(128, dtype=jnp.float32), s(128, dtype=jnp.float32)),
         lambda x, g, b: (x - x.mean((0, 2, 3), keepdims=True))
         / jnp.sqrt(x.var((0, 2, 3), keepdims=True) + 1e-5)
         * g.reshape(1, -1, 1, 1) + b.reshape(1, -1, 1, 1))
    case("batch_norm_fwd_bwd_64x128x28",
         (s(64, 128, 28, 28, dtype=jnp.float32),
          s(128, dtype=jnp.float32), s(128, dtype=jnp.float32)),
         fwd_bwd(lambda x, g, b: (x - x.mean((0, 2, 3), keepdims=True))
                 / jnp.sqrt(x.var((0, 2, 3), keepdims=True) + 1e-5)
                 * g.reshape(1, -1, 1, 1) + b.reshape(1, -1, 1, 1),
                 argnums=(0, 1, 2)))
    case("maxpool_3x3s2_64x64x112",
         (s(64, 64, 112, 112),),
         lambda x: lax.reduce_window(
             x, -jnp.inf, lax.max, (1, 1, 3, 3), (1, 1, 2, 2),
             [(0, 0), (0, 0), (1, 1), (1, 1)]))

    # -- norms / rotary / activations (llama + ERNIE hot paths) --
    case("layer_norm_fwd_bwd_8192x768",
         (s(8192, 768, dtype=jnp.float32),),
         fwd_bwd(lambda x: (x - x.mean(-1, keepdims=True))
                 / jnp.sqrt(x.var(-1, keepdims=True) + 1e-5)))
    case("rmsnorm_8x1024x4096",
         (s(8, 1024, 4096), s(4096)),
         lambda x, w: (x.astype(jnp.float32)
                       * jax.lax.rsqrt(jnp.mean(
                           jnp.square(x.astype(jnp.float32)), -1,
                           keepdims=True) + 1e-6)).astype(x.dtype) * w)
    case("rmsnorm_fwd_bwd_8x1024x4096",
         (s(8, 1024, 4096), s(4096)),
         fwd_bwd(lambda x, w: (x.astype(jnp.float32)
                               * jax.lax.rsqrt(jnp.mean(
                                   jnp.square(x.astype(jnp.float32)), -1,
                                   keepdims=True) + 1e-6)
                               ).astype(x.dtype) * w,
                 argnums=(0, 1)))
    case("rope_halfsplit_8x1024x6x128", None, None)  # built below
    case("gelu_fwd_bwd_8192x3072",
         (s(8192, 3072),),
         fwd_bwd(jax.nn.gelu))
    case("silu_mul_8x1024x11008",
         (s(8, 1024, 11008), s(8, 1024, 11008)),
         lambda a, b: jax.nn.silu(a) * b)

    from paddle_tpu.models.llama import rope_apply

    def _rope(q, k):
        out = rope_apply(q, k, 10000.0)
        return tuple(o._value if hasattr(o, "_value") else o for o in out)

    cases["rope_halfsplit_8x1024x6x128"] = (
        (s(8, 1024, 6, 128), s(8, 1024, 6, 128)),
        _rope)

    # -- softmax / cross-entropy (ERNIE scores + llama lm head) --
    case("softmax_scores_96x512x512",
         (s(96, 512, 512, dtype=jnp.float32),),
         lambda x: jax.nn.softmax(x, axis=-1))
    case("cross_entropy_fwd_bwd_8192x32000", None, None)  # built below

    # index bounds must shrink WITH the indexed dim on CPU, or the
    # shrunken table clamps/drops most accesses and the row times a
    # degenerate access pattern
    vocab_s = max(32000 // scale, 1) if scale > 1 else 32000
    labels = jnp.asarray(
        rng.randint(0, vocab_s, (max(8192 // scale, 1),)), jnp.int32)

    def _ce(logits):
        lg = logits.astype(jnp.float32)
        lse = jax.nn.logsumexp(lg, axis=-1)
        picked = jnp.take_along_axis(lg, labels[:, None], axis=-1)[:, 0]
        return jnp.mean(lse - picked)

    cases["cross_entropy_fwd_bwd_8192x32000"] = (
        (s(8192, 32000),),
        lambda lg: jax.value_and_grad(_ce)(lg))

    # -- embedding lookup + grad scatter --
    ids = jnp.asarray(
        rng.randint(0, vocab_s, (max(8192 // scale, 1),)), jnp.int32)
    case("embedding_lookup_8192_v32000x768",
         (s(32000, 768),),
         lambda w: jnp.take(w, ids, axis=0))
    case("embedding_grad_scatter_8192_v32000x768",
         (s(8192, 768), s(32000, 768)),
         lambda g, w: jnp.zeros_like(w).at[ids].add(g))

    # -- reduce family --
    case("reduce_max_8192x32000",
         (s(8192, 32000, dtype=jnp.float32),),
         lambda x: x.max(axis=-1))
    case("reduce_mean_axis0_8192x768",
         (s(8192, 768, dtype=jnp.float32),),
         lambda x: x.mean(axis=0))
    case("argmax_8192x32000",
         (s(8192, 32000, dtype=jnp.float32),),
         lambda x: jnp.argmax(x, axis=-1))
    case("cumsum_8192x768",
         (s(8192, 768, dtype=jnp.float32),),
         lambda x: jnp.cumsum(x, axis=-1))

    # -- elementwise / HBM-bound --
    n64m = max(64 * 1024 * 1024 // (scale * scale), 1)
    case("add_64M", (s(n64m), s(n64m)), jnp.add)
    case("mul_add_64M", (s(n64m), s(n64m), s(n64m)),
         lambda a, b, c: a * b + c)
    case("cast_bf16_fp32_64M", (s(n64m),),
         lambda x: x.astype(jnp.float32))
    case("where_64M", (s(n64m), s(n64m)),
         lambda a, b: jnp.where(a > 0, a, b))

    # -- optimizer updates (the per-step elementwise tax; BASELINE.md
    #    measured AdamW at 5.25 ms/step on the 134M config) --
    n25m = max(25 * 1000 * 1000 // (scale * scale), 1)
    p32 = s(n25m, dtype=jnp.float32)

    def adamw(p, g, m, v):
        b1, b2, eps, lr, wd = 0.9, 0.999, 1e-8, 1e-3, 0.01
        m2 = b1 * m + (1 - b1) * g
        v2 = b2 * v + (1 - b2) * g * g
        return p - lr * (m2 / (jnp.sqrt(v2) + eps) + wd * p), m2, v2

    case("adamw_update_25M",
         (p32, s(n25m, dtype=jnp.float32), s(n25m, dtype=jnp.float32),
          s(n25m, dtype=jnp.float32)),
         adamw)
    case("sgd_momentum_update_25M",
         (p32, s(n25m, dtype=jnp.float32), s(n25m, dtype=jnp.float32)),
         lambda p, g, mom: (p - 1e-3 * (0.9 * mom + g),
                            0.9 * mom + g))
    case("global_norm_clip_25M",
         (s(n25m, dtype=jnp.float32),),
         lambda g: g * (1.0 / jnp.maximum(
             1.0, jnp.sqrt(jnp.sum(g * g)) / 1.0)))

    # -- gradient compression (distributed/compress.py quantized sync:
    #    the per-step host-side tax of the ~4x wire saving; sized like
    #    the optimizer rows — one full grad pass) --
    from paddle_tpu.kernels.quant import (dequantize_int8_block,
                                          quantize_int8_block)

    qrows = max(n25m // 4096, 1)
    qx = jnp.asarray(rng.randn(qrows, 4096), jnp.float32)
    case("quantize_int8_block_25M", (qx,),
         lambda x: quantize_int8_block(x))
    qq, qs = quantize_int8_block(qx)
    case("dequantize_int8_block_25M", (qq, qs),
         lambda q, sc: dequantize_int8_block(q, sc))
    # KV-page shape (serving quant-kv, FLAGS_serving_quant_kv): per-
    # (position, head) vector scales over head_dim — the write-time
    # quantize and the fused-gather dequantize the paged-attention
    # views pay, at a serving-sized pool slab [pages*bs, Hkv, D]
    from paddle_tpu.kernels.quant import quantize_int8_page

    kvp = s(8192, 8, 128, dtype=jnp.float32)
    case("quantize_int8_page_kv8M", (kvp,),
         lambda x: quantize_int8_page(x))
    kq, ks = quantize_int8_page(kvp)
    case("dequantize_int8_page_kv8M", (kq, ks),
         lambda q, sc: dequantize_int8_block(q, sc))

    # -- manipulation family --
    case("transpose_0213_8x12x512x64",
         (s(8, 12, 512, 64),),
         lambda x: jnp.transpose(x, (0, 2, 1, 3)))
    case("concat_2x_8192x768",
         (s(8192, 768), s(8192, 768)),
         lambda a, b: jnp.concatenate([a, b], axis=-1))
    case("gather_rows_8192_from_65536x768",
         (s(65536, 768),),
         lambda w: jnp.take(w, ids, axis=0))
    case("stack_4x_2048x768",
         (s(2048, 768), s(2048, 768), s(2048, 768), s(2048, 768)),
         lambda *xs: jnp.stack(xs))

    # -- attention extra shapes --
    case("flash_attention_7b_1x2048x32x128",
         (s(1, 2048, 32, 128), s(1, 2048, 32, 128),
          s(1, 2048, 32, 128)),
         lambda q, k, v: flash_attention(q, k, v, causal=True))
    case("attention_xla_8x512x12x64",
         (s(8, 512, 12, 64), s(8, 512, 12, 64), s(8, 512, 12, 64)),
         lambda q, k, v: jax.nn.softmax(
             jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / 8.0, axis=-1
         ).astype(q.dtype) @ jnp.swapaxes(v, 1, 2))
    return cases


def _time_case(args, body, iters=None, reps=3):
    """ms/iteration via on-device scan loop minus empty-body overhead."""
    import jax
    import jax.numpy as jnp

    if iters is None:
        iters = 30 if jax.default_backend() == "tpu" else 5

    def perturb(x, c):
        # chain iterations through the scalar carry so XLA cannot hoist
        # the loop-invariant body out of the scan: additive zero for
        # floats, xor with the (zero-valued but data-dependent) carry
        # truncation for ints. The zero must be cast to x.dtype FIRST:
        # `x + 0*c` with an f32 carry silently promotes bf16 inputs to
        # f32 and the row times the wrong kernel (review-found).
        if jnp.issubdtype(x.dtype, jnp.floating):
            return x + (0 * c).astype(x.dtype)
        if jnp.issubdtype(x.dtype, jnp.integer):
            return x ^ c.astype(x.dtype)
        return x

    def loop(fn):
        @jax.jit
        def run_loop(a):
            def step(c, _):
                out = fn(*[perturb(x, c) for x in a])
                first = jax.tree_util.tree_leaves(out)[0]
                return jnp.sum(first.astype(jnp.float32)) * 1e-30, None

            c, _ = jax.lax.scan(step, jnp.zeros((), jnp.float32), None,
                                length=iters)
            return c

        return run_loop

    run_loop = loop(body)
    s = run_loop(args)
    float(s)  # compile + settle
    best = 1e30
    for _ in range(reps):
        t0 = time.perf_counter()
        s = run_loop(args)
        float(s)
        best = min(best, time.perf_counter() - t0)
    return best / iters * 1000.0


def run_bench(out_path=None):
    import jax

    results = {"platform": jax.default_backend(), "ops": {}}
    cases = _cases()
    # measured empty-loop overhead to subtract
    import jax.numpy as jnp

    overhead = _time_case((jnp.zeros((8, 128)),), lambda x: x + 1.0,
                          iters=50)
    results["overhead_ms"] = round(overhead, 4)
    for name, (args, body) in sorted(cases.items()):
        try:
            ms = _time_case(args, body)
        except Exception as e:
            # a crashed case must not kill the whole sweep — it shows
            # up as an UNGUARDED/MISSING row in the coverage report
            # instead of silently erasing every case after it
            results.setdefault("failed", {})[name] = repr(e)[:300]
            print("%-36s FAILED: %r" % (name, e))
            continue
        results["ops"][name] = round(max(ms - overhead, 1e-4), 4)
        print("%-36s %8.3f ms" % (name, results["ops"][name]))
    if out_path:
        with open(out_path, "w") as f:
            json.dump(results, f, indent=1, sort_keys=True)
        print("wrote", out_path)
    return results


def check_result(current, baseline, tolerance=0.15):
    """Gate logic (reference check_op_benchmark_result.py): fail when an
    op is >tolerance slower than baseline ON THE SAME PLATFORM; report
    speedups informationally. Returns (ok, report_lines)."""
    lines = []
    ok = True
    if current.get("platform") != baseline.get("platform"):
        lines.append("SKIP: platform mismatch (%s vs baseline %s) — "
                     "baselines are per-platform"
                     % (current.get("platform"), baseline.get("platform")))
        return True, lines
    for name, base_ms in sorted(baseline.get("ops", {}).items()):
        cur_ms = current.get("ops", {}).get(name)
        if cur_ms is None:
            ok = False
            lines.append("MISSING %s (in baseline, not measured)" % name)
            continue
        ratio = cur_ms / base_ms if base_ms else float("inf")
        if ratio > 1.0 + tolerance:
            ok = False
            lines.append("REGRESSION %-36s %.3f -> %.3f ms (%.0f%%)"
                         % (name, base_ms, cur_ms, (ratio - 1) * 100))
        elif ratio < 1.0 - tolerance:
            lines.append("improved   %-36s %.3f -> %.3f ms" %
                         (name, base_ms, cur_ms))
    for name in sorted(set(current.get("ops", {})) -
                       set(baseline.get("ops", {}))):
        lines.append("new        %-36s %.3f ms"
                     % (name, current["ops"][name]))
    return ok, lines


def coverage_report(current_names, baseline, strict=False):
    """The anti-vacuous-pass report: which measured cases the baseline
    actually guards. Platform-independent (it compares NAMES — a
    platform-mismatched check skips the timing gate but must still
    scream about rows nobody guards anywhere). Returns
    (ok, unguarded_names, report_lines); ok is False only under
    ``strict`` with a non-empty unguarded list."""
    current_names = set(current_names)
    base_names = set(baseline.get("ops", {}))
    guarded = sorted(current_names & base_names)
    unguarded = sorted(current_names - base_names)
    lines = ["COVERAGE baseline guards %d of %d measured cases"
             % (len(guarded), len(current_names))]
    for name in unguarded:
        lines.append("UNGUARDED  %-36s (no baseline entry — the gate "
                     "passes vacuously)" % name)
    if unguarded:
        lines.append("%d unguarded row(s)%s"
                     % (len(unguarded),
                        " — FAILING (--strict-coverage)" if strict
                        else "; run `update` in an on-chip window or "
                             "pass --strict-coverage to enforce"))
    ok = not (strict and unguarded)
    return ok, unguarded, lines


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("cmd", choices=["run", "check", "update"])
    ap.add_argument("--out")
    ap.add_argument("--baseline",
                    default=os.path.join(os.path.dirname(__file__),
                                         "op_bench_baseline.json"))
    ap.add_argument("--tolerance", type=float, default=0.15)
    ap.add_argument("--strict-coverage", action="store_true",
                    help="exit nonzero when any measured case has no "
                         "baseline entry (unguarded rows pass the "
                         "regression gate vacuously)")
    a = ap.parse_args(argv)
    if a.cmd == "run":
        cur = run_bench(a.out)
        # the sweep survives a crashed case (partial artifact beats
        # none), but the exit code stays loud about it
        if cur.get("failed"):
            print("%d case(s) FAILED: %s"
                  % (len(cur["failed"]), sorted(cur["failed"])))
            return 1
        return 0
    if a.cmd == "update":
        # measure FIRST, gate, then write: a mid-sweep crash (strict or
        # not — pre-resilient-sweep behavior was crash-before-write)
        # must not replace the committed baseline with a narrowed one
        # that every later non-strict check would pass vacuously
        cur = run_bench(None)
        all_names = set(cur.get("ops", {})) | set(cur.get("failed", {}))
        cov_ok, _, cov_lines = coverage_report(
            all_names, cur, strict=a.strict_coverage)
        print("\n".join(cov_lines))
        if cur.get("failed") or not cov_ok:
            print("baseline NOT written (%s): %s"
                  % ("case(s) crashed" if cur.get("failed")
                     else "coverage gate failed", a.baseline))
            return 1
        with open(a.baseline, "w") as f:
            json.dump(cur, f, indent=1, sort_keys=True)
        print("wrote", a.baseline)
        return 0
    cur = run_bench(None)
    with open(a.baseline) as f:
        base = json.load(f)
    ok, lines = check_result(cur, base, a.tolerance)
    print("\n".join(lines) or "all ops within tolerance")
    cov_ok, _, cov_lines = coverage_report(
        set(cur.get("ops", {})) | set(cur.get("failed", {})), base,
        strict=a.strict_coverage)
    print("\n".join(cov_lines))
    return 0 if (ok and cov_ok) else 1


if __name__ == "__main__":
    sys.exit(main())
