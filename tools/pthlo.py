#!/usr/bin/env python
"""pthlo CLI — compiled-graph static analysis over the repo's fixtures.

    python tools/pthlo.py                  # --check: lower every
                                           # registered fixture, run the
                                           # graph passes, verify the
                                           # collective contract
    python tools/pthlo.py --json           # JSON report on stdout
    python tools/pthlo.py --out report.json  # artifact (the battery row
                                           # commits tools/graph_report.json)
    python tools/pthlo.py --write-contract # regenerate
                                           # tools/graph_contract.json
                                           # (review the diff!)
    python tools/pthlo.py --fixtures serving_chunked,llama_train
    python tools/pthlo.py --list           # registered fixtures

Exit codes: 0 = clean (no findings, contract matches), 1 = findings or
contract drift, 2 = usage.

Passes (paddle_tpu/analysis/graph): donation/aliasing audit,
collective-schedule extraction + contract, host-transfer & f64 lint,
per-param-class sharding report. Config shares ptlint's surface:
``[tool.ptlint.graph]`` in pyproject.toml (fixtures, thresholds,
contract path).

Host-only by design: the run is forced onto 8 virtual CPU devices (the
tests/conftest.py harness) BEFORE jax loads, so the battery can run it
next to the ptlint row without touching — or waiting for — the tunnel
chip. The properties checked are lowering-structural, not timing.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_xla_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _xla_flags:
    os.environ["XLA_FLAGS"] = (
        _xla_flags + " --xla_force_host_platform_device_count=8").strip()

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from paddle_tpu.analysis import load_config  # noqa: E402
from paddle_tpu.analysis.graph import (  # noqa: E402
    GRAPH_FIXTURES, render_graph_text, run_graph)
from paddle_tpu.analysis.graph import contract as contract_mod  # noqa: E402
from paddle_tpu.analysis.graph.runner import graph_config  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="pthlo", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--root", default=_REPO,
                    help="repo root (default: the tools/ parent)")
    ap.add_argument("--check", action="store_true",
                    help="run passes + contract check (the default)")
    ap.add_argument("--write-contract", action="store_true",
                    help="regenerate the contract file from this run; "
                         "drift is superseded by the new file, but "
                         "donation/host/dtype findings still exit 1")
    ap.add_argument("--fixtures", default=None,
                    help="comma-separated subset of registered "
                         "fixtures")
    ap.add_argument("--list", action="store_true",
                    help="list registered fixtures and exit")
    ap.add_argument("--json", action="store_true",
                    help="JSON report on stdout instead of text")
    ap.add_argument("--out", default=None,
                    help="also write the JSON report to this path")
    ap.add_argument("--contract", default=None,
                    help="contract file (default from "
                         "[tool.ptlint.graph], else "
                         "tools/graph_contract.json)")
    ap.add_argument("--no-contract", action="store_true",
                    help="skip the contract comparison")
    args = ap.parse_args(argv)

    if args.list:
        for name in sorted(GRAPH_FIXTURES):
            fx = GRAPH_FIXTURES[name]
            print("%-26s devices>=%d %s%s" % (
                name, fx.needs_devices,
                "hot " if fx.hot else "", fx.doc))
        return 0
    if args.write_contract and args.no_contract:
        ap.error("--write-contract with --no-contract makes no sense")

    root = os.path.abspath(args.root)
    config = load_config(root)
    if args.contract:
        config.setdefault("graph", {})["contract"] = args.contract
    fixtures = None
    if args.fixtures:
        fixtures = [f.strip() for f in args.fixtures.split(",")
                    if f.strip()]
        unknown = [f for f in fixtures if f not in GRAPH_FIXTURES]
        if unknown:
            ap.error("unknown fixture(s) %s (have: %s)"
                     % (unknown, ",".join(sorted(GRAPH_FIXTURES))))
    if args.write_contract and fixtures:
        ap.error("--write-contract cannot be combined with "
                 "--fixtures: the contract is written whole, and a "
                 "subset run would silently drop every other "
                 "fixture's rows")

    report, findings = run_graph(
        root, config=config, fixtures=fixtures,
        check_contract=not (args.no_contract or args.write_contract))

    if args.write_contract:
        path = graph_config(config)["contract"]
        if not os.path.isabs(path):
            path = os.path.join(root, path)
        contract_mod.write(path, contract_mod.from_report(
            report["fixtures"]))
        print("pthlo: wrote contract for %d fixture(s) to %s"
              % (sum(1 for f in report["fixtures"].values()
                     if not f.get("skipped")),
                 os.path.relpath(path, root)))

    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1, sort_keys=True)
            f.write("\n")
    if args.json:
        json.dump(report, sys.stdout, indent=1, sort_keys=True)
        sys.stdout.write("\n")
    else:
        print(render_graph_text(report))
    if args.write_contract:
        # the refreshed contract supersedes drift; build/lint findings
        # — including the collectives pass's self-expectations
        # (collective-expectation) — still gate
        findings = [f for f in findings
                    if f.rule != contract_mod.RULE]
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
