"""Memory-plane snapshot artifact: the tunnel battery's mem row.

Runs the bench-family decoder for a few compiled steps with the memory
plane ON (``FLAGS_monitor_memory`` + ``FLAGS_perf_attribution`` so the
compiled transient peak feeds the headroom math) and commits the
/debugz/memory breakdown — per-component ledger, allocator
reconciliation, static-vs-transient split, headroom — as
``tools/mem_snapshot.json``.

Staleness discipline (bench.py / fleet_snapshot): when the measuring
child fails and a previous artifact exists, the previous artifact is
RE-EMITTED marked ``stale: true`` (+ ``stale_reason`` /
``stale_generations`` / ``stale_since``) and the exit code is 3 — a
photocopied memory table must confess from the artifact itself, and
the battery row goes red instead of silently committing a rotted
number.

Usage:
  python tools/mem_snapshot.py [--steps N] [--out tools/mem_snapshot.json]
  python tools/mem_snapshot.py --json          # print payload, no file
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(HERE, ".."))

DEFAULT_OUT = os.path.join(HERE, "mem_snapshot.json")


def _watchdog(seconds=540):
    def fire(signum, frame):
        sys.stderr.write("mem_snapshot watchdog: %ds, aborting\n"
                         % seconds)
        os._exit(3)

    signal.signal(signal.SIGALRM, fire)
    signal.alarm(seconds)


def measure(steps=5):
    """Bench-family decoder under the memory plane; returns the
    snapshot dict (ok=True)."""
    import numpy as np
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import mesh as pmesh
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.monitor import memory as ptmem
    from paddle_tpu.parallel.engine import CompiledTrainStep

    paddle.set_flags({"FLAGS_monitor_memory": True,
                      "FLAGS_perf_attribution": True})
    on_tpu = jax.default_backend() != "cpu"
    pmesh.build_hybrid_mesh(dp=1, devices=jax.devices()[:1])
    paddle.seed(0)
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=768,
                          intermediate_size=2048, num_hidden_layers=12,
                          num_attention_heads=6,
                          max_position_embeddings=2048,
                          use_parallel=False, dtype="bfloat16")
        batch, seq = 8, 1024
    else:
        cfg = LlamaConfig.tiny(use_parallel=False)
        batch, seq = 2, 32
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())

    def loss_fn(logits, labels):
        return F.cross_entropy(
            logits.reshape([-1, cfg.vocab_size]), labels.reshape([-1]))

    step = CompiledTrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(
        0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(rng.randint(
        0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    for _ in range(max(int(steps), 1)):
        loss = step(ids, labels)
    final = float(loss)
    assert np.isfinite(final), final
    # the compiled transient peak for the headroom split (the same
    # donation-aware number graph_report()/perf publish)
    analysis = step.perf_analysis(ids, labels)
    payload = ptmem.memory_payload()
    return {
        "kind": "mem_snapshot",
        "version": 1,
        "ok": True,
        "written_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                    time.gmtime()),
        "unix_time": time.time(),
        "pid": os.getpid(),
        "backend": jax.default_backend(),
        "config": {"batch": batch, "seq": seq,
                   "steps": max(int(steps), 1),
                   "hidden": cfg.hidden_size,
                   "layers": cfg.num_hidden_layers},
        "final_loss": final,
        "compiled_peak_bytes": analysis.get("hbm_peak_bytes"),
        "compiled_peak_is_estimate":
            bool(analysis.get("hbm_peak_is_estimate")),
        "memory": payload,
    }


def write_artifact(path, snap=None, stale_reason=None):
    """Write the artifact with the stale re-emit discipline. When the
    measurement failed (``snap is None`` / caller passes
    ``stale_reason``) and a previous artifact exists, re-emit it
    marked stale; otherwise write a not-ok stub. Returns the dict
    written."""
    if snap is None or stale_reason is not None:
        reason = stale_reason or "measurement failed"
        last = None
        if os.path.exists(path):
            try:
                with open(path) as f:
                    last = json.load(f)
            except (OSError, ValueError):
                last = None
        if last and last.get("kind") == "mem_snapshot":
            last["stale"] = True
            last["stale_reason"] = reason
            last["stale_generations"] = \
                int(last.get("stale_generations", 0)) + 1
            last.setdefault("stale_since", last.get("written_at"))
            snap = last
        else:
            snap = {"kind": "mem_snapshot", "version": 1, "ok": False,
                    "error": reason,
                    "written_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                time.gmtime())}
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(snap, f, indent=1, default=str)
        f.write("\n")
    os.replace(tmp, path)
    return snap


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--out", default=DEFAULT_OUT,
                    help="artifact path (stale re-emit on failure)")
    ap.add_argument("--json", action="store_true",
                    help="print the snapshot JSON to stdout")
    a = ap.parse_args(argv)
    _watchdog()

    try:
        snap = measure(a.steps)
    except Exception as e:
        sys.stderr.write("mem_snapshot: measurement failed: %r\n" % (e,))
        snap = write_artifact(a.out, None, stale_reason=repr(e))
        if a.json:
            print(json.dumps(snap, default=str))
        return 3
    write_artifact(a.out, snap)
    if a.json:
        print(json.dumps(snap, default=str))
    else:
        mem = snap["memory"]
        rec = mem.get("reconciliation") or {}
        print("mem_snapshot: wrote %s (backend=%s, ledger=%s bytes, "
              "witness=%s via %s)"
              % (a.out, snap["backend"], rec.get("ledger_bytes"),
                 rec.get("live_bytes"), rec.get("source")))
        for job, row in sorted((mem.get("jobs") or {}).items()):
            print("  job=%-8s ledger=%s  transient_peak=%s  headroom=%s"
                  % (job, row.get("ledger_bytes"),
                     row.get("transient_peak_bytes"),
                     row.get("headroom_bytes")))
    return 0


if __name__ == "__main__":
    sys.exit(main())
