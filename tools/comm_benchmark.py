"""Eager-collective wire benchmark: fp32 vs block-scaled int8.

The measurement companion of ``paddle_tpu.distributed.compress``: forks
a small multi-process world (rendezvous over the native TCP store, the
same transport multi-host eager sync rides), sweeps payload sizes, and
times ``all_reduce`` with the uncompressed fp32 wire format against the
quantized int8+scales format — reporting seconds/op, actual wire bytes
per op (from the ``comm_bytes_total`` registry counters, the same
series the acceptance gate asserts on), compression ratio, and max
relative error of the compressed reduction. One JSON row per (size,
format), ``serving_benchmark``-style.

Backend note: the store transport is host-side TCP — numbers are
transport numbers and mean the same thing on CPU or through the
tunnel; the battery's comms row records them per round.

Usage:
  python tools/comm_benchmark.py                      # CPU smoke sweep
  python tools/comm_benchmark.py --sizes 65536 1048576 --iters 5 \
      --out tools/comm_bench.json
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def worker_main(args):
    import numpy as np

    import paddle_tpu.distributed as dist
    from paddle_tpu import monitor
    from paddle_tpu.distributed import compress

    dist.init_parallel_env()
    pg = dist.collective._get_default_group().pg
    rank, world = pg.rank, pg.world_size
    rng = np.random.RandomState(1234 + rank)
    rows = []
    for numel in args.sizes:
        # wide dynamic range (what block scaling exists for), f32 wire
        payload = (rng.randn(numel)
                   * np.exp(rng.randn(numel) * 2)).astype(np.float32)
        ref = None
        for compressed in (False, True):
            label = "true" if compressed else "false"
            child = compress.COMM_BYTES.labels(path="eager",
                                               compressed=label)
            pg.barrier("comm_bench/%d/%s" % (numel, label))
            # one untimed warmup settles store-key allocation paths
            pg.allreduce(payload, "sum", compressed=compressed)
            b0 = child.value
            t0 = time.perf_counter()
            for _ in range(args.iters):
                out = pg.allreduce(payload, "sum",
                                   compressed=compressed)
            dt = (time.perf_counter() - t0) / args.iters
            wire = (child.value - b0) / args.iters
            compress.GRAD_SYNC_SECONDS.labels(path="eager").observe(dt)
            if not compressed:
                ref = out
                err = 0.0
            else:
                scale = float(np.abs(ref).max()) or 1.0
                err = float(np.abs(out - ref).max()) / scale
            rows.append({
                "payload_numel": numel,
                "payload_bytes": numel * 4,
                "world_size": world,
                "compressed": compressed,
                "seconds_per_op": round(dt, 6),
                "wire_bytes_per_op": int(wire),
                "max_rel_error": round(err, 6),
            })
    # fold in per-size ratios on the compressed rows
    by_size = {}
    for r in rows:
        by_size.setdefault(r["payload_numel"], {})[r["compressed"]] = r
    for numel, pair in by_size.items():
        if True in pair and False in pair and \
                pair[True]["wire_bytes_per_op"]:
            pair[True]["compression_ratio"] = round(
                pair[False]["wire_bytes_per_op"]
                / pair[True]["wire_bytes_per_op"], 3)
            if pair[True]["seconds_per_op"]:
                pair[True]["speedup"] = round(
                    pair[False]["seconds_per_op"]
                    / pair[True]["seconds_per_op"], 3)
    if rank == 0:
        print("COMM_RESULT " + json.dumps(rows))
    sys.stdout.flush()


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nranks", type=int, default=2)
    ap.add_argument("--sizes", type=int, nargs="+",
                    default=[1 << 14, 1 << 16, 1 << 18])
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--out")
    ap.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.worker:
        return worker_main(args)

    port = _free_port()
    procs = []
    for rank in range(args.nranks):
        env = dict(os.environ)
        env.update({
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
            "JAX_PLATFORMS": "cpu",
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(args.nranks),
            "PADDLE_MASTER": "127.0.0.1:%d" % port,
        })
        env.pop("PALLAS_AXON_POOL_IPS", None)
        cmd = [sys.executable, os.path.abspath(__file__), "--worker",
               "--nranks", str(args.nranks),
               "--iters", str(args.iters),
               "--sizes"] + [str(s) for s in args.sizes]
        procs.append(subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    rows = None
    for rank, p in enumerate(procs):
        out, err = p.communicate(timeout=600)
        if p.returncode != 0:
            sys.stderr.write(
                "comm_benchmark rank %d failed (rc=%d):\n%s\n%s\n"
                % (rank, p.returncode, out[-2000:], err[-3000:]))
            return 1
        for line in out.splitlines():
            if line.startswith("COMM_RESULT "):
                rows = json.loads(line[len("COMM_RESULT "):])
    if rows is None:
        sys.stderr.write("comm_benchmark: no result row from rank 0\n")
        return 1
    result = {
        "benchmark": "eager_allreduce_wire",
        "nranks": args.nranks,
        "iters": args.iters,
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                     time.gmtime()),
        "rows": rows,
    }
    for r in rows:
        print(json.dumps(r))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1, sort_keys=True)
        print("wrote", args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
