#!/usr/bin/env python
"""ptcheck CLI — deterministic interleaving explorer for the protocol
plane (store barrier / leader election / elastic membership / watchdog
bundles).

    python tools/ptcheck.py                  # --check: DFS-explore every
                                             # registered fixture
    python tools/ptcheck.py --json           # JSON report on stdout
    python tools/ptcheck.py --out tools/ptcheck_report.json
    python tools/ptcheck.py --fixtures barrier,election
    python tools/ptcheck.py --list           # registered fixtures
    python tools/ptcheck.py --mode walk --seed 7 --walks 200
    python tools/ptcheck.py --replay "barrier_legacy:s:r0,s:r1,..."

Exit codes: 0 = clean (live fixtures produced zero findings AND every
expected-finding regression fixture FOUND its historical bug), 1 =
findings (or a regression fixture that came back clean — the checker
lost power), 2 = usage.

Every finding prints a replayable schedule token string: ``--replay
"<fixture>:<tok,tok,...>"`` re-executes that exact interleaving.
Random-walk findings additionally carry the seed that derived them.
Config lives in ``[tool.ptlint.proto]`` in pyproject.toml
(max_schedules / walks / wall_s caps for CI).

Host-only: the sim store is in-process shared state — no sockets, no
accelerator, no real time (blocking waits ride a virtual clock).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from paddle_tpu.analysis import load_config  # noqa: E402
from paddle_tpu.analysis.proto import (  # noqa: E402
    PROTO_FIXTURES, render_proto_json, render_proto_text,
    replay_schedule, run_fixtures)
from paddle_tpu.analysis.proto.sched import ReplayDivergence  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="ptcheck", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--root", default=_REPO,
                    help="repo root (default: the tools/ parent)")
    ap.add_argument("--check", action="store_true",
                    help="explore + judge every fixture (the default)")
    ap.add_argument("--fixtures", default=None,
                    help="comma-separated subset of registered "
                         "fixtures")
    ap.add_argument("--list", action="store_true",
                    help="list registered fixtures and exit")
    ap.add_argument("--mode", choices=("dfs", "walk"), default="dfs",
                    help="dfs = bounded exhaustive exploration with "
                         "state dedup; walk = seeded random walks "
                         "(deeper schedules)")
    ap.add_argument("--seed", type=int, default=0,
                    help="random-walk seed (walk mode; findings "
                         "replay from it)")
    ap.add_argument("--walks", type=int, default=None,
                    help="random walks per fixture (walk mode)")
    ap.add_argument("--max-schedules", type=int, default=None,
                    help="DFS schedule budget override per fixture")
    ap.add_argument("--wall-s", type=float, default=None,
                    help="per-fixture wall budget override (seconds)")
    ap.add_argument("--replay", default=None, metavar="FIX:SCHEDULE",
                    help="re-run one schedule token string exactly "
                         "and judge it")
    ap.add_argument("--json", action="store_true",
                    help="JSON report on stdout instead of text")
    ap.add_argument("--out", default=None,
                    help="also write the JSON report to this path")
    args = ap.parse_args(argv)

    if args.list:
        for name in sorted(PROTO_FIXTURES):
            fixture = PROTO_FIXTURES[name]
            mark = "expect-finding " if fixture.expect_finding else ""
            print("%-16s %s%s" % (name, mark, fixture.doc))
        return 0

    if args.replay:
        name, _, schedule = args.replay.partition(":")
        if name not in PROTO_FIXTURES:
            ap.error("unknown fixture %r (have: %s)"
                     % (name, ",".join(sorted(PROTO_FIXTURES))))
        try:
            result, findings = replay_schedule(PROTO_FIXTURES[name],
                                               schedule)
        except ReplayDivergence as e:
            print("ptcheck: replay diverged: %s" % e)
            return 2
        payload = {
            "kind": "ptcheck_replay", "fixture": name,
            "schedule": result.schedule_str,
            "tasks": {t: {"status": row["status"],
                          "error": repr(row["error"])
                          if row["error"] else None}
                      for t, row in sorted(result.tasks.items())},
            "events": result.events,
            "log": [repr(ev) for ev in result.log],
            "findings": [f.to_dict() for f in findings],
        }
        if args.json or args.out:
            if args.out:
                with open(args.out, "w", encoding="utf-8") as f:
                    json.dump(payload, f, indent=1, default=str)
                    f.write("\n")
            if args.json:
                json.dump(payload, sys.stdout, indent=1, default=str)
                sys.stdout.write("\n")
        else:
            print("replayed %s (%d transitions)"
                  % (name, len(result.schedule)))
            for t, row in sorted(result.tasks.items()):
                print("  task %-10s %s%s"
                      % (t, row["status"],
                         " error=%r" % row["error"]
                         if row["error"] else ""))
            for kind, detail in result.events:
                print("  event %-9s %s" % (kind, json.dumps(
                    detail, sort_keys=True, default=str)))
            for f in findings:
                print("  FINDING %s: %s" % (f.prop, f.message))
        return 1 if findings else 0

    fixtures = None
    if args.fixtures:
        fixtures = [f.strip() for f in args.fixtures.split(",")
                    if f.strip()]
        unknown = [f for f in fixtures if f not in PROTO_FIXTURES]
        if unknown:
            ap.error("unknown fixture(s) %s (have: %s)"
                     % (unknown, ",".join(sorted(PROTO_FIXTURES))))

    config = dict(load_config(os.path.abspath(args.root))
                  .get("proto", {}))
    if args.max_schedules is not None:
        config["max_schedules"] = args.max_schedules
    if args.walks is not None:
        config["walks"] = args.walks
    if args.wall_s is not None:
        config["wall_s"] = args.wall_s

    report, findings = run_fixtures(
        PROTO_FIXTURES, names=fixtures, mode=args.mode,
        seed=args.seed, config=config)
    report = render_proto_json(report, meta={
        "root": os.path.abspath(args.root),
        "fixtures": fixtures or sorted(PROTO_FIXTURES),
        "config": config})
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1, sort_keys=True,
                      default=str)
            f.write("\n")
    if args.json:
        json.dump(report, sys.stdout, indent=1, sort_keys=True,
                  default=str)
        sys.stdout.write("\n")
    else:
        print(render_proto_text(report))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
