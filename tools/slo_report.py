"""SLO/incident snapshot artifact: the tunnel battery's slo row.

Runs the bench-family decoder for a few compiled steps with the SLO
plane ON (``FLAGS_monitor_slo`` — the timeseries ring, the objective
judge and the incident table) and commits the /debugz/slo verdicts +
/debugz/incidents table as ``tools/slo_snapshot.json``: per-objective
attainment, error-budget remaining, burn rates per alerting window,
open/resolved incidents. A compliant bench run judges clean (no
burn-rate alert, empty incident table) — the artifact proves the
judge ran, not that something burned.

Alternative sources:
  --endpoint URL   scrape a LIVE process's /debugz/slo +
                   /debugz/incidents instead of measuring (operator
                   mode, the fleet_snapshot shape)
  --once           emit the current in-process payload without
                   driving any workload (smoke mode)

Staleness discipline (bench.py / mem_snapshot): when the measurement
fails and a previous artifact exists, the previous artifact is
RE-EMITTED marked ``stale: true`` (+ ``stale_reason`` /
``stale_generations`` / ``stale_since``) and the exit code is 3 — a
photocopied verdict must confess from the artifact itself, and the
battery row goes red instead of silently committing a rotted number.

Usage:
  python tools/slo_report.py [--steps N] [--out tools/slo_snapshot.json]
  python tools/slo_report.py --json            # print payload, no file
  python tools/slo_report.py --endpoint http://127.0.0.1:8123
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time
import urllib.request

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(HERE, ".."))

DEFAULT_OUT = os.path.join(HERE, "slo_snapshot.json")


def _watchdog(seconds=540):
    def fire(signum, frame):
        sys.stderr.write("slo_report watchdog: %ds, aborting\n"
                         % seconds)
        os._exit(3)

    signal.signal(signal.SIGALRM, fire)
    signal.alarm(seconds)


def _base(source):
    return {
        "kind": "slo_snapshot",
        "version": 1,
        "ok": True,
        "source": source,
        "written_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                    time.gmtime()),
        "unix_time": time.time(),
        "pid": os.getpid(),
    }


def scrape(endpoint, timeout_s=5.0):
    """Operator mode: pull the verdicts from a live process."""
    out = _base("endpoint:%s" % endpoint)
    for route, key in (("debugz/slo", "slo"),
                       ("debugz/incidents", "incidents")):
        with urllib.request.urlopen(
                "%s/%s" % (endpoint.rstrip("/"), route),
                timeout=timeout_s) as r:
            out[key] = json.loads(r.read().decode())
    return out


def snapshot_local(source="once"):
    """The current in-process judge + table state."""
    from paddle_tpu.monitor import incidents as ptincidents
    from paddle_tpu.monitor import slo as ptslo

    out = _base(source)
    out["slo"] = ptslo.payload()
    out["incidents"] = ptincidents.payload()
    return out


def measure(steps=5):
    """Bench-family decoder under the SLO plane; returns the snapshot
    dict (ok=True)."""
    import numpy as np
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import mesh as pmesh
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.monitor import slo as ptslo
    from paddle_tpu.parallel.engine import CompiledTrainStep

    paddle.set_flags({"FLAGS_monitor_slo": True})
    ptslo.enable()      # latch windows/objectives before the workload
    on_tpu = jax.default_backend() != "cpu"
    pmesh.build_hybrid_mesh(dp=1, devices=jax.devices()[:1])
    paddle.seed(0)
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=768,
                          intermediate_size=2048, num_hidden_layers=12,
                          num_attention_heads=6,
                          max_position_embeddings=2048,
                          use_parallel=False, dtype="bfloat16")
        batch, seq = 8, 1024
    else:
        cfg = LlamaConfig.tiny(use_parallel=False)
        batch, seq = 2, 32
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())

    def loss_fn(logits, labels):
        return F.cross_entropy(
            logits.reshape([-1, cfg.vocab_size]), labels.reshape([-1]))

    step = CompiledTrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(
        0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(rng.randint(
        0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    for _ in range(max(int(steps), 1)):
        loss = step(ids, labels)
    final = float(loss)
    assert np.isfinite(final), final
    snap = snapshot_local("measure")
    snap["backend"] = jax.default_backend()
    snap["config"] = {"batch": batch, "seq": seq,
                      "steps": max(int(steps), 1),
                      "hidden": cfg.hidden_size,
                      "layers": cfg.num_hidden_layers}
    snap["final_loss"] = final
    return snap


def write_artifact(path, snap=None, stale_reason=None):
    """Write the artifact with the stale re-emit discipline. When the
    measurement failed (``snap is None`` / caller passes
    ``stale_reason``) and a previous artifact exists, re-emit it
    marked stale; otherwise write a not-ok stub. Returns the dict
    written."""
    if snap is None or stale_reason is not None:
        reason = stale_reason or "measurement failed"
        last = None
        if os.path.exists(path):
            try:
                with open(path) as f:
                    last = json.load(f)
            except (OSError, ValueError):
                last = None
        if last and last.get("kind") == "slo_snapshot":
            last["stale"] = True
            last["stale_reason"] = reason
            last["stale_generations"] = \
                int(last.get("stale_generations", 0)) + 1
            last.setdefault("stale_since", last.get("written_at"))
            snap = last
        else:
            snap = {"kind": "slo_snapshot", "version": 1, "ok": False,
                    "error": reason,
                    "written_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                time.gmtime())}
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(snap, f, indent=1, default=str)
        f.write("\n")
    os.replace(tmp, path)
    return snap


def _print_summary(snap, out_path):
    slo = snap.get("slo") or {}
    inc = snap.get("incidents") or {}
    print("slo_report: wrote %s (source=%s, objectives=%d, "
          "open_incidents=%d)"
          % (out_path, snap.get("source"),
             len(slo.get("objectives") or ()),
             len(inc.get("open") or ())))
    for o in slo.get("objectives") or ():
        att = o.get("attainment")
        bud = o.get("budget_remaining_ratio")
        alerting = [g for g, v in (o.get("alerting") or {}).items()
                    if v]
        print("  %-22s att=%-8s budget=%-8s samples=%-6s %s"
              % (o.get("objective"),
                 "%.4f" % att if isinstance(att, (int, float))
                 else "-",
                 "%.3f" % bud if isinstance(bud, (int, float))
                 else "-",
                 o.get("samples"),
                 "ALERTING:%s" % ",".join(alerting) if alerting
                 else ""))
    for i in inc.get("open") or ():
        print("  OPEN %s [%s] %s" % (i.get("key"), i.get("severity"),
                                     i.get("summary")))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--once", action="store_true",
                    help="emit the current in-process payload without "
                    "driving a workload")
    ap.add_argument("--endpoint",
                    help="scrape a live process's /debugz/slo + "
                    "/debugz/incidents instead of measuring")
    ap.add_argument("--out", default=DEFAULT_OUT,
                    help="artifact path (stale re-emit on failure)")
    ap.add_argument("--json", action="store_true",
                    help="print the snapshot JSON to stdout")
    a = ap.parse_args(argv)
    _watchdog()

    try:
        if a.endpoint:
            snap = scrape(a.endpoint)
        elif a.once:
            snap = snapshot_local()
        else:
            snap = measure(a.steps)
    except Exception as e:
        sys.stderr.write("slo_report: measurement failed: %r\n" % (e,))
        snap = write_artifact(a.out, None, stale_reason=repr(e))
        if a.json:
            print(json.dumps(snap, default=str))
        return 3
    write_artifact(a.out, snap)
    if a.json:
        print(json.dumps(snap, default=str))
    else:
        _print_summary(snap, a.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
