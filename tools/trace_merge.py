"""Merge per-rank chrome traces into ONE clock-aligned timeline.

Front-end for paddle_tpu.monitor.trace_merge: collect the per-rank
trace files a multi-process run produced (profiler.export_chrome_tracing
per rank, usually named ``*rank{r}*.json`` or ``worker_{r}.json``),
apply the per-rank clock offsets estimated at run time
(``clock_rank{r}.json``, written by
monitor.trace_merge.estimate_clock_offset + write_clock_file), and emit
a single merged trace with rank-prefixed pids — open it in
Perfetto/chrome://tracing to read cross-rank comm/compute overlap.

``--requests`` additionally merges span-journal artifacts
(``monitor.trace.write_journal``: per-request serving timelines and
per-step train spans) into the same view — each journal's wall-clock
spans are shifted by its own wall<->monotonic anchor onto the native
tracer's timebase, so one Perfetto file shows a request's journey
across engine steps.

``--capture`` accepts a collector ``fleet_capture_<ts>/`` directory
(monitor/fleet.py anomaly-triggered fleet capture): every rank's
journal tail merges with rank-prefixed pids, wall clocks aligned on
the collector's clock via the manifest's per-rank offsets — one
command renders the merged fleet chrome-trace from a capture.

``--fleet-router`` + ``--fleet-replica`` stitch a serving-fleet run:
the router's journal and each replica's (``RANK=path``) merge into one
timeline with ``router/`` / ``replica{r}/`` pids, replica wall clocks
aligned by ``RANK=offset`` pairs from ``--fleet-offset`` (the
collector-style NTP estimates; seconds, replica minus router), and
chrome flow arrows connecting each dispatch span to the replica
request span that adopted its traceparent — reroute causality in one
Perfetto view.

Usage:
  python tools/trace_merge.py --dir traces/ --out merged.json
  python tools/trace_merge.py --out merged.json r0.json r1.json ...
      (rank inferred from the last integer in each filename)
  python tools/trace_merge.py --out m.json 0=a.json 1=b.json.gz
  python tools/trace_merge.py --out m.json --requests journal.json \
      [--requests-clock wall] [rank traces...]
  python tools/trace_merge.py --out m.json --capture fleet_capture_<ts>/
  python tools/trace_merge.py --out fleet.json \
      --fleet-router router_journal.json \
      --fleet-replica 0=replica0.json --fleet-replica 1=replica1.json \
      [--fleet-offset 1=0.0031]
"""
from __future__ import annotations

import argparse
import glob
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

from paddle_tpu.monitor import trace_merge as tm  # noqa: E402


def collect_inputs(args):
    paths_by_rank = {}
    offsets = {}
    skipped = []
    if args.dir:
        pats = ("*.trace.json", "*.json", "*.json.gz")
        seen = set()
        for pat in pats:
            for path in sorted(glob.glob(os.path.join(args.dir, pat))):
                base = os.path.basename(path)
                if base.startswith("clock_rank") or path in seen \
                        or os.path.abspath(path) == \
                        os.path.abspath(args.out):
                    continue
                seen.add(path)
                rank = tm.rank_of_path(path)
                if rank is None:
                    skipped.append((path, "no rank in filename"))
                    continue
                if rank in paths_by_rank:
                    skipped.append((path, "rank %d already provided by "
                                    "%s" % (rank, paths_by_rank[rank])))
                    continue
                paths_by_rank[rank] = path
        offsets = tm.load_clock_offsets(args.dir)
    # a silently dropped file means the merged timeline is missing a
    # whole rank — always say what was excluded and why
    for path, why in skipped:
        print("trace_merge: SKIPPING %s (%s) — pass RANK=path "
              "explicitly to include it" % (path, why),
              file=sys.stderr)
    for spec in args.traces:
        if "=" in spec:
            r, _, path = spec.partition("=")
            rank = int(r)
        else:
            path = spec
            rank = tm.rank_of_path(spec)
            if rank is None:
                rank = len(paths_by_rank)
        paths_by_rank[rank] = path
        d = os.path.dirname(os.path.abspath(path))
        for rk, off in tm.load_clock_offsets(d).items():
            offsets.setdefault(rk, off)
    return paths_by_rank, offsets


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="merge per-rank chrome traces into one aligned "
                    "timeline")
    ap.add_argument("traces", nargs="*",
                    help="trace files, optionally RANK=path")
    ap.add_argument("--dir", help="directory holding per-rank traces "
                                  "(+ clock_rank*.json offsets)")
    ap.add_argument("--out", required=True, help="merged trace path")
    ap.add_argument("--no-offsets", action="store_true",
                    help="skip clock alignment (raw per-rank clocks)")
    ap.add_argument("--requests", action="append", default=[],
                    metavar="JOURNAL",
                    help="span-journal JSON (monitor.trace."
                         "write_journal) whose request/step spans "
                         "merge into the timeline; repeatable")
    ap.add_argument("--requests-clock", choices=("monotonic", "wall"),
                    default="monotonic",
                    help="timebase for journal spans: 'monotonic' "
                         "(default; aligns with same-process native "
                         "traces via the journal's clock anchor) or "
                         "'wall' (journal-only merges)")
    ap.add_argument("--capture", action="append", default=[],
                    metavar="DIR",
                    help="fleet_capture_<ts>/ directory (monitor/"
                         "fleet.py collector capture) whose per-rank "
                         "journal tails merge rank-prefixed and "
                         "clock-aligned; repeatable")
    ap.add_argument("--fleet-router", metavar="JOURNAL",
                    help="serving-fleet ROUTER journal: merge with "
                         "--fleet-replica journals into router/ + "
                         "replica{r}/ tracks with traceparent flow "
                         "arrows")
    ap.add_argument("--fleet-replica", action="append", default=[],
                    metavar="RANK=JOURNAL",
                    help="one replica's journal (requires "
                         "--fleet-router); repeatable")
    ap.add_argument("--fleet-offset", action="append", default=[],
                    metavar="RANK=SECONDS",
                    help="replica wall-clock offset vs the router "
                         "(NTP-style estimate); repeatable")
    args = ap.parse_args(argv)

    paths_by_rank, offsets = collect_inputs(args)
    extra = []
    for jp in args.requests:
        journal = tm.load_journal(jp)
        evs = tm.journal_events(journal, clock=args.requests_clock)
        print("requests: %s -> %d span/event(s) from %d trace(s)"
              % (jp, len(evs), len(journal.get("traces") or ())))
        extra.extend(evs)
    for cap in args.capture:
        manifest, evs = tm.capture_events(cap)
        print("capture: %s (%s) -> %d span/event(s) from rank(s) %s"
              % (cap, manifest.get("reason"), len(evs),
                 manifest.get("ranks")))
        extra.extend(evs)
    if args.fleet_replica and not args.fleet_router:
        ap.error("--fleet-replica requires --fleet-router")
    if args.fleet_router:
        replicas = {}
        for spec in args.fleet_replica:
            r, _, path = spec.partition("=")
            replicas[int(r)] = tm.load_journal(path)
        fleet_offsets = {}
        for spec in args.fleet_offset:
            r, _, off = spec.partition("=")
            fleet_offsets[int(r)] = float(off)
        evs = tm.merge_fleet_journals(
            tm.load_journal(args.fleet_router), replicas,
            offsets=fleet_offsets)
        print("fleet: router %s + %d replica journal(s) -> %d "
              "span/event(s)" % (args.fleet_router, len(replicas),
                                 len(evs)))
        extra.extend(evs)
    if not paths_by_rank and not extra:
        ap.error("no input traces found")
    if args.no_offsets:
        offsets = {}
    n = tm.merge_trace_files(paths_by_rank, args.out, offsets,
                             extra_events=extra)
    print("merged %d events (%d from %d rank(s), %d from journals) "
          "-> %s" % (n, n - len(extra), len(paths_by_rank),
                     len(extra), args.out))
    for r in sorted(paths_by_rank):
        print("  rank %d: %s (offset %+.0f us)"
              % (r, paths_by_rank[r], offsets.get(r, 0.0) * 1e6))
    return 0


if __name__ == "__main__":
    sys.exit(main())
