"""North-star model benchmarks (BASELINE.md table rows).

Parity: reference model-benchmark CI
(/root/reference/tools/ci_model_benchmark.sh runs end-to-end model
throughput jobs and records numbers). Here each subcommand measures one
BASELINE.md north-star row on whatever backend jax resolves (the real
chip via the axon tunnel, or CPU for plumbing checks — CPU numbers are
never recorded as baselines):

  resnet50   ResNet-50 train step            -> images/sec/chip
  ernie_dp   ERNIE-3.0-base-geometry DP step -> tokens/sec/chip
  widedeep   wide&deep through the PS path   -> examples/sec
  allreduce  ICI all-reduce bus bandwidth    -> GB/s  (needs >1 device)
  all        every row available on this host

Prints one JSON line per metric. Timing follows the tunnel-safe recipe
(BASELINE.md / bench.py): sync via scalar host readback, never
block_until_ready.

Usage: python tools/model_benchmark.py <sub> [--iters N] [--out FILE]
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _watchdog(seconds=1200):
    def fire(signum, frame):
        sys.stderr.write("model_benchmark watchdog: %ds, aborting\n"
                         % seconds)
        os._exit(3)

    signal.signal(signal.SIGALRM, fire)
    signal.alarm(seconds)


def _perf_fields(step, batch_args, units_per_step, units_per_s):
    """Hardware-normalized row fields (monitor/perf.py): mfu +
    hbm_peak_bytes from the compiled executable's cost/memory analysis
    over the measured rate. ``units`` are whatever the row counts
    (tokens, images, examples) — mfu only needs rate / per-step. Never
    fails the row."""
    try:
        from paddle_tpu.monitor import perf as _perf

        return _perf.bench_fields(
            step.perf_analysis(*batch_args),
            tokens_per_s=units_per_s, tokens_per_step=units_per_step)
    except Exception as e:
        return {"perf_fields_error": repr(e)[:200]}


def _emit(results, metric, value, unit, extra=None):
    import jax

    rec = {"metric": metric, "value": round(value, 1), "unit": unit,
           "backend": jax.default_backend(),
           "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime())}
    if extra:
        rec.update(extra)
    print(json.dumps(rec), flush=True)
    results.append(rec)


def bench_resnet50(results, iters=None):
    """ResNet-50 images/sec/chip: whole-graph train step (the static ->
    XLA config; reference measures the same model on GPU CI)."""
    import numpy as np
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.parallel.engine import CompiledTrainStep
    from paddle_tpu.vision.models import resnet50

    from paddle_tpu.distributed import mesh as pmesh

    on_tpu = jax.default_backend() != "cpu"
    batch = 64 if on_tpu else 4
    size = 224 if on_tpu else 32
    iters = iters or (20 if on_tpu else 2)
    # per-chip number: pin a 1-device mesh regardless of host topology
    pmesh.build_hybrid_mesh(dp=1, devices=jax.devices()[:1])

    def measure(layout):
        paddle.seed(0)
        model = resnet50(num_classes=1000, data_format=layout)
        if on_tpu:
            model.to(dtype="bfloat16")
        opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                        parameters=model.parameters())

        def loss_fn(logits, labels):
            return F.cross_entropy(logits, labels)

        step = CompiledTrainStep(model, loss_fn, opt)
        rng = np.random.RandomState(0)
        shape = ((batch, 3, size, size) if layout == "NCHW"
                 else (batch, size, size, 3))
        x = paddle.to_tensor(rng.rand(*shape).astype(np.float32) * 2 - 1)
        if on_tpu:
            # weights were cast to bf16 above; conv needs matching dtypes
            x = x.astype("bfloat16")
        y = paddle.to_tensor(rng.randint(0, 1000, (batch,)).astype(
            np.int32))
        for _ in range(2):
            loss = step(x, y)
        float(loss)
        t0 = time.perf_counter()
        for _ in range(iters):
            loss = step(x, y)
        final = float(loss)
        dt = time.perf_counter() - t0
        assert np.isfinite(final)
        ips = batch * iters / dt
        return ips, _perf_fields(step, (x, y), batch, ips)

    # NHWC is the TPU-native conv layout (channels ride the 128-lane
    # dim); NCHW is measured alongside so the layout win stays an
    # honest, attributed number instead of a silent methodology change
    measured = {fmt: measure(fmt) for fmt in ("NHWC", "NCHW")}
    per_layout = {fmt: v[0] for fmt, v in measured.items()}
    best = max(per_layout, key=per_layout.get)
    _emit(results, "resnet50_train_images_per_sec_per_chip",
          per_layout[best], "images/s",
          dict({"batch": batch, "image_size": size, "layout": best,
                "per_layout_images_per_sec":
                    {k: round(v, 1) for k, v in per_layout.items()}},
               **measured[best][1]))


def bench_ernie_dp(results, iters=None):
    """ERNIE-3.0-base geometry, data-parallel train step, tokens/sec/chip
    (BASELINE.md 'ERNIE-3.0-base (Fleet DP)'). On one chip the dp axis is
    degree 1 — the number is per-chip throughput through the same
    compiled-DP code path."""
    import numpy as np
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F  # noqa: F401
    from paddle_tpu.distributed import mesh as pmesh
    from paddle_tpu.models.ernie import ErnieConfig, ErnieForPretraining
    from paddle_tpu.parallel.engine import CompiledTrainStep

    on_tpu = jax.default_backend() != "cpu"
    if on_tpu:
        # fuse_qkv: one [768, 2304] projection — the measured MXU
        # narrow-matmul lever from the llama work (BASELINE.md)
        cfg = ErnieConfig.base(fuse_qkv=True)
        batch, seq = 16, 512
    else:
        cfg = ErnieConfig.tiny()
        batch, seq = 2, 64
    iters = iters or (20 if on_tpu else 2)
    # per-chip DP path: dp degree 1 on a 1-device mesh
    pmesh.build_hybrid_mesh(dp=1, devices=jax.devices()[:1])
    paddle.seed(0)
    model = ErnieForPretraining(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())

    import paddle_tpu.nn.functional as F

    def loss_fn(out, labels):
        # model(ids) -> (mlm_logits, sop_logits); MLM CE over the vocab
        mlm, _sop = out
        return F.cross_entropy(mlm.reshape([-1, cfg.vocab_size]),
                               labels.reshape([-1]))

    step = CompiledTrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    for _ in range(2):
        loss = step(ids, labels)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(ids, labels)
    final = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final)
    tok_s = batch * seq * iters / dt
    _emit(results, "ernie_base_dp_tokens_per_sec_per_chip",
          tok_s, "tokens/s",
          dict({"batch": batch, "seq": seq,
                # config provenance: BASELINE.md 69,508 was measured
                # with fuse_qkv=False — a jump from the fusion must be
                # attributed, not read as a silent win
                "fuse_qkv": bool(getattr(cfg, "fuse_qkv", False))},
               **_perf_fields(step, (ids, labels), batch * seq, tok_s)))


def bench_widedeep(results, iters=None):
    """wide&deep examples/sec through the PS path: native C++ tables over
    TCP (sparse pull/push on the host) + compiled dense step on the
    device (BASELINE.md 'wide&deep / DeepFM (PS path)')."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from paddle_tpu.distributed.ps import PsClient, PsServer

    on_tpu = jax.default_backend() != "cpu"
    batch = 512
    n_slots = 8
    emb_dim = 16
    vocab = 100_000
    iters = iters or (50 if on_tpu else 5)

    srv = PsServer()
    try:
        cli = PsClient(port=srv.port)
        cli.create_sparse_table(0, emb_dim, optimizer="adagrad", lr=0.05,
                                init_std=0.01)
        hidden = 64
        w1 = jnp.asarray(np.random.RandomState(0).randn(
            n_slots * emb_dim, hidden).astype(np.float32) * 0.05)
        w2 = jnp.asarray(np.random.RandomState(1).randn(
            hidden, 1).astype(np.float32) * 0.05)

        import jax as _jax

        @_jax.jit
        def dense_step(emb, w1, w2, y):
            def loss_fn(params):
                w1, w2 = params
                h = _jax.nn.relu(emb.reshape(batch, -1) @ w1)
                logit = (h @ w2)[:, 0]
                return jnp.mean(
                    jnp.maximum(logit, 0) - logit * y
                    + jnp.log1p(jnp.exp(-jnp.abs(logit))))

            loss, grads = _jax.value_and_grad(loss_fn)((w1, w2))
            return loss, grads

        rng = np.random.RandomState(2)

        def one_iter():
            ids = rng.randint(0, vocab, (batch, n_slots)).astype(np.int64)
            y = rng.randint(0, 2, (batch,)).astype(np.float32)
            rows = cli.pull_sparse(0, ids.reshape(-1))  # host PS pull
            emb = jnp.asarray(rows.reshape(batch, n_slots, emb_dim))
            loss, _ = dense_step(emb, w1, w2, jnp.asarray(y))
            # embedding grad push: use output grad proxy (all-ones) to
            # keep the host path realistic without a full embed backward
            cli.push_sparse(0, ids.reshape(-1),
                            np.asarray(rows, np.float32) * 0.001)
            return loss

        loss = one_iter()
        float(loss)
        t0 = time.perf_counter()
        for _ in range(iters):
            loss = one_iter()
        final = float(loss)
        dt = time.perf_counter() - t0
        assert np.isfinite(final)
        _emit(results, "widedeep_ps_examples_per_sec",
              batch * iters / dt, "examples/s",
              {"batch": batch, "slots": n_slots, "emb_dim": emb_dim})
        cli.close()
    finally:
        srv.stop()


def bench_allreduce(results, iters=None):
    """All-reduce bus bandwidth over the device mesh (BASELINE.md
    'Collective allreduce GB/s'). Needs >1 device (ICI on a pod slice;
    the single-chip tunnel cannot measure this — skipped there).
    Bus BW convention: 2*(n-1)/n * bytes / time."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    n = jax.device_count()
    if n < 2:
        print(json.dumps({"metric": "allreduce_bus_bandwidth_gb_s",
                          "skipped": "needs >1 device, have %d" % n}),
              flush=True)
        return
    iters = iters or 30
    mesh = Mesh(np.array(jax.devices()), ("x",))
    nbytes = 64 * (1 << 20)  # 64 MiB fp32
    elems = nbytes // 4
    x = jax.device_put(
        jnp.ones((n, elems // n), jnp.float32),
        NamedSharding(mesh, P("x", None)))

    # the version-portable shim (jax.shard_map only exists on newer
    # jax; 0.4.x ships it under experimental) lives in collective.py
    from paddle_tpu.distributed.collective import shard_map

    @jax.jit
    def ar(x):
        def body(x):
            return jax.lax.psum(x, "x")

        return shard_map(body, mesh=mesh, in_specs=(P("x", None),),
                         out_specs=P("x", None), check_rep=False)(x)

    y = ar(x)
    float(y[0, 0])
    t0 = time.perf_counter()
    for _ in range(iters):
        y = ar(y)
    float(y[0, 0])
    dt = time.perf_counter() - t0
    bus_bytes = 2 * (n - 1) / n * nbytes * iters
    extras = {"devices": n, "payload_mib": nbytes >> 20}
    if jax.default_backend() == "cpu":
        # quarantine: a host-mesh number says nothing about ICI; every
        # artifact citing this row must carry the label
        extras["cpu_mesh_sanity"] = True
        extras["note"] = ("virtual CPU-mesh sanity row only — NOT an ICI "
                          "measurement; the ICI row needs >1 real chip")
    _emit(results, "allreduce_bus_bandwidth_gb_s",
          bus_bytes / dt / 1e9, "GB/s", extras)


def bench_llama1b(results, iters=None):
    """~1B-param decoder train step: the weight-dominated MFU row
    (BASELINE.md round-4 'where does the other 40% go' characterization).
    At 953M params the arithmetic intensity is realistic — weights no
    longer fit alongside all activations, so per-layer recompute is on
    (LlamaConfig.recompute -> jax.checkpoint), the same recipe a real 1B+
    run on one 16GB v5e chip needs. MFU convention: model FLOPs
    (6*N/token + attention 12*L*S*H/token, x1.33 for the remat re-forward
    NOT counted — MFU counts useful FLOPs only) over the v5e bf16 peak
    197 TFLOP/s."""
    import numpy as np
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import mesh as pmesh
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.parallel.engine import CompiledTrainStep

    on_tpu = jax.default_backend() != "cpu"
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=5632, num_hidden_layers=16,
                          num_attention_heads=16,
                          max_position_embeddings=2048,
                          use_parallel=False, dtype="bfloat16",
                          recompute=True)
        batch, seq = 8, 1024
    else:
        cfg = LlamaConfig.tiny(use_parallel=False, recompute=True)
        batch, seq = 2, 64
    iters = iters or (10 if on_tpu else 2)
    pmesh.build_hybrid_mesh(dp=1, devices=jax.devices()[:1])
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())

    def loss_fn(logits, labels):
        return F.cross_entropy(logits.reshape([-1, cfg.vocab_size]),
                               labels.reshape([-1]))

    step = CompiledTrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    for _ in range(2):
        loss = step(ids, labels)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(ids, labels)
    final = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final)
    tok_s = batch * seq * iters / dt
    flops_per_tok = (6 * n_params
                     + 12 * cfg.num_hidden_layers * seq * cfg.hidden_size)
    mfu = tok_s * flops_per_tok / 197e12 if on_tpu else 0.0
    # both MFU conventions side by side: the analytic 6N/token formula
    # (useful FLOPs only — remat re-forward NOT counted) and the
    # executable's cost_analysis (counts the recompute; upper bound on
    # work, so its mfu reads HIGHER under remat). The gap between them
    # IS the remat tax.
    _emit(results, "llama1b_train_tokens_per_sec_per_chip", tok_s,
          "tokens/s",
          dict({"batch": batch, "seq": seq,
                "params_m": round(n_params / 1e6),
                "model_tflops": round(tok_s * flops_per_tok / 1e12, 1),
                "mfu_vs_197tf_peak": round(mfu, 3), "recompute": True},
               **_perf_fields(step, (ids, labels), batch * seq, tok_s)))


def bench_llama_int8(results, iters=None):
    """Serving throughput bf16 vs int8 (VERDICT r4 #7: the int8 path
    landed with zero perf evidence). Measures prefill (one forward over
    the prompt) and decode (generate loop) tokens/s on the bench-family
    llama, then converts Linear layers to s8 x s8 -> s32 MXU matmuls
    (quantization.convert_to_int8) and re-measures — the reference's
    analysis_predictor int8 serving intent, TPU-native."""
    import numpy as np
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.distributed import mesh as pmesh
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.quantization import PTQ, convert_to_int8

    on_tpu = jax.default_backend() != "cpu"
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=768,
                          intermediate_size=2048, num_hidden_layers=12,
                          num_attention_heads=6,
                          max_position_embeddings=2048,
                          use_parallel=False, dtype="bfloat16")
        batch, prompt, new = 8, 512, 128
    else:
        cfg = LlamaConfig.tiny(use_parallel=False)
        batch, prompt, new = 2, 16, 8
    iters = iters or (5 if on_tpu else 2)
    pmesh.build_hybrid_mesh(dp=1, devices=jax.devices()[:1])
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    model.eval()
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, prompt)).astype(np.int32))

    def measure(m, tag):
        # prefill: one full forward over the prompt
        out = m(ids)
        logits = out[0] if isinstance(out, tuple) else out
        float(logits.numpy()[0, 0, 0])
        t0 = time.perf_counter()
        for _ in range(iters):
            out = m(ids)
            logits = out[0] if isinstance(out, tuple) else out
        float(logits.numpy()[0, 0, 0])
        prefill = batch * prompt * iters / (time.perf_counter() - t0)
        # decode: compiled generate loop
        g = m.generate(ids, max_new_tokens=new)
        int(np.asarray(g.numpy())[0, 0])
        t0 = time.perf_counter()
        for _ in range(max(1, iters // 2)):
            g = m.generate(ids, max_new_tokens=new)
        int(np.asarray(g.numpy())[0, 0])
        decode = (batch * new * max(1, iters // 2)
                  / (time.perf_counter() - t0))
        return {"prefill_tokens_per_sec": round(prefill, 1),
                "decode_tokens_per_sec": round(decode, 1)}

    bf16 = measure(model, "bf16")
    # PTQ calibrate on a couple of prompt batches, then freeze to s8
    ptq = PTQ()
    qmodel = ptq.quantize(model, inplace=False)
    for _ in range(2):
        qmodel(ids)
    int8 = convert_to_int8(qmodel)
    int8.eval()
    q = measure(int8, "int8")
    _emit(results, "llama_serving_decode_tokens_per_sec_int8",
          q["decode_tokens_per_sec"], "tokens/s",
          {"batch": batch, "prompt": prompt, "new_tokens": new,
           "bf16": bf16, "int8": q,
           "int8_speedup_decode": round(
               q["decode_tokens_per_sec"]
               / max(bf16["decode_tokens_per_sec"], 1e-9), 3),
           "int8_speedup_prefill": round(
               q["prefill_tokens_per_sec"]
               / max(bf16["prefill_tokens_per_sec"], 1e-9), 3)})


SUBS = {"resnet50": bench_resnet50, "ernie_dp": bench_ernie_dp,
        "widedeep": bench_widedeep, "allreduce": bench_allreduce,
        "llama1b": bench_llama1b, "llama_int8": bench_llama_int8}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("sub", choices=list(SUBS) + ["all"])
    ap.add_argument("--iters", type=int, default=None)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    _watchdog()
    results = []
    subs = list(SUBS) if args.sub == "all" else [args.sub]
    for s in subs:
        try:
            SUBS[s](results, iters=args.iters)
        except Exception as e:  # keep measuring the other rows
            print(json.dumps({"metric": s, "error": repr(e)[:300]}),
                  flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(results, f, indent=1)


if __name__ == "__main__":
    main()
