"""Op-coverage audit: reference PHI kernel names vs this framework's op
registry (VERDICT r1 item 8).

Extracts every PD_REGISTER_KERNEL name from the reference's
paddle/phi/kernels/ tree, normalizes the naming differences (grad
suffixes, sparse/fused/legacy families, backend duplicates), and diffs
against paddle_tpu's OPS registry + public functional/tensor namespaces.
Writes OP_COVERAGE.md at the repo root.

Run:  python tools/op_coverage.py [--reference /root/reference]
"""
from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# reference kernels that are artifacts of the CUDA/fluid architecture,
# not user capabilities — a TPU-native framework has no analog to build.
# (kept visible in the report under "n/a by design" with the reason)
NA_BY_DESIGN = {
    # memory/layout/device plumbing (XLA/PJRT owns these)
    "memcpy": "XLA buffer assignment owns transfers",
    "memcpy_d2h": "PJRT device_get",
    "memcpy_h2d": "PJRT device_put",
    "memcpy_d2h_multi_io": "PJRT",
    "transfer_layout": "XLA layout assignment",
    "data_transform": "jit boundary handles dtype/layout",
    # fluid legacy / infrastructure ops
    "assign_pos": "MoE dispatch is jnp.take-based (parallel/moe.py)",
    "number_count": "MoE capacity math is vectorized in parallel/moe.py",
    "limit_by_capacity": "parallel/moe.py capacity mask",
    "prune_gate_by_capacity": "parallel/moe.py capacity mask",
    "random_routing": "parallel/moe.py gates",
    "seed": "framework.random key system",
    "ftrl": "CPU PS-era optimizer; not in paddle.optimizer public API",
    "dpsgd": "differential-privacy contrib op outside core API",
    "nop": "scheduling artifact",
    "run_program": "jit.to_static executes captured programs directly",
    "fetch_v2": "Executor returns fetch values natively",
    "feed_with_place": "Executor feed",
    "print": "Python",
    "share_buffer": "functional arrays",
    "share_data": "functional arrays",
    "shadow_output": "interpreter artifact",
    "shadow_feed": "interpreter artifact",
    "select_input": "lax.cond lowering",
    "select_output": "lax.cond lowering",
    "tensor_array_to_tensor": "no LoD TensorArray; jnp stacking",
    "reorder_lod_tensor_by_rank": "no LoD",
    "lod_reset": "no LoD",
    "is_empty": "static shapes",
    "read_file": "io pipeline is host-side (paddle_tpu.io)",
    "save": "framework.io",
    "load": "framework.io",
    "save_combine": "framework.io",
    "load_combine": "framework.io",
    "uniform_random_batch_size_like": "static shapes make _like rng trivial",
    "c_comm_init_all": "XLA collectives need no comm init",
    "c_gen_nccl_id": "no NCCL",
    "c_wait_comm": "XLA schedules collectives",
    "c_wait_compute": "XLA schedules collectives",
    "sparse_momentum": "SelectedRows-free design (dense momentum)",
    "get_tensor_from_selected_rows": "no SelectedRows",
    "merge_selected_rows": "no SelectedRows",
    "clip_by_norm_sr": "no SelectedRows",
    "fused_adam": "optimizer update is one fused XLA module already",
    "fused_linear_param_grad_add": "XLA fuses",
    "fused_embedding_eltwise_layernorm": "XLA fuses",
    "fused_fc_elementwise_layernorm": "XLA fuses",
    "fusion_group": "XLA fusion",
    "fusion_gru": "XLA fuses the lax.scan GRU",
    "fusion_lstm": "XLA fuses the lax.scan LSTM",
    "fusion_repeated_fc_relu": "XLA fuses",
    "fusion_seqconv_eltadd_relu": "no LoD sequence ops",
    "fusion_seqexpand_concat_fc": "no LoD sequence ops",
    "fusion_seqpool_concat": "no LoD sequence ops",
    "fusion_seqpool_cvm_concat": "no LoD sequence ops",
    "fusion_squared_mat_sub": "XLA fuses",
    "fusion_transpose_flatten_concat": "XLA fuses",
    "fused_elemwise_add_activation": "XLA fuses",
    "fused_scale_bias_relu_conv_bn": "XLA fuses",
    "fused_scale_bias_add_relu": "XLA fuses",
    "fused_dconv_drelu_dbn": "XLA fuses",
    "fused_dot_product_attention": "kernels/flash_attention.py",
    "fused_conv2d_add_act": "XLA fuses",
    "conv2d_fusion_cutlass": "vendor kernel",
    "fc": "nn.Linear + XLA fusion",
    "squeeze_excitation_block": "composite of existing ops",
    "yolo_box_head": "detection-serving fusion outside API surface",
    "yolo_box_post": "detection-serving fusion outside API surface",
    "fused_multi_transformer_int8": "quantization path differs (pass-based)",
    "fused_multi_transformer_cachekv_layout_trans": "serving artifact",
    "self_dp_attention": "CPU-only oneDNN fusion",
    "skip_layernorm": "XLA fuses",
    "fused_token_prune": "TRT-era serving op",
    "fused_gate_attention": "flash attention covers",
    "resnet_basic_block": "XLA fuses whole blocks",
    "resnet_unit": "XLA fuses whole blocks",
    "cudnn_lstm": "lax.scan LSTM",
    "miopen_lstm": "lax.scan LSTM",
    "max_pool2d_v2": "pool2d covers",
    "legacy_bilinear_interp": "bilinear_interp covers",
    "legacy_nearest_interp": "nearest_interp covers",
    "legacy_expand": "expand covers",
    "legacy_expand_grad": "expand covers",
    "legacy_reshape": "reshape covers",
    "legacy_slice": "slice covers",
    "legacy_generate_proposals": "generate_proposals covers",
    "quantize_linear_deprecated": "quantize_linear covers",
    "dequantize_linear_deprecated": "dequantize_linear covers",
    "moving_average_abs_max_scale": "quantization observers (python)",
    "straight_through_estimator": "quantization STE (python)",
    "straight_through_estimator_grad": "quantization STE (python)",
    "check_memory_continue": "XLA buffer assignment (no fused-buffer check)",
    "coalesce_tensor": "XLA fuses grad buffers; no flat-buffer op needed",
    "conv2d_fusion": "XLA fuses conv+bias+act",
    "convdnn": "backend-specific conv dispatch; XLA lowers conv directly",
    "fused_conv2d": "XLA fuses",
    "fused_softmax_mask": "XLA fuses mask+softmax",
    "merged_adam": "multi-tensor apply; the whole update is one XLA module",
    "merged_momentum": "multi-tensor apply; one XLA module",
    "npu_identity": "vendor (Ascend) artifact",
    "mask": "sparse masking via dense where() under GSPMD",
    "mask_helper": "sparse masking via dense where()",
    "sparse_mask": "sparse masking via dense where()",
    "sparse_mask_helper": "sparse masking via dense where()",
}

# reference-name (or stripped base) -> (display, target) where target is a
# MACHINE-RESOLVABLE dotted path under the paddle_tpu package ("Tensor.x"
# addresses a Tensor method/operator). tests/test_op_coverage.py resolves
# every target at gate time, so an alias cannot silently rot.
REF_TO_OURS = {
    "add": ("elementwise add (+)", "Tensor.__add__"),
    "grad_add": ("add", "add"),
    "add_n": ("add_n", "add_n"),
    "subtract": ("- operator", "Tensor.__sub__"),
    "multiply": ("* operator", "Tensor.__mul__"),
    "divide": ("/ operator", "Tensor.__truediv__"),
    "matmul_with_flatten": ("matmul", "matmul"),
    "batch_norm": ("F.batch_norm", "nn.functional.batch_norm"),
    "sync_batch_norm": ("nn.SyncBatchNorm", "nn.SyncBatchNorm"),
    "fused_bn_add_activation":
        ("F.batch_norm + XLA fusion", "nn.functional.batch_norm"),
    "cross_entropy_with_softmax": ("softmax_with_cross_entropy",
                                   "nn.functional.softmax_with_cross_entropy"),
    "c_softmax_with_cross_entropy":
        ("parallel_softmax_cross_entropy",
         "parallel.mp_layers.parallel_softmax_cross_entropy"),
    "sum": ("sum", "sum"),
    "mean": ("mean", "mean"),
    "mean_all": ("mean", "mean"),
    "flash_attn": ("kernels.flash_attention",
                   "kernels.flash_attention.flash_attention"),
    "flash_attn_unpadded": ("kernels.flash_attention (segment_ids)",
                            "kernels.flash_attention.flash_attention"),
    "fused_attention": ("kernels.flash_attention",
                        "kernels.flash_attention.flash_attention"),
    "memory_efficient_attention": ("kernels.flash_attention",
                                   "kernels.flash_attention.flash_attention"),
    "variable_length_memory_efficient_attention":
        ("F.variable_length_attention",
         "nn.functional.variable_length_attention"),
    "fused_multi_head_attention":
        ("F.scaled_dot_product_attention",
         "nn.functional.scaled_dot_product_attention"),
    "dropout_nd": ("F.dropout", "nn.functional.dropout"),
    "fused_dropout_add": ("F.dropout + XLA fusion", "nn.functional.dropout"),
    "c_allreduce": ("distributed.all_reduce", "distributed.all_reduce"),
    "mp_allreduce_sum": ("distributed.all_reduce", "distributed.all_reduce"),
    "all_reduce": ("distributed.all_reduce", "distributed.all_reduce"),
    "reduce": ("distributed.reduce", "distributed.reduce"),
    "c_allgather": ("distributed.all_gather", "distributed.all_gather"),
    "all_gather": ("distributed.all_gather", "distributed.all_gather"),
    "c_reducescatter": ("distributed.reduce_scatter",
                        "distributed.reduce_scatter"),
    "c_broadcast": ("distributed.broadcast", "distributed.broadcast"),
    "broadcast_tensors": ("broadcast_tensors", "broadcast_tensors"),
    "all_to_all": ("distributed.alltoall", "distributed.alltoall"),
    "global_scatter": ("distributed.utils.global_scatter (moe)",
                       "distributed.utils.global_scatter"),
    "global_gather": ("distributed.utils.global_gather (moe)",
                      "distributed.utils.global_gather"),
    "send_v2": ("distributed.send", "distributed.send"),
    "p_send": ("distributed.send", "distributed.send"),
    "partial_send": ("partial_send", "distributed.collective.partial_send"),
    "recv_v2": ("distributed.recv", "distributed.recv"),
    "p_recv": ("distributed.recv", "distributed.recv"),
    "partial_recv": ("partial_recv", "distributed.collective.partial_recv"),
    "partial_allgather": ("partial_allgather",
                          "distributed.collective.partial_allgather"),
    "c_identity": ("mp identity = sharding annotation",
                   "parallel.mp_layers.mark_sharding"),
    "c_concat": ("concat", "concat"),
    "c_split": ("split", "split"),
    "c_embedding": ("VocabParallelEmbedding",
                    "parallel.mp_layers.VocabParallelEmbedding"),
    "embedding_with_scaled_gradient": ("F.embedding",
                                       "nn.functional.embedding"),
    "embedding_grad_add_to": ("F.embedding", "nn.functional.embedding"),
    "embedding_sparse": ("F.embedding", "nn.functional.embedding"),
    "sparse_weight_embedding": ("F.embedding", "nn.functional.embedding"),
    "bce_loss": ("F.binary_cross_entropy",
                 "nn.functional.binary_cross_entropy"),
    "kldiv_loss": ("F.kl_div", "nn.functional.kl_div"),
    "bicubic_interp": ("F.interpolate", "nn.functional.interpolate"),
    "bilinear_interp": ("F.interpolate", "nn.functional.interpolate"),
    "nearest_interp": ("F.interpolate", "nn.functional.interpolate"),
    "linear_interp": ("F.interpolate", "nn.functional.interpolate"),
    "trilinear_interp": ("F.interpolate", "nn.functional.interpolate"),
    "bilinear_tensor_product": ("F.bilinear", "nn.functional.bilinear"),
    "check_finite_and_unscale": ("amp.GradScaler (XLA-fused)",
                                 "amp.GradScaler"),
    "update_loss_scaling": ("amp.GradScaler", "amp.GradScaler"),
    "depthwise_conv2d": ("F.conv2d(groups=C)", "nn.functional.conv2d"),
    "depthwise_conv2d_transpose": ("F.conv2d_transpose(groups=C)",
                                   "nn.functional.conv2d_transpose"),
    "elementwise_pow": ("pow", "pow"),
    "elementwise_heaviside": ("heaviside", "heaviside"),
    "fft_c2c": ("fft.fft", "fft.fft"),
    "fft_c2r": ("fft.irfft", "fft.irfft"),
    "fft_r2c": ("fft.rfft", "fft.rfft"),
    "frobenius_norm": ("linalg.norm", "linalg.norm"),
    "full_batch_size_like": ("full_like", "full_like"),
    "gaussian": ("randn", "randn"),
    "truncated_gaussian_random": ("nn.initializer.TruncatedNormal",
                                  "nn.initializer.TruncatedNormal"),
    "graph_sample_neighbors": ("geometric.sample_neighbors",
                               "geometric.sample_neighbors"),
    "matrix_rank_tol": ("linalg.matrix_rank", "linalg.matrix_rank"),
    "max_pool2d_with_index": ("F.max_pool2d(return_mask=True)",
                              "nn.functional.max_pool2d"),
    "max_pool3d_with_index": ("F.max_pool3d", "nn.functional.max_pool3d"),
    "maxpool": ("F.max_pool2d", "nn.functional.max_pool2d"),
    "negative": ("neg", "neg"),
    "p_norm": ("linalg.norm", "linalg.norm"),
    "pad3d": ("F.pad", "nn.functional.pad"),
    "pool2d": ("F.avg_pool2d/max_pool2d", "nn.functional.avg_pool2d"),
    "pool3d": ("F.avg_pool3d/max_pool3d", "nn.functional.avg_pool3d"),
    "repeat_interleave_with_tensor_index": ("repeat_interleave",
                                            "repeat_interleave"),
    "rnn": ("nn.SimpleRNN/LSTM/GRU (lax.scan)", "nn.LSTM"),
    "segment_pool": ("geometric.segment_sum/mean/min/max",
                     "geometric.segment_sum"),
    "set_value_with_tensor": ("Tensor.set_value", "Tensor.set_value"),
    "sgd_sparse_param_sparse_grad": ("optimizer.SGD", "optimizer.SGD"),
    "split_with_num": ("split", "split"),
    "tril_triu": ("tril/triu", "tril"),
    "uniform_inplace": ("uniform", "uniform"),
    "unpool": ("F.max_unpool2d", "nn.functional.max_unpool2d"),
    "assign_value": ("assign", "assign"),
    "coo_to_csr": ("SparseCooTensor.to_sparse_csr",
                   "sparse.SparseCooTensor.to_sparse_csr"),
    "csr_to_coo": ("SparseCsrTensor.to_sparse_coo",
                   "sparse.SparseCsrTensor.to_sparse_coo"),
    "coo_to_dense": ("SparseCooTensor.to_dense",
                     "sparse.SparseCooTensor.to_dense"),
    "csr_to_dense": ("SparseCsrTensor.to_dense",
                     "sparse.SparseCsrTensor.to_dense"),
    "dense_to_coo": ("sparse.sparse_coo_tensor", "sparse.sparse_coo_tensor"),
    "dense_to_csr": ("sparse.sparse_csr_tensor", "sparse.sparse_csr_tensor"),
    "values_coo": ("SparseCooTensor.values", "sparse.SparseCooTensor.values"),
    "values_csr": ("SparseCsrTensor.values", "sparse.SparseCsrTensor.values"),
    "indices_coo": ("SparseCooTensor.indices",
                    "sparse.SparseCooTensor.indices"),
    "divide_scalar": ("sparse.divide", "sparse.divide"),
    "determinant": ("linalg.det", "linalg.det"),
    "spectral_norm": ("nn.utils.spectral_norm", "nn.utils.spectral_norm"),
    "identity_loss": ("incubate.identity_loss", "incubate.identity_loss"),
    "fill_diagonal_tensor": ("fill_diagonal_tensor", "fill_diagonal_tensor"),
    "decode_jpeg": ("vision.ops.decode_jpeg", "vision.ops.decode_jpeg"),
    "crop": ("crop", "crop"),
    "average_accumulates": ("incubate.optimizer.ModelAverage",
                            "incubate.optimizer.ModelAverage"),
    # reference DGC (deep gradient compression) family: this build's
    # gradient compression is the block-scaled int8 quantized sync with
    # error feedback (distributed/compress.py) — same role (cut grad
    # comm bytes on bandwidth-poor links), different algorithm
    "dgc": ("distributed.compress (quantized grad sync)",
            "distributed.compress.sync_gradients_compressed"),
    "dgc_momentum": ("distributed.compress error feedback",
                     "distributed.compress.reduce_grads_traced"),
    # the quantize/dequantize primitives behind it
}

# ops this build ADDS with no reference PHI kernel (the coverage audit
# runs reference->ours; these are the other direction, listed in the
# report so they stay visible and their targets rot-gated the same way)
BEYOND_REFERENCE = [
    ("quantize_int8_block", "block-scaled int8 gradient quantize "
     "(distributed compress wire/step payload)",
     "kernels.quant.quantize_int8_block"),
    ("dequantize_int8_block", "inverse of quantize_int8_block",
     "kernels.quant.dequantize_int8_block"),
]


def resolve_alias(target):
    """Resolve a REF_TO_OURS target ('a.b.C.attr' under paddle_tpu, or
    'Tensor.method') to a live object; returns None if it no longer
    exists. Submodules not imported by the package root are imported on
    demand."""
    import importlib
    import types

    if target.startswith("Tensor."):
        import paddle_tpu

        obj = paddle_tpu.Tensor
        parts = target.split(".")[1:]
    else:
        obj = importlib.import_module("paddle_tpu")
        parts = target.split(".")
    for part in parts:
        nxt = getattr(obj, part, None)
        if nxt is None and isinstance(obj, types.ModuleType):
            try:
                nxt = importlib.import_module(obj.__name__ + "." + part)
            except ImportError:
                return None
        if nxt is None:
            return None
        obj = nxt
    return obj

def reference_kernel_names(ref):
    out = subprocess.run(
        ["grep", "-rhoP", r"PD_REGISTER_KERNEL(_FOR_ALL_DTYPE)?\(\s*\K\w+",
         os.path.join(ref, "paddle/phi/kernels")],
        capture_output=True, text=True)
    names = set(out.stdout.split())
    return names


def our_op_names():
    sys.path.insert(0, REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import paddle_tpu  # noqa: F401
    from paddle_tpu.core.dispatch import OPS

    names = set(OPS)
    # public functional / tensor namespaces count as capabilities too
    import paddle_tpu.nn.functional as F
    import paddle_tpu.sparse as sparse
    from paddle_tpu.core.tensor import Tensor

    import paddle_tpu.metric
    import paddle_tpu.optimizer
    import paddle_tpu.vision.ops as vops

    mods = [F, paddle_tpu, sparse, paddle_tpu.linalg, paddle_tpu.fft,
            paddle_tpu.signal, paddle_tpu.geometric, paddle_tpu.metric,
            paddle_tpu.optimizer, vops, paddle_tpu.incubate.nn.functional
            if hasattr(paddle_tpu.incubate.nn, "functional")
            else paddle_tpu.incubate.nn]
    for mod in mods:
        names |= {n for n in dir(mod) if not n.startswith("_")}
    names |= {n for n in dir(Tensor) if not n.startswith("_")}
    return names


_SUFFIXES = [
    "_double_grad", "_triple_grad", "_grad_grad", "_grad", "_raw", "_sr",
    "_array", "_dense_param_sparse_grad", "_coo_coo", "_csr_csr",
    "_coo_dense", "_csr_dense", "_csr_coo", "_dense_coo", "_coo", "_csr",
    "_dense", "_intermediate", "_with_kernel", "_infer",
]


def strip_variants(name):
    """Peel backend/layout/autodiff suffixes: `add_coo_coo_grad` -> `add`,
    `adamw_dense_param_sparse_grad` -> `adamw`, `max_raw` -> `max`."""
    changed = True
    while changed:
        changed = False
        # longest-first so "_dense_param_sparse_grad" wins over "_grad"
        for s in sorted(_SUFFIXES, key=len, reverse=True):
            if name.endswith(s) and len(name) > len(s):
                name = name[:-len(s)]
                changed = True
    return name


def normalize(name):
    return name.lower()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reference", default="/root/reference")
    args = ap.parse_args()

    ref_names = reference_kernel_names(args.reference)
    ours = {normalize(n) for n in our_op_names()}
    alias_cover = dict(REF_TO_OURS)

    covered, via_alias, na, missing = [], [], [], []
    for name in sorted(ref_names):
        base = strip_variants(name)
        # grad-only strip too: full variant stripping can eat real name
        # parts ("coo_to_dense_grad" -> "coo_to"), so check both forms
        g = name
        for s in ("_double_grad", "_triple_grad", "_grad_grad", "_sparse_grad", "_grad"):
            while g.endswith(s) and len(g) > len(s):
                g = g[:-len(s)]
        base2 = base[len("sparse_"):] if base.startswith("sparse_") else base
        forms = (name, g, base, base2)
        if any(c in ours for c in forms):
            covered.append(name)
        elif any(c in alias_cover for c in forms):
            key = next(c for c in forms if c in alias_cover)
            disp, target = alias_cover[key]
            via_alias.append((name, disp, target))
        elif any(c in NA_BY_DESIGN for c in forms):
            na.append((name, next(NA_BY_DESIGN[c] for c in forms
                                  if c in NA_BY_DESIGN)))
        else:
            missing.append(name)

    total = len(ref_names)
    lines = []
    lines.append("# OP COVERAGE — reference PHI kernels vs paddle_tpu\n")
    lines.append("Generated by `tools/op_coverage.py`. Reference: %d "
                 "registered kernel names (`paddle/phi/kernels/`, "
                 "PD_REGISTER_KERNEL).\n" % total)
    lines.append("| bucket | count |")
    lines.append("|---|---|")
    lines.append("| covered (same name) | %d |" % len(covered))
    lines.append("| covered (alias) | %d |" % len(via_alias))
    lines.append("| n/a by design (CUDA/fluid artifact) | %d |" % len(na))
    lines.append("| missing | %d |" % len(missing))
    pct = 100.0 * (len(covered) + len(via_alias) + len(na)) / total
    lines.append("\n**Accounted: %.1f%%**\n" % pct)
    lines.append("## Missing (%d)\n" % len(missing))
    lines.append(", ".join("`%s`" % m for m in missing) or "(none)")
    # every alias target must resolve to a live object (rot gate; also
    # enforced by tests/test_op_coverage.py)
    unresolved = sorted({t for _, _, t in via_alias
                         if resolve_alias(t) is None})
    lines.append("\n## Covered via alias (%d)\n" % len(via_alias))
    lines.append("\n".join(
        "- `%s` -> %s (`paddle_tpu.%s`)" % (a, d, t)
        for a, d, t in via_alias))
    lines.append("\n## n/a by design (%d)\n" % len(na))
    lines.append("\n".join("- `%s` — %s" % (a, b) for a, b in na))
    unresolved += sorted({t for _, _, t in BEYOND_REFERENCE
                          if resolve_alias(t) is None})
    lines.append("\n## Beyond reference (%d)\n" % len(BEYOND_REFERENCE))
    lines.append("Ops this build adds with no reference PHI kernel "
                 "(rot-gated like aliases):\n")
    lines.append("\n".join(
        "- `%s` — %s (`paddle_tpu.%s`)" % (a, d, t)
        for a, d, t in BEYOND_REFERENCE))
    lines.append("\n> Note: the old manual \"metrics documented?\" "
                 "checklist item is superseded by ptlint's "
                 "metric-registry pass (`python tools/ptlint.py "
                 "--rules metric`), which machine-checks that every "
                 "registered metric is literal, family-prefixed, "
                 "label-consistent, and documented in README/BASELINE.")
    report = "\n".join(lines) + "\n"
    with open(os.path.join(REPO, "OP_COVERAGE.md"), "w") as f:
        f.write(report)
    print("missing=%d covered=%d alias=%d na=%d (accounted %.1f%%)"
          % (len(missing), len(covered), len(via_alias), len(na), pct))
    print("\n".join(missing))
    if unresolved:
        print("UNRESOLVED alias targets: %s" % unresolved)
        sys.exit(1)


if __name__ == "__main__":
    main()
