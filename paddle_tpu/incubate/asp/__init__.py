"""ASP — automatic structured (n:m) sparsity.

Parity: reference python/paddle/incubate/asp/ (asp.py: decorate :217,
prune_model :303, ASPHelper :516; utils.py: get_mask_1d/get_mask_2d_greedy/
get_mask_2d_best/create_mask/check_sparsity/calculate_density). Semantics
are identical — n nonzeros per m consecutive weights — computed on host
numpy exactly as the reference does. TPU note: there is no sparse-tensor-
core speedup to harvest on the MXU; ASP here serves model-compression
parity, and masks stay applied through optimizer steps via `decorate`.
"""
from __future__ import annotations

import itertools
from enum import Enum

import numpy as np


class MaskAlgo(Enum):
    MASK_1D = "get_mask_1d"
    MASK_2D_GREEDY = "get_mask_2d_greedy"
    MASK_2D_BEST = "get_mask_2d_best"


class CheckMethod(Enum):
    CHECK_1D = "check_mask_1d"
    CHECK_2D = "check_mask_2d"

    @staticmethod
    def get_checking_method(mask_algo):
        return (CheckMethod.CHECK_1D if mask_algo == MaskAlgo.MASK_1D
                else CheckMethod.CHECK_2D)


def calculate_density(x):
    """Fraction of nonzeros (reference utils.py calculate_density)."""
    x = np.asarray(x)
    return float(np.count_nonzero(x)) / x.size


def _reshape_1d(mat, m):
    """Pad the last dim to a multiple of m and view as rows of m."""
    mat = np.asarray(mat)
    if mat.shape[1] % m == 0:
        return mat.reshape(-1, m), mat.shape
    pad = m - mat.shape[1] % m
    padded = np.concatenate(
        [mat, np.zeros((mat.shape[0], pad), mat.dtype)], axis=1)
    return padded.reshape(-1, m), padded.shape


def get_mask_1d(mat, n, m):
    """Keep the n largest-|w| of every m consecutive weights per row."""
    mat = np.asarray(mat)
    rows, shape = _reshape_1d(mat, m)
    mask = np.zeros_like(rows, dtype=mat.dtype)
    idx = np.argsort(np.abs(rows), axis=1)[:, -n:]
    np.put_along_axis(mask, idx, 1, axis=1)
    return mask.reshape(shape)[:mat.shape[0], :mat.shape[1]]


def check_mask_1d(mat, n, m):
    mat = np.asarray(mat)
    rows, _ = _reshape_1d(mat, m)
    return bool(np.all(np.count_nonzero(rows, axis=1) <= n))


def _valid_2d_patterns(n, m):
    """All m x m binary matrices with exactly n ones per row AND column."""
    row_patterns = [p for p in itertools.product((0, 1), repeat=m)
                    if sum(p) == n]
    valid = []
    for combo in itertools.product(row_patterns, repeat=m):
        arr = np.array(combo)
        if np.all(arr.sum(axis=0) == n):
            valid.append(arr)
    return np.array(valid)


_PATTERN_CACHE = {}


def get_mask_2d_best(mat, n, m):
    """Exhaustive search over valid n:m 2D patterns per m x m block,
    maximizing retained |w| (reference utils.py get_mask_2d_best)."""
    mat = np.asarray(mat)
    key = (n, m)
    if key not in _PATTERN_CACHE:
        _PATTERN_CACHE[key] = _valid_2d_patterns(n, m)
    patterns = _PATTERN_CACHE[key]  # [P, m, m]
    h, w = mat.shape
    ph, pw = (-h) % m, (-w) % m
    padded = np.pad(np.abs(mat), ((0, ph), (0, pw)))
    H, W = padded.shape
    blocks = padded.reshape(H // m, m, W // m, m).transpose(0, 2, 1, 3)
    # score every pattern on every block, pick argmax
    scores = np.einsum("abij,pij->abp", blocks, patterns)
    best = np.argmax(scores, axis=-1)
    chosen = patterns[best]  # [H/m, W/m, m, m]
    mask = chosen.transpose(0, 2, 1, 3).reshape(H, W)[:h, :w]
    return mask.astype(mat.dtype)


def get_mask_2d_greedy(mat, n, m):
    """Greedy per-block assignment (reference get_mask_2d_greedy): walk
    block entries by descending |w|, keep while row/col budgets allow."""
    mat = np.asarray(mat)
    h, w = mat.shape
    ph, pw = (-h) % m, (-w) % m
    padded = np.pad(np.abs(mat), ((0, ph), (0, pw)))
    H, W = padded.shape
    mask = np.zeros((H, W), mat.dtype)
    for bi in range(0, H, m):
        for bj in range(0, W, m):
            block = padded[bi:bi + m, bj:bj + m]
            order = np.dstack(np.unravel_index(
                np.argsort(-block, axis=None), (m, m)))[0]
            row_budget = np.full(m, n)
            col_budget = np.full(m, n)
            for i, j in order:
                if row_budget[i] > 0 and col_budget[j] > 0:
                    mask[bi + i, bj + j] = 1
                    row_budget[i] -= 1
                    col_budget[j] -= 1
    return mask[:h, :w]


def check_mask_2d(mat, n, m):
    mat = np.asarray(mat)
    h, w = mat.shape
    ph, pw = (-h) % m, (-w) % m
    padded = np.pad(np.abs(mat), ((0, ph), (0, pw)))
    H, W = padded.shape
    blocks = padded.reshape(H // m, m, W // m, m).transpose(0, 2, 1, 3)
    nz = blocks != 0
    return bool(np.all(nz.sum(axis=2) <= n) and np.all(nz.sum(axis=3) <= n))


def create_mask(tensor, func_name=MaskAlgo.MASK_1D, n=2, m=4):
    """Dispatch to a mask algorithm; >2D tensors are masked over their
    last two dims flattened (reference create_mask reshapes the same way)."""
    if isinstance(func_name, str):
        func_name = MaskAlgo(func_name if func_name.startswith("get_")
                             else "get_" + func_name)
    t = np.asarray(tensor)
    shape = t.shape
    if t.ndim == 1:
        mat = t.reshape(1, -1)
    elif t.ndim == 2:
        mat = t
    else:
        mat = t.reshape(-1, shape[-1])
    fn = globals()[func_name.value]
    return fn(mat, n, m).reshape(shape)


def check_sparsity(tensor, func_name=CheckMethod.CHECK_1D, n=2, m=4):
    if isinstance(func_name, str):
        func_name = CheckMethod(func_name)
    t = np.asarray(tensor)
    mat = t.reshape(1, -1) if t.ndim == 1 else t.reshape(-1, t.shape[-1])
    return globals()[func_name.value](mat, n, m)


# ---- model-level API --------------------------------------------------------

class ASPHelper:
    """Per-parameter masks and exclusion list (reference asp.py:516).
    Masks live ON the parameter object (`param._asp_mask`) so their
    lifetime is the parameter's — no global registry to leak or to
    mis-apply via recycled object ids."""

    MASK_APPENDDED_NAME = "asp_mask"
    _excluded = set()

    @classmethod
    def set_excluded_layers(cls, param_names):
        cls._excluded.update(param_names)

    @classmethod
    def reset_excluded_layers(cls):
        cls._excluded = set()

    @classmethod
    def _supported(cls, name, param):
        if any(ex in name for ex in cls._excluded):
            return False
        shape = param.shape
        # reference supports Linear/Conv weights; needs both dims % 4 == 0
        return (len(shape) >= 2 and shape[-1] % 4 == 0
                and int(np.prod(shape[:-1])) % 4 == 0)

    @classmethod
    def prune_model(cls, model, n=2, m=4, mask_algo=MaskAlgo.MASK_1D,
                    with_mask=True):
        masks = {}
        for name, param in model.named_parameters():
            if not cls._supported(name, param):
                continue
            mask = create_mask(param.numpy(), mask_algo, n, m)
            param.set_value(param.numpy() * mask)
            if with_mask:
                param._asp_mask = mask
            masks[name] = mask
        return masks

    @classmethod
    def apply_masks(cls, parameters):
        for p in parameters:
            mask = getattr(p, "_asp_mask", None)
            if mask is not None:
                p.set_value(p.numpy() * mask)


def set_excluded_layers(param_names, main_program=None):
    ASPHelper.set_excluded_layers(param_names)


def reset_excluded_layers(main_program=None):
    ASPHelper.reset_excluded_layers()


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    algo = {"mask_1d": MaskAlgo.MASK_1D,
            "mask_2d_greedy": MaskAlgo.MASK_2D_GREEDY,
            "mask_2d_best": MaskAlgo.MASK_2D_BEST}[mask_algo]
    return ASPHelper.prune_model(model, n=n, m=m, mask_algo=algo,
                                 with_mask=with_mask)


class OptimizerWithSparsityGuarantee:
    """Re-applies ASP masks after every optimizer step (reference
    asp.py decorate: masks multiplied back post-update)."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def step(self):
        self._optimizer.step()
        ASPHelper.apply_masks(self._optimizer._parameter_list or [])


def decorate(optimizer):
    return OptimizerWithSparsityGuarantee(optimizer)
