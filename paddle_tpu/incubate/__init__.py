"""paddle.incubate namespace — experimental features.

Parity: reference python/paddle/incubate/ (asp structured sparsity,
autotune, fused nn ops). Graph/autograd incubations that the reference
keeps here (primitive autodiff) are core features of this framework —
everything is already traced functionally — so they need no incubation.
"""
from . import asp, autograd, autotune, nn, optimizer  # noqa: F401
from .autotune import set_config  # noqa: F401

# -- reference incubate top-level names (graph aliases + optimizers) --------
from .optimizer import LookAhead, ModelAverage  # noqa: F401,E402
from ..geometric import (  # noqa: F401,E402
    segment_max,
    segment_mean,
    segment_min,
    segment_sum,
)
from ..geometric import reindex_graph as graph_reindex  # noqa: F401,E402
from ..geometric import (  # noqa: F401,E402
    sample_neighbors as graph_sample_neighbors,
)


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """Legacy alias (reference incubate.graph_send_recv -> geometric
    send_u_recv)."""
    from ..geometric import send_u_recv

    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop neighbor sampling (reference incubate.graph_khop_sampler)
    by iterating the one-hop sampler per hop; returns
    (edge_src, edge_dst, sample_index, reindex_nodes). Edge ids are not
    tracked by the TPU sampler, so return_eids=True raises (deviation:
    the reference threads eids through its CSC kernel)."""
    import jax.numpy as jnp
    import numpy as np

    from ..core.tensor import Tensor
    from ..geometric import sample_neighbors

    if return_eids:
        raise NotImplementedError(
            "graph_khop_sampler(return_eids=True): edge ids are not "
            "tracked; gather them host-side from (edge_src, edge_dst)")

    def _np_of(t):
        return np.asarray(t._value if hasattr(t, "_value") else t)

    cur = input_nodes
    all_src, all_dst = [], []
    for k in sample_sizes:
        out_neigh, out_count = sample_neighbors(row, colptr, cur,
                                                sample_size=k)
        nv = _np_of(out_neigh)
        cv = _np_of(out_count)
        dst = np.repeat(_np_of(cur).reshape(-1), cv)
        all_src.append(nv)
        all_dst.append(dst)
        cur = Tensor(jnp.asarray(np.unique(nv)))

    src = np.concatenate(all_src) if all_src else np.zeros(0, np.int64)
    dst = np.concatenate(all_dst) if all_dst else np.zeros(0, np.int64)
    uniq, inv = np.unique(np.concatenate([dst, src]), return_inverse=True)
    n_dst = len(dst)
    # reindex_nodes: the INPUT nodes' positions in the relabeled space
    in_nodes = _np_of(input_nodes).reshape(-1)
    reindex = np.searchsorted(uniq, in_nodes)
    return (Tensor(jnp.asarray(inv[n_dst:])),
            Tensor(jnp.asarray(inv[:n_dst])),
            Tensor(jnp.asarray(uniq)),
            Tensor(jnp.asarray(reindex)))


def softmax_mask_fuse(x, mask, name=None):
    """Fused masked softmax (reference incubate.softmax_mask_fuse —
    fused_softmax_mask CUDA kernel): softmax(x + mask) in one pass; XLA
    fuses the add into the softmax the same way."""
    import paddle_tpu.nn.functional as F

    return F.softmax(x + mask, axis=-1)


def identity_loss(x, reduction="none"):
    """reference incubate.identity_loss: marks a tensor as a loss for
    IPU pipelines; mathematically reduce-or-passthrough."""
    if reduction in (0, "sum"):
        return x.sum()
    if reduction in (1, "mean"):
        return x.mean()
    return x


def softmax_mask_fuse_upper_triangle(x):
    """Fused causal-masked softmax (reference
    incubate.softmax_mask_fuse_upper_triangle): mask = upper triangle
    (strictly future positions) of the last two dims."""
    import jax.numpy as jnp

    import paddle_tpu.nn.functional as F
    from ..core.tensor import Tensor

    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    n, m = v.shape[-2], v.shape[-1]
    causal = jnp.tril(jnp.ones((n, m), bool))
    masked = jnp.where(causal, v, -1e30)
    return F.softmax(Tensor(masked), axis=-1)


def unzip(input, lod, len=None):  # noqa: A002
    """reference incubate.unzip (pscore): scatter compressed rows back to
    their LoD slots, zero elsewhere. lod: [B+1] offsets."""
    import jax.numpy as jnp
    import numpy as np

    from ..core.tensor import Tensor

    v = input._value if isinstance(input, Tensor) else jnp.asarray(input)
    offs = np.asarray(lod._value if isinstance(lod, Tensor)
                      else lod).astype(np.int64).reshape(-1)
    n_rows = (offs.shape[0] - 1) if len is None else int(len)
    rows = []
    for b in range(n_rows):
        if b + 1 < offs.shape[0] and offs[b + 1] > offs[b]:
            rows.append(v[int(offs[b])])
        else:
            rows.append(jnp.zeros(v.shape[1:], v.dtype))
    return Tensor(jnp.stack(rows))
