"""paddle.incubate namespace — experimental features.

Parity: reference python/paddle/incubate/ (asp structured sparsity,
autotune, fused nn ops). Graph/autograd incubations that the reference
keeps here (primitive autodiff) are core features of this framework —
everything is already traced functionally — so they need no incubation.
"""
from . import asp, autograd, autotune, nn, optimizer  # noqa: F401
from .autotune import set_config  # noqa: F401
