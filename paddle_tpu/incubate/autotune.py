"""paddle.incubate.autotune — runtime tuning knobs.

Parity: reference python/paddle/incubate/autotune.py set_config(config)
with "kernel" (exhaustive cudnn algo search), "layout" (NCHW<->NHWC
autotune), "dataloader" (num_workers tuning) sections. TPU-native mapping:
- kernel  -> XLA's autotuner already picks MXU tilings per-compile; the
  knob toggles jax persistent compilation caching so tuned programs are
  reused across processes.
- layout  -> conv layouts: XLA on TPU canonicalizes internally; we record
  the preference for the conv lowering.
- dataloader -> tunes DataLoader prefetch depth.
"""
from __future__ import annotations

import json

_config = {
    "kernel": {"enable": False, "tuning_range": [1, 10]},
    "layout": {"enable": False},
    "dataloader": {"enable": False},
}


def set_config(config=None):
    """Accepts a dict or a path to a JSON file (reference autotune.py:24)."""
    global _config
    if config is None:
        for section in _config.values():
            section["enable"] = True
        _apply()
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    if not isinstance(config, dict):
        raise TypeError("set_config expects dict, JSON path, or None")
    for key, val in config.items():
        if key in _config and isinstance(val, dict):
            _config[key].update(val)
    _apply()


def get_config():
    return {k: dict(v) for k, v in _config.items()}


def _apply():
    if _config["kernel"]["enable"]:
        import jax

        try:  # persistent compilation cache = cross-process kernel reuse
            jax.config.update("jax_compilation_cache_dir",
                              "/tmp/paddle_tpu_xla_cache")
        # ptlint: silent-except-ok — older jax without the
        # compilation-cache config key; tuning stays best-effort
        except Exception:
            pass
