"""Functional higher-order autodiff — paddle.incubate.autograd parity.

Reference: /root/reference/python/paddle/incubate/autograd/ — primapi.py
(jvp/vjp/forward_grad/grad), functional.py (Jacobian/Hessian), primx.py:678
orig2prim / :703 prim2orig (lowering ops to ~30 differentiable primitives
so transforms compose).

TPU-native design: the lowering-to-primitives machinery is unnecessary —
every op body here is already a pure JAX function, so jax's functional
transforms (jax.vjp / jax.jvp / jacrev / jacfwd / hessian) compose
directly over the SAME op bodies that eager mode dispatches. What remains
of the reference API is the Tensor-level wrapping and the lazy
Jacobian/Hessian views.

Eager double-backward (paddle_tpu.grad(create_graph=True)) lives in
core/autograd.py; this module is the functional mirror.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import no_grad
from ...core.tensor import Tensor

__all__ = ["vjp", "jvp", "Jacobian", "Hessian", "forward_grad", "grad",
           "enable_prim", "disable_prim", "prim_enabled"]


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _wrap(v):
    return Tensor(v, stop_gradient=True)


def _as_seq(xs):
    return list(xs) if isinstance(xs, (list, tuple)) else [xs]


def _array_fn(func, n_in):
    """Lift a Tensor->Tensor(s) function to an array->array(s) function.

    Runs the body under no_grad: inside a jax transform the values are
    tracers and the transform itself supplies the differentiation; the
    eager tape must not also record.
    """

    def fn(*arrs):
        with no_grad():
            out = func(*[Tensor(a) for a in arrs[:n_in]])
        if isinstance(out, (list, tuple)):
            return tuple(_unwrap(o) for o in out)
        return _unwrap(out)

    return fn


def vjp(func, xs, v=None):
    """(outputs, input cotangents) — reference primapi vjp semantics:
    v defaults to ones like the outputs."""
    xs = _as_seq(xs)
    fn = _array_fn(func, len(xs))
    vals = [_unwrap(x) for x in xs]
    out, pullback = jax.vjp(fn, *vals)
    if v is None:
        cot = (jax.tree_util.tree_map(jnp.ones_like, out)
               if isinstance(out, tuple) else jnp.ones_like(out))
    else:
        vv = _as_seq(v)
        cot = (tuple(_unwrap(c) for c in vv) if isinstance(out, tuple)
               else _unwrap(vv[0]))
    grads = pullback(cot)
    outs = ([_wrap(o) for o in out] if isinstance(out, tuple)
            else _wrap(out))
    gs = [_wrap(g) for g in grads]
    return outs, (gs if len(gs) > 1 else gs[0])


def jvp(func, xs, v=None):
    """(outputs, output tangents) — forward-mode directional derivative."""
    xs = _as_seq(xs)
    fn = _array_fn(func, len(xs))
    vals = [_unwrap(x) for x in xs]
    if v is None:
        tangents = [jnp.ones_like(x) for x in vals]
    else:
        tangents = [_unwrap(t) for t in _as_seq(v)]
    out, tang = jax.jvp(fn, tuple(vals), tuple(tangents))
    outs = ([_wrap(o) for o in out] if isinstance(out, tuple)
            else _wrap(out))
    ts = ([_wrap(t) for t in tang] if isinstance(tang, tuple)
          else _wrap(tang))
    return outs, ts


class Jacobian:
    """Lazy Jacobian view (reference functional.py Jacobian): J[i, j]
    d out_i / d in_j, evaluated on first access, row-batched."""

    def __init__(self, func, xs, is_batched=False):
        xs = _as_seq(xs)
        self._single_in = len(xs) == 1
        fn = _array_fn(func, len(xs))
        vals = [_unwrap(x) for x in xs]
        self._is_batched = is_batched
        self._jac = None

        def compute():
            jac = jax.jacrev(fn, argnums=tuple(range(len(vals))))(*vals)
            return jac

        self._compute = compute
        self._vals = vals

    def _materialize(self):
        if self._jac is None:
            jac = self._compute()
            if self._single_in:
                jac = jac[0] if isinstance(jac, tuple) else jac
            # flatten to the reference's 2D [out_size, in_size] view
            # (batched: [B, out, in])
            self._jac = jac
        return self._jac

    @property
    def shape(self):
        return jnp.shape(self._materialize())

    def __getitem__(self, idx):
        return _wrap(jnp.asarray(self._materialize())[idx])

    def numpy(self):
        import numpy as np

        return np.asarray(self._materialize())


class Hessian:
    """Lazy Hessian view: H[i, j] = d^2 f / dx_i dx_j for scalar-output
    func (reference functional.py Hessian)."""

    def __init__(self, func, xs, is_batched=False):
        xs = _as_seq(xs)
        fn = _array_fn(func, len(xs))
        vals = [_unwrap(x) for x in xs]

        def scalar_fn(*vs):
            out = fn(*vs)
            out = out[0] if isinstance(out, tuple) else out
            return jnp.reshape(out, ())

        self._hess = None
        self._compute = lambda: jax.hessian(scalar_fn)(*vals)

    def _materialize(self):
        if self._hess is None:
            self._hess = self._compute()
        return self._hess

    @property
    def shape(self):
        return jnp.shape(self._materialize())

    def __getitem__(self, idx):
        return _wrap(jnp.asarray(self._materialize())[idx])

    def numpy(self):
        import numpy as np

        return np.asarray(self._materialize())


def forward_grad(func, xs, v=None):
    """Forward-mode gradient (reference primapi.forward_grad)."""
    _, tang = jvp(func, xs, v)
    return tang


def grad(func, xs, v=None):
    """Reverse-mode gradient of `func` at `xs` (functional form)."""
    _, gs = vjp(func, xs, v)
    return gs


# The reference gates prim-based autodiff behind enable_prim/disable_prim
# (primx.py). Here the "primitive" lowering is XLA itself, so these are
# compatibility no-ops that report enabled.
_prim_state = {"enabled": True}


def enable_prim():
    _prim_state["enabled"] = True


def disable_prim():
    _prim_state["enabled"] = False


def prim_enabled():
    return _prim_state["enabled"]
