"""Functional higher-order autodiff — paddle.incubate.autograd parity.

Reference: /root/reference/python/paddle/incubate/autograd/ — primapi.py
(jvp/vjp/forward_grad/grad), functional.py (Jacobian/Hessian), primx.py:678
orig2prim / :703 prim2orig (lowering ops to ~30 differentiable primitives
so transforms compose).

TPU-native design: the lowering-to-primitives machinery is unnecessary —
every op body here is already a pure JAX function, so jax's functional
transforms (jax.vjp / jax.jvp / jacrev / jacfwd / hessian) compose
directly over the SAME op bodies that eager mode dispatches. What remains
of the reference API is the Tensor-level wrapping and the lazy
Jacobian/Hessian views.

Eager double-backward (paddle_tpu.grad(create_graph=True)) lives in
core/autograd.py; this module is the functional mirror.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import no_grad
from ...core.tensor import Tensor

__all__ = ["vjp", "jvp", "Jacobian", "Hessian", "forward_grad", "grad",
           "enable_prim", "disable_prim", "prim_enabled"]


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _wrap(v):
    return Tensor(v, stop_gradient=True)


def _as_seq(xs):
    return list(xs) if isinstance(xs, (list, tuple)) else [xs]


def _array_fn(func, n_in):
    """Lift a Tensor->Tensor(s) function to an array->array(s) function.

    Runs the body under no_grad: inside a jax transform the values are
    tracers and the transform itself supplies the differentiation; the
    eager tape must not also record.
    """

    def fn(*arrs):
        with no_grad():
            out = func(*[Tensor(a) for a in arrs[:n_in]])
        if isinstance(out, (list, tuple)):
            return tuple(_unwrap(o) for o in out)
        return _unwrap(out)

    return fn


def vjp(func, xs, v=None):
    """(outputs, input cotangents) — reference primapi vjp semantics:
    v defaults to ones like the outputs."""
    xs = _as_seq(xs)
    fn = _array_fn(func, len(xs))
    vals = [_unwrap(x) for x in xs]
    out, pullback = jax.vjp(fn, *vals)
    if v is None:
        cot = (jax.tree_util.tree_map(jnp.ones_like, out)
               if isinstance(out, tuple) else jnp.ones_like(out))
    else:
        vv = _as_seq(v)
        cot = (tuple(_unwrap(c) for c in vv) if isinstance(out, tuple)
               else _unwrap(vv[0]))
    grads = pullback(cot)
    outs = ([_wrap(o) for o in out] if isinstance(out, tuple)
            else _wrap(out))
    gs = [_wrap(g) for g in grads]
    return outs, (gs if len(gs) > 1 else gs[0])


def jvp(func, xs, v=None):
    """(outputs, output tangents) — forward-mode directional derivative."""
    xs = _as_seq(xs)
    fn = _array_fn(func, len(xs))
    vals = [_unwrap(x) for x in xs]
    if v is None:
        tangents = [jnp.ones_like(x) for x in vals]
    else:
        tangents = [_unwrap(t) for t in _as_seq(v)]
    out, tang = jax.jvp(fn, tuple(vals), tuple(tangents))
    outs = ([_wrap(o) for o in out] if isinstance(out, tuple)
            else _wrap(out))
    ts = ([_wrap(t) for t in tang] if isinstance(tang, tuple)
          else _wrap(tang))
    return outs, ts


def _flat_fn(func, vals, is_batched):
    """Lift func to a function of ONE flat vector (all inputs raveled and
    concatenated; batched mode keeps dim0 and flattens the rest), the
    coordinate system of the reference's 2D Jacobian/Hessian views."""
    if is_batched:
        b = vals[0].shape[0]
        sizes = [int(np.prod(v.shape[1:], dtype=np.int64)) if v.ndim > 1
                 else 1 for v in vals]
    else:
        sizes = [int(v.size) for v in vals]
    offsets = np.cumsum([0] + sizes)
    n_in = len(vals)
    fn = _array_fn(func, n_in)

    def unpack(flat):
        parts = []
        for i, v in enumerate(vals):
            seg = flat[..., offsets[i]:offsets[i + 1]]
            parts.append(seg.reshape(v.shape))
        return parts

    def flat_in(flat):
        out = fn(*unpack(flat))
        if isinstance(out, tuple):
            out = jnp.concatenate(
                [o.reshape((o.shape[0], -1)) if is_batched
                 else o.reshape(-1) for o in out], axis=-1)
        else:
            out = (out.reshape((out.shape[0], -1)) if is_batched
                   else out.reshape(-1))
        return out

    if is_batched:
        flat0 = jnp.concatenate(
            [v.reshape((b, -1)) for v in vals], axis=-1)
    else:
        flat0 = jnp.concatenate([v.reshape(-1) for v in vals])
    return flat_in, flat0


class Jacobian:
    """Lazy Jacobian view (reference functional.py Jacobian): the 2D
    [out_size, in_size] matrix over ALL inputs flattened-and-concatenated
    (batched: [B, out_size, in_size]), evaluated on first access."""

    def __init__(self, func, xs, is_batched=False):
        xs = _as_seq(xs)
        vals = [_unwrap(x) for x in xs]
        self._is_batched = is_batched
        flat_in, flat0 = _flat_fn(func, vals, is_batched)
        self._jac = None

        if is_batched:
            def compute():
                # per-sample jacobian: vmap over the batch dim
                return jax.vmap(jax.jacrev(
                    lambda f1: flat_in(f1[None])[0]))(flat0)
        else:
            def compute():
                return jax.jacrev(flat_in)(flat0)

        self._compute = compute

    def _materialize(self):
        if self._jac is None:
            self._jac = self._compute()
        return self._jac

    @property
    def shape(self):
        return jnp.shape(self._materialize())

    def __getitem__(self, idx):
        return _wrap(jnp.asarray(self._materialize())[idx])

    def numpy(self):
        return np.asarray(self._materialize())


class Hessian:
    """Lazy Hessian view (reference functional.py Hessian): the full
    [in_size, in_size] matrix over ALL inputs flattened-and-concatenated
    — including cross-input blocks — for scalar-output func."""

    def __init__(self, func, xs, is_batched=False):
        xs = _as_seq(xs)
        vals = [_unwrap(x) for x in xs]
        flat_in, flat0 = _flat_fn(func, vals, is_batched)

        if is_batched:
            def scalar_fn(f1):
                return jnp.reshape(flat_in(f1[None]), ())

            self._compute = lambda: jax.vmap(jax.hessian(scalar_fn))(flat0)
        else:
            def scalar_fn(flat):
                return jnp.reshape(flat_in(flat), ())

            self._compute = lambda: jax.hessian(scalar_fn)(flat0)
        self._hess = None

    def _materialize(self):
        if self._hess is None:
            self._hess = self._compute()
        return self._hess

    @property
    def shape(self):
        return jnp.shape(self._materialize())

    def __getitem__(self, idx):
        return _wrap(jnp.asarray(self._materialize())[idx])

    def numpy(self):
        return np.asarray(self._materialize())


def forward_grad(func, xs, v=None):
    """Forward-mode gradient (reference primapi.forward_grad)."""
    _, tang = jvp(func, xs, v)
    return tang


def grad(func, xs, v=None):
    """Reverse-mode gradient of `func` at `xs` (functional form)."""
    _, gs = vjp(func, xs, v)
    return gs


# The reference gates prim-based autodiff behind enable_prim/disable_prim
# (primx.py). Here the "primitive" lowering is XLA itself, so these are
# compatibility no-ops that report enabled.
_prim_state = {"enabled": True}


def enable_prim():
    _prim_state["enabled"] = True


def disable_prim():
    _prim_state["enabled"] = False


def prim_enabled():
    return _prim_state["enabled"]
