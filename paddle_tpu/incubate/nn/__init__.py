"""Fused layers (reference python/paddle/incubate/nn/__init__.py:
FusedMultiHeadAttention, FusedFeedForward, FusedTransformerEncoderLayer,
FusedMultiTransformer, FusedLinear from layer/fused_transformer.py and
layer/fused_linear.py). Each wraps the single-traced-region functional in
incubate.nn.functional — see that module for the TPU fusion story.
"""
from __future__ import annotations

from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer import Layer

from . import functional  # noqa: F401
from .functional import (  # noqa: F401
    fused_bias_dropout_residual_layer_norm,
    fused_feedforward,
    fused_linear,
    fused_matmul_bias,
    fused_multi_head_attention,
    fused_multi_transformer,
)


class FusedLinear(Layer):
    """reference incubate/nn/layer/fused_linear.py FusedLinear."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        shape = ([out_features, in_features] if transpose_weight
                 else [in_features, out_features])
        self.weight = self.create_parameter(
            shape, attr=weight_attr,
            default_initializer=None if weight_attr else I.XavierNormal())
        self.bias = (None if bias_attr is False else
                     self.create_parameter([out_features], attr=bias_attr,
                                           is_bias=True))
        self.transpose_weight = transpose_weight

    def forward(self, x):
        return fused_linear(x, self.weight, self.bias,
                            transpose_weight=self.transpose_weight)


class FusedMultiHeadAttention(Layer):
    """reference incubate/nn/layer/fused_transformer.py
    FusedMultiHeadAttention (qkv_weight layout [3, H, D, E])."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        head_dim = embed_dim // num_heads
        self.embed_dim, self.num_heads = embed_dim, num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.epsilon = epsilon
        self.qkv_weight = self.create_parameter(
            [3, num_heads, head_dim, embed_dim], attr=qkv_weight_attr,
            default_initializer=None if qkv_weight_attr
            else I.XavierNormal())
        self.qkv_bias = self.create_parameter(
            [3, num_heads, head_dim], attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter(
            [embed_dim, embed_dim], attr=linear_weight_attr,
            default_initializer=None if linear_weight_attr
            else I.XavierNormal())
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=linear_bias_attr, is_bias=True)
        ones = I.Constant(1.0)
        self.pre_ln_scale = self.create_parameter(
            [embed_dim], attr=pre_ln_scale_attr, default_initializer=ones)
        self.pre_ln_bias = self.create_parameter(
            [embed_dim], attr=pre_ln_bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=ln_scale_attr, default_initializer=ones)
        self.ln_bias = self.create_parameter(
            [embed_dim], attr=ln_bias_attr, is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        return fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            pre_ln_epsilon=self.epsilon, qkv_bias=self.qkv_bias,
            linear_bias=self.linear_bias, cache_kv=cache,
            attn_mask=attn_mask, dropout_rate=self.dropout_rate,
            attn_dropout_rate=self.attn_dropout_rate,
            ln_epsilon=self.epsilon, training=self.training,
            num_heads=self.num_heads)


class FusedFeedForward(Layer):
    """reference FusedFeedForward (fused_transformer.py)."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.activation = activation
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = (dropout_rate if act_dropout_rate is None
                                 else act_dropout_rate)
        self.epsilon = epsilon
        xavier = I.XavierNormal()
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], attr=linear1_weight_attr,
            default_initializer=None if linear1_weight_attr else xavier)
        self.linear1_bias = self.create_parameter(
            [dim_feedforward], attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], attr=linear2_weight_attr,
            default_initializer=None if linear2_weight_attr else xavier)
        self.linear2_bias = self.create_parameter(
            [d_model], attr=linear2_bias_attr, is_bias=True)
        ones = I.Constant(1.0)
        self.ln1_scale = self.create_parameter(
            [d_model], attr=ln1_scale_attr, default_initializer=ones)
        self.ln1_bias = self.create_parameter(
            [d_model], attr=ln1_bias_attr, is_bias=True)
        self.ln2_scale = self.create_parameter(
            [d_model], attr=ln2_scale_attr, default_initializer=ones)
        self.ln2_bias = self.create_parameter(
            [d_model], attr=ln2_bias_attr, is_bias=True)

    def forward(self, x):
        return fused_feedforward(
            x, self.linear1_weight, self.linear2_weight,
            linear1_bias=self.linear1_bias, linear2_bias=self.linear2_bias,
            ln1_scale=self.ln1_scale, ln1_bias=self.ln1_bias,
            ln2_scale=self.ln2_scale, ln2_bias=self.ln2_bias,
            dropout1_rate=self.act_dropout_rate,
            dropout2_rate=self.dropout_rate, activation=self.activation,
            ln1_epsilon=self.epsilon, ln2_epsilon=self.epsilon,
            pre_layer_norm=self.normalize_before, training=self.training)


class FusedTransformerEncoderLayer(Layer):
    """reference FusedTransformerEncoderLayer = fused MHA + fused FFN."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False):
        super().__init__()
        attn_dropout_rate = (dropout_rate if attn_dropout_rate is None
                             else attn_dropout_rate)
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask, cache=cache)
        if cache is not None:
            out, new_cache = out
            return self.ffn(out), new_cache
        return self.ffn(out)
