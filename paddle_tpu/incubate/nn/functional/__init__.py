"""Fused-op functionals.

Parity: reference python/paddle/incubate/nn/functional/ (fused_transformer.py
fused_multi_head_attention :464, fused_feedforward, fused_multi_transformer,
fused_bias_dropout_residual_layer_norm; fused_matmul_bias.py), which call
monolithic CUDA kernels (operators/fused/fused_attention_op.cu,
fused_feedforward_op.cu). TPU-native: "fused" means ONE traced region —
XLA fuses the elementwise chain into the matmuls, and attention uses the
Pallas flash kernel on TPU — so these are compositions, not custom kernels,
with identical signatures/semantics to the reference.
"""
from __future__ import annotations

import paddle_tpu.nn.functional as F


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False):
    """reference fused_matmul_bias (cublasLt epilogue fusion)."""
    import paddle_tpu as paddle

    out = paddle.matmul(x, y, transpose_x=transpose_x,
                        transpose_y=transpose_y)
    if bias is not None:
        out = out + bias
    return out


def fused_linear(x, weight, bias=None, transpose_weight=False):
    return fused_matmul_bias(x, weight, bias, transpose_y=transpose_weight)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True):
    """out = layer_norm(residual + dropout(x + bias))."""
    if bias is not None:
        x = x + bias
    x = F.dropout(x, p=dropout_rate, training=training)
    x = residual + x
    dim = x.shape[-1]
    return F.layer_norm(x, [dim], weight=ln_scale, bias=ln_bias,
                        epsilon=ln_epsilon)


def fused_multi_head_attention(
        x, qkv_weight, linear_weight, pre_layer_norm=False,
        pre_ln_scale=None, pre_ln_bias=None, ln_scale=None, ln_bias=None,
        pre_ln_epsilon=1e-5, qkv_bias=None, linear_bias=None,
        cache_kv=None, attn_mask=None, dropout_rate=0.5,
        attn_dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        num_heads=None):
    """reference incubate/nn/functional/fused_transformer.py:464.

    x: [B, S, E]; qkv_weight: [3, num_heads, head_dim, E] (reference
    layout); linear_weight: [E, E]. Computes (optionally pre-LN)
    transformer self-attention with residual + dropout + (post-)LN in one
    traced region.
    """
    import paddle_tpu as paddle

    embed_dim = x.shape[-1]
    if num_heads is None:
        num_heads = qkv_weight.shape[1]
    head_dim = embed_dim // num_heads
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, [embed_dim], weight=pre_ln_scale,
                         bias=pre_ln_bias, epsilon=pre_ln_epsilon)
    # qkv projection: [B,S,E] x [3*E, E]^T
    w = paddle.reshape(qkv_weight, [3 * num_heads * head_dim, embed_dim])
    qkv = paddle.matmul(x, w, transpose_y=True)
    if qkv_bias is not None:
        qkv = qkv + paddle.reshape(qkv_bias, [3 * num_heads * head_dim])
    b, s = x.shape[0], x.shape[1]
    qkv = paddle.reshape(qkv, [b, s, 3, num_heads, head_dim])
    q, k, v = paddle.unbind(qkv, axis=2)  # each [B,S,H,D]
    if cache_kv is not None:
        pk, pv = cache_kv
        k = paddle.concat([pk, k], axis=1)
        v = paddle.concat([pv, v], axis=1)
    out = F.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=attn_dropout_rate,
        training=training)
    out = paddle.reshape(out, [b, s, embed_dim])
    out = paddle.matmul(out, linear_weight)
    if linear_bias is not None:
        out = out + linear_bias
    out = F.dropout(out, p=dropout_rate, training=training)
    out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, [embed_dim], weight=ln_scale, bias=ln_bias,
                           epsilon=ln_epsilon)
    if cache_kv is not None:
        return out, (k, v)
    return out


def fused_feedforward(
        x, linear1_weight, linear2_weight, linear1_bias=None,
        linear2_bias=None, ln1_scale=None, ln1_bias=None, ln2_scale=None,
        ln2_bias=None, dropout1_rate=0.5, dropout2_rate=0.5,
        activation="relu", ln1_epsilon=1e-5, ln2_epsilon=1e-5,
        pre_layer_norm=False, training=True):
    """reference fused_feedforward: residual + LN + MLP in one region."""
    import paddle_tpu as paddle

    embed_dim = x.shape[-1]
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, [embed_dim], weight=ln1_scale, bias=ln1_bias,
                         epsilon=ln1_epsilon)
    x = paddle.matmul(x, linear1_weight)
    if linear1_bias is not None:
        x = x + linear1_bias
    x = getattr(F, activation)(x)
    x = F.dropout(x, p=dropout1_rate, training=training)
    x = paddle.matmul(x, linear2_weight)
    if linear2_bias is not None:
        x = x + linear2_bias
    x = F.dropout(x, p=dropout2_rate, training=training)
    out = residual + x
    if not pre_layer_norm:
        out = F.layer_norm(out, [embed_dim], weight=ln2_scale, bias=ln2_bias,
                           epsilon=ln2_epsilon)
    return out


def fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, pre_layer_norm=True,
        epsilon=1e-5, cache_kvs=None, attn_mask=None, dropout_rate=0.0,
        activation="gelu", training=False):
    """reference fused_multi_transformer_op: a whole decoder stack in one
    region (the serving fast path). Layers run sequentially; XLA pipelines
    and fuses across them."""
    new_caches = [] if cache_kvs is not None else None
    for i in range(len(qkv_weights)):
        cache = cache_kvs[i] if cache_kvs is not None else None
        out = fused_multi_head_attention(
            x, qkv_weights[i], linear_weights[i], pre_layer_norm=pre_layer_norm,
            pre_ln_scale=ln_scales[i], pre_ln_bias=ln_biases[i],
            ln_scale=ln_scales[i], ln_bias=ln_biases[i],
            pre_ln_epsilon=epsilon, qkv_bias=qkv_biases[i],
            linear_bias=linear_biases[i], cache_kv=cache,
            attn_mask=attn_mask, dropout_rate=dropout_rate,
            attn_dropout_rate=dropout_rate, ln_epsilon=epsilon,
            training=training)
        if cache is not None:
            out, new_cache = out
            new_caches.append(new_cache)
        x = fused_feedforward(
            out, ffn1_weights[i], ffn2_weights[i],
            linear1_bias=ffn1_biases[i], linear2_bias=ffn2_biases[i],
            ln1_scale=ffn_ln_scales[i], ln1_bias=ffn_ln_biases[i],
            ln2_scale=ffn_ln_scales[i], ln2_bias=ffn_ln_biases[i],
            dropout1_rate=dropout_rate, dropout2_rate=dropout_rate,
            activation=activation, ln1_epsilon=epsilon, ln2_epsilon=epsilon,
            pre_layer_norm=pre_layer_norm, training=training)
    if cache_kvs is not None:
        return x, new_caches
    return x
