"""Incubate optimizers (reference python/paddle/incubate/optimizer/).

ModelAverage rebuilds the reference's average_accumulates op
(phi/kernels/average_accumulates_kernel.h) as functional python state:
windowed running sums of parameter values with apply()/restore() swap.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp

from ..core.tensor import Tensor


class ModelAverage:
    """Running average of parameter values over a trailing window
    (reference incubate/optimizer/modelaverage.py + the
    average_accumulates kernel's sum_1/sum_2/sum_3 rotation)."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000000):
        self.rate = average_window_rate
        self.min_w = min_average_window
        self.max_w = max_average_window
        self.params = list(parameters or [])
        self._sum1 = [jnp.zeros_like(p._value) for p in self.params]
        self._sum2 = [jnp.zeros_like(p._value) for p in self.params]
        self._sum3 = [jnp.zeros_like(p._value) for p in self.params]
        self._num_acc = 0
        self._old_num_acc = 0
        self._num_updates = 0
        self._backup = None

    # precision shelf cadence (reference kMaxNumAccumulates,
    # average_accumulates_kernel_impl.h:45)
    _MAX_NUM_ACCUMULATES = 16384

    def step(self):
        """Accumulate current parameter values (the reference op's
        per-step update, average_accumulates_kernel_impl.h:113-134:
        sum1 += param each step; every 16384 updates shelve sum1 into
        sum2; when the window is exceeded fold sum1+sum2 into sum3 and
        zero both)."""
        self._num_updates += 1
        self._num_acc += 1
        window = max(self.min_w,
                     min(self.max_w, int(self._num_updates * self.rate)))
        for i, p in enumerate(self.params):
            self._sum1[i] = self._sum1[i] + p._value
        if self._num_updates % self._MAX_NUM_ACCUMULATES == 0:
            for i in range(len(self.params)):
                self._sum2[i] = self._sum2[i] + self._sum1[i]
                self._sum1[i] = jnp.zeros_like(self._sum1[i])
        if self._num_acc >= window:
            # window too long: discard the old sum3, fold the live sums
            for i in range(len(self.params)):
                self._sum3[i] = self._sum1[i] + self._sum2[i]
                self._sum1[i] = jnp.zeros_like(self._sum1[i])
                self._sum2[i] = jnp.zeros_like(self._sum2[i])
            self._old_num_acc = self._num_acc
            self._num_acc = 0

    def _averaged(self):
        # sum1+sum2 hold num_acc live samples, sum3 holds the previous
        # closed window of old_num_acc samples
        total_n = self._num_acc + self._old_num_acc
        outs = []
        for i in range(len(self.params)):
            s = self._sum1[i] + self._sum2[i] + self._sum3[i]
            outs.append(s / max(total_n, 1))
        return outs

    @contextlib.contextmanager
    def apply(self, need_restore=True):
        """Swap params to their averaged values inside the context."""
        self._backup = [p._value for p in self.params]
        if self._num_acc + self._old_num_acc > 0:
            for p, avg in zip(self.params, self._averaged()):
                p._value = avg
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self):
        if self._backup is not None:
            for p, v in zip(self.params, self._backup):
                p._value = v
            self._backup = None


def average_accumulates(param, sum1, sum2, sum3, num_acc, old_num_acc,
                        num_updates, average_window, max_average_window,
                        min_average_window):
    """Functional form of the reference average_accumulates op (one
    param). Pass the PRE-increment counters (as the reference op takes
    in_num_* and outputs out_num_*); returns the updated
    (sum1, sum2, sum3, num_acc, old_num_acc, num_updates)."""
    num_updates = int(num_updates) + 1
    num_acc = int(num_acc) + 1
    window = max(min_average_window,
                 min(max_average_window, int(num_updates * average_window)))
    s1 = jnp.asarray(sum1) + jnp.asarray(
        param._value if isinstance(param, Tensor) else param)
    s2, s3 = jnp.asarray(sum2), jnp.asarray(sum3)
    old = int(old_num_acc)
    if num_updates % ModelAverage._MAX_NUM_ACCUMULATES == 0:
        s2, s1 = s2 + s1, jnp.zeros_like(s1)
    if num_acc >= window:
        s3 = s1 + s2
        s1, s2 = jnp.zeros_like(s1), jnp.zeros_like(s2)
        old = num_acc
        num_acc = 0
    return s1, s2, s3, num_acc, old, num_updates


class LookAhead:
    """Lookahead optimizer wrapper (reference incubate/optimizer/lookahead.py,
    arXiv:1907.08610): run the inner optimizer k fast steps, then move
    slow weights alpha toward the fast ones and reset."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha must be in [0, 1]")
        if k < 1:
            raise ValueError("k must be a positive integer")
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._step_count = 0
        self._slow = {}

    def _params(self):
        return self.inner_optimizer._get_params() \
            if hasattr(self.inner_optimizer, "_get_params") \
            else self.inner_optimizer._parameters

    def step(self):
        import jax.numpy as jnp

        params = self._params()
        if not self._slow:
            for p in params:
                self._slow[id(p)] = jnp.asarray(p._value)
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k == 0:
            a = self.alpha
            for p in params:
                slow = self._slow[id(p)] + a * (jnp.asarray(p._value)
                                                - self._slow[id(p)])
                self._slow[id(p)] = slow
                p._value = slow

    def clear_grad(self, *a, **k):
        return self.inner_optimizer.clear_grad(*a, **k)

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()
        self.clear_grad()

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self):
        import numpy as np

        # slow weights keyed by parameter ORDER (ids don't survive a
        # process restart)
        slow = [np.asarray(self._slow[id(p)]) if id(p) in self._slow
                else None for p in self._params()]
        return {"inner": self.inner_optimizer.state_dict(),
                "step_count": self._step_count,
                "slow": slow}

    def set_state_dict(self, sd):
        import jax.numpy as jnp

        self.inner_optimizer.set_state_dict(sd.get("inner", {}))
        self._step_count = sd.get("step_count", 0)
        slow = sd.get("slow")
        if slow is not None:
            self._slow = {}
            for p, s in zip(self._params(), slow):
                if s is not None:
                    self._slow[id(p)] = jnp.asarray(s)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self.inner_optimizer, name)
