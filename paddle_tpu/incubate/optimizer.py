"""Incubate optimizers (reference python/paddle/incubate/optimizer/).

ModelAverage rebuilds the reference's average_accumulates op
(phi/kernels/average_accumulates_kernel.h) as functional python state:
windowed running sums of parameter values with apply()/restore() swap.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp

from ..core.tensor import Tensor


class ModelAverage:
    """Running average of parameter values over a trailing window
    (reference incubate/optimizer/modelaverage.py + the
    average_accumulates kernel's sum_1/sum_2/sum_3 rotation)."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000000):
        self.rate = average_window_rate
        self.min_w = min_average_window
        self.max_w = max_average_window
        self.params = list(parameters or [])
        self._sum1 = [jnp.zeros_like(p._value) for p in self.params]
        self._sum2 = [jnp.zeros_like(p._value) for p in self.params]
        self._sum3 = [jnp.zeros_like(p._value) for p in self.params]
        self._num_acc = 0
        self._old_num_acc = 0
        self._num_updates = 0
        self._backup = None

    def step(self):
        """Accumulate current parameter values (the reference op's
        per-step update: rotate sums when the window is exceeded)."""
        self._num_updates += 1
        self._num_acc += 1
        window = max(self.min_w,
                     min(self.max_w, int(self._num_updates * self.rate)))
        for i, p in enumerate(self.params):
            self._sum1[i] = self._sum1[i] + p._value
        if self._num_acc >= window:
            # rotate: sum_3 <- sum_2 <- sum_1, restart the live window
            for i in range(len(self.params)):
                self._sum3[i] = self._sum2[i]
                self._sum2[i] = self._sum1[i]
                self._sum1[i] = jnp.zeros_like(self._sum1[i])
            self._old_num_acc = self._num_acc
            self._num_acc = 0

    def _averaged(self):
        total_n = self._num_acc + 2 * self._old_num_acc
        outs = []
        for i in range(len(self.params)):
            s = self._sum1[i] + self._sum2[i] + self._sum3[i]
            outs.append(s / max(total_n, 1))
        return outs

    @contextlib.contextmanager
    def apply(self, need_restore=True):
        """Swap params to their averaged values inside the context."""
        self._backup = [p._value for p in self.params]
        if self._num_acc + self._old_num_acc > 0:
            for p, avg in zip(self.params, self._averaged()):
                p._value = avg
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self):
        if self._backup is not None:
            for p, v in zip(self.params, self._backup):
                p._value = v
            self._backup = None


def average_accumulates(param, sum1, sum2, sum3, num_acc, old_num_acc,
                        num_updates, average_window, max_average_window,
                        min_average_window):
    """Functional form of the reference average_accumulates op (one
    param): returns updated (sum1, sum2, sum3, num_acc, old_num_acc)."""
    num_updates = int(num_updates)
    num_acc = int(num_acc) + 1
    window = max(min_average_window,
                 min(max_average_window, int(num_updates * average_window)))
    s1 = jnp.asarray(sum1) + jnp.asarray(
        param._value if isinstance(param, Tensor) else param)
    s2, s3 = jnp.asarray(sum2), jnp.asarray(sum3)
    old = int(old_num_acc)
    if num_acc >= window:
        s3, s2, s1 = s2, s1, jnp.zeros_like(s1)
        old = num_acc
        num_acc = 0
    return s1, s2, s3, num_acc, old
