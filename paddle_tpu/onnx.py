"""paddle.onnx namespace.

Parity: reference python/paddle/onnx/export.py — `paddle.onnx.export`
delegates to the external paddle2onnx package. Neither onnx nor
paddle2onnx ships in this environment (gated per packaging policy): the
portable serialized format of the TPU build is StableHLO via
paddle.jit.save, which this export() produces alongside a clear message
when ONNX itself is requested.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Export `layer` for deployment (reference onnx/export.py export).

    Without the onnx/paddle2onnx packages installed this saves the
    portable StableHLO artifact at `path` (loadable by paddle.jit.load,
    the C/C++/Go inference APIs, and any StableHLO consumer) and raises
    only if the caller explicitly requires a .onnx file.
    """
    if path.endswith(".onnx"):
        raise RuntimeError(
            "ONNX export needs the onnx/paddle2onnx packages (not shipped "
            "in this environment). The portable artifact here is StableHLO:"
            " call paddle.onnx.export(layer, path_without_suffix, "
            "input_spec=...) or paddle.jit.save directly")
    from . import jit

    jit.save(layer, path, input_spec=input_spec)
    return path
