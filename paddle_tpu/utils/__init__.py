"""paddle.utils (reference python/paddle/utils/)."""
from __future__ import annotations


def try_import(name):
    import importlib

    try:
        return importlib.import_module(name)
    except ImportError as e:
        raise ImportError(
            "Optional dependency %r is not installed" % name) from e


def unique_name(prefix="tmp"):
    global _UNIQUE_COUNTER
    _UNIQUE_COUNTER += 1
    return "%s_%d" % (prefix, _UNIQUE_COUNTER)


_UNIQUE_COUNTER = 0


def flatten(nest):
    import jax

    from ..core.tensor import Tensor

    leaves, _ = jax.tree_util.tree_flatten(
        nest, is_leaf=lambda x: isinstance(x, Tensor))
    return leaves


def pack_sequence_as(structure, flat):
    import jax

    from ..core.tensor import Tensor

    _, treedef = jax.tree_util.tree_flatten(
        structure, is_leaf=lambda x: isinstance(x, Tensor))
    return jax.tree_util.tree_unflatten(treedef, flat)


def run_check():
    """paddle.utils.run_check analog: verifies device visibility + a matmul."""
    import jax
    import jax.numpy as jnp

    devs = jax.devices()
    x = jnp.ones((128, 128))
    y = (x @ x).block_until_ready()
    print("paddle_tpu is installed successfully! devices:", devs)
    return True


class deprecated:
    def __init__(self, since=None, update_to=None, reason=None):
        self.update_to = update_to

    def __call__(self, fn):
        return fn

from . import cpp_extension  # noqa: F401
