"""paddle.utils (reference python/paddle/utils/)."""
from __future__ import annotations

import functools
import warnings

from . import dlpack, unique_name  # noqa: F401
from .install_check import run_check  # noqa: F401


_deprecated_seen = set()


def deprecated(update_to="", since="", reason="", level=0):
    """Decorator marking an API deprecated (reference utils/deprecated.py):
    warns once per call site with the replacement hint."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            import sys

            frame = sys._getframe(1)
            key = (fn, frame.f_code.co_filename, frame.f_lineno)
            if key not in _deprecated_seen:
                _deprecated_seen.add(key)
                msg = "API %r is deprecated" % fn.__name__
                if since:
                    msg += " since %s" % since
                if update_to:
                    msg += ", use %r instead" % update_to
                if reason:
                    msg += " (%s)" % reason
                # visible even outside __main__, WITHOUT permanently
                # mutating the process-global filter list (the reference
                # simplefilter('always') leaks past user ignores)
                with warnings.catch_warnings():
                    warnings.simplefilter("always", DeprecationWarning)
                    warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return wrapper

    return deco


def try_import(name):
    import importlib

    try:
        return importlib.import_module(name)
    except ImportError as e:
        raise ImportError(
            "Optional dependency %r is not installed" % name) from e


def flatten(nest):
    import jax

    from ..core.tensor import Tensor

    leaves, _ = jax.tree_util.tree_flatten(
        nest, is_leaf=lambda x: isinstance(x, Tensor))
    return leaves


def pack_sequence_as(structure, flat):
    import jax

    from ..core.tensor import Tensor

    _, treedef = jax.tree_util.tree_flatten(
        structure, is_leaf=lambda x: isinstance(x, Tensor))
    return jax.tree_util.tree_unflatten(treedef, flat)


from . import cpp_extension  # noqa: F401
