"""Custom C++ operator loading — paddle.utils.cpp_extension analog.

Parity: reference custom-op runtime loading
(/root/reference/paddle/fluid/framework/custom_operator.cc: user .so
built against paddle/extension.h, REGISTER_OP'd at dlopen time) and
python/paddle/utils/cpp_extension/ (JIT g++ build + load).

TPU-native design: the custom kernel runs on the HOST (the reference's
CPU custom-op path); inside jit it is staged as jax.pure_callback, so
compiled programs call back to the C function with device arrays
round-tripped through host memory — the same data path the reference
uses for CPU custom kernels inside GPU graphs. Gradients come from an
optional `<name>_grad` symbol and register as a custom VJP.

C ABI (fp32, shape-preserving — the dominant custom-op shape in the
reference's tests):
    void NAME(const float* x, float* y, int64_t n);            // unary
    void NAME_grad(const float* x, const float* gy, float* gx,
                   int64_t n);                                  // vjp
    void NAME(const float* x, const float* y, float* z,
              int64_t n);                                       // binary
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import primitive
from ..core.tensor import Tensor

_BUILD_DIR = os.path.join(os.path.expanduser("~"), ".cache",
                          "paddle_tpu_extensions")


def _build_so(name, sources, extra_cflags=None, build_directory=None):
    out_dir = build_directory or _BUILD_DIR
    os.makedirs(out_dir, exist_ok=True)
    tag = hashlib.sha1(
        ("".join(sorted(sources)) + str(extra_cflags)).encode()
    ).hexdigest()[:10]
    so_path = os.path.join(out_dir, "%s_%s.so" % (name, tag))
    srcs_mtime = max(os.path.getmtime(s) for s in sources)
    if not os.path.exists(so_path) or \
            os.path.getmtime(so_path) < srcs_mtime:
        cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-shared",
               "-o", so_path] + list(sources) + (extra_cflags or [])
        r = subprocess.run(cmd, capture_output=True, text=True)
        if r.returncode != 0:
            raise RuntimeError("cpp_extension build failed:\n%s" % r.stderr)
    return so_path


class CustomOpModule:
    """Handle over a loaded .so; get_op() binds + registers ops."""

    def __init__(self, name, so_path):
        self.name = name
        self.so_path = so_path
        self._lib = ctypes.CDLL(so_path)
        self._ops = {}

    def _sym(self, name):
        try:
            return getattr(self._lib, name)
        except AttributeError:
            return None

    def get_op(self, op_name, arity=1):
        """Bind symbol `op_name` (and `<op_name>_grad` if exported) and
        register it as a framework primitive. Returns the op callable."""
        if op_name in self._ops:
            return self._ops[op_name]
        fn = self._sym(op_name)
        if fn is None:
            raise ValueError("symbol %r not exported by %s"
                             % (op_name, self.so_path))
        c = ctypes
        if arity == 1:
            fn.argtypes = [c.c_void_p, c.c_void_p, c.c_longlong]
        else:
            fn.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p,
                           c.c_longlong]
        fn.restype = None
        grad = self._sym(op_name + "_grad")
        if grad is not None:
            grad.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p,
                             c.c_longlong]
            grad.restype = None

        def host_call(*arrays):
            arrays = [np.ascontiguousarray(a, np.float32) for a in arrays]
            out = np.empty_like(arrays[0])
            ptrs = [a.ctypes.data for a in arrays] + [out.ctypes.data]
            fn(*ptrs, arrays[0].size)
            return out

        def host_grad(x, gy):
            x = np.ascontiguousarray(x, np.float32)
            gy = np.ascontiguousarray(gy, np.float32)
            gx = np.empty_like(x)
            grad(x.ctypes.data, gy.ctypes.data, gx.ctypes.data, x.size)
            return gx

        def stage(*vals):
            shape = jax.ShapeDtypeStruct(jnp.shape(vals[0]), jnp.float32)
            return jax.pure_callback(host_call, shape, *vals)

        if grad is not None:
            @jax.custom_vjp
            def core(*vals):
                return stage(*vals)

            def core_fwd(*vals):
                return stage(*vals), vals

            def core_bwd(res, gy):
                x = res[0]
                shape = jax.ShapeDtypeStruct(jnp.shape(x), jnp.float32)
                gx = jax.pure_callback(host_grad, shape, x, gy)
                # only the first operand gets a custom grad (reference
                # custom grad kernels declare their own outputs)
                return (gx,) + tuple(
                    jnp.zeros_like(v) for v in res[1:])

            core.defvjp(core_fwd, core_bwd)
        else:
            def core(*vals):
                return stage(*vals)

        @primitive(name="custom_" + op_name)
        def op(*args):
            return core(*(jnp.asarray(a) for a in args))

        self._ops[op_name] = op
        return op


def load(name, sources, extra_cflags=None, extra_cuda_cflags=None,
         extra_ldflags=None, build_directory=None, verbose=False):
    """JIT-build + load a custom-op .so (reference
    utils/cpp_extension/cpp_extension.py load)."""
    so_path = _build_so(name, sources, extra_cflags, build_directory)
    return CustomOpModule(name, so_path)


def load_op_library(so_path):
    """Load a prebuilt custom-op library (reference
    paddle.utils.load_op_library / custom_operator.cc dlopen path)."""
    return CustomOpModule(os.path.basename(so_path), so_path)
