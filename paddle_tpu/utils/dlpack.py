"""paddle.utils.dlpack — zero-copy tensor exchange.

Parity: reference python/paddle/utils/dlpack.py (to_dlpack/from_dlpack
over the DLPack capsule protocol). jax arrays implement the standard
`__dlpack__` protocol, so interchange with torch/numpy/cupy works
without a copy where device semantics allow.
"""
from __future__ import annotations

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Tensor -> DLPack-protocol object (reference dlpack.py:27 returns a
    legacy capsule; modern consumers — torch.from_dlpack, np.from_dlpack,
    jax — take protocol objects carrying __dlpack__/__dlpack_device__,
    which the underlying array already is, and a bare capsule cannot
    provide __dlpack_device__)."""
    from ..core.tensor import Tensor

    return x._value if isinstance(x, Tensor) else x


def from_dlpack(dlpack):
    """DLPack capsule (or any __dlpack__-protocol object, e.g. a torch
    tensor) -> Tensor (reference dlpack.py:64)."""
    import jax.dlpack

    from ..core.tensor import Tensor

    return Tensor(jax.dlpack.from_dlpack(dlpack))
