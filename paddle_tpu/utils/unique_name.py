"""paddle.utils.unique_name — namespaced unique-name generation.

Parity: reference python/paddle/utils/unique_name.py (generate/guard/
switch over a per-key counter map; guard() scopes a fresh generator so
two programs built under separate guards get identical names).
"""
from __future__ import annotations

import contextlib

__all__ = ["generate", "guard", "switch"]


class UniqueNameGenerator:
    def __init__(self, prefix=""):
        self.ids = {}
        self.prefix = prefix

    def __call__(self, key):
        n = self.ids.get(key, 0)
        self.ids[key] = n + 1
        return "%s%s_%d" % (self.prefix, key, n)


_generator = UniqueNameGenerator()


def generate(key):
    """Unique name for `key`: key_0, key_1, ... (reference generate)."""
    return _generator(key)


def switch(new_generator=None):
    """Swap the active generator, returning the old one."""
    global _generator
    old = _generator
    _generator = new_generator or UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    """Scope a fresh generator (reference guard): names inside restart
    from _0; a string argument becomes the prefix."""
    if isinstance(new_generator, str):
        new_generator = UniqueNameGenerator(new_generator)
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
