"""paddle.utils.run_check — install sanity check.

Parity: reference python/paddle/utils/install_check.py run_check():
verify the framework computes on this machine's devices — a tiny layer
fwd+bwd on one device, then a sharded run over every local device (the
reference tries fleet data-parallel the same way).
"""
from __future__ import annotations

__all__ = ["run_check"]


def run_check():
    import jax
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F

    print("Running verify PaddlePaddle(TPU) program ...")
    paddle.seed(0)
    m = nn.Linear(4, 2)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    loss = F.square_error_cost(
        m(x), paddle.to_tensor(np.zeros((2, 2), np.float32))).mean()
    loss.backward()
    assert m.weight.grad is not None
    n = len(jax.devices())
    if n > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()), ("dp",))
        xb = jax.device_put(
            np.ones((n * 2, 4), np.float32), NamedSharding(mesh, P("dp")))
        out = jax.jit(lambda a: (a @ np.ones((4, 2), np.float32)).sum())(xb)
        assert np.isfinite(float(out))
        print("PaddlePaddle(TPU) works well on %d devices." % n)
    else:
        print("PaddlePaddle(TPU) works well on 1 device.")
    print("PaddlePaddle(TPU) is installed successfully!")
