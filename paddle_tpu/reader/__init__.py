"""paddle.reader — legacy reader-decorator pipeline combinators.

Parity: reference python/paddle/reader/decorator.py (cache, map_readers,
shuffle, chain, compose, buffered, firstn, xmap_readers). Pure-python
sample pipelines kept for ported code; new code uses paddle.io.
"""
from __future__ import annotations

import itertools
import random as _random
import threading
import queue as _queue

__all__ = ["cache", "map_readers", "shuffle", "chain", "compose",
           "buffered", "firstn", "xmap_readers"]


def cache(reader):
    """Materialize once, replay from memory (reference decorator.py:45)."""
    all_data = []
    filled = []

    def cached():
        if not filled:
            all_data.extend(reader())
            filled.append(True)
        return iter(all_data)

    return cached


def map_readers(func, *readers):
    """Zip readers, map func over the tuples (reference :85)."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    """Buffered shuffle (reference :127)."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return data_reader


def chain(*readers):
    """Concatenate readers (reference :176)."""

    def reader():
        return itertools.chain(*[r() for r in readers])

    return reader


def compose(*readers, **kwargs):
    """Parallel composition: yield tuples drawing one sample from each
    (reference compose; check_alignment=True raises on ragged ends)."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    _missing = object()  # private sentinel: readers may yield None

    def reader():
        rs = [r() for r in readers]
        if check_alignment:
            for items in itertools.zip_longest(*rs, fillvalue=_missing):
                if any(i is _missing for i in items):
                    raise RuntimeError(
                        "readers have different lengths (set "
                        "check_alignment=False to truncate)")
                yield sum((make_tuple(i) for i in items), ())
        else:
            for items in zip(*rs):
                yield sum((make_tuple(i) for i in items), ())

    return reader


def buffered(reader, size):
    """Prefetch into a bounded queue on a worker thread (reference
    buffered)."""

    class _End:
        pass

    class _Err:
        def __init__(self, e):
            self.e = e

    def data_reader():
        q = _queue.Queue(maxsize=size)
        stop = threading.Event()

        def put_or_stop(item):
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except _queue.Full:
                    continue
            return False

        def fill():
            try:
                for d in reader():
                    if not put_or_stop(d):
                        return
            except BaseException as e:  # surface in the consumer
                put_or_stop(_Err(e))
                return
            put_or_stop(_End)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        try:
            while True:
                e = q.get()
                if e is _End:
                    break
                if isinstance(e, _Err):
                    raise e.e
                yield e
        finally:
            # consumer abandoned early (e.g. firstn): release the fill
            # thread instead of leaving it blocked on a full queue
            stop.set()

    return data_reader


def firstn(reader, n):
    """First n samples (reference firstn)."""

    def data_reader():
        return itertools.islice(reader(), n)

    return data_reader


def xmap_readers(mapper, reader, process_num, buffer_size,
                 order=False):
    """Thread-pool map over a reader (reference xmap_readers). `order`
    preserves input order."""
    from concurrent.futures import ThreadPoolExecutor

    def data_reader():
        import collections

        from concurrent.futures import FIRST_COMPLETED, wait

        with ThreadPoolExecutor(max_workers=process_num) as pool:
            # bounded window either way (an eager pool.map would drain
            # infinite readers); order=False yields as-completed
            window = collections.deque()
            for d in reader():
                window.append(pool.submit(mapper, d))
                if len(window) >= max(buffer_size, 1):
                    if order:
                        yield window.popleft().result()
                    else:
                        done, _ = wait(window, return_when=FIRST_COMPLETED)
                        f = next(iter(done))
                        window.remove(f)
                        yield f.result()
            while window:
                if order:
                    yield window.popleft().result()
                else:
                    done, _ = wait(window, return_when=FIRST_COMPLETED)
                    f = next(iter(done))
                    window.remove(f)
                    yield f.result()

    return data_reader
