"""paddle_tpu.signal — STFT/ISTFT.

Parity: reference python/paddle/signal.py (stft :161, istft :324) backed by
frame/overlap_add ops (phi kernels frame_kernel, overlap_add_kernel).
TPU-native: framing is a gather-free as_strided-style reshape + rfft; XLA
maps the batched FFTs onto the VPU.
"""
from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import primitive

_A = jnp.asarray


def _frame(x, frame_length, hop_length):
    """[..., T] -> [..., n_frames, frame_length]"""
    t = x.shape[-1]
    n_frames = 1 + (t - frame_length) // hop_length
    idx = (jnp.arange(n_frames)[:, None] * hop_length
           + jnp.arange(frame_length)[None, :])
    return x[..., idx]


@primitive
def frame(x, frame_length, hop_length, axis=-1):
    x = _A(x)
    if axis not in (-1, x.ndim - 1):
        x = jnp.moveaxis(x, axis, -1)
    out = _frame(x, frame_length, hop_length)
    if axis not in (-1, x.ndim - 1):
        out = jnp.moveaxis(out, -2, axis)
    return out


@primitive
def overlap_add(x, hop_length, axis=-1):
    """[..., n_frames, frame_length] -> [..., T] (reference overlap_add)."""
    x = _A(x)
    *batch, n_frames, frame_length = x.shape
    t = (n_frames - 1) * hop_length + frame_length
    out = jnp.zeros(tuple(batch) + (t,), x.dtype)
    idx = (jnp.arange(n_frames)[:, None] * hop_length
           + jnp.arange(frame_length)[None, :])
    return out.at[..., idx.reshape(-1)].add(
        x.reshape(tuple(batch) + (-1,)))


@primitive
def stft_op(x, window, n_fft, hop_length, center, pad_mode, onesided):
    x = _A(x)
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pad, mode=pad_mode)
    frames = _frame(x, n_fft, hop_length)          # [..., n_frames, n_fft]
    if window is not None:
        frames = frames * _A(window)
    fftfn = jnp.fft.rfft if onesided else jnp.fft.fft
    spec = fftfn(frames, axis=-1)                  # [..., n_frames, bins]
    return jnp.swapaxes(spec, -1, -2)              # [..., bins, n_frames]


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """reference signal.py:161 stft. x: [..., T] real or complex Tensor."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is not None and win_length != n_fft:
        # center-pad the window to n_fft, as the reference does
        import numpy as np

        w = window.numpy() if hasattr(window, "numpy") else np.asarray(window)
        lpad = (n_fft - win_length) // 2
        w = np.pad(w, (lpad, n_fft - win_length - lpad))
        window = w
    out = stft_op(x, window, n_fft=n_fft, hop_length=hop_length,
                  center=center, pad_mode=pad_mode, onesided=onesided)
    if normalized:
        import math

        out = out / math.sqrt(n_fft)
    return out


@primitive
def istft_op(spec, window, n_fft, hop_length, center, onesided, length):
    spec = _A(spec)
    frames_f = jnp.swapaxes(spec, -1, -2)          # [..., n_frames, bins]
    ifftfn = jnp.fft.irfft if onesided else jnp.fft.ifft
    frames = ifftfn(frames_f, n=n_fft, axis=-1)
    if not onesided:
        frames = frames.real
    if window is not None:
        w = _A(window)
        frames = frames * w
        wsq = jnp.broadcast_to(w * w, frames.shape)
    else:
        wsq = jnp.ones_like(frames)
    *batch, n_frames, _ = frames.shape
    t = (n_frames - 1) * hop_length + n_fft
    idx = (jnp.arange(n_frames)[:, None] * hop_length
           + jnp.arange(n_fft)[None, :]).reshape(-1)
    num = jnp.zeros(tuple(batch) + (t,), frames.dtype).at[..., idx].add(
        frames.reshape(tuple(batch) + (-1,)))
    den = jnp.zeros(tuple(batch) + (t,), frames.dtype).at[..., idx].add(
        wsq.reshape(tuple(batch) + (-1,)))
    out = num / jnp.maximum(den, 1e-10)
    if center:
        out = out[..., n_fft // 2:]
        if length is not None:
            out = out[..., :length]
        else:
            out = out[..., :t - n_fft]
    elif length is not None:
        out = out[..., :length]
    return out


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """reference signal.py:324 istft (overlap-add with window-square
    normalization)."""
    hop_length = hop_length or n_fft // 4
    if normalized:
        import math

        x = x * math.sqrt(n_fft)
    return istft_op(x, window, n_fft=n_fft, hop_length=hop_length,
                    center=center, onesided=onesided, length=length)
