"""paddle.nn.functional namespace."""
from .activation import (  # noqa: F401
    celu,
    elu,
    gelu,
    glu,
    gumbel_softmax,
    hardshrink,
    hardsigmoid,
    hardswish,
    hardtanh,
    leaky_relu,
    log_softmax,
    maxout,
    mish,
    prelu,
    relu,
    relu6,
    rrelu,
    selu,
    sigmoid,
    silu,
    softmax,
    softplus,
    softshrink,
    softsign,
    swish,
    tanh,
    tanhshrink,
    thresholded_relu,
)
from .attention import (  # noqa: F401
    scaled_dot_product_attention,
    sequence_parallel_attention,
    sparse_attention,
    variable_length_attention,
)
from ..decode import gather_tree  # noqa: F401
from ...ops.manipulation import diag_embed  # noqa: F401
from .common import (  # noqa: F401
    affine_grid,
    sequence_mask,
    unfold,
    zeropad2d,
    alpha_dropout,
    bilinear,
    channel_shuffle,
    fold,
    grid_sample,
    temporal_shift,
    cosine_similarity,
    dropout,
    dropout2d,
    dropout3d,
    embedding,
    interpolate,
    label_smooth,
    linear,
    normalize,
    pixel_shuffle,
    pixel_unshuffle,
    upsample,
)
from .conv import (  # noqa: F401
    conv1d,
    conv1d_transpose,
    conv2d,
    conv2d_transpose,
    deformable_conv,
    conv3d,
    conv3d_transpose,
)
from .loss import (  # noqa: F401
    multi_label_soft_margin_loss,
    npair_loss,
    soft_margin_loss,
    binary_cross_entropy,
    class_center_sample,
    ctc_loss,
    hsigmoid_loss,
    huber_loss,
    margin_cross_entropy,
    sigmoid_cross_entropy_with_logits,
    sigmoid_focal_loss,
    warpctc,
    binary_cross_entropy_with_logits,
    cosine_embedding_loss,
    cross_entropy,
    ctc_loss_dense,
    hinge_embedding_loss,
    kl_div,
    l1_loss,
    log_loss,
    margin_ranking_loss,
    mse_loss,
    nll_loss,
    smooth_l1_loss,
    softmax_with_cross_entropy,
    square_error_cost,
    triplet_margin_loss,
)
from .norm import (  # noqa: F401
    batch_norm_infer,
    batch_norm_train,
    group_norm,
    instance_norm,
    layer_norm,
    local_response_norm,
    rms_norm,
)
from .pooling import (  # noqa: F401
    adaptive_avg_pool1d,
    adaptive_avg_pool2d,
    adaptive_max_pool1d,
    adaptive_max_pool2d,
    avg_pool1d,
    avg_pool2d,
    avg_pool3d,
    max_pool1d,
    max_pool2d,
    max_pool3d,
    max_unpool2d,
)

from ...ops.manipulation import one_hot, pad  # noqa: F401


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW"):
    """Stateful batch_norm facade; layers use the split train/infer kernels."""
    if not training:
        return batch_norm_infer(x, running_mean, running_var, weight, bias,
                                epsilon=epsilon, data_format=data_format)
    out, mean, var = batch_norm_train(x, weight, bias, epsilon=epsilon,
                                      data_format=data_format)
    # update running stats in-place on the provided tensors
    running_mean.set_value(
        running_mean._value * momentum + mean._value * (1.0 - momentum))
    running_var.set_value(
        running_var._value * momentum + var._value * (1.0 - momentum))
    return out

from .activation import log_sigmoid, _inplace  # noqa: E402
from . import activation as _act  # noqa: E402
from .pooling import (  # noqa: F401,E402
    adaptive_avg_pool3d,
    adaptive_max_pool3d,
    max_unpool1d,
    max_unpool3d,
)
from .loss import (  # noqa: F401,E402
    dice_loss,
    multi_margin_loss,
    pairwise_distance,
    rnnt_loss,
    triplet_margin_with_distance_loss,
)

relu_ = _inplace(relu)
elu_ = _inplace(elu)
tanh_ = _inplace(tanh)
softmax_ = _inplace(softmax)
del _inplace, _act
