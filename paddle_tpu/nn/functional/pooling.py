"""Pooling (reference python/paddle/nn/functional/pooling.py,
phi/kernels/pool_kernel). lax.reduce_window lowers to the TPU vector unit."""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...core.dispatch import primitive

_A = jnp.asarray


def _norm(v, n):
    if isinstance(v, (int, float)):
        return (int(v),) * n
    v = tuple(int(i) for i in v)
    return v * n if len(v) == 1 else v


def _pads(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def _pool(x, kernel, stride, padding, n, reducer, init, channel_last):
    x = _A(x)
    kernel = _norm(kernel, n)
    stride = _norm(stride if stride is not None else kernel, n)
    if channel_last:
        dims = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
    else:
        dims = (1, 1) + kernel
        strides = (1, 1) + stride
    pads = _pads(padding, n)
    if isinstance(pads, str):
        pad_cfg = pads
    else:
        pad_cfg = ([(0, 0), (0, 0)] + pads) if not channel_last else (
            [(0, 0)] + pads + [(0, 0)])
    return jax.lax.reduce_window(x, init, reducer, dims, strides, pad_cfg)


@primitive
def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW"):
    xv = _A(x)
    if return_mask:
        # max_pool2d_with_index (reference phi/kernels/pool_kernel.h
        # MaxPoolWithIndex): indices are flattened positions in the
        # input H*W plane, as max_unpool2d expects.
        if data_format == "NHWC":
            xv = jnp.transpose(xv, (0, 3, 1, 2))
        ks = _norm(kernel_size, 2)
        st = _norm(stride if stride is not None else kernel_size, 2)
        pd = _pads(padding, 2)
        N, C, H, W = xv.shape
        if isinstance(pd, str):
            if pd == "SAME":
                # same split reduce_window uses for SAME padding
                pd = []
                for size, k, s in ((H, ks[0], st[0]), (W, ks[1], st[1])):
                    out_sz = -(-size // s)
                    total = max((out_sz - 1) * s + k - size, 0)
                    pd.append((total // 2, total - total // 2))
            else:  # VALID
                pd = [(0, 0), (0, 0)]
        # finite lowest (NOT -inf): the patch extraction lowers to a
        # convolution with 0/1 filters and -inf * 0 would produce NaN
        neg = float(jnp.finfo(jnp.float32).min)
        xp = jnp.pad(xv.astype(jnp.float32),
                     ((0, 0), (0, 0), pd[0], pd[1]),
                     constant_values=neg)
        patches = jax.lax.conv_general_dilated_patches(
            xp, filter_shape=tuple(ks), window_strides=tuple(st),
            padding="VALID", dimension_numbers=("NCHW", "OIHW", "NCHW"))
        n, ckk, oh, ow = patches.shape
        p = patches.reshape(N, C, ks[0] * ks[1], oh, ow)
        out = jnp.max(p, axis=2)
        arg = jnp.argmax(p, axis=2)  # local patch index
        di = arg // ks[1]
        dj = arg % ks[1]
        ohs = jnp.arange(oh)[None, None, :, None]
        ows = jnp.arange(ow)[None, None, None, :]
        iy = ohs * st[0] - pd[0][0] + di
        ix = ows * st[1] - pd[1][0] + dj
        mask = (iy * W + ix).astype(jnp.int32)
        out = out.astype(xv.dtype)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
            mask = jnp.transpose(mask, (0, 2, 3, 1))
        return out, mask
    out = _pool(xv, kernel_size, stride, padding, 2, jax.lax.max,
                -jnp.inf if jnp.issubdtype(xv.dtype, jnp.floating)
                else jnp.iinfo(xv.dtype).min,
                data_format == "NHWC")
    return out.astype(xv.dtype)


@primitive
def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None):
    """Inverse of max_pool2d(return_mask=True) (reference
    unpool_kernel.h): scatter pooled values back to their argmax
    positions; everything else zero."""
    xv = _A(x)
    idx = _A(indices).astype(jnp.int32)
    if data_format == "NHWC":
        xv = jnp.transpose(xv, (0, 3, 1, 2))
        idx = jnp.transpose(idx, (0, 3, 1, 2))
    ks = _norm(kernel_size, 2)
    st = _norm(stride if stride is not None else kernel_size, 2)
    N, C, oh, ow = xv.shape
    if output_size is None:
        pd = _pads(padding, 2)
        pd = pd if not isinstance(pd, str) else [(0, 0), (0, 0)]
        H = (oh - 1) * st[0] - pd[0][0] - pd[0][1] + ks[0]
        W = (ow - 1) * st[1] - pd[1][0] - pd[1][1] + ks[1]
    else:
        H, W = [int(s) for s in output_size[-2:]]
    flat = jnp.zeros((N, C, H * W), xv.dtype)
    out = flat.at[
        jnp.arange(N)[:, None, None],
        jnp.arange(C)[None, :, None],
        idx.reshape(N, C, -1),
    ].add(xv.reshape(N, C, -1))
    out = out.reshape(N, C, H, W)
    if data_format == "NHWC":
        out = jnp.transpose(out, (0, 2, 3, 1))
    return out


@primitive
def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCL"):
    x = _A(x)
    return _pool(x, kernel_size, stride, padding, 1, jax.lax.max, -jnp.inf,
                 data_format == "NLC").astype(x.dtype)


@primitive
def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW"):
    x = _A(x)
    return _pool(x, kernel_size, stride, padding, 3, jax.lax.max, -jnp.inf,
                 data_format == "NDHWC").astype(x.dtype)


def _avg_pool(x, kernel_size, stride, padding, n, exclusive, channel_last):
    x = _A(x)
    s = _pool(x, kernel_size, stride, padding, n, jax.lax.add, 0.0, channel_last)
    if exclusive:
        ones = jnp.ones_like(x)
        cnt = _pool(ones, kernel_size, stride, padding, n, jax.lax.add, 0.0,
                    channel_last)
        return (s / cnt).astype(x.dtype)
    k = _norm(kernel_size, n)
    return (s / float(np.prod(k))).astype(x.dtype)


@primitive
def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL"):
    return _avg_pool(x, kernel_size, stride, padding, 1, exclusive,
                     data_format == "NLC")


@primitive
def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW"):
    return _avg_pool(x, kernel_size, stride, padding, 2, exclusive,
                     data_format == "NHWC")


@primitive
def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW"):
    return _avg_pool(x, kernel_size, stride, padding, 3, exclusive,
                     data_format == "NDHWC")


def _adaptive_sizes(in_size, out_size):
    # adaptive pooling = variable windows; when divisible use uniform windows
    return in_size % out_size == 0


@primitive
def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    x = _A(x)
    out_hw = _norm(output_size, 2)
    channel_last = data_format == "NHWC"
    h, w = (x.shape[1], x.shape[2]) if channel_last else (x.shape[2], x.shape[3])
    if h % out_hw[0] == 0 and w % out_hw[1] == 0:
        kh, kw = h // out_hw[0], w // out_hw[1]
        return _avg_pool(x, (kh, kw), (kh, kw), 0, 2, False, channel_last)
    # general case: mean over per-output-bin slices via resize-style gather
    return _adaptive_pool_general(x, out_hw, channel_last, "avg")


@primitive
def adaptive_max_pool2d(x, output_size, return_mask=False, data_format="NCHW"):
    x = _A(x)
    out_hw = _norm(output_size, 2)
    channel_last = data_format == "NHWC"
    h, w = (x.shape[1], x.shape[2]) if channel_last else (x.shape[2], x.shape[3])
    if h % out_hw[0] == 0 and w % out_hw[1] == 0:
        kh, kw = h // out_hw[0], w // out_hw[1]
        return _pool(x, (kh, kw), (kh, kw), 0, 2, jax.lax.max, -jnp.inf,
                     channel_last).astype(x.dtype)
    return _adaptive_pool_general(x, out_hw, channel_last, "max")


def _adaptive_pool_general(x, out_hw, channel_last, mode):
    h_ax, w_ax = (1, 2) if channel_last else (2, 3)
    h, w = x.shape[h_ax], x.shape[w_ax]

    def bins(in_size, out_size, axis):
        starts = (np.arange(out_size) * in_size) // out_size
        ends = ((np.arange(out_size) + 1) * in_size + out_size - 1) // out_size
        return starts, ends

    hs, he = bins(h, out_hw[0], h_ax)
    ws, we = bins(w, out_hw[1], w_ax)
    rows = []
    for i in range(out_hw[0]):
        cols = []
        for j in range(out_hw[1]):
            sl = [slice(None)] * x.ndim
            sl[h_ax] = slice(int(hs[i]), int(he[i]))
            sl[w_ax] = slice(int(ws[j]), int(we[j]))
            patch = x[tuple(sl)]
            red = jnp.mean if mode == "avg" else jnp.max
            cols.append(red(patch, axis=(h_ax, w_ax), keepdims=True))
        rows.append(jnp.concatenate(cols, axis=w_ax))
    return jnp.concatenate(rows, axis=h_ax)


@primitive
def adaptive_avg_pool1d(x, output_size):
    x = _A(x)
    out = _norm(output_size, 1)[0]
    l = x.shape[2]
    if l % out == 0:
        k = l // out
        return _avg_pool(x, (k,), (k,), 0, 1, False, False)
    x4 = x[:, :, None, :]
    o = _adaptive_pool_general(x4, (1, out), False, "avg")
    return o[:, :, 0, :]


@primitive
def adaptive_max_pool1d(x, output_size, return_mask=False):
    x = _A(x)
    out = _norm(output_size, 1)[0]
    l = x.shape[2]
    if l % out == 0:
        k = l // out
        return _pool(x, (k,), (k,), 0, 1, jax.lax.max, -jnp.inf, False).astype(x.dtype)
    x4 = x[:, :, None, :]
    o = _adaptive_pool_general(x4, (1, out), False, "max")
    return o[:, :, 0, :]


def _adaptive_pool_nd(x, out_sizes, channel_last, mode, nd):
    """General N-d adaptive pool: mean/max over per-output-bin slices
    (bins per reference adaptive_pool semantics: start = floor(i*in/out),
    end = ceil((i+1)*in/out)). Shares the bin math with the 2d
    _adaptive_pool_general; assembled via one stack+reshape."""
    import itertools

    sp_axes = list(range(1, 1 + nd)) if channel_last \
        else list(range(2, 2 + nd))
    N, C = x.shape[0], (x.shape[-1] if channel_last else x.shape[1])

    def bins(in_size, out_size):
        starts = (np.arange(out_size) * in_size) // out_size
        ends = ((np.arange(out_size) + 1) * in_size
                + out_size - 1) // out_size
        return starts, ends

    per_axis = [bins(x.shape[ax], out_sizes[i])
                for i, ax in enumerate(sp_axes)]
    vals = []
    for coords in itertools.product(*[range(s) for s in out_sizes]):
        sl = [slice(None)] * x.ndim
        for i, ax in enumerate(sp_axes):
            st, en = per_axis[i]
            sl[ax] = slice(int(st[coords[i]]), int(en[coords[i]]))
        piece = x[tuple(sl)]
        vals.append(piece.mean(axis=tuple(sp_axes)) if mode == "avg"
                    else piece.max(axis=tuple(sp_axes)))  # [N, C]
    out = jnp.stack(vals, axis=-1).reshape((N, C) + tuple(out_sizes))
    if channel_last:
        out = jnp.moveaxis(out, 1, -1)
    return out


@primitive
def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    """reference adaptive_avg_pool3d (pool_kernel.h adaptive path)."""
    x = _A(x)
    out = _norm(output_size, 3)
    channel_last = data_format == "NDHWC"
    sp = (x.shape[1:4] if channel_last else x.shape[2:5])
    if (jnp.issubdtype(x.dtype, jnp.floating)
            and all(sp[i] % out[i] == 0 for i in range(3))):
        # divisible: one strided reduce-window instead of prod(out) slices
        # (float only — the window init values are float)
        ks = tuple(sp[i] // out[i] for i in range(3))
        return _avg_pool(x, ks, ks, 0, 3, False, channel_last)
    return _adaptive_pool_nd(x, list(out), channel_last, "avg", 3)


@primitive
def adaptive_max_pool3d(x, output_size, return_mask=False,
                        data_format="NCDHW"):
    """reference adaptive_max_pool3d; return_mask unsupported for the
    general 3d path (reference GPU kernel also computes it separately)."""
    x = _A(x)
    out = _norm(output_size, 3)
    if return_mask:
        raise NotImplementedError(
            "adaptive_max_pool3d(return_mask=True): indices for the "
            "variable-window 3d path are not provided; use max_pool3d")
    channel_last = data_format == "NDHWC"
    sp = (x.shape[1:4] if channel_last else x.shape[2:5])
    if (jnp.issubdtype(x.dtype, jnp.floating)
            and all(sp[i] % out[i] == 0 for i in range(3))):
        ks = tuple(sp[i] // out[i] for i in range(3))
        return _pool(x, ks, ks, 0, 3, jax.lax.max, -jnp.inf,
                     channel_last).astype(x.dtype)
    return _adaptive_pool_nd(x, list(out), channel_last, "max", 3)


def _max_unpool_nd(x, indices, spatial_out):
    """Scatter pooled values back by flat spatial index (reference
    unpool_kernel.h), any spatial rank; channel-first layouts only."""
    xv = _A(x)
    idx = _A(indices).astype(jnp.int32)
    N, C = xv.shape[0], xv.shape[1]
    total = 1
    for s in spatial_out:
        total *= int(s)
    flat = jnp.zeros((N, C, total), xv.dtype)
    out = flat.at[
        jnp.arange(N)[:, None, None],
        jnp.arange(C)[None, :, None],
        idx.reshape(N, C, -1),
    ].add(xv.reshape(N, C, -1))
    return out.reshape((N, C) + tuple(int(s) for s in spatial_out))


@primitive
def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None):
    """reference max_unpool1d: inverse of max_pool1d(return_mask=True)."""
    if data_format != "NCL":
        raise ValueError(
            "max_unpool1d only supports NCL (reference check)")
    xv = _A(x)
    ks = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    st = ks if stride is None else (
        stride if isinstance(stride, int) else stride[0])
    pd = padding if isinstance(padding, int) else padding[0]
    L = (xv.shape[-1] - 1) * st - 2 * pd + ks if output_size is None \
        else int(output_size[-1])
    return _max_unpool_nd(x, indices, [L])


@primitive
def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None):
    """reference max_unpool3d: inverse of max_pool3d(return_mask=True)."""
    if data_format != "NCDHW":
        raise ValueError(
            "max_unpool3d only supports NCDHW (reference check)")
    xv = _A(x)
    ks = _norm(kernel_size, 3)
    st = _norm(stride if stride is not None else kernel_size, 3)
    pd = _norm(padding, 3)
    if output_size is None:
        spatial = [
            (xv.shape[2 + i] - 1) * st[i] - 2 * pd[i] + ks[i]
            for i in range(3)]
    else:
        spatial = [int(s) for s in output_size[-3:]]
    return _max_unpool_nd(x, indices, spatial)
