"""Pooling (reference python/paddle/nn/functional/pooling.py,
phi/kernels/pool_kernel). lax.reduce_window lowers to the TPU vector unit."""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...core.dispatch import primitive

_A = jnp.asarray


def _norm(v, n):
    if isinstance(v, (int, float)):
        return (int(v),) * n
    v = tuple(int(i) for i in v)
    return v * n if len(v) == 1 else v


def _pads(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def _pool(x, kernel, stride, padding, n, reducer, init, channel_last):
    x = _A(x)
    kernel = _norm(kernel, n)
    stride = _norm(stride if stride is not None else kernel, n)
    if channel_last:
        dims = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
    else:
        dims = (1, 1) + kernel
        strides = (1, 1) + stride
    pads = _pads(padding, n)
    if isinstance(pads, str):
        pad_cfg = pads
    else:
        pad_cfg = ([(0, 0), (0, 0)] + pads) if not channel_last else (
            [(0, 0)] + pads + [(0, 0)])
    return jax.lax.reduce_window(x, init, reducer, dims, strides, pad_cfg)


@primitive
def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW"):
    out = _pool(_A(x), kernel_size, stride, padding, 2, jax.lax.max,
                -jnp.inf if jnp.issubdtype(_A(x).dtype, jnp.floating) else jnp.iinfo(_A(x).dtype).min,
                data_format == "NHWC")
    return out.astype(_A(x).dtype)


@primitive
def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCL"):
    x = _A(x)
    return _pool(x, kernel_size, stride, padding, 1, jax.lax.max, -jnp.inf,
                 data_format == "NLC").astype(x.dtype)


@primitive
def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW"):
    x = _A(x)
    return _pool(x, kernel_size, stride, padding, 3, jax.lax.max, -jnp.inf,
                 data_format == "NDHWC").astype(x.dtype)


def _avg_pool(x, kernel_size, stride, padding, n, exclusive, channel_last):
    x = _A(x)
    s = _pool(x, kernel_size, stride, padding, n, jax.lax.add, 0.0, channel_last)
    if exclusive:
        ones = jnp.ones_like(x)
        cnt = _pool(ones, kernel_size, stride, padding, n, jax.lax.add, 0.0,
                    channel_last)
        return (s / cnt).astype(x.dtype)
    k = _norm(kernel_size, n)
    return (s / float(np.prod(k))).astype(x.dtype)


@primitive
def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL"):
    return _avg_pool(x, kernel_size, stride, padding, 1, exclusive,
                     data_format == "NLC")


@primitive
def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW"):
    return _avg_pool(x, kernel_size, stride, padding, 2, exclusive,
                     data_format == "NHWC")


@primitive
def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW"):
    return _avg_pool(x, kernel_size, stride, padding, 3, exclusive,
                     data_format == "NDHWC")


def _adaptive_sizes(in_size, out_size):
    # adaptive pooling = variable windows; when divisible use uniform windows
    return in_size % out_size == 0


@primitive
def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    x = _A(x)
    out_hw = _norm(output_size, 2)
    channel_last = data_format == "NHWC"
    h, w = (x.shape[1], x.shape[2]) if channel_last else (x.shape[2], x.shape[3])
    if h % out_hw[0] == 0 and w % out_hw[1] == 0:
        kh, kw = h // out_hw[0], w // out_hw[1]
        return _avg_pool(x, (kh, kw), (kh, kw), 0, 2, False, channel_last)
    # general case: mean over per-output-bin slices via resize-style gather
    return _adaptive_pool_general(x, out_hw, channel_last, "avg")


@primitive
def adaptive_max_pool2d(x, output_size, return_mask=False, data_format="NCHW"):
    x = _A(x)
    out_hw = _norm(output_size, 2)
    channel_last = data_format == "NHWC"
    h, w = (x.shape[1], x.shape[2]) if channel_last else (x.shape[2], x.shape[3])
    if h % out_hw[0] == 0 and w % out_hw[1] == 0:
        kh, kw = h // out_hw[0], w // out_hw[1]
        return _pool(x, (kh, kw), (kh, kw), 0, 2, jax.lax.max, -jnp.inf,
                     channel_last).astype(x.dtype)
    return _adaptive_pool_general(x, out_hw, channel_last, "max")


def _adaptive_pool_general(x, out_hw, channel_last, mode):
    h_ax, w_ax = (1, 2) if channel_last else (2, 3)
    h, w = x.shape[h_ax], x.shape[w_ax]

    def bins(in_size, out_size, axis):
        starts = (np.arange(out_size) * in_size) // out_size
        ends = ((np.arange(out_size) + 1) * in_size + out_size - 1) // out_size
        return starts, ends

    hs, he = bins(h, out_hw[0], h_ax)
    ws, we = bins(w, out_hw[1], w_ax)
    rows = []
    for i in range(out_hw[0]):
        cols = []
        for j in range(out_hw[1]):
            sl = [slice(None)] * x.ndim
            sl[h_ax] = slice(int(hs[i]), int(he[i]))
            sl[w_ax] = slice(int(ws[j]), int(we[j]))
            patch = x[tuple(sl)]
            red = jnp.mean if mode == "avg" else jnp.max
            cols.append(red(patch, axis=(h_ax, w_ax), keepdims=True))
        rows.append(jnp.concatenate(cols, axis=w_ax))
    return jnp.concatenate(rows, axis=h_ax)


@primitive
def adaptive_avg_pool1d(x, output_size):
    x = _A(x)
    out = _norm(output_size, 1)[0]
    l = x.shape[2]
    if l % out == 0:
        k = l // out
        return _avg_pool(x, (k,), (k,), 0, 1, False, False)
    x4 = x[:, :, None, :]
    o = _adaptive_pool_general(x4, (1, out), False, "avg")
    return o[:, :, 0, :]


@primitive
def adaptive_max_pool1d(x, output_size, return_mask=False):
    x = _A(x)
    out = _norm(output_size, 1)[0]
    l = x.shape[2]
    if l % out == 0:
        k = l // out
        return _pool(x, (k,), (k,), 0, 1, jax.lax.max, -jnp.inf, False).astype(x.dtype)
    x4 = x[:, :, None, :]
    o = _adaptive_pool_general(x4, (1, out), False, "max")
    return o[:, :, 0, :]
