"""Common functionals: linear, embedding, dropout, normalize, interpolate...
(reference python/paddle/nn/functional/{common,input,vision}.py)."""
from __future__ import annotations

import functools as _functools

import jax
import jax.numpy as jnp

from ...core.dispatch import primitive
from ...core.tensor import Tensor
from ...framework import random as _random

_A = jnp.asarray


@primitive
def linear(x, weight, bias=None):
    # paddle stores weight as [in_features, out_features]
    out = jnp.matmul(_A(x), _A(weight))
    if bias is not None:
        out = out + _A(bias)
    return out


@_functools.lru_cache(maxsize=None)
def _lookup_matmul_grad_fn(vocab, wdtype_name):
    """Embedding lookup whose weight grad is a one-hot contraction over
    the token dims instead of XLA's take-grad scatter: under GSPMD a
    scatter-add from a batch-sharded cotangent into an mp/sharding-
    sharded weight grad triggers "Involuntary full rematerialization"
    (all-gather + remat); the dot partitions cleanly (partial sums ->
    reduce-scatter) and rides the MXU. vocab/dtype are static, hence the
    closure factory (custom_vjp residuals must be JAX types)."""
    import numpy as np

    @jax.custom_vjp
    def lk(w, x):
        return jnp.take(w, x, axis=0)

    def fwd(w, x):
        return jnp.take(w, x, axis=0), x

    def bwd(x, g):
        oh = jax.nn.one_hot(x, vocab, dtype=g.dtype)
        xdims = tuple(range(x.ndim))
        gw = jax.lax.dot_general(oh, g, ((xdims, xdims), ((), ())),
                                 preferred_element_type=jnp.float32)
        return (gw.astype(wdtype_name),
                np.zeros(x.shape, jax.dtypes.float0))

    lk.defvjp(fwd, bwd)
    return lk


@primitive
def embedding(x, weight, padding_idx=None, sparse=False):
    # gathers rows of weight; on TPU this lowers to a dynamic-gather that XLA
    # vectorizes — the analog of phi/kernels/embedding_kernel (lookup_table_v2)
    x = _A(x).astype(jnp.int32)
    w = _A(weight)
    # The one-hot grad only pays off when the WEIGHT itself can be
    # sharded (mp vocab rows / ZeRO grads): gate on an explicitly built
    # mesh with a >1 mp or sharding axis. Never call get_mesh() here —
    # it would fabricate a default mesh as a side effect, and dp-only
    # (batch) sharding partitions the take-grad scatter fine.
    sharded_weight = False
    try:
        from ...distributed import mesh as _mesh_mod

        mesh = _mesh_mod._global_mesh
        sharded_weight = mesh is not None and any(
            mesh.shape.get(a, 1) > 1 for a in ("mp", "sharding"))
    # ptlint: silent-except-ok — mesh introspection is best-effort;
    # the default is the unsharded lookup path
    except Exception:
        pass
    if sharded_weight:
        out = _lookup_matmul_grad_fn(w.shape[0], w.dtype.name)(w, x)
    else:  # single chip / dp-only: take-grad scatter is cheaper
        out = jnp.take(w, x, axis=0)
    if padding_idx is not None:
        if padding_idx < 0:
            padding_idx = w.shape[0] + padding_idx
        mask = (x == padding_idx)[..., None]
        out = jnp.where(mask, jnp.zeros_like(out), out)
    return out


def _mask_key(key):
    """Dropout-mask key under FLAGS_dropout_rng_impl: 'rbg' re-wraps the
    key for the TPU hardware RNG (far cheaper per bit than threefry for
    the big per-layer masks; dropout needs statistical, not crypto,
    quality). Applied by every dropout variant. Unknown values raise
    (a typo'd flag silently measuring threefry would waste an on-chip
    ablation window)."""
    from ...core import flags as _flg

    impl = _flg.flag("FLAGS_dropout_rng_impl")
    if impl == "threefry":
        return key
    if impl != "rbg":
        raise ValueError(
            "FLAGS_dropout_rng_impl must be 'threefry' or 'rbg', got %r"
            % (impl,))
    d = jax.random.key_data(key).astype(jnp.uint32).reshape(-1)
    return jax.random.wrap_key_data(
        jnp.concatenate([d, d])[:4], impl="rbg")


@primitive
def dropout(x, p=0.5, training=True, mode="upscale_in_train", seed=None):
    x = _A(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return x * (1.0 - p)
        return x
    key = jax.random.key(seed) if seed is not None else _random.next_key()
    keep = jax.random.bernoulli(_mask_key(key), 1.0 - p, x.shape)
    if mode == "upscale_in_train":
        return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)
    return jnp.where(keep, x, 0.0).astype(x.dtype)


@primitive
def dropout2d(x, p=0.5, training=True, data_format="NCHW"):
    x = _A(x)
    if not training or p == 0.0:
        return x
    shape = list(x.shape)
    if data_format == "NCHW":
        shape[2] = shape[3] = 1
    else:
        shape[1] = shape[2] = 1
    keep = jax.random.bernoulli(_mask_key(_random.next_key()), 1.0 - p,
                                tuple(shape))
    return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW"):
    return _dropout3d(x, p=p, training=training, data_format=data_format)


@primitive(name="dropout3d")
def _dropout3d(x, p=0.5, training=True, data_format="NCDHW"):
    x = _A(x)
    if not training or p == 0.0:
        return x
    shape = list(x.shape)
    if data_format == "NCDHW":
        shape[2] = shape[3] = shape[4] = 1
    else:
        shape[1] = shape[2] = shape[3] = 1
    keep = jax.random.bernoulli(_mask_key(_random.next_key()), 1.0 - p,
                                tuple(shape))
    return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)


@primitive
def alpha_dropout(x, p=0.5, training=True):
    x = _A(x)
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = jax.random.bernoulli(_random.next_key(), 1.0 - p, x.shape)
    a = (1.0 / ((1.0 - p) * (1.0 + p * alpha_p**2)) ** 0.5)
    b = -a * alpha_p * p
    return (a * jnp.where(keep, x, alpha_p) + b).astype(x.dtype)


@primitive
def normalize(x, p=2.0, axis=1, epsilon=1e-12):
    x = _A(x)
    norm = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=True) ** (1.0 / p)
    return x / jnp.maximum(norm, epsilon)


@primitive
def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    x1, x2 = _A(x1), _A(x2)
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


@primitive
def label_smooth(label, prior_dist=None, epsilon=0.1):
    label = _A(label)
    k = label.shape[-1]
    if prior_dist is not None:
        return (1.0 - epsilon) * label + epsilon * _A(prior_dist)
    return (1.0 - epsilon) * label + epsilon / k


@primitive
def interpolate(
    x,
    size=None,
    scale_factor=None,
    mode="nearest",
    align_corners=False,
    data_format="NCHW",
):
    """Image resize (reference phi/kernels/interpolate_kernel). Uses
    jax.image.resize; align_corners handled for (bi)linear."""
    x = _A(x)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    spatial_ndim = x.ndim - 2
    if channel_last:
        spatial = x.shape[1:-1]
    else:
        spatial = x.shape[2:]
    if size is None:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * spatial_ndim
        size = [int(s * f) for s, f in zip(spatial, scale_factor)]
    size = [int(s) for s in (size if isinstance(size, (list, tuple)) else [size])]
    if channel_last:
        out_shape = (x.shape[0], *size, x.shape[-1])
    else:
        out_shape = (x.shape[0], x.shape[1], *size)
    method = {
        "nearest": "nearest",
        "bilinear": "linear",
        "linear": "linear",
        "trilinear": "linear",
        "bicubic": "cubic",
        "area": "linear",
    }[mode]
    if align_corners and method != "nearest":
        # jax.image.resize implements half-pixel centers; emulate
        # align_corners with an explicit coordinate map via lax.gather-free
        # linear interpolation.
        return _resize_align_corners(x, out_shape, channel_last)
    return jax.image.resize(x, out_shape, method=method).astype(x.dtype)


def _resize_align_corners(x, out_shape, channel_last):
    sp_slice = slice(1, -1) if channel_last else slice(2, None)
    in_sp = x.shape[sp_slice]
    out_sp = out_shape[sp_slice]
    out = x
    for i, (ins, outs) in enumerate(zip(in_sp, out_sp)):
        axis = (1 + i) if channel_last else (2 + i)
        if ins == outs:
            continue
        if outs == 1 or ins == 1:
            idx = jnp.zeros((outs,), jnp.int32)
            out = jnp.take(out, idx, axis=axis)
            continue
        pos = jnp.arange(outs) * (ins - 1) / (outs - 1)
        lo = jnp.floor(pos).astype(jnp.int32)
        hi = jnp.minimum(lo + 1, ins - 1)
        w = (pos - lo).astype(out.dtype)
        shape = [1] * out.ndim
        shape[axis] = outs
        w = w.reshape(shape)
        out = jnp.take(out, lo, axis=axis) * (1 - w) + jnp.take(out, hi, axis=axis) * w
    return out


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             data_format="NCHW"):
    return interpolate(x, size=size, scale_factor=scale_factor, mode=mode,
                       align_corners=align_corners, data_format=data_format)


@primitive
def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    x = _A(x)
    r = int(upscale_factor)
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c // (r * r), r, r, h, w)
        x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
        return x.reshape(n, c // (r * r), h * r, w * r)
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, r, r, c // (r * r))
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(n, h * r, w * r, c // (r * r))


@primitive
def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    x = _A(x)
    r = int(downscale_factor)
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c, h // r, r, w // r, r)
        x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
        return x.reshape(n, c * r * r, h // r, w // r)
    n, h, w, c = x.shape
    x = x.reshape(n, h // r, r, w // r, r, c)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(n, h // r, w // r, c * r * r)


@primitive
def bilinear(x1, x2, weight, bias=None):
    # out[b, o] = x1[b, i] W[o, i, j] x2[b, j]  (reference bilinear_tensor_product)
    out = jnp.einsum("bi,oij,bj->bo", _A(x1), _A(weight), _A(x2))
    if bias is not None:
        out = out + _A(bias)
    return out


# -- spatial sampling / rearrangement long tail (VERDICT r1 item 8) --------

def _gs_unnormalize(coord, size, align_corners):
    if align_corners:
        return (coord + 1.0) * 0.5 * (size - 1)
    return ((coord + 1.0) * size - 1.0) * 0.5


def _gs_reflect(coord, size, align_corners):
    # reflect across the valid range, torch/paddle semantics
    if align_corners:
        span = size - 1
        if span == 0:
            return jnp.zeros_like(coord)
        c = jnp.abs(coord) % (2 * span)
        return jnp.where(c > span, 2 * span - c, c)
    span = size
    c = jnp.abs(coord + 0.5) % (2 * span)
    c = jnp.where(c > span, 2 * span - c, c) - 0.5
    return jnp.clip(c, 0, size - 1)


@primitive
def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True):
    """Spatial sampler (reference phi/kernels/grid_sample_kernel.h,
    operators/grid_sampler_op): x [N,C,H,W], grid [N,Hg,Wg,2] with
    normalized (x, y) in [-1, 1]."""
    x = _A(x)
    grid = _A(grid)
    N, C, H, W = x.shape
    gx = _gs_unnormalize(grid[..., 0].astype(jnp.float32), W, align_corners)
    gy = _gs_unnormalize(grid[..., 1].astype(jnp.float32), H, align_corners)
    if padding_mode == "border":
        gx = jnp.clip(gx, 0, W - 1)
        gy = jnp.clip(gy, 0, H - 1)
    elif padding_mode == "reflection":
        gx = _gs_reflect(gx, W, align_corners)
        gy = _gs_reflect(gy, H, align_corners)
    xv = jnp.transpose(x, (0, 2, 3, 1)).astype(jnp.float32)  # [N,H,W,C]
    nidx = jnp.arange(N)[:, None, None]

    def sample(iy, ix):
        valid = ((iy >= 0) & (iy < H) & (ix >= 0) & (ix < W))
        v = xv[nidx, jnp.clip(iy, 0, H - 1), jnp.clip(ix, 0, W - 1)]
        return jnp.where(valid[..., None], v, 0.0)

    if mode == "nearest":
        out = sample(jnp.round(gy).astype(jnp.int32),
                     jnp.round(gx).astype(jnp.int32))
    else:  # bilinear
        x0 = jnp.floor(gx)
        y0 = jnp.floor(gy)
        x1, y1 = x0 + 1, y0 + 1
        wx1 = gx - x0
        wy1 = gy - y0
        wx0, wy0 = 1.0 - wx1, 1.0 - wy1
        out = (
            sample(y0.astype(jnp.int32), x0.astype(jnp.int32))
            * (wy0 * wx0)[..., None]
            + sample(y0.astype(jnp.int32), x1.astype(jnp.int32))
            * (wy0 * wx1)[..., None]
            + sample(y1.astype(jnp.int32), x0.astype(jnp.int32))
            * (wy1 * wx0)[..., None]
            + sample(y1.astype(jnp.int32), x1.astype(jnp.int32))
            * (wy1 * wx1)[..., None]
        )
    return jnp.transpose(out, (0, 3, 1, 2)).astype(x.dtype)


@primitive
def affine_grid(theta, out_shape, align_corners=True):
    """Affine sampling grid (reference affine_grid_kernel): theta
    [N, 2, 3], out_shape (N, C, H, W) -> grid [N, H, W, 2]."""
    theta = _A(theta).astype(jnp.float32)
    N, _, H, W = [int(s) for s in out_shape]

    def axis_coords(n):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, n)
        step = 2.0 / n
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, n)

    ys = axis_coords(H)
    xs = axis_coords(W)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [H, W, 3]
    return jnp.einsum("hwk,nik->nhwi", base, theta)


@primitive
def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    """col2im (reference fold_kernel) — exact transpose of unfold, derived
    from it via vjp so the two stay inverse-consistent."""
    from ...ops.manipulation import unfold as _unfold_op

    x = _A(x)
    os_ = output_sizes if isinstance(output_sizes, (list, tuple)) \
        else [output_sizes] * 2
    N = x.shape[0]
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) \
        else [kernel_sizes] * 2
    C = x.shape[1] // (ks[0] * ks[1])
    zeros = jnp.zeros((N, C, int(os_[0]), int(os_[1])), x.dtype)
    _, vjp = jax.vjp(
        lambda img: _unfold_op.raw_fn(img, kernel_sizes, strides, paddings,
                                      dilations), zeros)
    (out,) = vjp(x)
    return out


@primitive
def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    """TSM channel time-shift (reference temporal_shift_kernel):
    x [N*T, C, H, W]; first `ratio` of channels shift t-1, next shift
    t+1, rest stay."""
    x = _A(x)
    if data_format == "NHWC":
        x = jnp.transpose(x, (0, 3, 1, 2))
    nt, c, h, w = x.shape
    t = seg_num
    n = nt // t
    xr = x.reshape(n, t, c, h, w)
    c1 = int(c * shift_ratio)
    c2 = int(c * 2 * shift_ratio)
    back = jnp.concatenate(
        [xr[:, 1:, :c1], jnp.zeros_like(xr[:, :1, :c1])], axis=1)
    fwd = jnp.concatenate(
        [jnp.zeros_like(xr[:, :1, c1:c2]), xr[:, :-1, c1:c2]], axis=1)
    out = jnp.concatenate([back, fwd, xr[:, :, c2:]], axis=2)
    out = out.reshape(nt, c, h, w)
    if data_format == "NHWC":
        out = jnp.transpose(out, (0, 2, 3, 1))
    return out


@primitive
def channel_shuffle(x, groups, data_format="NCHW"):
    """reference channel_shuffle_kernel: interleave channel groups."""
    x = _A(x)
    if data_format == "NHWC":
        x = jnp.transpose(x, (0, 3, 1, 2))
    n, c, h, w = x.shape
    out = x.reshape(n, groups, c // groups, h, w)
    out = jnp.swapaxes(out, 1, 2).reshape(n, c, h, w)
    if data_format == "NHWC":
        out = jnp.transpose(out, (0, 2, 3, 1))
    return out


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col over [N, C, H, W] -> [N, C*kh*kw, L] (reference
    nn/functional/common.py:38 unfold / phi unfold_kernel)."""
    from ...ops.manipulation import unfold as _unfold_op

    return _unfold_op(x, kernel_sizes, strides, paddings, dilations)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """Zero-pad the spatial dims; padding = [left, right, top, bottom]
    (reference nn/functional/common.py zeropad2d)."""
    from ...ops.manipulation import pad as _pad

    return _pad(x, padding, mode="constant", value=0.0,
                data_format=data_format)


@primitive(nondiff=True)
def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """mask[..., j] = j < x[...] (reference functional/extension.py:162
    sequence_mask over LoD-free length tensors)."""
    v = _A(x)
    if maxlen is None:
        maxlen = int(v.max())  # concrete lengths only in this case
    pos = jnp.arange(maxlen)
    mask = pos[None, :] < v.reshape(v.shape + (1,))
    mask = mask.reshape(v.shape + (maxlen,))
    return mask.astype(dtype)
