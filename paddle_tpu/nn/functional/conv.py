"""Convolutions (reference python/paddle/nn/functional/conv.py,
phi/kernels/gpu/conv_kernel.cu → cudnn). On TPU, XLA lowers
lax.conv_general_dilated straight onto the MXU; NCHW in, weights OIHW —
XLA's layout assignment picks the fast internal layout, so no cudnn-style
algo search is needed."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import primitive

_A = jnp.asarray


def _norm_tuple(v, n):
    if isinstance(v, (int, float)):
        return (int(v),) * n
    v = tuple(int(i) for i in v)
    if len(v) == 1:
        return v * n
    return v


def _norm_padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def _conv(x, weight, bias, stride, padding, dilation, groups, n,
          channel_last, preferred_element_type=None):
    # preferred_element_type: int8 serving convs accumulate in int32
    # (quantization.Int8Conv2D) — same padding/stride normalization,
    # different accumulator
    x, w = _A(x), _A(weight)
    stride = _norm_tuple(stride, n)
    dilation = _norm_tuple(dilation, n)
    padding = _norm_padding(padding, n)
    sp = "DHW"[-n:] if n > 1 else "W"
    if channel_last:
        lhs_spec = "N" + sp + "C"
    else:
        lhs_spec = "NC" + sp
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w.shape, (lhs_spec, "OI" + sp, lhs_spec)
    )
    out = jax.lax.conv_general_dilated(
        x,
        w,
        window_strides=stride,
        padding=padding,
        rhs_dilation=dilation,
        dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=preferred_element_type,
    )
    if bias is not None:
        b = _A(bias)
        shape = [1] * out.ndim
        shape[1 if not channel_last else -1] = b.size
        out = out + b.reshape(shape)
    return out


@primitive
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 channel_last=data_format == "NLC")


@primitive
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 channel_last=data_format == "NHWC")


@primitive
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 channel_last=data_format == "NDHWC")


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, n, channel_last):
    x, w = _A(x), _A(weight)
    stride = _norm_tuple(stride, n)
    dilation = _norm_tuple(dilation, n)
    output_padding = _norm_tuple(output_padding, n)
    sp = "DHW"[-n:] if n > 1 else "W"
    lhs_spec = ("N" + sp + "C") if channel_last else ("NC" + sp)
    # paddle conv_transpose weight layout: [in_channels, out_channels//groups, *k]
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w.shape, (lhs_spec, "IO" + sp, lhs_spec)
    )
    if isinstance(padding, str):
        pad_cfg = padding.upper()
    else:
        pads = _norm_padding(padding, n)
        # transposed conv: effective padding = k_eff - 1 - pad
        ksp = w.shape[2:]
        pad_cfg = []
        for i in range(n):
            k_eff = (ksp[i] - 1) * dilation[i] + 1
            lo = k_eff - 1 - pads[i][0]
            hi = k_eff - 1 - pads[i][1] + output_padding[i]
            pad_cfg.append((lo, hi))
    out = jax.lax.conv_general_dilated(
        x,
        jnp.flip(w, axis=tuple(range(2, 2 + n))),
        window_strides=(1,) * n,
        padding=pad_cfg,
        lhs_dilation=stride,
        rhs_dilation=dilation,
        dimension_numbers=dn,
        feature_group_count=groups,
    )
    if bias is not None:
        b = _A(bias)
        shape = [1] * out.ndim
        shape[1 if not channel_last else -1] = b.size
        out = out + b.reshape(shape)
    return out


@primitive
def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, data_format="NCL"):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, data_format == "NLC")


@primitive
def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, data_format="NCHW"):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format == "NHWC")


@primitive
def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, data_format="NCDHW"):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format == "NDHWC")


@primitive
def deformable_conv(x, offset, weight, mask=None, bias=None, stride=1,
                    padding=0, dilation=1, deformable_groups=1, groups=1):
    """Deformable convolution v1/v2 (reference
    phi/kernels/deformable_conv_kernel.h; v2 when `mask` given).

    x [N,C,H,W]; offset [N, 2*dg*kh*kw, OH, OW] as (dy, dx) pairs;
    mask [N, dg*kh*kw, OH, OW]; weight [Cout, C/groups, kh, kw].
    Implemented as bilinear sampling of one patch tensor followed by a
    single big einsum — the patch gather feeds the MXU contraction the
    same way the reference's im2col-with-offsets does."""
    x = _A(x)
    offset = _A(offset).astype(jnp.float32)
    w = _A(weight)
    N, C, H, W = x.shape
    Cout, Cg, kh, kw = w.shape
    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dl = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    OH, OW = offset.shape[2], offset.shape[3]
    K = kh * kw
    dg = deformable_groups
    off = offset.reshape(N, dg, K, 2, OH, OW)

    # base sampling positions per output pixel and tap (K = kh*kw taps,
    # row-major over the kernel window)
    oy = jnp.broadcast_to(
        jnp.arange(OH, dtype=jnp.float32)[:, None] * st[0] - pd[0],
        (OH, OW))
    ox = jnp.broadcast_to(
        jnp.arange(OW, dtype=jnp.float32)[None, :] * st[1] - pd[1],
        (OH, OW))
    ky_flat = jnp.repeat(jnp.arange(kh, dtype=jnp.float32) * dl[0], kw)
    kx_flat = jnp.tile(jnp.arange(kw, dtype=jnp.float32) * dl[1], kh)
    base_y = oy[None] + ky_flat[:, None, None]              # [K, OH, OW]
    base_x = ox[None] + kx_flat[:, None, None]              # [K, OH, OW]

    py = base_y[None, None] + off[:, :, :, 0]               # [N,dg,K,OH,OW]
    px = base_x[None, None] + off[:, :, :, 1]

    xv = jnp.transpose(x, (0, 2, 3, 1)).astype(jnp.float32)  # [N,H,W,C]
    nidx = jnp.arange(N)[:, None, None, None, None]

    def sample(iy, ix):
        valid = (iy >= 0) & (iy < H) & (ix >= 0) & (ix < W)
        v = xv[nidx, jnp.clip(iy, 0, H - 1), jnp.clip(ix, 0, W - 1)]
        return jnp.where(valid[..., None], v, 0.0)  # [N,dg,K,OH,OW,C]

    y0 = jnp.floor(py)
    x0 = jnp.floor(px)
    wy1 = py - y0
    wx1 = px - x0
    wy0, wx0 = 1.0 - wy1, 1.0 - wx1
    patches = (
        sample(y0.astype(jnp.int32), x0.astype(jnp.int32))
        * (wy0 * wx0)[..., None]
        + sample(y0.astype(jnp.int32), (x0 + 1).astype(jnp.int32))
        * (wy0 * wx1)[..., None]
        + sample((y0 + 1).astype(jnp.int32), x0.astype(jnp.int32))
        * (wy1 * wx0)[..., None]
        + sample((y0 + 1).astype(jnp.int32), (x0 + 1).astype(jnp.int32))
        * (wy1 * wx1)[..., None]
    )  # [N, dg, K, OH, OW, C]
    if mask is not None:
        m = _A(mask).astype(jnp.float32).reshape(N, dg, K, OH, OW)
        patches = patches * m[..., None]
    # channels belong to their deformable group: split C into dg chunks
    patches = patches.reshape(N, dg, K, OH, OW, dg, C // dg)
    didx = jnp.arange(dg)
    patches = patches[:, didx, :, :, :, didx]  # [dg, N, K, OH, OW, C/dg]
    patches = jnp.moveaxis(patches, 0, 1)      # [N, dg, K, OH, OW, C/dg]
    patches = jnp.moveaxis(patches, (1, 5), (4, 5))  # [N,K,OH,OW,dg,C/dg]
    patches = patches.reshape(N, K, OH, OW, C)
    wr = w.reshape(Cout, Cg, K).astype(jnp.float32)
    if groups == 1:
        out = jnp.einsum("nkhwc,ock->nohw", patches, wr)
    else:
        pg = patches.reshape(N, K, OH, OW, groups, C // groups)
        wg = wr.reshape(groups, Cout // groups, Cg, K)
        out = jnp.einsum("nkhwgc,gock->ngohw", pg, wg).reshape(
            N, Cout, OH, OW)
    if bias is not None:
        out = out + _A(bias).reshape(1, -1, 1, 1)
    return out.astype(x.dtype)
