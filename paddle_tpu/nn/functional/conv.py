"""Convolutions (reference python/paddle/nn/functional/conv.py,
phi/kernels/gpu/conv_kernel.cu → cudnn). On TPU, XLA lowers
lax.conv_general_dilated straight onto the MXU; NCHW in, weights OIHW —
XLA's layout assignment picks the fast internal layout, so no cudnn-style
algo search is needed."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import primitive

_A = jnp.asarray


def _norm_tuple(v, n):
    if isinstance(v, (int, float)):
        return (int(v),) * n
    v = tuple(int(i) for i in v)
    if len(v) == 1:
        return v * n
    return v


def _norm_padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def _conv(x, weight, bias, stride, padding, dilation, groups, n, channel_last):
    x, w = _A(x), _A(weight)
    stride = _norm_tuple(stride, n)
    dilation = _norm_tuple(dilation, n)
    padding = _norm_padding(padding, n)
    sp = "DHW"[-n:] if n > 1 else "W"
    if channel_last:
        lhs_spec = "N" + sp + "C"
    else:
        lhs_spec = "NC" + sp
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w.shape, (lhs_spec, "OI" + sp, lhs_spec)
    )
    out = jax.lax.conv_general_dilated(
        x,
        w,
        window_strides=stride,
        padding=padding,
        rhs_dilation=dilation,
        dimension_numbers=dn,
        feature_group_count=groups,
    )
    if bias is not None:
        b = _A(bias)
        shape = [1] * out.ndim
        shape[1 if not channel_last else -1] = b.size
        out = out + b.reshape(shape)
    return out


@primitive
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 channel_last=data_format == "NLC")


@primitive
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 channel_last=data_format == "NHWC")


@primitive
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 channel_last=data_format == "NDHWC")


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, n, channel_last):
    x, w = _A(x), _A(weight)
    stride = _norm_tuple(stride, n)
    dilation = _norm_tuple(dilation, n)
    output_padding = _norm_tuple(output_padding, n)
    sp = "DHW"[-n:] if n > 1 else "W"
    lhs_spec = ("N" + sp + "C") if channel_last else ("NC" + sp)
    # paddle conv_transpose weight layout: [in_channels, out_channels//groups, *k]
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w.shape, (lhs_spec, "IO" + sp, lhs_spec)
    )
    if isinstance(padding, str):
        pad_cfg = padding.upper()
    else:
        pads = _norm_padding(padding, n)
        # transposed conv: effective padding = k_eff - 1 - pad
        ksp = w.shape[2:]
        pad_cfg = []
        for i in range(n):
            k_eff = (ksp[i] - 1) * dilation[i] + 1
            lo = k_eff - 1 - pads[i][0]
            hi = k_eff - 1 - pads[i][1] + output_padding[i]
            pad_cfg.append((lo, hi))
    out = jax.lax.conv_general_dilated(
        x,
        jnp.flip(w, axis=tuple(range(2, 2 + n))),
        window_strides=(1,) * n,
        padding=pad_cfg,
        lhs_dilation=stride,
        rhs_dilation=dilation,
        dimension_numbers=dn,
        feature_group_count=groups,
    )
    if bias is not None:
        b = _A(bias)
        shape = [1] * out.ndim
        shape[1 if not channel_last else -1] = b.size
        out = out + b.reshape(shape)
    return out


@primitive
def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, data_format="NCL"):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, data_format == "NLC")


@primitive
def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, data_format="NCHW"):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format == "NHWC")


@primitive
def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, data_format="NCDHW"):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format == "NDHWC")
