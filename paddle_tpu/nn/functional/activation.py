"""Activation functionals (reference python/paddle/nn/functional/activation.py,
phi/kernels/activation_kernel). Pure jnp — XLA fuses these into neighbors."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import primitive

_A = jnp.asarray


@primitive
def relu(x):
    return jax.nn.relu(_A(x))


@primitive
def relu6(x):
    return jnp.clip(_A(x), 0.0, 6.0)


@primitive
def gelu(x, approximate=False):
    return jax.nn.gelu(_A(x), approximate=approximate)


@primitive
def sigmoid(x):
    return jax.nn.sigmoid(_A(x))


@primitive
def tanh(x):
    return jnp.tanh(_A(x))


@primitive
def silu(x):
    return jax.nn.silu(_A(x))


def swish(x):
    return silu(x)


@primitive
def mish(x):
    x = _A(x)
    return x * jnp.tanh(jax.nn.softplus(x))


@primitive
def elu(x, alpha=1.0):
    return jax.nn.elu(_A(x), alpha=alpha)


@primitive
def selu(
    x,
    scale=1.0507009873554804934193349852946,
    alpha=1.6732632423543772848170429916717,
):
    x = _A(x)
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@primitive
def celu(x, alpha=1.0):
    return jax.nn.celu(_A(x), alpha=alpha)


@primitive
def leaky_relu(x, negative_slope=0.01):
    return jax.nn.leaky_relu(_A(x), negative_slope=negative_slope)


@primitive
def prelu(x, weight, data_format="NCHW"):
    x, w = _A(x), _A(weight)
    if w.size > 1 and x.ndim > 1:
        shape = [1] * x.ndim
        ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
        shape[ch_axis] = w.size
        w = w.reshape(shape)
    return jnp.where(x > 0, x, w * x)


@primitive
def rrelu(x, lower=0.125, upper=0.3333333333333333, training=False):
    x = _A(x)
    slope = (lower + upper) / 2.0
    return jnp.where(x >= 0, x, slope * x)


@primitive
def hardtanh(x, min=-1.0, max=1.0):
    return jnp.clip(_A(x), min, max)


@primitive
def hardsigmoid(x, slope=0.1666667, offset=0.5):
    return jnp.clip(_A(x) * slope + offset, 0.0, 1.0)


@primitive
def hardswish(x):
    x = _A(x)
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


@primitive
def hardshrink(x, threshold=0.5):
    x = _A(x)
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


@primitive
def softshrink(x, threshold=0.5):
    x = _A(x)
    return jnp.where(
        x > threshold, x - threshold, jnp.where(x < -threshold, x + threshold, 0.0)
    )


@primitive
def tanhshrink(x):
    x = _A(x)
    return x - jnp.tanh(x)


@primitive
def softplus(x, beta=1.0, threshold=20.0):
    x = _A(x)
    return jnp.where(
        x * beta > threshold, x, jax.nn.softplus(x * beta) / beta
    )


@primitive
def softsign(x):
    return jax.nn.soft_sign(_A(x))


@primitive
def softmax(x, axis=-1, dtype=None):
    from ...core import dtype as _dt

    x = _A(x)
    if dtype is not None:
        x = x.astype(_dt.to_jax(dtype))
    return jax.nn.softmax(x, axis=int(axis))


@primitive
def log_softmax(x, axis=-1, dtype=None):
    from ...core import dtype as _dt

    x = _A(x)
    if dtype is not None:
        x = x.astype(_dt.to_jax(dtype))
    return jax.nn.log_softmax(x, axis=int(axis))


@primitive
def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1):
    from ...framework import random as _random

    x = _A(x)
    g = jax.random.gumbel(_random.next_key(), x.shape, x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis)
        oh = jax.nn.one_hot(idx, y.shape[axis], axis=axis, dtype=y.dtype)
        y = oh + y - jax.lax.stop_gradient(y)  # straight-through estimator
    return y


@primitive
def maxout(x, groups, axis=1):
    x = _A(x)
    axis = axis % x.ndim
    c = x.shape[axis]
    shape = list(x.shape)
    shape[axis] = c // groups
    shape.insert(axis + 1, groups)
    return jnp.max(x.reshape(shape), axis=axis + 1)


@primitive
def glu(x, axis=-1):
    return jax.nn.glu(_A(x), axis=axis)


@primitive
def thresholded_relu(x, threshold=1.0):
    x = _A(x)
    return jnp.where(x > threshold, x, 0.0)

@primitive
def log_sigmoid(x, name=None):
    """reference log_sigmoid (stable -softplus(-x))."""
    import jax

    return jax.nn.log_sigmoid(jnp.asarray(x))


def _inplace(fn):
    from ...ops.extras import _make_inplace

    return _make_inplace(fn.__name__ + "_", fn)
