"""Attention functionals.

The reference ships fused CUDA attention (operators/fused/fused_attention_op)
and sparse attention; here the TPU path is a Pallas flash-attention kernel
(paddle_tpu/kernels/flash_attention.py) with a pure-XLA fallback that still
fuses well. Long-context ring attention lives in paddle_tpu/parallel.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import primitive

_A = jnp.asarray


def _sdpa_reference(q, k, v, mask=None, dropout_p=0.0, causal=False, scale=None):
    # q,k,v: [B, N, H, D] (paddle convention: batch, seq, heads, head_dim)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    logits = jnp.einsum("bnhd,bmhd->bhnm", qf, kf) * scale
    if causal:
        # start-aligned (query i attends keys j <= i) — the ONE causal
        # convention across this fallback, the Pallas kernels, and ring
        # attention (kernels/flash_attention.py docstring). Cached decode
        # must pass an explicit end-aligned mask instead of is_causal
        # (models/llama.py does).
        n, m = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((n, m), bool))
        logits = jnp.where(cm, logits, -1e30)
    if mask is not None:
        mask = _A(mask)
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhnm,bmhd->bnhd", probs.astype(v.dtype), v)
    return out


@primitive
def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, scale=None,
                                 training=True, _warn_rect_causal=True):
    """Scaled dot-product attention over [B, N, H, D] inputs (reference
    nn/functional/flash_attention.py convention).

    Causal convention: ``is_causal=True`` applies a START-aligned mask —
    query i attends keys j <= i — uniformly across the XLA fallback, the
    Pallas flash kernels, and ring attention. This differs from the
    FA2/PyTorch bottom-right (end-aligned) convention when
    ``q_len != kv_len``: for cached decode, pass an explicit end-aligned
    ``attn_mask`` instead of ``is_causal`` (see models/llama.py).
    A warning is emitted for the ambiguous rectangular-causal case
    (``_warn_rect_causal=False`` silences it where start-aligned truly is
    intended, e.g. prefill against a preallocated decode cache).
    """
    q, k, v = _A(query), _A(key), _A(value)
    if (is_causal and attn_mask is None and _warn_rect_causal
            and q.shape[1] != k.shape[1]):
        import warnings

        warnings.warn(
            "scaled_dot_product_attention: is_causal=True with "
            "q_len != kv_len uses START-aligned masking (query i "
            "attends keys j <= i). For cached decode (bottom-right "
            "alignment), pass an explicit end-aligned attn_mask.",
            stacklevel=2)
    from ...core import flags as _flags

    min_d = _flags.get_flags("FLAGS_flash_min_head_dim")[
        "FLAGS_flash_min_head_dim"]
    use_flash = (
        jax.default_backend() == "tpu"
        and attn_mask is None
        and dropout_p == 0.0
        # validated head_dims only: 128-multiples (measured) and exactly
        # 64 (kernel-exact, flag-gated pending on-chip Mosaic check) —
        # NOT every 64-multiple (192/320 are untested lane layouts)
        and (q.shape[-1] % 128 == 0 or q.shape[-1] == 64)
        and q.shape[-1] >= min_d
        and q.shape[1] % 128 == 0
        and k.shape[1] % 128 == 0
    )
    if use_flash:
        try:
            from ...kernels.flash_attention import flash_attention as _fa

            return _fa(q, k, v, causal=is_causal, scale=scale)
        except Exception as e:
            from ...monitor.registry import warn_once

            warn_once(
                "attention.flash_fallback",
                "paddle_tpu.nn.functional: flash_attention path "
                "unavailable, using reference SDPA (slower): "
                "%r" % (e,))
    return _sdpa_reference(q, k, v, mask=attn_mask, dropout_p=dropout_p,
                           causal=is_causal, scale=scale)


@primitive
def sequence_parallel_attention(query, key, value, is_causal=True,
                                scale=None, axis_name="sep"):
    """Ring attention over the 'sep' mesh axis (kernels/ring_attention.py
    — sequence/context parallelism, the capability the reference snapshot
    lacks, SURVEY §5). Falls back to regular attention when the mesh has
    no sep axis, so models can enable it unconditionally."""
    q, k, v = _A(query), _A(key), _A(value)
    from ...distributed import mesh as _mesh

    mesh = _mesh.get_mesh()
    if (axis_name not in mesh.axis_names
            or mesh.shape.get(axis_name, 1) <= 1):
        return scaled_dot_product_attention.raw_fn(
            q, k, v, is_causal=is_causal, scale=scale)
    from ...kernels.ring_attention import (
        sequence_parallel_attention as _ring,
    )

    return _ring(q, k, v, mesh=mesh, causal=is_causal, scale=scale,
                 axis_name=axis_name)


@primitive
def sparse_attention(query, key, value, sparse_csr_offset=None,
                     sparse_csr_columns=None, attn_mask=None):
    # Block-sparse attention degenerates to dense + mask on TPU; packed
    # variable-length serving goes through variable_length_attention
    # (segment-masked flash kernel).
    q, k, v = _A(query), _A(key), _A(value)
    return _sdpa_reference(q, k, v, mask=attn_mask)


@primitive
def variable_length_attention(query, key, value, seq_lens=None,
                              segment_ids=None, is_causal=True,
                              scale=None):
    """Ragged/packed attention (reference varlen fused attention,
    flash_attn_unpadded / variable_length_memory_efficient_attention):
    multiple sequences packed along one axis; tokens attend only within
    their own sequence. Provide per-batch `seq_lens` (list of lengths
    summing to N, converted to segment ids) or `segment_ids` [B, N]."""
    q, k, v = _A(query), _A(key), _A(value)
    if segment_ids is None:
        if seq_lens is None:
            raise ValueError("need seq_lens or segment_ids")
        import numpy as _np

        lens = _np.asarray(seq_lens)
        if lens.ndim == 1:
            lens = lens[None]
        total = q.shape[1]
        segs = _np.zeros((lens.shape[0], total), _np.int32)
        for bi in range(lens.shape[0]):
            off = 0
            for si, L in enumerate(lens[bi]):
                segs[bi, off:off + int(L)] = si
                off += int(L)
            # tail padding (if any) gets its own segment id
            segs[bi, off:] = lens.shape[1]
        segment_ids = jnp.asarray(segs)
    from ...kernels.flash_attention import flash_attention as _fa

    return _fa(q, k, v, causal=is_causal, scale=scale,
               segment_ids=_A(segment_ids))
