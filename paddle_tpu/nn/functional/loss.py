"""Loss functionals (reference python/paddle/nn/functional/loss.py,
phi/kernels/*cross_entropy*). Softmax+CE fused in one expression so XLA emits
a single stable fused kernel — the analog of the reference's fused
softmax_with_cross_entropy op."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.dispatch import primitive

_A = jnp.asarray


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


@primitive
def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0):
    x = _A(input)
    lbl = _A(label)
    n_cls = x.shape[axis]
    if (use_softmax and not soft_label and weight is None
            and label_smoothing == 0.0):
        # Hot path (decoder LM loss): loss = logsumexp(x) - x[label] on
        # fp32-upcast logits. Cheaper than log_softmax both ways: forward
        # reduces [N, V] to [N] without materializing log-probabilities,
        # and the VJP is softmax(x) - onehot recomputed from (x, lse)
        # elementwise rather than saving a second [N, V] residual.
        # (reference fuses the same pair in
        # phi/kernels/gpu/cross_entropy_kernel.cu)
        li = lbl.astype(jnp.int32)
        if li.ndim == x.ndim and li.shape[axis] == 1:
            li = jnp.squeeze(li, axis=axis)
        xf = x.astype(jnp.float32)
        lse = jax.nn.logsumexp(xf, axis=axis)
        picked = jnp.take_along_axis(
            xf, jnp.expand_dims(jnp.clip(li, 0, n_cls - 1), axis), axis=axis)
        loss = lse - jnp.squeeze(picked, axis=axis)
        valid = li != ignore_index
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            denom = jnp.sum(valid.astype(loss.dtype))
            return jnp.sum(loss) / jnp.maximum(denom, 1.0)
        # fp32 statistics, input-dtype result — same contract as the
        # log_softmax path below (bf16 in -> bf16 per-token loss)
        return _reduce(loss, reduction).astype(x.dtype)
    logp = jax.nn.log_softmax(x, axis=axis) if use_softmax else jnp.log(
        jnp.maximum(x, 1e-30))
    if soft_label:
        soft = _A(lbl).astype(logp.dtype)
        loss = -jnp.sum(soft * logp, axis=axis)
        valid = jnp.ones_like(loss, dtype=bool)
    else:
        li = lbl.astype(jnp.int32)
        if li.ndim == x.ndim and li.shape[axis] == 1:
            li = jnp.squeeze(li, axis=axis)
        if label_smoothing > 0.0:
            oh = jax.nn.one_hot(li, n_cls, axis=axis, dtype=logp.dtype)
            soft = oh * (1.0 - label_smoothing) + label_smoothing / n_cls
            loss = -jnp.sum(soft * logp, axis=axis)
        else:
            picked = jnp.take_along_axis(
                logp, jnp.expand_dims(jnp.clip(li, 0, n_cls - 1), axis), axis=axis
            )
            loss = -jnp.squeeze(picked, axis=axis)
        valid = li != ignore_index
        loss = jnp.where(valid, loss, 0.0)
        if weight is not None:
            w = jnp.take(_A(weight), jnp.clip(li, 0, n_cls - 1))
            loss = loss * jnp.where(valid, w, 0.0)
            if reduction == "mean":
                denom = jnp.sum(jnp.where(valid, w, 0.0))
                return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
    if reduction == "mean" and not soft_label:
        denom = jnp.sum(valid.astype(loss.dtype))
        return jnp.sum(loss) / jnp.maximum(denom, 1.0)
    return _reduce(loss, reduction)


def softmax_with_cross_entropy(logits, label, soft_label=False, axis=-1,
                               ignore_index=-100, return_softmax=False):
    loss = cross_entropy(logits, label, soft_label=soft_label, axis=axis,
                         ignore_index=ignore_index, reduction="none")
    from .activation import softmax as _softmax

    loss = loss.unsqueeze(axis) if hasattr(loss, "unsqueeze") else loss
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


@primitive
def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):
    logp = _A(input)
    li = _A(label).astype(jnp.int32)
    n_cls = logp.shape[-1] if logp.ndim == 1 else logp.shape[1]
    if logp.ndim > 2:
        # [N,C,d1..] -> [N,d1..,C]
        logp = jnp.moveaxis(logp, 1, -1)
    picked = jnp.take_along_axis(
        logp, jnp.expand_dims(jnp.clip(li, 0, n_cls - 1), -1), axis=-1
    )
    loss = -jnp.squeeze(picked, -1)
    valid = li != ignore_index
    loss = jnp.where(valid, loss, 0.0)
    if weight is not None:
        w = jnp.take(_A(weight), jnp.clip(li, 0, n_cls - 1))
        loss = loss * jnp.where(valid, w, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(valid, w, 0.0)), 1e-12)
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
    return _reduce(loss, reduction)


@primitive
def mse_loss(input, label, reduction="mean"):
    return _reduce(jnp.square(_A(input) - _A(label)), reduction)


@primitive
def l1_loss(input, label, reduction="mean"):
    return _reduce(jnp.abs(_A(input) - _A(label)), reduction)


@primitive
def smooth_l1_loss(input, label, reduction="mean", delta=1.0):
    d = _A(input) - _A(label)
    ad = jnp.abs(d)
    loss = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
    return _reduce(loss, reduction)


@primitive
def binary_cross_entropy(input, label, weight=None, reduction="mean"):
    p = jnp.clip(_A(input), 1e-12, 1.0 - 1e-12)
    y = _A(label)
    loss = -(y * jnp.log(p) + (1.0 - y) * jnp.log(1.0 - p))
    if weight is not None:
        loss = loss * _A(weight)
    return _reduce(loss, reduction)


@primitive
def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None):
    x = _A(logit)
    y = _A(label)
    # numerically stable: max(x,0) - x*y + log(1+exp(-|x|))
    loss = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
    if pos_weight is not None:
        pw = _A(pos_weight)
        log_sig = jax.nn.log_sigmoid(x)
        log_sig_neg = jax.nn.log_sigmoid(-x)
        loss = -(pw * y * log_sig + (1.0 - y) * log_sig_neg)
    if weight is not None:
        loss = loss * _A(weight)
    return _reduce(loss, reduction)


@primitive
def kl_div(input, label, reduction="mean"):
    logp = _A(input)
    y = _A(label)
    loss = y * (jnp.log(jnp.maximum(y, 1e-30)) - logp)
    if reduction == "batchmean":
        return jnp.sum(loss) / logp.shape[0]
    return _reduce(loss, reduction)


@primitive
def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):
    x = _A(input)
    y = _A(label)
    loss = jnp.where(y == 1.0, x, jnp.maximum(0.0, margin - x))
    return _reduce(loss, reduction)


@primitive
def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):
    loss = jnp.maximum(0.0, -_A(label) * (_A(input) - _A(other)) + margin)
    return _reduce(loss, reduction)


@primitive
def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean"):
    x1, x2 = _A(input1), _A(input2)
    y = _A(label)
    cos = jnp.sum(x1 * x2, -1) / jnp.maximum(
        jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12)
    loss = jnp.where(y == 1, 1.0 - cos, jnp.maximum(0.0, cos - margin))
    return _reduce(loss, reduction)


@primitive
def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean"):
    a, pos, neg = _A(input), _A(positive), _A(negative)

    def dist(u, v):
        return jnp.sum(jnp.abs(u - v + epsilon) ** p, axis=-1) ** (1.0 / p)

    d_ap = dist(a, pos)
    d_an = dist(a, neg)
    if swap:
        d_pn = dist(pos, neg)
        d_an = jnp.minimum(d_an, d_pn)
    loss = jnp.maximum(0.0, d_ap - d_an + margin)
    return _reduce(loss, reduction)


@primitive
def log_loss(input, label, epsilon=1e-4):
    p = _A(input)
    y = _A(label)
    return -y * jnp.log(p + epsilon) - (1.0 - y) * jnp.log(1.0 - p + epsilon)


@primitive
def square_error_cost(input, label):
    return jnp.square(_A(input) - _A(label))


@primitive
def ctc_loss_dense(log_probs, labels, input_lengths, label_lengths, blank=0,
                   reduction="mean"):
    """CTC via the standard alpha recursion in log space using lax.scan
    (reference warpctc op); log_probs [T,N,C], labels [N,S]."""
    lp = _A(log_probs)
    lbl = _A(labels).astype(jnp.int32)
    T, N, C = lp.shape
    S = lbl.shape[1]
    # extended label seq: blank, l1, blank, l2, ... blank  (len 2S+1)
    ext = jnp.full((N, 2 * S + 1), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(lbl)
    ext_len = 2 * _A(label_lengths).astype(jnp.int32) + 1
    neg_inf = jnp.asarray(-1e30, lp.dtype)

    init = jnp.full((N, 2 * S + 1), neg_inf)
    init = init.at[:, 0].set(lp[0, jnp.arange(N), blank])
    init = init.at[:, 1].set(
        jnp.where(S > 0, lp[0, jnp.arange(N), ext[:, 1]], neg_inf))

    same_as_prev2 = jnp.concatenate(
        [jnp.ones((N, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

    def step(alpha, lp_t):
        a0 = alpha
        a1 = jnp.concatenate([jnp.full((N, 1), neg_inf), alpha[:, :-1]], 1)
        a2 = jnp.concatenate([jnp.full((N, 2), neg_inf), alpha[:, :-2]], 1)
        a2 = jnp.where(same_as_prev2, neg_inf, a2)
        m = jnp.maximum(jnp.maximum(a0, a1), a2)
        sum_ = jnp.where(
            m <= neg_inf / 2, neg_inf,
            m + jnp.log(jnp.exp(a0 - m) + jnp.exp(a1 - m) + jnp.exp(a2 - m)))
        emit = jnp.take_along_axis(lp_t, ext, axis=1)
        new_alpha = sum_ + emit
        return new_alpha, new_alpha

    _, alphas_rest = jax.lax.scan(step, init, lp[1:])
    # alphas[t, n, s] for t = 0..T-1; each sample reads its own final frame
    alphas = jnp.concatenate([init[None], alphas_rest], axis=0)
    t_last = jnp.clip(_A(input_lengths).astype(jnp.int32) - 1, 0, T - 1)
    alpha = alphas[t_last, jnp.arange(N)]  # [N, 2S+1]
    idx_last = ext_len - 1
    ll_blank = jnp.take_along_axis(alpha, idx_last[:, None], 1)[:, 0]
    ll_label = jnp.take_along_axis(
        alpha, jnp.maximum(idx_last - 1, 0)[:, None], 1)[:, 0]
    m = jnp.maximum(ll_blank, ll_label)
    ll = m + jnp.log(jnp.exp(ll_blank - m) + jnp.exp(ll_label - m))
    loss = -ll
    if reduction == "mean":
        return jnp.mean(loss / jnp.maximum(_A(label_lengths), 1))
    return _reduce(loss, reduction)


# -- long-tail losses (VERDICT r1 item 8) -----------------------------------

@primitive
def huber_loss(input, label, delta=1.0, reduction="mean"):
    """reference phi/kernels/huber_loss_kernel.h."""
    d = _A(input) - _A(label)
    ad = jnp.abs(d)
    loss = jnp.where(ad <= delta, 0.5 * d * d, delta * (ad - 0.5 * delta))
    return _reduce(loss, reduction)


@primitive
def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25,
                       gamma=2.0, reduction="sum"):
    """reference phi/kernels/sigmoid_cross_entropy_with_logits + focal
    weighting (python/paddle/nn/functional/loss.py sigmoid_focal_loss)."""
    x = _A(logit).astype(jnp.float32)
    y = _A(label).astype(jnp.float32)
    p = jax.nn.sigmoid(x)
    ce = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
    p_t = p * y + (1 - p) * (1 - y)
    a_t = alpha * y + (1 - alpha) * (1 - y)
    loss = a_t * ((1 - p_t) ** gamma) * ce
    if normalizer is not None:
        loss = loss / _A(normalizer)
    return _reduce(loss, reduction)


@primitive
def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      normalize=False):
    """reference sigmoid_cross_entropy_with_logits_kernel."""
    xv = _A(x).astype(jnp.float32)
    y = _A(label).astype(jnp.float32)
    loss = jnp.maximum(xv, 0) - xv * y + jnp.log1p(jnp.exp(-jnp.abs(xv)))
    valid = _A(label) != ignore_index
    loss = jnp.where(valid, loss, 0.0)
    if normalize:
        loss = loss / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    return loss


@primitive
def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, return_softmax=False,
                         reduction="mean"):
    """ArcFace/CosFace margin softmax (reference
    phi/kernels/margin_cross_entropy_kernel — the c_margin op family):
    logits are cosines; the target class gets
    cos(m1*theta + m2) - m3, everything scaled by s."""
    x = _A(logits).astype(jnp.float32)
    li = _A(label).astype(jnp.int32).reshape(-1)
    n_cls = x.shape[-1]
    cos_t = jnp.clip(x, -1.0, 1.0)
    theta = jnp.arccos(cos_t)
    modified = jnp.cos(margin1 * theta + margin2) - margin3
    oh = jax.nn.one_hot(li, n_cls, dtype=x.dtype)
    out = jnp.where(oh > 0, modified, cos_t) * scale
    lse = jax.nn.logsumexp(out, axis=-1)
    picked = jnp.sum(oh * out, axis=-1)
    loss = lse - picked
    loss = _reduce(loss, reduction)
    if return_softmax:
        return loss, jax.nn.softmax(out, axis=-1)
    return loss


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """Public CTC API (reference python/paddle/nn/functional/loss.py
    ctc_loss; kernel parity warpctc_kernel.h) over the lax.scan alpha
    recursion in ctc_loss_dense."""
    loss = ctc_loss_dense(log_probs, labels, input_lengths, label_lengths,
                          blank=blank, reduction="none")
    if norm_by_times:
        ll = _A(input_lengths).astype(jnp.float32).reshape(-1)
        loss = loss / jnp.maximum(ll, 1.0)
    return _reduce(loss, reduction)


def warpctc(logits, label, logits_length, labels_length, blank=0,
            norm_by_times=False):
    """reference warpctc op name: softmax-normalizes then runs the CTC
    recursion per sample (reduction none)."""
    lp = jax.nn.log_softmax(_A(logits), axis=-1)
    return ctc_loss(lp, label, logits_length, labels_length, blank=blank,
                    reduction="none", norm_by_times=norm_by_times)


@primitive
def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False):
    """Hierarchical sigmoid loss (reference hsigmoid_loss_kernel.h).

    Default tree: complete binary tree over classes — leaf of class c is
    node (c + num_classes); walking to the root visits internal nodes
    (1-indexed 1..num_classes-1) whose rows of `weight` score the
    left/right decision. Custom trees come in as path_table/path_code
    (rows padded with -1)."""
    x = _A(input).astype(jnp.float32)           # [N, D]
    li = _A(label).astype(jnp.int32).reshape(-1)
    w = _A(weight).astype(jnp.float32)          # [num_classes-1, D]
    b = None if bias is None else _A(bias).astype(jnp.float32).reshape(-1)
    if path_table is not None:
        table = _A(path_table).astype(jnp.int32)   # [N, L] node ids
        code = _A(path_code).astype(jnp.float32)   # [N, L] 0/1
        valid = table >= 0
        rows = jnp.clip(table, 0, w.shape[0] - 1)
    else:
        depth = max(1, int(math.ceil(math.log2(max(num_classes, 2)))) + 1)
        node = li + num_classes
        tables, codes = [], []
        for _ in range(depth):
            parent = node // 2
            tables.append(parent)
            codes.append((node % 2).astype(jnp.float32))
            node = parent
        table = jnp.stack(tables, axis=1)       # parent ids (1-indexed)
        code = jnp.stack(codes, axis=1)
        valid = table >= 1
        rows = jnp.clip(table - 1, 0, w.shape[0] - 1)
    logits = jnp.einsum("nd,nld->nl", x, w[rows])
    if b is not None:
        logits = logits + b[rows]
    # BCE-with-logits against the path code, masked to the real path
    ce = jnp.maximum(logits, 0) - logits * code + jnp.log1p(
        jnp.exp(-jnp.abs(logits)))
    loss = jnp.sum(jnp.where(valid, ce, 0.0), axis=1)
    return loss[:, None]


@primitive(nondiff=True)
def class_center_sample(label, num_classes, num_samples, group=None):
    """reference class_center_sample_kernel: sample `num_samples` class
    centers always containing the positives; returns (remapped_label,
    sampled_class_indices). Host-side (data-dependent unique set)."""
    import numpy as np

    li = np.asarray(_A(label)).astype(np.int64).reshape(-1)
    pos = np.unique(li)
    # fresh, paddle.seed-controlled randomness per call (reference kernel
    # draws from the device generator each invocation)
    from ...framework import random as _random

    seed = int(jax.random.randint(_random.next_key(), (), 0, 2 ** 31 - 1))
    rng = np.random.RandomState(seed)
    neg_pool = np.setdiff1d(np.arange(num_classes, dtype=np.int64), pos)
    n_extra = max(0, min(num_samples, num_classes) - pos.size)
    extra = rng.choice(neg_pool, size=n_extra, replace=False) \
        if n_extra > 0 else np.empty((0,), np.int64)
    sampled = np.concatenate([pos, np.sort(extra)])
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(sampled.size)
    return jnp.asarray(remap[li]), jnp.asarray(sampled)


@primitive
def soft_margin_loss(input, label, reduction="mean", name=None):
    """log(1 + exp(-label * input)), label in {-1, 1} (reference
    nn/functional/loss.py:3770)."""
    iv = _A(input)
    lv = _A(label).astype(iv.dtype)
    loss = jnp.logaddexp(0.0, -lv * iv)  # stable log(1+exp(z))
    return _reduce(loss, reduction)


@primitive
def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    """Per-class sigmoid BCE averaged over classes (reference
    nn/functional/loss.py:3043)."""
    iv = _A(input)
    lv = _A(label).astype(iv.dtype)
    loss = -(lv * jax.nn.log_sigmoid(iv)
             + (1.0 - lv) * jax.nn.log_sigmoid(-iv))
    if weight is not None:
        loss = loss * _A(weight)
    loss = loss.mean(axis=-1)
    return _reduce(loss, reduction)


@primitive
def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """N-pair metric loss (reference nn/functional/loss.py:314): L2 on
    the embeddings + softmax CE over the anchor@positive^T similarity
    with same-label soft targets."""
    a, p = _A(anchor), _A(positive)
    lab = _A(labels).reshape(-1)
    batch = a.shape[0]
    l2loss = l2_reg * (jnp.sum(a * a) + jnp.sum(p * p)) / batch * 0.25
    sim = a @ p.T                                      # [N, N]
    same = (lab[:, None] == lab[None, :]).astype(a.dtype)
    target = same / same.sum(axis=1, keepdims=True)
    logp = jax.nn.log_softmax(sim, axis=1)
    ce = -(target * logp).sum(axis=1).mean()
    return l2loss + ce


@primitive
def dice_loss(input, label, epsilon=1e-5):
    """reference nn/functional/loss.py dice_loss: 1 - 2|X∩Y|/(|X|+|Y|)
    over the last dim's class probabilities vs int labels."""
    x = _A(input)
    lbl = _A(label)
    if lbl.ndim == x.ndim and lbl.shape[-1] == 1:
        lbl = jnp.squeeze(lbl, -1)
    onehot = jax.nn.one_hot(lbl.astype(jnp.int32), x.shape[-1],
                            dtype=x.dtype)
    reduce_dims = tuple(range(1, x.ndim))
    inter = jnp.sum(x * onehot, axis=reduce_dims)
    union = jnp.sum(x, axis=reduce_dims) + jnp.sum(onehot, axis=reduce_dims)
    return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))


@primitive
def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """reference multi_margin_loss: mean_i max(0, margin - x[y] + x[i])^p
    over i != y."""
    x = _A(input)
    y = _A(label).astype(jnp.int32).reshape(-1)
    n, c = x.shape
    picked = jnp.take_along_axis(x, y[:, None], axis=1)
    base = jnp.maximum(0.0, margin - picked + x)
    if weight is not None:
        # weight multiplies INSIDE the power (reference loss.py:3746:
        # clip(w * (margin - x_y + x), 0)^p)
        base = base * _A(weight)[y][:, None]
    m = base ** p
    m = m.at[jnp.arange(n), y].set(0.0)
    loss = m.sum(axis=1) / c
    return _reduce(loss, reduction)


@primitive
def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False,
                      name=None):
    """reference pairwise_distance: ||x - y + eps||_p over the last dim."""
    d = _A(x) - _A(y) + epsilon
    out = jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)
    if keepdim:
        out = out[..., None]
    return out


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """reference triplet_margin_with_distance_loss: user-supplied
    distance; composite of existing primitives (stays differentiable
    through whatever `distance_function` does)."""
    from ...core.tensor import Tensor as _T
    import paddle_tpu as paddle

    dist = distance_function if distance_function is not None \
        else (lambda a, b: _T(pairwise_distance.raw_fn(
            a._value if isinstance(a, _T) else a,
            b._value if isinstance(b, _T) else b)))
    d_pos = dist(input, positive)
    d_neg = dist(input, negative)
    if swap:
        d_pn = dist(positive, negative)
        d_neg = paddle.minimum(d_neg, d_pn)
    loss = paddle.clip(d_pos - d_neg + margin, min=0.0)
    if reduction == "mean":
        return loss.mean()
    if reduction == "sum":
        return loss.sum()
    return loss


@primitive
def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean", name=None):
    """RNN-T transducer loss (reference rnnt_loss over the warprnnt
    kernel; public forward-variable recursion, fresh implementation).

    input: [B, Tmax, Umax+1, V] logits; label: [B, Umax] int;
    alpha(t, u) = logaddexp(alpha(t-1, u) + blank(t-1, u),
                            alpha(t, u-1) + y(t, u-1)) in log space,
    loss = -(alpha(T-1, U) + blank(T-1, U)).

    Deviation: FastEmit regularization (nonzero fastemit_lambda) is not
    implemented — it needs the beta recursion's emission posteriors; a
    nonzero value raises rather than silently computing plain RNNT (the
    default here is therefore 0.0, not the reference's 0.001).
    """
    if fastemit_lambda:
        raise NotImplementedError(
            "rnnt_loss: FastEmit regularization (fastemit_lambda != 0) "
            "is not implemented; pass fastemit_lambda=0.0")
    logp = jax.nn.log_softmax(_A(input).astype(jnp.float32), axis=-1)
    lbl = _A(label).astype(jnp.int32)
    T_len = _A(input_lengths).astype(jnp.int32)
    U_len = _A(label_lengths).astype(jnp.int32)
    B, Tm, Um1, V = logp.shape
    Um = Um1 - 1
    NEG = -1e30

    blank_lp = logp[..., blank]                       # [B, T, U+1]
    y_lp = jnp.take_along_axis(
        logp[:, :, :Um, :], lbl[:, None, :, None].repeat(Tm, 1),
        axis=-1)[..., 0]                              # [B, T, U]

    u_idx = jnp.arange(Um1)

    def step(alpha_prev, t):
        # arrival from below via blank at (t-1, u); t=0 seeds u=0 only
        base = jnp.where(
            t == 0,
            jnp.where(u_idx[None, :] == 0, 0.0, NEG),
            alpha_prev + blank_lp[:, t - 1, :])
        y_t = y_lp[:, t, :]                            # [B, Um]

        def chain(carry, u):
            # within-t recurrence: alpha(t,u) = logaddexp(base(u),
            # alpha(t,u-1) + y(t,u-1))
            b_u = base[:, u]
            val = jnp.where(
                u == 0, b_u,
                jnp.logaddexp(b_u, carry + y_t[:, jnp.maximum(u - 1, 0)]))
            return val, val

        _, cols = jax.lax.scan(chain, jnp.full((B,), NEG), u_idx)
        new = jnp.swapaxes(cols, 0, 1)                 # [B, U+1]
        return new, new

    init = jnp.full((B, Um1), NEG)
    _, alphas = jax.lax.scan(step, init, jnp.arange(Tm))  # [T, B, U+1]
    alphas = jnp.swapaxes(alphas, 0, 1)               # [B, T, U+1]
    final = jnp.take_along_axis(
        jnp.take_along_axis(alphas, (T_len - 1)[:, None, None]
                            .repeat(Um1, 2), axis=1)[:, 0, :],
        U_len[:, None], axis=1)[:, 0]
    final_blank = jnp.take_along_axis(
        jnp.take_along_axis(blank_lp, (T_len - 1)[:, None, None]
                            .repeat(Um1, 2), axis=1)[:, 0, :],
        U_len[:, None], axis=1)[:, 0]
    nll = -(final + final_blank)
    return _reduce(nll, reduction)
