"""Normalization functionals (reference python/paddle/nn/functional/norm.py,
phi/kernels/{batch_norm,layer_norm,group_norm}_kernel). Stateless math here;
running-stat bookkeeping lives in the Layer classes."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.dispatch import primitive

_A = jnp.asarray


@primitive
def batch_norm_infer(x, running_mean, running_var, weight=None, bias=None,
                     epsilon=1e-5, data_format="NCHW"):
    x = _A(x)
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    shape = [1] * x.ndim
    shape[ch_axis] = -1
    mean = _A(running_mean).reshape(shape)
    var = _A(running_var).reshape(shape)
    out = (x - mean) / jnp.sqrt(var + epsilon)
    if weight is not None:
        out = out * _A(weight).reshape(shape)
    if bias is not None:
        out = out + _A(bias).reshape(shape)
    return out


@primitive
def batch_norm_train(x, weight=None, bias=None, epsilon=1e-5,
                     data_format="NCHW"):
    """Returns (out, batch_mean, batch_var). Caller updates running stats."""
    x = _A(x)
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    mean = jnp.mean(x, axis=axes)
    var = jnp.var(x, axis=axes)
    shape = [1] * x.ndim
    shape[ch_axis] = -1
    out = (x - mean.reshape(shape)) / jnp.sqrt(var.reshape(shape) + epsilon)
    if weight is not None:
        out = out * _A(weight).reshape(shape)
    if bias is not None:
        out = out + _A(bias).reshape(shape)
    return out, mean, var


@primitive
def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5):
    x = _A(x)
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    n = len(tuple(normalized_shape))
    axes = tuple(range(x.ndim - n, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) / jnp.sqrt(var + epsilon)
    if weight is not None:
        out = out * _A(weight)
    if bias is not None:
        out = out + _A(bias)
    return out


@primitive
def rms_norm(x, weight=None, epsilon=1e-6):
    """RMSNorm — not in the reference snapshot but required by the Llama
    family; computed in float32 for bf16 inputs (TPU numerics practice)."""
    x = _A(x)
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    out = xf * jnp.reciprocal(jnp.sqrt(ms + epsilon))
    out = out.astype(dtype)
    if weight is not None:
        out = out * _A(weight)
    return out


@primitive
def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format="NCHW"):
    x = _A(x)
    channel_last = not data_format.startswith("NC")
    if channel_last:
        x_ = jnp.moveaxis(x, -1, 1)
    else:
        x_ = x
    n, c = x_.shape[:2]
    g = int(num_groups)
    xg = x_.reshape(n, g, c // g, *x_.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    out = ((xg - mean) / jnp.sqrt(var + epsilon)).reshape(x_.shape)
    shape = [1, c] + [1] * (x_.ndim - 2)
    if weight is not None:
        out = out * _A(weight).reshape(shape)
    if bias is not None:
        out = out + _A(bias).reshape(shape)
    if channel_last:
        out = jnp.moveaxis(out, 1, -1)
    return out


@primitive
def instance_norm(x, weight=None, bias=None, epsilon=1e-5, data_format="NCHW"):
    x = _A(x)
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    axes = tuple(i for i in range(2, x.ndim)) if ch_axis == 1 else tuple(
        range(1, x.ndim - 1))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) / jnp.sqrt(var + epsilon)
    if weight is not None:
        shape = [1] * x.ndim
        shape[ch_axis] = -1
        out = out * _A(weight).reshape(shape)
    if bias is not None:
        shape = [1] * x.ndim
        shape[ch_axis] = -1
        out = out + _A(bias).reshape(shape)
    return out


@primitive
def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW"):
    import jax

    x = _A(x)
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    sq = jnp.square(x)
    half = size // 2
    pad = [(0, 0)] * x.ndim
    pad[ch_axis] = (half, size - half - 1)
    sq = jnp.pad(sq, pad)
    dims = [1] * x.ndim
    dims[ch_axis] = size
    s = jax.lax.reduce_window(sq, 0.0, jax.lax.add, tuple(dims), (1,) * x.ndim,
                              "VALID")
    return x / jnp.power(k + alpha * s, beta)
