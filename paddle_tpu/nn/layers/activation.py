"""Activation layers (reference python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from ..layer import Layer


def _simple(fn_name, **fixed):
    class _Act(Layer):
        def __init__(self, name=None, **kwargs):
            super().__init__()
            self._kwargs = {**fixed, **{k: v for k, v in kwargs.items()
                                        if k != "name"}}

        def forward(self, x):
            return getattr(F, fn_name)(x, **self._kwargs)

    _Act.__name__ = fn_name.title().replace("_", "")
    return _Act


class ReLU(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.relu(x)


class ReLU6(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.relu6(x)


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return F.gelu(x, approximate=self.approximate)


class Sigmoid(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.sigmoid(x)


class Tanh(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.tanh(x)


class Silu(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.silu(x)


class Swish(Silu):
    pass


class Mish(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.mish(x)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.elu(x, alpha=self.alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772,
                 name=None):
        super().__init__()
        self.scale = scale
        self.alpha = alpha

    def forward(self, x):
        return F.selu(x, scale=self.scale, alpha=self.alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.celu(x, alpha=self.alpha)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, negative_slope=self.negative_slope)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self.data_format)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return F.hardtanh(x, min=self.min, max=self.max)


class Hardsigmoid(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.hardsigmoid(x)


class Hardswish(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.hardswish(x)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, threshold=self.threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.softshrink(x, threshold=self.threshold)


class Tanhshrink(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.tanhshrink(x)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self.beta, self.threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, beta=self.beta, threshold=self.threshold)


class Softsign(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.softsign(x)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, axis=self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self.axis)


class LogSigmoid(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        import jax

        from ...core.dispatch import primitive

        return _log_sigmoid(x)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.thresholded_relu(x, threshold=self.threshold)


from ...core.dispatch import primitive  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


@primitive(name="log_sigmoid")
def _log_sigmoid(x):
    return jax.nn.log_sigmoid(jnp.asarray(x))

class RReLU(Layer):
    """reference nn RReLU: random slope in [lower, upper] when training,
    their mean in eval."""

    def __init__(self, lower=0.125, upper=1.0 / 3.0, name=None):
        super().__init__()
        self._lower, self._upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self._lower, self._upper,
                       training=self.training)


class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW inputs (reference nn
    Softmax2D)."""

    def forward(self, x):
        return F.softmax(x, axis=-3)
