"""Common layers (reference python/paddle/nn/layer/common.py)."""
from __future__ import annotations

from ... import ops
from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from ..layer import Layer


class Linear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=None if weight_attr else I.XavierNormal())
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                [out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return "in=%d, out=%d" % (self.in_features, self.out_features)


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=None if weight_attr else I.Normal(0.0, 1.0))
        if padding_idx is not None:
            self.weight._value = self.weight._value.at[padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx)


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, training=self.training, mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        return ops.manipulation.flatten(x, self.start_axis, self.stop_axis)


class Identity(Layer):
    def forward(self, x):
        return x


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, size=self.size, scale_factor=self.scale_factor,
                             mode=self.mode, align_corners=self.align_corners,
                             data_format=self.data_format)


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL"):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return ops.manipulation.pad(x, self.padding, mode=self.mode,
                                    value=self.value,
                                    data_format=self.data_format)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW"):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return ops.manipulation.pad(x, self.padding, mode=self.mode,
                                    value=self.value,
                                    data_format=self.data_format)


class Pad3D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW"):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return ops.manipulation.pad(x, self.padding, mode=self.mode,
                                    value=self.value,
                                    data_format=self.data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW"):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1):
        super().__init__()
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return ops.manipulation.unfold(x, self.kernel_sizes, self.strides,
                                       self.paddings, self.dilations)

class Fold(Layer):
    """col2im layer (reference nn/layer/common.py:1612 Fold)."""

    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self._out = output_sizes
        self._args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, self._out, *self._args)


class Unflatten(Layer):
    """Inverse of flatten on one axis (reference nn/layer/common.py
    Unflatten)."""

    def __init__(self, axis, shape, name=None):
        super().__init__()
        self._axis = axis
        self._shape = list(shape)

    def forward(self, x):
        from ... import ops

        s = list(x.shape)
        ax = self._axis if self._axis >= 0 else self._axis + len(s)
        new = s[:ax] + self._shape + s[ax + 1:]
        return ops.manipulation.reshape(x, new)

class ChannelShuffle(Layer):
    """reference nn ChannelShuffle."""

    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self._args = (groups, data_format)

    def forward(self, x):
        return F.channel_shuffle(x, *self._args)


class PixelUnshuffle(Layer):
    """reference nn PixelUnshuffle."""

    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self._args = (downscale_factor, data_format)

    def forward(self, x):
        return F.pixel_unshuffle(x, *self._args)


class ZeroPad2D(Layer):
    """reference nn ZeroPad2D."""

    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__()
        self._args = (padding, data_format)

    def forward(self, x):
        return F.zeropad2d(x, *self._args)


class UpsamplingBilinear2D(Layer):
    """reference nn UpsamplingBilinear2D."""

    def __init__(self, size=None, scale_factor=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._kw = dict(size=size, scale_factor=scale_factor,
                        mode="bilinear", align_corners=True,
                        data_format=data_format)

    def forward(self, x):
        return F.interpolate(x, **self._kw)


class UpsamplingNearest2D(Layer):
    """reference nn UpsamplingNearest2D."""

    def __init__(self, size=None, scale_factor=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._kw = dict(size=size, scale_factor=scale_factor,
                        mode="nearest", data_format=data_format)

    def forward(self, x):
        return F.interpolate(x, **self._kw)
