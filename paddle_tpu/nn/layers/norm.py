"""Normalization layers (reference python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from ..layer import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr, is_bias=False,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                [num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features])))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features])))

    def forward(self, x):
        training = self.training and not self.use_global_stats
        if not training:
            return F.batch_norm_infer(
                x, self._mean, self._variance, self.weight, self.bias,
                epsilon=self.epsilon, data_format=self.data_format)
        out, mean, var = F.batch_norm_train(
            x, self.weight, self.bias, epsilon=self.epsilon,
            data_format=self.data_format)
        m = self.momentum
        # running-stat update through DISPATCHED ops (not raw arrays): under
        # static capture these land on the Program tape, and set_value
        # registers the state assignment so the Executor threads
        # mean/variance through replays (reference batch_norm op updates
        # MeanOut/VarianceOut in-graph, phi/kernels/batch_norm_kernel).
        # no_grad + detach: the update is a statistic, not a grad path.
        from ...core.dispatch import no_grad

        with no_grad():
            # `mean`/`var` used directly (NOT detached): no_grad already
            # keeps grads off, and the tape needs the op-output tensors
            # so replays recompute the update from the fresh batch stats
            nm = self._mean * m + mean * (1.0 - m)
            nv = self._variance * m + var * (1.0 - m)
        self._mean.set_value(nm)
        self._variance.set_value(nv)
        return out


class BatchNorm(_BatchNormBase):
    """Legacy paddle.nn.BatchNorm (act fused)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 data_layout="NCHW", **kwargs):
        super().__init__(num_channels, momentum, epsilon,
                         data_format=data_layout)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act == "relu":
            out = F.relu(out)
        elif self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm. Under SPMD compilation the batch axis is
    sharded over the mesh and XLA's all-reduce inside mean/var makes this
    exact (the reference needs a dedicated sync_batch_norm CUDA op,
    operators/sync_batch_norm_op.cu). Eagerly it behaves like BatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            out = SyncBatchNorm(layer.num_features, layer.momentum,
                                layer.epsilon, data_format=layer.data_format)
            if layer.weight is not None:
                out.weight.set_value(layer.weight)
            if layer.bias is not None:
                out.bias.set_value(layer.bias)
            out._mean.set_value(layer._mean)
            out._variance.set_value(layer._variance)
        for name, sub in list(layer._sub_layers.items()):
            out.add_sublayer(name, cls.convert_sync_batchnorm(sub))
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self.normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                self.normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            epsilon=self.epsilon)


class RMSNorm(Layer):
    """TPU-first addition (Llama family); reference lacks it."""

    def __init__(self, hidden_size, epsilon=1e-6):
        super().__init__()
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, epsilon=self.epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.num_groups = num_groups
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.weight, self.bias,
                            epsilon=self.epsilon, data_format=self.data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self.epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)
        self.data_format = data_format

    def forward(self, x):
        return F.instance_norm(x, self.weight, self.bias,
                               epsilon=self.epsilon,
                               data_format=self.data_format)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format, name)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format, name)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, axis=0, power_iters=1, epsilon=1e-12,
                 name=None):
        super().__init__()
        self.axis = axis
        self.power_iters = power_iters
        self.epsilon = epsilon
        self.weight_shape = weight_shape

    def forward(self, weight):
        import jax.numpy as jnp

        from ...core.dispatch import primitive

        w = weight._value if isinstance(weight, Tensor) else jnp.asarray(weight)
        h = w.shape[self.axis]
        w_mat = jnp.moveaxis(w, self.axis, 0).reshape(h, -1)
        u = jnp.ones((h,), w.dtype)
        for _ in range(self.power_iters):
            v = w_mat.T @ u
            v = v / (jnp.linalg.norm(v) + self.epsilon)
            u = w_mat @ v
            u = u / (jnp.linalg.norm(u) + self.epsilon)
        sigma = u @ w_mat @ v
        return Tensor(w / sigma)
